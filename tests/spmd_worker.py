"""Worker script for SPMD multi-process tests (launched by test_spmd.py).

Runs the full framework API as one rank of an N-process job — the analog of
the reference's parallel test suite executed under horovodrun
(reference: test/parallel/test_torch.py run at np=2, .buildkite/
gen-pipeline.sh:231). Asserts rank-locally; any failure exits non-zero.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    assert hvd.is_initialized()
    assert 0 <= rank < size

    # -- allreduce: average (default) and sum ------------------------------
    x = jnp.arange(8, dtype=jnp.float32) * (rank + 1)
    avg = hvd.allreduce(x, name="ar.avg")
    factor = sum(r + 1 for r in range(size)) / size
    np.testing.assert_allclose(np.asarray(avg),
                               np.arange(8, dtype=np.float32) * factor,
                               rtol=1e-5)
    tot = hvd.allreduce(x, name="ar.sum", op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(tot),
                               np.arange(8, dtype=np.float32) * factor * size,
                               rtol=1e-5)

    # Steady state: same names again must ride the response-cache fast path.
    for _ in range(3):
        again = hvd.allreduce(x, name="ar.avg")
        np.testing.assert_allclose(np.asarray(again), np.asarray(avg),
                                   rtol=1e-6)

    # -- grouped allreduce --------------------------------------------------
    ts = [jnp.full((3,), float(rank), jnp.float32),
          jnp.full((2, 2), float(rank) * 2, jnp.float32)]
    outs = hvd.grouped_allreduce(ts, name="gar", op=hvd.Sum)
    sum_ranks = sum(range(size))
    np.testing.assert_allclose(np.asarray(outs[0]), np.full((3,), sum_ranks))
    np.testing.assert_allclose(np.asarray(outs[1]),
                               np.full((2, 2), 2.0 * sum_ranks))

    # -- min / max / product -----------------------------------------------
    v = jnp.full((4,), float(rank + 1), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(v, name="mn", op=hvd.Min)), 1.0)
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(v, name="mx", op=hvd.Max)), float(size))
    prod = 1.0
    for r in range(size):
        prod *= r + 1
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(v, name="pr", op=hvd.Product)), prod)

    # -- prescale / postscale ----------------------------------------------
    s = hvd.allreduce(jnp.ones(4, jnp.float32), name="scaled", op=hvd.Sum,
                      prescale_factor=2.0, postscale_factor=0.5)
    np.testing.assert_allclose(np.asarray(s), float(size))

    # -- ragged allgather ---------------------------------------------------
    mine = jnp.full((rank + 1, 2), float(rank), jnp.float32)
    gathered = hvd.allgather(mine, name="ag")
    total_rows = sum(r + 1 for r in range(size))
    assert gathered.shape == (total_rows, 2), gathered.shape
    off = 0
    for r in range(size):
        np.testing.assert_allclose(np.asarray(gathered[off:off + r + 1]),
                                   float(r))
        off += r + 1

    # -- broadcast ----------------------------------------------------------
    b = jnp.full((5,), float(rank), jnp.float32)
    out = hvd.broadcast(b, root_rank=min(1, size - 1), name="bc")
    np.testing.assert_allclose(np.asarray(out), float(min(1, size - 1)))

    # 0-d broadcast keeps its shape (regression: the native wire only
    # carries ndim>0 shapes, so scalars came back as (1,)).
    sc = hvd.broadcast(jnp.asarray(float(rank), jnp.float32),
                       root_rank=0, name="bc.scalar")
    assert sc.shape == (), sc.shape
    np.testing.assert_allclose(np.asarray(sc), 0.0)

    # -- broadcast_object ---------------------------------------------------
    obj = {"rank": rank, "payload": list(range(10))}
    got = hvd.broadcast_object(obj, root_rank=0, name="bo")
    assert got["rank"] == 0 and got["payload"] == list(range(10))

    # -- alltoall -----------------------------------------------------------
    splits = jnp.array([1] * size, jnp.int32)
    a2a_in = jnp.arange(size, dtype=jnp.float32) + 100 * rank
    a2a_out, rsplits = hvd.alltoall(a2a_in, splits=splits, name="a2a")
    np.testing.assert_array_equal(np.asarray(rsplits), np.ones(size))
    np.testing.assert_allclose(
        np.asarray(a2a_out),
        np.array([100.0 * r + rank for r in range(size)], np.float32))

    # -- reducescatter -------------------------------------------------------
    rs_in = jnp.ones((2 * size, 3), jnp.float32) * (rank + 1)
    rs_out = hvd.reducescatter(rs_in, op=hvd.Sum, name="rs")
    assert rs_out.shape == (2, 3), rs_out.shape
    np.testing.assert_allclose(np.asarray(rs_out),
                               sum(r + 1 for r in range(size)))

    # -- large payload -------------------------------------------------------
    # Per-step SendRecv payloads here far exceed kernel socket buffers; a
    # blocking send in the duplex exchange would deadlock the ring
    # (regression: transport.cc SendRecv must use nonblocking partial writes).
    big = jnp.ones((4 * 1024 * 1024,), jnp.float32) * (rank + 1)
    big_sum = hvd.allreduce(big, name="big", op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(big_sum[:4]),
                               sum(r + 1 for r in range(size)))
    np.testing.assert_allclose(np.asarray(big_sum[-4:]),
                               sum(r + 1 for r in range(size)))

    # -- many in-flight async ops (fusion + handle stress) -------------------
    handles = [hvd.allreduce_async(jnp.full((257,), float(i + rank)),
                                   op=hvd.Sum, name=f"flood.{i}")
               for i in range(64)]
    for i, h in enumerate(handles):
        out = hvd.synchronize(h)
        np.testing.assert_allclose(
            np.asarray(out)[0], sum(i + rr for rr in range(size)),
            rtol=1e-5)

    # -- barrier ------------------------------------------------------------
    hvd.barrier()

    # -- Adasum on the host data plane ---------------------------------------
    # Oracle: VHDD == the pairwise tree a<-(1-dot/2|a|^2)a+(1-dot/2|b|^2)b
    # (reference: adasum/adasum.h:397-407); power-of-two sizes only.
    from horovod_tpu.ops.adasum import adasum_pair_np as np_adasum

    ada_rng = np.random.RandomState(7)
    ada_vecs = [ada_rng.randn(33).astype(np.float32)
                for _ in range(size)]
    if size & (size - 1) == 0:
        out = hvd.allreduce(jnp.asarray(ada_vecs[rank]), op=hvd.Adasum,
                            name="ada")
        expect = ada_vecs
        while len(expect) > 1:
            expect = [np_adasum(expect[i], expect[i + 1])
                      for i in range(0, len(expect), 2)]
        np.testing.assert_allclose(np.asarray(out), expect[0], rtol=1e-5,
                                   atol=1e-6)
        # Grouped adasum reduces PER TENSOR (never concat-fused: the dot
        # coefficients are per-tensor). A 0-d member checks the grouped
        # path preserves scalar shapes (the wire only carries ndim>0).
        gouts = hvd.grouped_allreduce(
            [jnp.asarray(ada_vecs[rank]), jnp.asarray(ada_vecs[rank] * 3.0)],
            op=hvd.Adasum, name="gada")
        g0 = hvd.grouped_allreduce(
            [jnp.asarray(np.float32(1.0 + rank))], op=hvd.Adasum,
            name="gada0")
        assert g0[0].shape == (), g0[0].shape
        for scale, gout in zip((1.0, 3.0), gouts):
            ge = [v * scale for v in ada_vecs]
            while len(ge) > 1:
                ge = [np_adasum(ge[i], ge[i + 1])
                      for i in range(0, len(ge), 2)]
            np.testing.assert_allclose(np.asarray(gout), ge[0], rtol=1e-5,
                                       atol=1e-6)
    else:
        # Non-power-of-two must fail with a clear error, not hang.
        try:
            hvd.allreduce(jnp.asarray(ada_vecs[rank]), op=hvd.Adasum,
                          name="ada.bad")
            raised = False
        except hvd.HorovodInternalError as e:
            raised = "power-of-two" in str(e)
        assert raised, "adasum at non-power-of-two size must error"

    # -- duplicate name rejection -------------------------------------------
    # Deterministic in-flight window: rank 0's "dup" cannot complete
    # until every rank submits it, and the peers submit LATE — so the
    # duplicate submit below is guaranteed to find the name pending.
    # (Back-to-back submits on all ranks would race the cycle thread:
    # a fast negotiation can finish between the two Python calls.)
    if rank == 0:
        h1 = hvd.allreduce_async(jnp.ones(1024, jnp.float32), name="dup")
        try:
            try:
                hvd.allreduce_async(jnp.ones(1024, jnp.float32),
                                    name="dup")
                raised = False
            except hvd.DuplicateNameError:
                raised = True
            assert raised, "duplicate name must be rejected"
        finally:
            hvd.synchronize(h1)
    else:
        time.sleep(0.3)
        hvd.allreduce(jnp.ones(1024, jnp.float32), name="dup")

    # -- cross-rank validation error ----------------------------------------
    bad_shape = (3,) if rank == 0 else (4,)
    err_text = None
    try:
        hvd.allreduce(jnp.zeros(bad_shape, jnp.float32), name="bad")
    except hvd.HorovodInternalError as e:
        err_text = str(e)
    assert err_text is not None and "mismatched" in err_text, \
        f"shape mismatch must fail on every rank; got {err_text!r}"

    # -- process sets --------------------------------------------------------
    # A strict subset (a set equal to the global one is rejected, matching
    # the reference's duplicate-ranks check).
    members = [0, size - 1] if size >= 3 else [0]
    ps = hvd.add_process_set(members)
    if rank in members:
        r = hvd.allreduce(jnp.full((3,), float(rank + 1)), op=hvd.Sum,
                          name="ps.ar", process_set=ps)
        np.testing.assert_allclose(np.asarray(r),
                                   sum(m + 1 for m in members))
        assert ps.rank() == members.index(rank)
    else:
        assert ps.rank() is None
        assert not ps.included()
    hvd.remove_process_set(ps)

    # -- make_train_step host-plane dispatch (r4 regression) ----------------
    # Without an explicit mesh in multi-process mode, the step must reduce
    # gradients ACROSS PROCESSES (jitted local grad + eager allreduce), not
    # pmean over the 1-device local mesh. Oracle: ranks start identical,
    # train on divergent data, end identical with the cross-rank mean grad.
    import optax
    import horovod_tpu.jax as hvd_jax

    w0 = {"w": jnp.ones((3,), jnp.float32)}
    tsopt = hvd_jax.DistributedOptimizer(optax.sgd(1.0))

    def ts_loss(p, b):
        return jnp.sum(p["w"] * b)

    ts_step = hvd_jax.make_train_step(ts_loss, tsopt)
    bvec = jnp.full((3,), float(rank + 1), jnp.float32)
    new_p, _, ts_l = ts_step(w0, tsopt.init(w0), bvec)
    # grad = b per rank; mean over ranks = (n+1)/2; w = 1 - mean
    mean_b = sum(r + 1 for r in range(size)) / size
    np.testing.assert_allclose(np.asarray(new_p["w"]), 1.0 - mean_b,
                               rtol=1e-5)
    # loss averaged across ranks like the shard_map path's pmean
    np.testing.assert_allclose(float(ts_l), 3.0 * mean_b, rtol=1e-5)

    # has_aux variant (the TF-bridge train step's shape): aux state is
    # rank-averaged like the shard_map path pmeans batch stats.
    def ts_loss_aux(p, aux, b):
        return jnp.sum(p["w"] * b), {"stat": aux["stat"] + rank + 1.0}

    aux_opt = hvd_jax.DistributedOptimizer(optax.sgd(1.0))
    aux_step = hvd_jax.make_train_step(ts_loss_aux, aux_opt,
                                       has_aux=True)
    _, new_aux, _, _ = aux_step(w0, {"stat": jnp.zeros(())},
                                aux_opt.init(w0), bvec)
    np.testing.assert_allclose(float(new_aux["stat"]), mean_b, rtol=1e-5)

    # ZeRO has no host-plane variant: must refuse, not silently train
    # each rank alone on the 1-device local mesh.
    try:
        hvd_jax.make_zero_train_step(ts_loss, aux_opt)
    except RuntimeError as e:
        assert "host-plane" in str(e)
    else:
        raise AssertionError("make_zero_train_step did not refuse "
                             "host-plane SPMD mode")

    # -- join with unequal work ---------------------------------------------
    if rank % 2 == 1:
        last = hvd.join()
        assert 0 <= last < size
    else:
        extra = hvd.allreduce(jnp.ones(6, jnp.float32), op=hvd.Sum,
                              name="tail")
        evens = len([r for r in range(size) if r % 2 == 0])
        np.testing.assert_allclose(np.asarray(extra), float(evens))
        # Cached tensor with peers joined: rides the cache fast path, which
        # requires joined ranks to report all-hit bitvectors and execute the
        # agreed responses entry-less (regression: joined-rank cache
        # livelock, controller.cc local_joined_). "ar.avg" was cached by the
        # steady-state loop above (same params: Average); joined ranks
        # contribute the sum identity, the average still divides by size.
        again = hvd.allreduce(x, name="ar.avg")
        np.testing.assert_allclose(
            np.asarray(again),
            np.arange(8, dtype=np.float32) * sum(
                r + 1 for r in range(size) if r % 2 == 0) / size,
            rtol=1e-5)
        # Min with peers joined: joined ranks must contribute the op's
        # identity (+inf), not zeros (regression: core.cc joined-rank fill).
        mn = hvd.allreduce(jnp.full((4,), float(rank + 7), jnp.float32),
                           name="tail.min", op=hvd.Min)
        np.testing.assert_allclose(np.asarray(mn), 7.0)
        hvd.join()

    hvd.shutdown()
    print(f"rank {rank}/{size}: OK", flush=True)


if __name__ == "__main__":
    main()
