"""Primary elastic driver subprocess for the control-plane HA chaos
rows (tests/test_chaos_matrix.py): runs a real ElasticDriver whose HA
knobs (HVDTPU_DRIVER_JOURNAL / HVDTPU_DRIVER_STANDBY_ADDRS /
HVDTPU_DRIVER_PORT / HVDTPU_JOB_TOKEN) come straight from the
environment, so the test can SIGKILL or chaos-partition a genuine
separate driver process while the standby (in the test process)
tails its journal."""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from horovod_tpu.runner import spawn  # noqa: E402
from horovod_tpu.runner.elastic_driver import (ElasticDriver,  # noqa: E402
                                               ElasticSettings)
from horovod_tpu.runner.job import Settings  # noqa: E402


def main():
    worker_env = json.loads(os.environ["HA_WORKER_ENV"])
    settings = Settings(num_proc=2, start_timeout=60, env=worker_env,
                        rendezvous_addr="127.0.0.1")
    es = ElasticSettings(
        settings,
        discovery_script=os.environ["HA_DISCOVERY"],
        min_np=1, max_np=8, discovery_interval=0.2,
        heartbeat_timeout=float(os.environ.get("HA_HEARTBEAT_TIMEOUT",
                                               "30")))
    spawn.reset_capture_dir(None)
    driver = ElasticDriver(es, [sys.executable,
                                os.environ["HA_WORKER"]])
    print(f"HA_PRIMARY_UP port={driver.port} term={driver.term}",
          flush=True)
    return driver.run()


if __name__ == "__main__":
    sys.exit(main())
