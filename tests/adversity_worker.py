"""Adversity scenarios for the native core (launched by test_spmd.py).

The reference exercises failure paths through integration scripts that
kill ranks and let tensors stall (reference: test/integration/test_stall.py,
elastic integration kill tests). Scenario selected via ADVERSITY_MODE:

- stall:    rank 0 submits a tensor nobody else ever submits; with the
            stall-shutdown knob set the coordinator must fail it with a
            rank-naming StalledTensorError while healthy traffic continues.
- kill:     the highest rank dies abruptly mid-stream; survivors must get
            HorovodInternalError (not hang) from in-flight or subsequent
            collectives.
- inflight: rank 0 holds unmatched async operations while every rank
            shuts down; the handles must fail cleanly, no hang, no crash.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402

MODE = os.environ["ADVERSITY_MODE"]


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    # Healthy traffic first so the mesh is known-good.
    out = hvd.allreduce(jnp.ones(4), op=hvd.Sum, name="warm")
    np.testing.assert_allclose(np.asarray(out), float(size))

    if MODE == "stall":
        sync = os.environ["ADVERSITY_SYNC"]
        if rank == 0:
            try:
                hvd.allreduce(jnp.ones(8), name="lonely")
                raise SystemExit("stalled tensor did not fail")
            except hvd.StalledTensorError as e:
                msg = str(e)
                assert "lonely" in msg and "missing ranks" in msg, msg
                # Every non-submitting rank is named.
                for r in range(1, size):
                    assert str(r) in msg, msg
            open(sync, "w").close()
        else:
            # Submit "post" only once rank 0's stall resolved: with the
            # tiny shutdown threshold, a tensor one rank submits seconds
            # before the others would itself be declared stalled.
            deadline = time.monotonic() + 60
            while not os.path.exists(sync):
                assert time.monotonic() < deadline, "no stall signal"
                time.sleep(0.05)
        # Post-stall: the job still works.
        out = hvd.allreduce(jnp.ones(4), op=hvd.Sum, name="post")
        np.testing.assert_allclose(np.asarray(out), float(size))

    elif MODE == "stall_cached":
        # Steady-state stall: the tensor is CACHED on every rank, then one
        # rank stops submitting. The cache-hit requeue loop is invisible to
        # the coordinator's message table, so the controller must escalate
        # long-unagreed hits to the slow path for the stall machinery to
        # fire (regression: controller.cc hit_pending_since_).
        sync = os.environ["ADVERSITY_SYNC"]
        for i in range(3):
            hvd.allreduce(jnp.ones(8), name="steady")
        if rank == 0:
            try:
                hvd.allreduce(jnp.ones(8), name="steady")
                raise SystemExit("cached stalled tensor did not fail")
            except hvd.StalledTensorError as e:
                assert "steady" in str(e), str(e)
            open(sync, "w").close()
        else:
            deadline = time.monotonic() + 60
            while not os.path.exists(sync):
                assert time.monotonic() < deadline, "no stall signal"
                time.sleep(0.05)
        out = hvd.allreduce(jnp.ones(4), op=hvd.Sum, name="post2")
        np.testing.assert_allclose(np.asarray(out), float(size))

    elif MODE == "kill":
        if rank == size - 1:
            # Die abruptly mid-stream: no shutdown, no consensus.
            os._exit(17)
        # Survivors: collectives involving the dead rank must error, not
        # hang (transport failure fails all in-flight handles).
        try:
            for i in range(50):
                hvd.allreduce(jnp.ones(1024), op=hvd.Sum, name=f"k{i}")
            raise SystemExit("collectives kept succeeding with a dead peer")
        except hvd.HorovodInternalError:
            pass

    elif MODE == "inflight":
        if rank == 0:
            handles = [hvd.allreduce_async(jnp.ones(16), name=f"orphan{i}")
                       for i in range(5)]
            hvd.shutdown()
            failed = 0
            for h in handles:
                try:
                    hvd.synchronize(h)
                except hvd.HorovodInternalError:
                    failed += 1
            assert failed == 5, f"only {failed}/5 orphans failed"
        else:
            time.sleep(0.5)  # let rank 0's orphans enter negotiation
            hvd.shutdown()
        print(f"rank {rank}/{size}: ADVERSITY-{MODE} OK", flush=True)
        return

    else:
        raise SystemExit(f"unknown mode {MODE}")

    hvd.shutdown()
    print(f"rank {rank}/{size}: ADVERSITY-{MODE} OK", flush=True)


if __name__ == "__main__":
    main()
