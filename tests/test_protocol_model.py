"""hvd-model: the explicit-state protocol checker.

Four layers, mirroring the checker's own guarantees:

- **spec-is-implementation** — the runtime modules (runner/journal.py,
  fleet/ledger.py, serving/migration.py, serving/kv_cache.py) must
  delegate their transition logic to the pure spec modules under
  analysis/protocol/ by IDENTITY, so exploring the models exercises
  the exact functions production executes;
- **explorer semantics** — BFS completeness, budget findings, fair-
  scheduling liveness, replay/minimize, on toy models small enough to
  reason about by hand;
- **mutation proof** — every seeded historical bug yields a minimized
  counterexample with the expected invariant and trace, while the
  shipped (bug=None) models explore their full bounded space clean;
- **rendering/CLI** — violations ride the existing hvd-lint machinery
  (HVD701/702/703 diagnostics, text counterexamples, SARIF codeFlows)
  and the ``hvd-model`` entry point honors its exit-code contract.
"""

import dataclasses
import json

import pytest

from horovod_tpu.analysis.protocol import (cli as model_cli, journal_spec,
                                           lease_spec, machines,
                                           migration_spec)
from horovod_tpu.analysis.protocol.model import (Action, Step, explore,
                                                 minimize, replay,
                                                 result_diagnostics,
                                                 violation_diagnostic)
from horovod_tpu.analysis.simulate import render_trace


def labels_of(violation):
    return [s.label for s in violation.trace]


# ==========================================================================
# Spec-is-implementation: the runtime executes the spec functions
# ==========================================================================
class TestSpecIsImplementation:
    def test_journal_delegates_to_spec(self):
        from horovod_tpu.runner import journal
        assert journal.apply_entry is journal_spec.apply_entry
        assert journal.state_digest is journal_spec.state_digest
        assert journal.new_state is journal_spec.new_state
        assert journal.durable_key is journal_spec.durable_key
        assert journal.term_fences is journal_spec.term_fences
        assert journal.DURABLE_SCOPES is journal_spec.DURABLE_SCOPES
        assert journal.JournalError is journal_spec.JournalError

    def test_fence_sites_use_the_spec_predicate(self):
        """The HTTP write fence and the driver's probe fence must call
        the ONE fencing predicate the model checks — a re-derived
        comparison at either site would drift out from under the
        checker."""
        import inspect

        from horovod_tpu.runner import elastic_driver, http_server
        assert "term_fences(" in inspect.getsource(
            http_server.KVStoreServer._check_write_term)
        assert "term_fences(" in inspect.getsource(
            http_server._KVStoreHandler._fence_term)
        src = inspect.getsource(elastic_driver)
        assert "term_fences(" in src

    def test_ledger_delegates_to_spec(self):
        from horovod_tpu.fleet import ledger
        assert ledger.next_state is lease_spec.next_state
        assert ledger.resume_action is lease_spec.resume_action
        assert ledger._check_transition is lease_spec.check_transition
        assert ledger.CHAINS is lease_spec.CHAINS
        assert ledger.TERMINAL_STATES is lease_spec.TERMINAL_STATES
        assert ledger.LeaseStateError is lease_spec.LeaseStateError

    def test_serving_migration_delegates_to_spec(self):
        from horovod_tpu.serving import migration as serving_migration
        assert serving_migration.chunk_pages \
            is migration_spec.chunk_pages

    def test_staging_offer_executes_spec_transition(self, monkeypatch):
        """InboundStaging.offer is lock + clock around
        migration_spec.stage_chunk — the spy proves the call goes
        through the spec, parameters and return value intact."""
        from horovod_tpu.serving import migration as serving_migration
        calls = []
        real = migration_spec.stage_chunk

        def spy(entries, payload, **kw):
            calls.append(dict(kw))
            return real(entries, payload, **kw)

        monkeypatch.setattr(migration_spec, "stage_chunk", spy)
        staging = serving_migration.InboundStaging(max_staged=2,
                                                   ttl_s=5.0)
        record = staging.offer({
            "mid": "m", "chunk": 0, "total": 1,
            "pages": [{"payload": "x", "digest": "d"}],
            "meta": {"id": "s"}, "commit": True})
        assert calls and calls[0]["max_staged"] == 2
        assert calls[0]["ttl_s"] == 5.0
        assert record["id"] == "s"
        assert [p["digest"] for p in record["pages"]] == ["d"]

    def test_staging_limit_maps_to_staging_full(self):
        from horovod_tpu.serving import migration as serving_migration
        staging = serving_migration.InboundStaging(max_staged=1,
                                                   ttl_s=900.0)
        assert staging.offer({"mid": "a", "chunk": 0, "total": 2,
                              "pages": []}) is None
        with pytest.raises(serving_migration.StagingFull):
            staging.offer({"mid": "b", "chunk": 0, "total": 2,
                           "pages": []})

    def test_pagepool_admission_is_the_spec_predicate(self,
                                                      monkeypatch):
        from horovod_tpu.serving import kv_cache
        seen = []

        def deny(free, need, watermark):
            seen.append((free, need, watermark))
            return False

        monkeypatch.setattr(kv_cache, "admits", deny)
        pool = kv_cache.PagePool(num_pages=8, page_size=4)
        assert pool.can_admit(4) is False
        with pytest.raises(kv_cache.NoHeadroom):
            pool.alloc_admit(1)
        assert len(seen) == 2
        assert all(w == pool.watermark for _, _, w in seen)


# ==========================================================================
# Explorer semantics on hand-checkable toy models
# ==========================================================================
def _counter_model(limit, loop_at_end=False):
    from horovod_tpu.analysis.protocol.model import Model

    def init():
        return {"n": 0, "x": False}

    def actions(state):
        acts = []
        if state["n"] < limit:
            def inc(s):
                s["n"] += 1
                return s
            acts.append(Action("inc", "m", inc))

        def mark(s):
            s["x"] = True
            return s
        acts.append(Action("mark", "m", mark))
        if loop_at_end and state["n"] == limit:
            acts.append(Action("loop", "m", lambda s: s))
        return acts

    return Model("toy", init, actions)


class TestExplorer:
    def test_complete_exploration_counts(self):
        # states: n in 0..3 x marked/unmarked = 8; every state has a
        # mark edge, n<3 states have an inc edge.
        result = explore(_counter_model(3))
        assert result.ok
        assert result.states == 8
        assert result.depth == 4
        assert result.edges == 8 + 6

    def test_already_seen_successor_at_horizon_is_complete(self):
        """The depth bound only trips on a genuinely NEW state past the
        horizon; self-loops at the frontier must not mark the
        exploration incomplete."""
        result = explore(_counter_model(3, loop_at_end=True),
                         max_depth=4)
        assert result.complete

    def test_depth_budget_is_a_finding(self):
        result = explore(_counter_model(10), max_depth=3)
        assert not result.complete
        (v,) = [v for v in result.violations if v.kind == "budget"]
        assert "depth bound 3" in v.message
        model = _counter_model(10)
        diag = violation_diagnostic(model, v)
        assert diag.rule == "HVD703"
        assert "--depth" in diag.hint

    def test_state_budget_is_a_finding(self):
        result = explore(_counter_model(100), max_states=5)
        assert not result.complete
        assert any("state bound 5" in v.message
                   for v in result.violations)

    def test_wall_clock_budget_is_a_finding(self):
        result = explore(_counter_model(100), deadline_s=0.0)
        assert not result.complete
        assert any("wall clock" in v.message
                   for v in result.violations)

    def test_replay_follows_labels_and_rejects_disabled(self):
        model = _counter_model(3)
        states = replay(model, ["inc", "mark", "inc"])
        assert states[-1] == {"n": 2, "x": True}
        assert replay(model, ["inc", "zzz"]) is None
        # replay never mutates earlier states in the list
        assert states[0] == {"n": 0, "x": False}

    def test_minimize_strips_irrelevant_steps(self):
        model = _counter_model(3)
        steps = [Step("mark", "m", False, "<f>", 0),
                 Step("inc", "m", False, "<f>", 0),
                 Step("mark", "m", False, "<f>", 0),
                 Step("inc", "m", False, "<f>", 0)]
        slim = minimize(model, steps, lambda s: s["n"] >= 2)
        assert [s.label for s in slim] == ["inc", "inc"]

    def test_safety_counterexample_is_minimized(self):
        from horovod_tpu.analysis.protocol.model import Model
        model = _counter_model(5)
        model.invariants = [
            ("n_bounded",
             lambda s: "too big" if s["n"] >= 2 else None)]
        result = explore(model)
        (v,) = result.violations
        assert v.kind == "safety" and v.name == "n_bounded"
        assert labels_of(v) == ["inc", "inc"]   # no 'mark' noise
        assert not result.ok

    def test_liveness_judges_fair_edges_only(self):
        """goal is reachable from the wedge VIA A FAULT, which must not
        count: liveness asks whether the protocol gets there once the
        faults stop."""
        from horovod_tpu.analysis.protocol.model import Model

        def init():
            return {"at": "start"}

        def actions(state):
            acts = []
            if state["at"] == "start":
                def good(s):
                    s["at"] = "goal"
                    return s

                def to_b(s):
                    s["at"] = "b"
                    return s
                acts = [Action("good", "m", good),
                        Action("to_b", "m", to_b, fault=True)]
            elif state["at"] == "b":
                def fault_out(s):
                    s["at"] = "goal"
                    return s
                acts = [Action("fault_out", "m", fault_out,
                               fault=True)]
            return acts

        model = Model("wedge", init, actions,
                      liveness=[("reaches_goal",
                                 lambda s: s["at"] == "goal")])
        result = explore(model)
        assert result.complete
        (v,) = [v for v in result.violations if v.kind == "liveness"]
        assert labels_of(v) == ["to_b"]
        assert violation_diagnostic(model, v).rule == "HVD702"

    def test_keep_going_collects_multiple_violations(self):
        model = _counter_model(5)
        model.invariants = [
            ("n_bounded",
             lambda s: "too big" if s["n"] >= 3 else None)]
        first = explore(model, stop_on_first=True)
        every = explore(model, stop_on_first=False)
        assert len(first.violations) == 1
        assert len(every.violations) > 1


# ==========================================================================
# Shipped models: full bounded space, zero counterexamples
# ==========================================================================
class TestShippedModels:
    # Pinned space sizes: a silently-shrunk model (an action that
    # stopped being enabled, a fault that stopped firing) would pass
    # a bare ok-check while exploring nothing.
    EXPECTED_STATES = {"ha": 36, "lease": (28, 22), "migration": 202}

    @pytest.mark.parametrize("protocol", machines.PROTOCOLS)
    def test_full_exploration_clean(self, protocol):
        expected = self.EXPECTED_STATES[protocol]
        if not isinstance(expected, tuple):
            expected = (expected,)
        models = machines.build(protocol)
        assert len(models) == len(expected)
        for model, want in zip(models, expected):
            result = explore(model)
            assert result.ok, (
                model.name,
                [dataclasses.asdict(v) for v in result.violations])
            assert result.states == want, (
                f"{model.name}: bounded space changed "
                f"({result.states} states, expected {want}) — "
                "intentional model change? update the pin")

    def test_registry_is_exhaustive(self):
        assert set(machines.BUGS) == set(machines.PROTOCOLS)
        with pytest.raises(ValueError):
            machines.build("nope")
        with pytest.raises(ValueError):
            machines.build("ha", bug="not_a_bug")


# ==========================================================================
# Mutation proof: every seeded bug yields a minimized counterexample
# ==========================================================================
class TestMutationProof:
    def test_ha_skip_fence_is_split_brain(self):
        (model,) = machines.build("ha", bug="skip_fence")
        result = explore(model)
        (v,) = result.violations
        assert v.kind == "safety"
        assert v.name == "single_writer_per_term"
        # Minimized: crash the primary, promote the standby (term+1),
        # resurrect the stale primary, let it write unfenced. No
        # sync/extra writes survive minimization.
        assert labels_of(v) == ["p1:crash", "standby:promote",
                                "p1:restart", "p1:write"]

    def test_lease_actuate_before_ledger_in_both_directions(self):
        models = machines.build("lease", bug="actuate_before_ledger")
        firsts = ("preempting", "draining")
        for model, first in zip(models, firsts):
            result = explore(model)
            (v,) = result.violations
            assert v.kind == "safety"
            assert v.name == "effects_are_ledgered"
            # The very first actuation is already unledgered — the
            # crash isn't even needed to expose the window.
            assert labels_of(v) == ["arbiter:open",
                                    f"arbiter:actuate[{first}]"]

    def test_migration_double_import_needs_the_dup_fault(self):
        (model,) = machines.build("migration", bug="double_import")
        result = explore(model)
        (v,) = result.violations
        assert v.kind == "safety"
        assert v.name == "no_double_import"
        assert labels_of(v) == [
            "source:send", "source:send", "target:deliver[0]",
            "net:dup[1]", "target:deliver[1]", "target:deliver[1]"]
        # The counterexample genuinely requires the duplication fault.
        assert [s.label for s in v.trace if s.fault] == ["net:dup[1]"]

    def test_migration_skip_admit_trips_organically(self):
        (model,) = machines.build("migration", bug="skip_admit")
        result = explore(model)
        (v,) = result.violations
        assert v.kind == "safety"
        assert v.name == "watermark_respected"
        assert labels_of(v) == ["source:send", "source:send",
                                "target:deliver[0]",
                                "target:deliver[1]"]
        # No fault needed: the bug admits past the reserve on the
        # happy path.
        assert not any(s.fault for s in v.trace)


# ==========================================================================
# Rendering: HVD70x diagnostics through the hvd-lint machinery
# ==========================================================================
class TestRendering:
    def test_safety_diagnostic_anchors_at_the_spec(self):
        (model,) = machines.build("migration", bug="double_import")
        result = explore(model)
        (diag,) = result_diagnostics(model, result)
        assert diag.rule == "HVD701"
        assert "no_double_import" in diag.message
        # The location is the spec transition that lands in the bad
        # state, not the model harness.
        assert diag.file.endswith("migration_spec.py")
        assert diag.line > 0
        assert "hvd-model --protocol migration" in diag.hint

    def test_trace_renders_through_the_simulator(self):
        (model,) = machines.build("ha", bug="skip_fence")
        result = explore(model)
        (diag,) = result_diagnostics(model, result)
        text = render_trace(diag)
        assert "counterexample (cohort: ha)" in text
        assert "rank p1:" in text
        assert "rank standby:" in text
        assert "[fault]" in text        # crash/restart marked
        assert "standby:promote" in text

    def test_budget_diagnostic_has_no_trace(self):
        model = machines.build("migration")[0]
        result = explore(model, max_states=5)
        diags = result_diagnostics(model, result)
        assert [d.rule for d in diags] == ["HVD703"]
        assert diags[0].trace is None
        assert render_trace(diags[0]) == ""


# ==========================================================================
# CLI: exit codes, formats, SARIF structure
# ==========================================================================
class TestCli:
    def test_list(self, capsys):
        assert model_cli.main(["--list"]) == 0
        out = capsys.readouterr().out
        for proto in machines.PROTOCOLS:
            assert f"{proto}:" in out
        assert "skip_fence" in out and "double_import" in out

    def test_clean_sweep_exits_zero(self, capsys):
        assert model_cli.main(["--protocol", "all"]) == 0
        out = capsys.readouterr().out
        assert "0 finding(s) across 4 model(s)" in out
        assert out.count("complete") == 4
        assert "INCOMPLETE" not in out

    def test_seeded_bug_exits_one_with_counterexample(self, capsys):
        rc = model_cli.main(["--protocol", "migration",
                             "--seed-bug", "double_import"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "HVD701" in out
        assert "counterexample (cohort: migration)" in out
        assert "[seeded bug: double_import]" in out

    def test_seed_bug_requires_single_protocol(self, capsys):
        assert model_cli.main(["--seed-bug", "skip_fence"]) == 2
        assert "single --protocol" in capsys.readouterr().err

    def test_unknown_bug_is_usage_error(self, capsys):
        rc = model_cli.main(["--protocol", "ha", "--seed-bug", "zzz"])
        assert rc == 2
        assert "no seeded bug" in capsys.readouterr().err

    def test_budget_overrun_fails_at_default_severity(self, capsys):
        rc = model_cli.main(["--protocol", "migration",
                             "--max-states", "5"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "HVD703" in out and "INCOMPLETE" in out

    def test_fail_on_never_reports_but_exits_zero(self, capsys):
        rc = model_cli.main(["--protocol", "ha",
                             "--seed-bug", "skip_fence",
                             "--fail-on", "never"])
        assert rc == 0
        assert "HVD701" in capsys.readouterr().out

    def test_sarif_has_tool_name_and_code_flows(self, capsys):
        rc = model_cli.main(["--protocol", "lease",
                             "--seed-bug", "actuate_before_ledger",
                             "--format", "sarif"])
        assert rc == 1
        doc = json.loads(capsys.readouterr().out)
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "hvd-model"
        results = run["results"]
        assert len(results) == 2    # one per lease direction
        assert {r["ruleId"] for r in results} == {"HVD701"}
        for r in results:
            flows = r["codeFlows"][0]["threadFlows"]
            assert flows, "counterexample lost on the SARIF path"

    def test_json_format_round_trips(self, capsys):
        rc = model_cli.main(["--protocol", "ha",
                             "--seed-bug", "skip_fence",
                             "--format", "json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert [d["rule"] for d in payload] == ["HVD701"]
        assert "single_writer_per_term" in payload[0]["message"]
