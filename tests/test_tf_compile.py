"""graph→JAX compile path (horovod_tpu/tensorflow/compile.py): TF2 model
math on the accelerator. Oracle is TF itself — forward parity, then
training behavior (loss decrease, buffer updates, write-back).

Reference contract being replaced: the TF binding delivering accelerator
compute (horovod/tensorflow/mpi_ops.cc:486-493 kernel registration,
xla_mpi_ops.cc:174-232 XLA bridge); here the accelerator path is the
traced-to-JAX function."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import jax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.tensorflow.compile import tpu_compile  # noqa: E402


@pytest.fixture(scope="module", autouse=True)
def _init():
    hvd.init()
    yield


class _ConvNet(tf.Module):
    def __init__(self):
        tf.random.set_seed(0)
        init = tf.random.normal
        self.wc = tf.Variable(init([3, 3, 1, 8], stddev=0.1), name="wc")
        self.bc = tf.Variable(tf.zeros([8]), name="bc")
        self.w1 = tf.Variable(init([14 * 14 * 8, 32], stddev=0.05),
                              name="w1")
        self.b1 = tf.Variable(tf.zeros([32]), name="b1")
        self.w2 = tf.Variable(init([32, 10], stddev=0.05), name="w2")
        self.b2 = tf.Variable(tf.zeros([10]), name="b2")

    def loss(self, x, y):
        h = tf.nn.conv2d(x, self.wc, strides=1, padding="SAME") + self.bc
        h = tf.nn.relu(h)
        h = tf.nn.max_pool2d(h, 2, 2, padding="VALID")
        h = tf.reshape(h, [tf.shape(h)[0], -1])
        h = tf.nn.relu(tf.matmul(h, self.w1) + self.b1)
        logits = tf.matmul(h, self.w2) + self.b2
        return tf.reduce_mean(
            tf.nn.sparse_softmax_cross_entropy_with_logits(
                labels=y, logits=logits))


def _mnist_batch(batch=16, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(batch, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, size=(batch,)).astype(np.int64)
    return x, y


def test_convnet_forward_parity():
    m = _ConvNet()
    x, y = _mnist_batch()
    tf_loss = float(m.loss(tf.constant(x), tf.constant(y)))
    compiled = tpu_compile(m.loss, example_inputs=(x, y))
    jax_loss = float(compiled(x, y))
    assert abs(tf_loss - jax_loss) < 1e-4


def test_convnet_trains_and_writes_back():
    optax = pytest.importorskip("optax")
    m = _ConvNet()
    x, y = _mnist_batch()
    compiled = tpu_compile(m.loss, example_inputs=(x, y))
    step = compiled.make_train_step(optax.sgd(0.1))
    losses = [float(step((x, y))) for _ in range(8)]
    assert losses[-1] < losses[0], losses
    compiled.copy_params_to_variables()
    # TF-side eval sees the trained weights: its loss matches the jax
    # loss at the final parameters.
    tf_loss = float(m.loss(tf.constant(x), tf.constant(y)))
    jax_loss = float(compiled(x, y))
    assert abs(tf_loss - jax_loss) < 1e-3


def test_gradient_parity_with_tf():
    """d(loss)/d(vars) computed by JAX on the rebuilt graph matches
    tf.GradientTape on the original — the contract that makes
    make_train_step equivalent to TF-side training."""
    m = _ConvNet()
    x, y = _mnist_batch(8)
    with tf.GradientTape() as tape:
        loss = m.loss(tf.constant(x), tf.constant(y))
    tf_vars = [m.wc, m.bc, m.w1, m.b1, m.w2, m.b2]
    tf_grads = {v.name: g.numpy() for v, g in
                zip(tf_vars, tape.gradient(loss, tf_vars))}

    compiled = tpu_compile(m.loss, example_inputs=(x, y))

    def scalar_loss(params):
        out, _ = compiled.apply(params, [x, y])
        return out

    jax_grads = jax.grad(scalar_loss)(compiled.params)
    assert set(jax_grads) == set(tf_grads)
    for name, g in tf_grads.items():
        np.testing.assert_allclose(np.asarray(jax_grads[name]), g,
                                   rtol=1e-3, atol=1e-5)


_KERAS_MODEL_SCRIPT = r"""
import os, sys
os.environ["KERAS_BACKEND"] = "tensorflow"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import tensorflow as tf
import jax, optax
jax.config.update("jax_platforms", "cpu")  # axon self-selects otherwise
import horovod_tpu as hvd_core
from horovod_tpu.tensorflow.compile import tpu_compile
hvd_core.init()

# BN/dropout keras model: PartitionedCall recursion, FusedBatchNormV3
# buffer writes, PRNG-driven stateless dropout.
tf.random.set_seed(0)
model = tf.keras.Sequential([
    tf.keras.layers.Input((16,)),
    tf.keras.layers.Dense(32, activation="relu"),
    tf.keras.layers.BatchNormalization(),
    tf.keras.layers.Dropout(0.1),
    tf.keras.layers.Dense(10),
])
lossf = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)
def loss_fn(x, y):
    return lossf(y, model(x, training=True))
rng = np.random.RandomState(0)
x = rng.rand(32, 16).astype(np.float32)
y = rng.randint(0, 10, size=(32,)).astype(np.int64)
compiled = tpu_compile(loss_fn, example_inputs=(x, y))
step = compiled.make_train_step(optax.sgd(0.05))
mmk = next(k for k in compiled.buffers if "moving_mean" in k)
mm0 = np.array(compiled.buffers[mmk])
losses = [float(step((x, y), rng=jax.random.PRNGKey(i))) for i in range(8)]
assert losses[-1] < losses[0], losses
assert not np.allclose(mm0, np.array(compiled.buffers[mmk])), "BN stale"

# training=False parity: BN moving stats, dropout off — exact vs eager.
tf.random.set_seed(1)
model2 = tf.keras.Sequential([
    tf.keras.layers.Input((16,)),
    tf.keras.layers.Dense(32, activation="tanh"),
    tf.keras.layers.BatchNormalization(),
    tf.keras.layers.Dropout(0.5),
    tf.keras.layers.Dense(4),
])
def fwd(x):
    return model2(x, training=False)
x2 = np.random.RandomState(3).rand(8, 16).astype(np.float32)
compiled2 = tpu_compile(fwd, example_inputs=(x2,))
np.testing.assert_allclose(np.asarray(compiled2(x2)),
                           model2(tf.constant(x2)).numpy(),
                           rtol=1e-4, atol=1e-5)

# MHA transformer block (Einsum, Erfc-gelu, Softmax, BatchMatMul):
# forward parity + training descent.
tf.random.set_seed(0)
inp = tf.keras.Input((16, 32))
h = tf.keras.layers.MultiHeadAttention(num_heads=4, key_dim=8)(inp, inp)
h = tf.keras.layers.LayerNormalization()(h + inp)
f = tf.keras.layers.Dense(64, activation="gelu")(h)
f = tf.keras.layers.Dense(32)(f)
mha_model = tf.keras.Model(inp, tf.keras.layers.LayerNormalization()(h + f))
xm = np.random.RandomState(0).rand(2, 16, 32).astype(np.float32)
cm = tpu_compile(lambda x: mha_model(x, training=False),
                 example_inputs=(xm,))
np.testing.assert_allclose(np.asarray(cm(xm)),
                           mha_model(tf.constant(xm)).numpy(),
                           rtol=1e-4, atol=1e-5)
xt = np.random.RandomState(3).rand(8, 16, 32).astype(np.float32)
yt = np.random.RandomState(1).rand(8, 16, 32).astype(np.float32)
def mha_loss(x, y):
    return tf.reduce_mean(tf.square(mha_model(x, training=True) - y))
cmt = tpu_compile(mha_loss, example_inputs=(xt, yt))
ms = cmt.make_train_step(optax.adam(1e-3))
mlosses = [float(ms((xt, yt))) for _ in range(6)]
assert mlosses[-1] < mlosses[0], mlosses

# Recurrence (LSTM -> TensorList while loop) must fail LOUD, not
# silently mis-train.
tf.random.set_seed(1)
lstm = tf.keras.Sequential([
    tf.keras.layers.Input((12,), dtype="int32"),
    tf.keras.layers.Embedding(100, 16),
    tf.keras.layers.LSTM(8),
    tf.keras.layers.Dense(2)])
ids = np.random.RandomState(1).randint(0, 100, size=(2, 12)).astype(np.int32)
cl = tpu_compile(lambda x: lstm(x, training=False), example_inputs=(ids,))
try:
    cl(ids)
    raise SystemExit("LSTM did not fail loud")
except NotImplementedError:
    pass

print("KERAS-BRIDGE OK")
"""


def _run_bridge_subprocess(script_body, marker, **fmt):
    """Run a bridge scenario in its own interpreter. The keras backend
    binds at import (another module may have claimed jax), and
    JAX_PLATFORMS must be in the env BEFORE the interpreter starts —
    the axon sitecustomize reads it at startup and force-selects the
    real chip otherwise (an in-script setdefault is too late)."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, KERAS_BACKEND="tensorflow",
               JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", script_body.format(repo=repo, **fmt)],
        capture_output=True, text=True, timeout=600, env=env)
    assert out.returncode == 0, out.stderr[-4000:]
    assert marker in out.stdout


def test_keras_model_bridge_subprocess():
    """tf.keras models through the bridge: PartitionedCall recursion, BN
    buffer writes, PRNG dropout, inference parity, the MHA transformer
    block, and LSTM failing loud."""
    _run_bridge_subprocess(_KERAS_MODEL_SCRIPT, "KERAS-BRIDGE OK")


def test_image_resize_parity():
    def fwd(x):
        up = tf.image.resize(x, (8, 8), method="bilinear")
        return tf.image.resize(up, (2, 2), method="nearest")

    x = np.random.RandomState(2).rand(2, 4, 4, 3).astype(np.float32)
    compiled = tpu_compile(fwd, example_inputs=(x,))
    np.testing.assert_allclose(np.asarray(compiled(x)),
                               fwd(tf.constant(x)).numpy(),
                               rtol=1e-5, atol=1e-6)


_APPLICATIONS_SCRIPT = r"""
import os, sys
os.environ["KERAS_BACKEND"] = "tensorflow"
sys.path.insert(0, {repo!r})
import numpy as np
import tensorflow as tf
import jax
jax.config.update("jax_platforms", "cpu")
import horovod_tpu as hvd_core
from horovod_tpu.tensorflow.compile import tpu_compile
hvd_core.init()
tf.random.set_seed(0)
model = getattr(tf.keras.applications, {name!r})(
    weights=None, input_shape=(96, 96, 3), classes=10)
x = np.random.RandomState(0).rand(2, 96, 96, 3).astype(np.float32)
c = tpu_compile(lambda a: model(a, training=False), example_inputs=(x,))
d = float(np.abs(np.asarray(c(x)) - model(tf.constant(x)).numpy()).max())
assert d < 1e-4, d
print("APPLICATIONS OK", d)
"""


@pytest.mark.parametrize("name", ["MobileNetV2", "EfficientNetB0",
                                  "DenseNet121", "InceptionV3",
                                  "ConvNeXtTiny", "Xception",
                                  "MobileNetV3Small"])
def test_keras_applications_through_bridge(name):
    """The tf.keras.applications families the tf_on_tpu doc advertises:
    exact forward parity through the graph→JAX bridge (depthwise convs,
    swish/relu6, BN inference, skip connections, global pooling).
    Subprocess: keras backend binds per process."""
    _run_bridge_subprocess(_APPLICATIONS_SCRIPT, "APPLICATIONS OK",
                           name=name)


def test_embedding_and_einsum():
    """ResourceGather (embedding) + Einsum + LayerNorm-style math."""
    tf.random.set_seed(2)
    table = tf.Variable(tf.random.normal([64, 8]), name="emb")
    wq = tf.Variable(tf.random.normal([8, 8], stddev=0.3), name="wq")

    def fwd(ids):
        e = tf.nn.embedding_lookup(table, ids)
        q = tf.einsum("bsd,de->bse", e, wq)
        s = tf.nn.softmax(tf.matmul(q, e, transpose_b=True), axis=-1)
        return tf.reduce_mean(tf.matmul(s, e), axis=1)

    ids = np.random.RandomState(0).randint(0, 64, size=(4, 10))
    compiled = tpu_compile(fwd, example_inputs=(ids,))
    np.testing.assert_allclose(
        np.asarray(compiled(ids)),
        fwd(tf.constant(ids, tf.int32)).numpy(), rtol=1e-4, atol=1e-5)


def test_div_no_nan_gradient_finite():
    """divide_no_nan with a zero denominator must have finite gradients
    (the where-div pitfall): masked-mean losses hit this on all-masked
    batches."""
    w = tf.Variable(tf.ones([4]), name="w")

    def fwd(x, mask):
        s = tf.reduce_sum(x * w * mask)
        return tf.math.divide_no_nan(s, tf.reduce_sum(mask))

    x = np.ones(4, np.float32)
    mask = np.zeros(4, np.float32)  # fully masked: denominator 0
    compiled = tpu_compile(fwd, example_inputs=(x, mask))

    def loss(params):
        out, _ = compiled.apply(params, [x, mask])
        return out

    g = jax.grad(loss)(compiled.params)
    assert np.isfinite(np.asarray(g["w:0"])).all()


def test_unsupported_op_is_loud():
    def fwd(x):
        return tf.raw_ops.MatrixInverse(input=x)

    x = np.eye(3, dtype=np.float32)[None]
    compiled = tpu_compile(fwd, example_inputs=(x,))
    with pytest.raises(NotImplementedError, match="MatrixInverse"):
        compiled(x)


def test_int64_inputs_narrow():
    def fwd(ids):
        return tf.cast(ids, tf.float32) * 2.0

    ids = np.arange(6, dtype=np.int64)
    compiled = tpu_compile(fwd, example_inputs=(ids,))
    np.testing.assert_allclose(np.asarray(compiled(ids)),
                               (ids * 2).astype(np.float32))


def _attention_module(causal, heads=4, key_dim=16, d_model=64,
                      out_dim=8):
    """The exact op pattern keras-3 MultiHeadAttention emits (einsum
    projections, scalar Mul scale, SelectV2 masked softmax, combine
    einsum) hand-rolled with raw TF ops. keras itself binds to whichever
    backend the test SESSION imported first (process-global), so
    building a tf.keras layer here is not order-safe — the bridge's
    pattern matcher sees the identical graph either way (it is
    label-generic, verified standalone against real keras MHA)."""
    tf.random.set_seed(0)

    class MHA(tf.Module):
        def __init__(self):
            init = tf.random.normal
            self.wq = tf.Variable(init([d_model, heads, key_dim],
                                       stddev=0.05), name="wq")
            self.wk = tf.Variable(init([d_model, heads, key_dim],
                                       stddev=0.05), name="wk")
            self.wv = tf.Variable(init([d_model, heads, key_dim],
                                       stddev=0.05), name="wv")
            self.wo = tf.Variable(init([heads, key_dim, out_dim],
                                       stddev=0.05), name="wo")

        def __call__(self, x):
            q = tf.einsum("bsc,cnh->bsnh", x, self.wq)
            k = tf.einsum("bsc,cnh->bsnh", x, self.wk)
            v = tf.einsum("bsc,cnh->bsnh", x, self.wv)
            s = tf.einsum("bqnh,bknh->bnqk", q, k)
            s = s * (1.0 / float(key_dim) ** 0.5)
            if causal:
                n = tf.shape(x)[1]
                rows = tf.range(n)
                keep = rows[:, None] >= rows[None, :]
                cond = tf.logical_and(tf.ones_like(s, tf.bool),
                                      keep[None, None])
                s = tf.where(cond, s, tf.constant(-1e9))
            p = tf.nn.softmax(s)
            out = tf.einsum("bnqk,bknh->bqnh", p, v)
            return tf.einsum("bqnh,nho->bqo", out, self.wo)

    return MHA()


@pytest.mark.parametrize("use_causal_mask", [False, True])
def test_attention_pattern_flash_routing_parity(monkeypatch,
                                                use_causal_mask):
    """The Einsum→[scale]→[mask]→Softmax→Einsum pattern lowers to the
    Pallas flash kernel (the SelectV2 causal mask is recognized as such
    after shape-derived const folding) with einsum-path parity."""
    model = _attention_module(use_causal_mask)
    x = np.random.RandomState(0).normal(size=(2, 32, 64)).astype(
        np.float32)

    monkeypatch.setenv("HVDTPU_BRIDGE_FLASH", "never")
    ref = np.asarray(tpu_compile(model, example_inputs=(
        tf.constant(x),))(x))

    from horovod_tpu.ops import flash_attention as fa_mod
    hits = []
    orig = fa_mod.flash_attention

    def spy(*args, **kwargs):
        hits.append(kwargs.get("causal"))
        return orig(*args, **kwargs)

    monkeypatch.setattr(fa_mod, "flash_attention", spy)
    monkeypatch.setenv("HVDTPU_BRIDGE_FLASH", "always")
    out = np.asarray(tpu_compile(model, example_inputs=(
        tf.constant(x),))(x))
    assert hits == [use_causal_mask]
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_attention_pattern_flash_training_gradients(monkeypatch):
    """Training through the flash-routed attention still converges (the
    kernel's custom VJP feeds the projection weights)."""
    optax = pytest.importorskip("optax")
    model = _attention_module(False)
    x = np.random.RandomState(1).normal(size=(8, 32, 64)).astype(
        np.float32)
    y = np.random.RandomState(2).normal(size=(8, 32, 8)).astype(
        np.float32)

    def loss_fn(a, t):
        pred = model(a)
        return tf.reduce_mean(tf.square(pred - t))

    monkeypatch.setenv("HVDTPU_BRIDGE_FLASH", "always")
    compiled = tpu_compile(loss_fn,
                           example_inputs=(tf.constant(x), tf.constant(y)))
    step = compiled.make_train_step(optax.adam(1e-2))
    losses = [float(step((x, y))) for _ in range(5)]
    assert np.all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_attention_pattern_flash_fallback_on_padding_mask(monkeypatch):
    """A data-dependent key-padding mask cannot const-fold: the pattern
    must fall back to the einsum lowering and stay correct."""
    base = _attention_module(False)

    def masked_model(x, mask):
        q = tf.einsum("bsc,cnh->bsnh", x, base.wq)
        k = tf.einsum("bsc,cnh->bsnh", x, base.wk)
        v = tf.einsum("bsc,cnh->bsnh", x, base.wv)
        s = tf.einsum("bqnh,bknh->bnqk", q, k) * 0.25
        cond = tf.logical_and(tf.ones_like(s, tf.bool),
                              mask[:, None, None, :])
        s = tf.where(cond, s, tf.constant(-1e9))
        p = tf.nn.softmax(s)
        out = tf.einsum("bnqk,bknh->bqnh", p, v)
        return tf.einsum("bqnh,nho->bqo", out, base.wo)

    x = np.random.RandomState(0).normal(size=(2, 32, 64)).astype(
        np.float32)
    mask = np.ones((2, 32), bool)
    mask[:, -7:] = False

    monkeypatch.setenv("HVDTPU_BRIDGE_FLASH", "never")
    ref = np.asarray(tpu_compile(masked_model, example_inputs=(
        tf.constant(x), tf.constant(mask)))(x, mask))

    from horovod_tpu.ops import flash_attention as fa_mod
    hits = []
    orig = fa_mod.flash_attention

    def spy(*args, **kwargs):
        hits.append(1)
        return orig(*args, **kwargs)

    monkeypatch.setattr(fa_mod, "flash_attention", spy)
    monkeypatch.setenv("HVDTPU_BRIDGE_FLASH", "always")
    out = np.asarray(tpu_compile(masked_model, example_inputs=(
        tf.constant(x), tf.constant(mask)))(x, mask))
    assert not hits, "padding mask must not route to the flash kernel"
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_compute_dtype_bf16_parity_and_training():
    """compute_dtype=bf16 (the torch bridge's XLA_USE_BF16 analog on
    the TF side): master weights stay fp32, forward parity holds at
    bf16 tolerance, and training still converges."""
    import jax.numpy as jnp
    optax = pytest.importorskip("optax")
    m = _ConvNet()
    x, y = _mnist_batch()
    c32 = tpu_compile(m.loss, example_inputs=(x, y))
    c16 = tpu_compile(m.loss, example_inputs=(x, y),
                      compute_dtype=jnp.bfloat16)
    l32 = float(np.asarray(c32(x, y)))
    l16 = float(np.asarray(c16(x, y)))
    assert abs(l32 - l16) / max(abs(l32), 1e-6) < 0.05
    # params stay fp32 masters
    assert all(np.asarray(v).dtype == np.float32
               for v in c16.params.values())
    step = c16.make_train_step(optax.sgd(0.05))
    losses = [float(step((x, y))) for _ in range(6)]
    assert np.all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_real_keras_mha_flash_routing_subprocess():
    """The REAL tf.keras MultiHeadAttention graph routes to the flash
    kernel — run in a fresh interpreter because keras binds its backend
    at first import (this test session may already hold the jax
    backend), mirroring the bench.py isolation. Guards against a keras
    upgrade changing the emitted attention pattern without the
    hand-rolled replica tests noticing."""
    import subprocess
    from conftest import clean_spawn_env

    script = r"""
import os, sys
sys.path.insert(0, os.environ["HVDTPU_REPO"])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import tensorflow as tf
import horovod_tpu.tensorflow as hvd
from horovod_tpu.tensorflow.compile import tpu_compile
hvd.init()
tf.keras.utils.set_random_seed(0)
inp = tf.keras.Input((32, 64))
h = tf.keras.layers.MultiHeadAttention(num_heads=4, key_dim=16)(
    inp, inp, use_causal_mask=True)
model = tf.keras.Model(inp, h)
x = np.random.RandomState(0).normal(size=(2, 32, 64)).astype(np.float32)
def f(a):
    return model(a, training=False)
os.environ["HVDTPU_BRIDGE_FLASH"] = "never"
ref = np.asarray(tpu_compile(f, example_inputs=(tf.constant(x),))(x))
from horovod_tpu.ops import flash_attention as fa
hits = []
orig = fa.flash_attention
def spy(*a, **kw):
    hits.append(kw.get("causal")); return orig(*a, **kw)
fa.flash_attention = spy
os.environ["HVDTPU_BRIDGE_FLASH"] = "always"
out = np.asarray(tpu_compile(f, example_inputs=(tf.constant(x),))(x))
assert hits == [True], hits
np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)
print("MHA-FLASH OK")
"""
    import os
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = clean_spawn_env(HVDTPU_REPO=repo)
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, timeout=600)
    out = proc.stdout.decode() + proc.stderr.decode()
    assert proc.returncode == 0, out[-4000:]
    assert "MHA-FLASH OK" in out
