"""hvd-sanitize: the runtime concurrency sanitizer (lock-order graph,
blocking-call tripwire, thread-leak audit, NULL disabled mode), the
HVD301–305 static rules over the fixture corpus, the knob registry
cross-check (HVD306), and the `hvd-lint --self` self-analysis sweep
that pins horovod_tpu/ itself clean.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from horovod_tpu.analysis import ast_lint, sanitizer
from horovod_tpu.exceptions import LockOrderError
from horovod_tpu.utils import envparse

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "horovod_tpu")
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
KNOB_DOCS = os.path.join(REPO, "docs", "knobs.md")


def rules_of(diags):
    return sorted(d.rule for d in diags)


@pytest.fixture
def sanitize_on(monkeypatch):
    monkeypatch.setenv("HVDTPU_SANITIZE", "1")
    sanitizer.reset()
    yield sanitizer
    monkeypatch.delenv("HVDTPU_SANITIZE")
    sanitizer.reset()   # restores time.sleep and drops graph state


@pytest.fixture
def sanitize_off(monkeypatch):
    monkeypatch.delenv("HVDTPU_SANITIZE", raising=False)
    monkeypatch.delenv("HOROVOD_TPU_SANITIZE", raising=False)
    sanitizer.reset()
    yield sanitizer
    sanitizer.reset()


# ==========================================================================
# Runtime layer: lock-order graph
# ==========================================================================
class TestLockOrder:
    def test_abba_cycle_names_both_stacks(self, sanitize_on):
        """Acceptance: a deterministic two-thread ABBA fixture. Thread 1
        nests A->B (recording the order); thread 2 then nests B->A and
        must get LockOrderError BEFORE blocking, with both acquisition
        stacks in the message."""
        A = sanitizer.make_lock("fixture.A")
        B = sanitizer.make_lock("fixture.B")
        recorded = threading.Event()

        def first():
            with A:
                with B:
                    pass
            recorded.set()

        caught = []

        def second():
            recorded.wait(5)
            try:
                with B:
                    with A:
                        pass
            except LockOrderError as exc:
                caught.append(exc)

        t1 = threading.Thread(target=first, name="abba-t1")
        t2 = threading.Thread(target=second, name="abba-t2")
        t1.start()
        t1.join(5)
        t2.start()
        t2.join(5)
        assert caught, "LockOrderError did not fire on the ABBA cycle"
        msg = str(caught[0])
        assert "'fixture.A'" in msg and "'fixture.B'" in msg
        # Both stacks, each attributed to its thread.
        assert "current acquisition (thread 'abba-t2')" in msg
        assert "first recorded 'fixture.B' -> 'fixture.A'" in msg \
            or "first recorded 'fixture.A' -> 'fixture.B'" in msg
        assert "thread 'abba-t1'" in msg
        assert msg.count("in first") >= 1 and msg.count("in second") >= 1

    def test_correct_order_still_works_after_abba_error(self,
                                                        sanitize_on):
        """The offending reverse edge must NOT be recorded when the
        cycle raises — otherwise the graph is poisoned and the
        LEGITIMATE order raises forever after the first offender."""
        A = sanitizer.make_lock("poison.A")
        B = sanitizer.make_lock("poison.B")
        with A:
            with B:
                pass
        with pytest.raises(LockOrderError):
            with B:
                with A:
                    pass
        with A:       # the legitimate order keeps working
            with B:
                pass

    def test_consistent_order_is_quiet(self, sanitize_on):
        A = sanitizer.make_lock("ord.A")
        B = sanitizer.make_lock("ord.B")
        for _ in range(3):
            with A:
                with B:
                    pass
        assert not [f for f in sanitizer.findings()]

    def test_reentrant_rlock_is_not_a_cycle(self, sanitize_on):
        R = sanitizer.make_rlock("reent.R")
        with R:
            with R:
                pass  # same object: reentrancy, not ordering

    def test_same_named_sibling_locks_flagged(self, sanitize_on):
        """Two instances of one lock class nesting under each other have
        no instance order — flagged like a cycle."""
        l1 = sanitizer.make_lock("pool.slot")
        l2 = sanitizer.make_lock("pool.slot")
        with pytest.raises(LockOrderError):
            with l1:
                with l2:
                    pass

    def test_nonblocking_try_acquire_is_exempt_and_clean(self,
                                                         sanitize_on):
        """acquire(blocking=False) is the deadlock-AVOIDANCE pattern:
        no order check (a reverse-order try is legitimate) and no edge
        recorded (a failed try must not poison the graph)."""
        A = sanitizer.make_lock("try.A")
        B = sanitizer.make_lock("try.B")
        with A:
            with B:
                pass
        with B:
            assert A.acquire(blocking=False)  # reverse order: no raise
            A.release()
        with A:       # and the legitimate order is unpoisoned
            with B:
                pass

    def test_condition_wraps_tracked_rlock(self, sanitize_on):
        cond = sanitizer.make_condition("cv.test")
        with cond:
            cond.notify_all()
        # wait() exercises _release_save/_acquire_restore delegation
        with cond:
            assert cond.wait(timeout=0.01) is False

    def test_transitive_cycle_through_intermediate(self, sanitize_on):
        """A->B and B->C recorded separately; C->A closes the cycle
        only through reachability (no direct A->C edge exists), and the
        report still names a recorded edge on the offending path."""
        A = sanitizer.make_lock("tri.A")
        B = sanitizer.make_lock("tri.B")
        C = sanitizer.make_lock("tri.C")
        with A:
            with B:
                pass
        with B:
            with C:
                pass
        with pytest.raises(LockOrderError) as err:
            with C:
                with A:
                    pass
        msg = str(err.value)
        assert "acquiring 'tri.A' while holding 'tri.C'" in msg
        assert "first recorded 'tri.A' -> 'tri.B'" in msg
        # And neither recorded order was poisoned by the offender.
        with A:
            with B:
                pass
        with B:
            with C:
                pass


# ==========================================================================
# Runtime layer: blocking-call tripwire + thread-leak audit
# ==========================================================================
class TestTripwire:
    def _run_on_fake_cycle_thread(self, fn):
        def body():
            sanitizer.mark_critical("fake-cycle")
            try:
                fn()
            finally:
                sanitizer.unmark_critical()
        t = threading.Thread(target=body, name="fake-cycle")
        t.start()
        t.join(10)

    def test_flags_sleep_and_wait_on_critical_thread(self, sanitize_on):
        def body():
            time.sleep(sanitizer.SLEEP_ALLOWANCE_S + 0.05)
            sanitizer.check_blocking("Handle.wait", "grad.0")
        self._run_on_fake_cycle_thread(body)
        whats = [f.what for f in sanitizer.findings()]
        assert any(w.startswith("time.sleep") for w in whats), whats
        assert any("Handle.wait" in w for w in whats), whats
        assert all("fake-cycle" in f.thread
                   for f in sanitizer.findings())

    def test_pacing_sleep_and_allowed_scope_are_exempt(self, sanitize_on):
        def body():
            time.sleep(0.001)  # cycle pacing: under the allowance
            with sanitizer.allowed("bounded board I/O"):
                sanitizer.check_blocking("urlopen", "http://kv/x")
        self._run_on_fake_cycle_thread(body)
        assert sanitizer.findings() == []

    def test_critical_mark_is_released_on_thread_exit(self,
                                                      sanitize_on):
        """Loop bodies unmark in a finally: thread idents are recycled,
        so a stale entry would smear 'critical' onto a later unrelated
        thread (elastic stop/start cycles)."""
        self._run_on_fake_cycle_thread(lambda: None)
        state = sanitizer._state()
        assert state._critical == {}, state._critical

    def test_non_critical_thread_is_exempt(self, sanitize_on):
        sanitizer.check_blocking("urlopen", "http://kv/y")
        time.sleep(0.001)
        assert sanitizer.findings() == []

    def test_handle_wait_tripwire_is_wired(self, sanitize_on):
        """coordinator.Handle.wait goes through check_blocking."""
        from horovod_tpu.coordinator import Handle

        def body():
            h = Handle("tripwire.op")
            h._complete(42)
            assert h.wait(timeout=1) == 42
        self._run_on_fake_cycle_thread(body)
        assert any("Handle.wait" in f.what
                   for f in sanitizer.findings())

    def test_thread_leak_audit_names_non_daemon_threads(self,
                                                        sanitize_on):
        release = threading.Event()
        leak = threading.Thread(target=release.wait, name="leaky-worker",
                                daemon=False)
        leak.start()
        try:
            leaks = sanitizer.audit_shutdown()
            assert "leaky-worker" in leaks
            assert any(f.kind == "thread-leak"
                       and "leaky-worker" in f.what
                       for f in sanitizer.findings())
        finally:
            release.set()
            leak.join(5)

    def test_findings_dedupe_per_call_site(self, sanitize_on):
        """A blocking call inside a ms-cadence loop must yield ONE
        finding, not one multi-KB stack per cycle for hours."""
        def body():
            for _ in range(5):
                sanitizer.check_blocking("urlopen", "http://kv/x")
        self._run_on_fake_cycle_thread(body)
        assert len(sanitizer.findings()) == 1

    def test_finding_format_names_kind_call_and_thread(self, sanitize_on):
        def body():
            sanitizer.check_blocking("Handle.wait", "grad.7")
        self._run_on_fake_cycle_thread(body)
        (finding,) = sanitizer.findings()
        text = finding.format()
        assert "hvd-sanitize [blocking-call]" in text
        assert "Handle.wait(grad.7)" in text
        assert "fake-cycle" in text
        assert finding.stack  # the acquisition stack rode along

    def test_allowed_scopes_nest(self, sanitize_on):
        """allowed() is depth-counted: leaving an inner scope must not
        re-arm the tripwire while the outer scope is still open."""
        def body():
            with sanitizer.allowed("outer"):
                with sanitizer.allowed("inner"):
                    sanitizer.check_blocking("urlopen", "http://kv/a")
                sanitizer.check_blocking("urlopen", "http://kv/b")
            sanitizer.check_blocking("urlopen", "http://kv/c")
        self._run_on_fake_cycle_thread(body)
        assert [f.what for f in sanitizer.findings()] == \
            ["urlopen(http://kv/c)"]

    def test_daemon_threads_pass_the_audit(self, sanitize_on):
        release = threading.Event()
        t = threading.Thread(target=release.wait, name="daemon-ok",
                             daemon=True)
        t.start()
        try:
            assert "daemon-ok" not in sanitizer.audit_shutdown()
        finally:
            release.set()
            t.join(5)


# ==========================================================================
# Disabled mode: the NULL guard (zero instrumentation)
# ==========================================================================
class TestDisabledGuard:
    def test_factories_return_plain_primitives(self, sanitize_off):
        assert not sanitizer.enabled()
        plain_lock_t = type(threading.Lock())
        plain_rlock_t = type(threading.RLock())
        assert type(sanitizer.make_lock("x")) is plain_lock_t
        assert type(sanitizer.make_rlock("x")) is plain_rlock_t
        assert type(sanitizer.make_condition("x")) is threading.Condition

    def test_time_sleep_is_unpatched(self, sanitize_off):
        assert not getattr(time.sleep, "__hvd_sanitize__", False)

    def test_guards_are_noops_and_nothing_accumulates(self, sanitize_off):
        sanitizer.mark_critical("anything")
        sanitizer.check_blocking("urlopen", "http://x")
        time.sleep(0.001)
        sanitizer.unmark_critical()
        assert sanitizer.audit_shutdown() == []
        assert sanitizer.findings() == []

    def test_enable_then_disable_restores_sleep(self, monkeypatch):
        monkeypatch.setenv("HVDTPU_SANITIZE", "1")
        sanitizer.reset()
        assert sanitizer.enabled()
        assert getattr(time.sleep, "__hvd_sanitize__", False)
        monkeypatch.delenv("HVDTPU_SANITIZE")
        sanitizer.reset()
        assert not sanitizer.enabled()
        assert not getattr(time.sleep, "__hvd_sanitize__", False)


# ==========================================================================
# Static layer: HVD301–305 fixture corpus
# ==========================================================================
class TestConcurrencyRules:
    def lint(self, name):
        return ast_lint.lint_file(os.path.join(FIXTURES, name))

    def test_shared_attr_fixture(self):
        diags = self.lint("bad_thread_shared_attr.py")
        assert rules_of(diags) == ["HVD301"]
        assert "self.count" in diags[0].message

    def test_bare_acquire_fixture(self):
        diags = self.lint("bad_bare_acquire.py")
        assert rules_of(diags) == ["HVD302"]
        # the try/finally variant in the same file is NOT flagged
        assert diags[0].line < 15

    def test_blocking_loop_fixture(self):
        diags = self.lint("bad_blocking_loop.py")
        assert rules_of(diags) == ["HVD303", "HVD303"]
        msgs = " ".join(d.message for d in diags)
        assert "urlopen" in msgs and "wait" in msgs

    def test_raw_env_fixture(self):
        diags = self.lint("bad_raw_env.py")
        assert rules_of(diags) == ["HVD304", "HVD304"]

    def test_undaemoned_thread_fixture(self):
        assert rules_of(self.lint("bad_undaemoned_thread.py")) == \
            ["HVD305", "HVD305"]

    def test_clean_threading_fixture(self):
        assert self.lint("good_threading.py") == []

    def test_suppression_applies_to_hvd3xx(self):
        src = ("import os\n"
               "x = os.environ.get('HVDTPU_FOO')"
               "  # hvd-lint: disable=HVD304\n")
        assert ast_lint.lint_source(src) == []

    def test_locked_writes_are_clean(self):
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self.n = 0\n"
               "        self._t = threading.Thread(target=self._loop,\n"
               "                                   daemon=True)\n"
               "    def _loop(self):\n"
               "        with self._lock:\n"
               "            self.n += 1\n"
               "    def bump(self):\n"
               "        with self._lock:\n"
               "            self.n = 0\n")
        assert ast_lint.lint_source(src) == []

    def test_bounded_calls_in_loops_are_clean(self):
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._stop = threading.Event()\n"
               "        self._t = threading.Thread(\n"
               "            target=self._loop, name='x-heartbeat',\n"
               "            daemon=True)\n"
               "    def _loop(self):\n"
               "        while not self._stop.wait(timeout=1.0):\n"
               "            self._stop.wait(0.1)\n")
        assert ast_lint.lint_source(src) == []


# ==========================================================================
# Knob registry <-> docs cross-check (HVD306)
# ==========================================================================
class TestKnobRegistry:
    def test_registry_matches_docs(self):
        diags = ast_lint.check_knob_docs(KNOB_DOCS)
        assert diags == [], "\n".join(d.format() for d in diags)

    def test_detects_drift_both_ways(self, tmp_path, monkeypatch):
        doc = tmp_path / "knobs.md"
        rows = [f"| `HVDTPU_{name}` | {meta['default'] or '—'} | x |"
                for name, meta in sorted(envparse.KNOBS.items())
                if name != "SANITIZE"]
        rows.append("| `HVDTPU_IMAGINARY_KNOB` | x | x |")
        doc.write_text("\n".join(rows) + "\n")
        diags = ast_lint.check_knob_docs(str(doc))
        msgs = " ".join(d.message for d in diags)
        assert rules_of(diags) == ["HVD306", "HVD306"]
        assert "SANITIZE" in msgs            # registered, undocumented
        assert "IMAGINARY_KNOB" in msgs      # documented, unregistered

    def test_detects_default_mismatch(self, tmp_path):
        """The registered default is CHECKED data: a docs row whose
        default cell disagrees with register() is HVD306."""
        doc = tmp_path / "knobs.md"
        rows = []
        for name, meta in sorted(envparse.KNOBS.items()):
            default = ("999999" if name == "KV_RETRIES"
                       else meta["default"] or "—")
            rows.append(f"| `HVDTPU_{name}` | {default} | x |")
        doc.write_text("\n".join(rows) + "\n")
        diags = ast_lint.check_knob_docs(str(doc))
        assert rules_of(diags) == ["HVD306"]
        assert "KV_RETRIES" in diags[0].message
        assert "999999" in diags[0].message

    def test_default_normalization_accepts_equivalents(self):
        from horovod_tpu.analysis.ast_lint import _norm_default
        assert _norm_default("0 (off)") == _norm_default("0")
        assert _norm_default("—") == _norm_default("")

    def test_previously_raw_knobs_are_registered(self):
        for name in ("SANITIZE", "ELASTIC_CHECK_INTERVAL",
                     "START_TIMEOUT", "BRIDGE_FLASH", "FLASH_DROPOUT",
                     "FLASH_DROPOUT_MASK_LIMIT"):
            assert name in envparse.KNOBS, name
            assert envparse.KNOBS[name]["doc"]

    def test_registered_knobs_resolve_through_prefixes(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_TPU_ELASTIC_CHECK_INTERVAL", "3.5")
        assert envparse.get_float(envparse.ELASTIC_CHECK_INTERVAL,
                                  0.2) == 3.5


# ==========================================================================
# Self-analysis: horovod_tpu/ must hold to its own rules (tier-1)
# ==========================================================================
def test_self_sweep_clean():
    """Acceptance: every rule over the whole package + the knob-docs
    cross-check, zero findings."""
    diags = ast_lint.lint_paths([PKG]) + ast_lint.check_knob_docs(
        KNOB_DOCS)
    assert diags == [], "\n".join(d.format() for d in diags)


def test_coordinator_restart_runs_exactly_one_cycle_thread():
    """stop() then start() must drain the old cycle thread before
    spawning the new one — a revived old loop would double-dispatch."""
    import types

    from horovod_tpu.coordinator import Coordinator
    runtime = types.SimpleNamespace(
        topology=types.SimpleNamespace(rank=0, size=1),
        mode="single", backend=None, timeline=None, autotuner=None)
    coord = Coordinator(runtime)
    coord.start()
    first = coord._thread
    coord.stop()
    coord.start()
    try:
        # The old thread was drained BEFORE the new one spawned (other
        # coordinators may live in this process, so assert on THIS
        # coordinator's threads, not the global enumeration).
        assert coord._thread is not first
        assert not first.is_alive()
        assert coord._thread.is_alive()
    finally:
        coord.stop()


def _run_cli(*args):
    from conftest import clean_spawn_env
    env = clean_spawn_env(
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.cli", *args],
        env=env, capture_output=True, text=True, timeout=120)


def test_cli_self_flag_runs_clean():
    proc = _run_cli("--self")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_cli_check_knobs_only():
    proc = _run_cli("--check-knobs")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_knobs_md_implies_check(tmp_path):
    """--knobs-md PATH without --check-knobs must still read the file
    (a named file the user expects to be validated), and an unreadable
    explicit path is a finding, not a silent green."""
    proc = _run_cli("--knobs-md", str(tmp_path / "missing.md"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "cannot read knob docs" in proc.stdout


def test_cli_check_metrics_only():
    proc = _run_cli("--check-metrics")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_metrics_md_implies_check(tmp_path):
    """--metrics-md PATH without --check-metrics must still validate
    the named file; an unreadable explicit path is a finding."""
    proc = _run_cli("--metrics-md", str(tmp_path / "missing.md"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "cannot read metric docs" in proc.stdout


def test_cli_detects_hvd3xx_in_fixtures():
    proc = _run_cli(FIXTURES, "--format", "json", "--fail-on", "warning")
    assert proc.returncode == 1
    import json as _json
    found = {d["rule"] for d in _json.loads(proc.stdout)}
    assert {"HVD301", "HVD302", "HVD303", "HVD304",
            "HVD305"} <= found, found


def test_list_rules_includes_hvd3xx():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule in ("HVD301", "HVD302", "HVD303", "HVD304", "HVD305",
                 "HVD306"):
        assert rule in proc.stdout
