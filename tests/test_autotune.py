"""Autotune tests: unit-level knob sweep on a fake runtime + a whole-job
SPMD run observing convergence and cross-rank winner agreement
(VERDICT round-1 item 8)."""

import os
import types

import pytest

from test_spmd import launch

HERE = os.path.dirname(os.path.abspath(__file__))


class _FakeCore:
    def __init__(self):
        self.thresholds = []

    def set_fusion_threshold(self, v):
        self.thresholds.append(v)


def _fake_runtime():
    from horovod_tpu import basics
    coord = types.SimpleNamespace(bytes_processed=0, fusion_threshold=0,
                                  cycle_time_s=0.001)
    backend = types.SimpleNamespace(core=_FakeCore())
    rt = types.SimpleNamespace(mode=basics.MODE_SINGLE, coordinator=coord,
                               backend=backend, topology=None)
    return rt


def _drive(pm, rt, rates, max_cycles=2000):
    """Feed synthetic per-candidate byte rates until convergence; the
    currently applied candidate's rate drives the score."""
    observed = []
    for _ in range(max_cycles):
        cand = None
        if pm._pos >= 0:
            cand = pm._active[pm._pos]
        rt.coordinator.bytes_processed += rates.get(cand, 5)
        pm.record_cycle()
        observed.append((rt.coordinator.fusion_threshold,
                         rt.coordinator.cycle_time_s))
        if not pm.enabled:
            return observed
    raise AssertionError("did not converge")


def test_parameter_manager_halving_and_convergence(monkeypatch, tmp_path):
    monkeypatch.setenv("HVDTPU_AUTOTUNE_FUSION_CANDIDATES_MIB", "1,2")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLE_CANDIDATES_MS", "0.5,1.0")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_WARMUP_CYCLES", "2")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLES_PER_CANDIDATE", "8")
    log = tmp_path / "tune.log"
    monkeypatch.setenv("HVDTPU_AUTOTUNE_LOG", str(log))

    from horovod_tpu.autotune import ParameterManager
    rt = _fake_runtime()
    pm = ParameterManager(rt)
    assert len(pm._grid) == 4          # 2 fusion x 2 cycle x 1 bucket
    # 4 candidates -> 2 halving rounds; first-round budget 8 >> 1 = 4.
    assert pm._budget == 4

    # Candidate 2 (fusion=2MiB cycle=0.5ms) is the clear winner.
    observed = _drive(pm, rt, rates={0: 10, 1: 20, 2: 99, 3: 30})

    assert not pm.enabled, "did not converge"
    assert pm.best == (2 * 1024 * 1024, 0.5, None)
    # The sweep walked multiple candidates before converging.
    assert len(set(observed)) >= 3, set(observed)
    # Winner pushed into the native controller.
    assert rt.backend.core.thresholds[-1] == 2 * 1024 * 1024
    # Log has both rounds' scores with the starred winner; the loser half
    # appears only in round 0 (the successive-halving shape).
    content = log.read_text()
    assert "*" in content
    assert content.count("r0,") == 4
    assert content.count("r1,") == 2


def test_parameter_manager_tunes_delegated_bucket(monkeypatch):
    """With a delegated backend, the bucket knob joins the space and a
    small-tensor flood picks a non-default winner that is pushed to the
    backend (VERDICT r2 item 6)."""
    monkeypatch.setenv("HVDTPU_AUTOTUNE_FUSION_CANDIDATES_MIB", "1")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLE_CANDIDATES_MS", "0.5")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_BUCKET_CANDIDATES", "256,65536")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_WARMUP_CYCLES", "1")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLES_PER_CANDIDATE", "4")

    from horovod_tpu.autotune import ParameterManager
    rt = _fake_runtime()
    buckets = []
    rt.backend.set_min_bucket = buckets.append
    pm = ParameterManager(rt)
    assert len(pm._grid) == 2

    # The big-bucket candidate (index 1) wins the synthetic flood: fewer,
    # fuller launches -> higher bytes/sec.
    _drive(pm, rt, rates={0: 10, 1: 80})
    assert pm.best == (1024 * 1024, 0.5, 65536)
    assert buckets[-1] == 65536


def test_autotune_spmd_convergence():
    pytest.importorskip("jax")
    extra = {
        "HVDTPU_AUTOTUNE": "1",
        "HVDTPU_AUTOTUNE_FUSION_CANDIDATES_MIB": "1,4",
        "HVDTPU_AUTOTUNE_CYCLE_CANDIDATES_MS": "0.2,1.0",
        "HVDTPU_AUTOTUNE_WARMUP_CYCLES": "3",
        "HVDTPU_AUTOTUNE_CYCLES_PER_CANDIDATE": "4",
    }
    codes, outs = launch(2, script=os.path.join(HERE, "autotune_worker.py"),
                         extra_env=extra, timeout=300)
    for rank, (code, out) in enumerate(zip(codes, outs)):
        assert code == 0, f"rank {rank} failed (exit {code}):\n{out[-4000:]}"
        assert "AUTOTUNE OK" in out
