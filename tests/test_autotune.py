"""Autotune tests (ISSUE 12; docs/autotune.md).

Unit-level knob sweep on a fake runtime + a whole-job SPMD run
observing convergence and cross-rank winner agreement (VERDICT round-1
item 8), extended for the trace-driven tuner package: per-arm
successive halving over every perf plane, the trace-derived steps/sec
score source, the persistent warm-start store (hit before the first
scored window, corrupt/stale degradation, elastic re-validation), the
cross-rank determinism pin under divergent rank-local scores, the
disabled-mode guard, the overlay, and the `hvd-autotune` CLI.
"""

import json
import logging
import os
import types

import numpy as np
import pytest

from test_spmd import launch

HERE = os.path.dirname(os.path.abspath(__file__))

MIB = 1024 * 1024


class _LogSpy(logging.Handler):
    """The horovod_tpu logger doesn't propagate (rank-prefixed handler
    of its own), so 'loud' contracts are pinned with a direct spy."""

    def __init__(self):
        super().__init__()
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())

    def grep(self, needle):
        return [m for m in self.messages if needle in m]


@pytest.fixture
def logspy():
    from horovod_tpu.utils.logging_util import get_logger
    log = get_logger()
    spy = _LogSpy()
    old_level = log.level
    log.addHandler(spy)
    log.setLevel(logging.INFO)
    yield spy
    log.removeHandler(spy)
    log.setLevel(old_level)


@pytest.fixture(autouse=True)
def _clean_overlay():
    """The overlay is process-global on purpose (construction-time
    readers); tests must not leak tuned values into each other."""
    from horovod_tpu.autotune import overlay
    overlay.clear()
    yield
    overlay.clear()


@pytest.fixture
def metrics_on(monkeypatch):
    from horovod_tpu.telemetry import core as telemetry
    monkeypatch.setenv("HOROVOD_TPU_METRICS", "1")
    telemetry.reset()
    assert telemetry.enabled()
    yield telemetry
    monkeypatch.delenv("HOROVOD_TPU_METRICS", raising=False)
    telemetry.reset()


def _metric(name, labels=None):
    from horovod_tpu.telemetry import core as telemetry
    fam = (telemetry.snapshot().get("families") or {}).get(name)
    if not fam:
        return None
    for s in fam.get("samples") or []:
        if labels is None or (s.get("labels") or {}) == labels:
            return s.get("value")
    return None


class _FakeCore:
    def __init__(self):
        self.thresholds = []

    def set_fusion_threshold(self, v):
        self.thresholds.append(v)


def _fake_runtime():
    from horovod_tpu import basics
    coord = types.SimpleNamespace(bytes_processed=0, fusion_threshold=0,
                                  cycle_time_s=0.001)
    backend = types.SimpleNamespace(core=_FakeCore())
    rt = types.SimpleNamespace(mode=basics.MODE_SINGLE, coordinator=coord,
                               backend=backend, topology=None)
    return rt


def _drive(pm, rt, rates, max_cycles=2000):
    """Feed synthetic per-candidate byte rates until convergence; the
    currently applied candidate's rate drives the score."""
    observed = []
    for _ in range(max_cycles):
        cand = None
        if pm._pos >= 0:
            cand = pm._active[pm._pos]
        rt.coordinator.bytes_processed += rates.get(cand, 5)
        pm.record_cycle()
        observed.append((rt.coordinator.fusion_threshold,
                         rt.coordinator.cycle_time_s))
        if not pm.enabled:
            return observed
    raise AssertionError("did not converge")


def test_parameter_manager_halving_and_convergence(monkeypatch, tmp_path):
    monkeypatch.setenv("HVDTPU_AUTOTUNE_FUSION_CANDIDATES_MIB", "1,2")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLE_CANDIDATES_MS", "0.5,1.0")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_WARMUP_CYCLES", "2")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLES_PER_CANDIDATE", "8")
    log = tmp_path / "tune.log"
    monkeypatch.setenv("HVDTPU_AUTOTUNE_LOG", str(log))

    from horovod_tpu.autotune import ParameterManager
    rt = _fake_runtime()
    pm = ParameterManager(rt)
    assert len(pm._grid) == 4          # 2 fusion x 2 cycle x 1 bucket
    # 4 candidates -> 2 halving rounds; first-round budget 8 >> 1 = 4.
    assert pm._budget == 4

    # Candidate 2 (fusion=2MiB cycle=0.5ms) is the clear winner.
    observed = _drive(pm, rt, rates={0: 10, 1: 20, 2: 99, 3: 30})

    assert not pm.enabled, "did not converge"
    assert pm.best == (2 * 1024 * 1024, 0.5, None)
    # The sweep walked multiple candidates before converging.
    assert len(set(observed)) >= 3, set(observed)
    # Winner pushed into the native controller.
    assert rt.backend.core.thresholds[-1] == 2 * 1024 * 1024
    # Log has both rounds' scores with the starred winner; the loser half
    # appears only in round 0 (the successive-halving shape).
    content = log.read_text()
    assert "*" in content
    assert content.count("r0,") == 4
    assert content.count("r1,") == 2


def test_parameter_manager_tunes_delegated_bucket(monkeypatch):
    """With a delegated backend, the bucket knob joins the space and a
    small-tensor flood picks a non-default winner that is pushed to the
    backend (VERDICT r2 item 6)."""
    monkeypatch.setenv("HVDTPU_AUTOTUNE_FUSION_CANDIDATES_MIB", "1")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLE_CANDIDATES_MS", "0.5")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_BUCKET_CANDIDATES", "256,65536")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_WARMUP_CYCLES", "1")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLES_PER_CANDIDATE", "4")

    from horovod_tpu.autotune import ParameterManager
    rt = _fake_runtime()
    buckets = []
    rt.backend.set_min_bucket = buckets.append
    pm = ParameterManager(rt)
    assert len(pm._grid) == 2

    # The big-bucket candidate (index 1) wins the synthetic flood: fewer,
    # fuller launches -> higher bytes/sec.
    _drive(pm, rt, rates={0: 10, 1: 80})
    assert pm.best == (1024 * 1024, 0.5, 65536)
    assert buckets[-1] == 65536


def test_autotune_spmd_convergence():
    pytest.importorskip("jax")
    extra = {
        "HVDTPU_AUTOTUNE": "1",
        "HVDTPU_AUTOTUNE_FUSION_CANDIDATES_MIB": "1,4",
        "HVDTPU_AUTOTUNE_CYCLE_CANDIDATES_MS": "0.2,1.0",
        "HVDTPU_AUTOTUNE_WARMUP_CYCLES": "3",
        "HVDTPU_AUTOTUNE_CYCLES_PER_CANDIDATE": "4",
    }
    codes, outs = launch(2, script=os.path.join(HERE, "autotune_worker.py"),
                         extra_env=extra, timeout=300)
    for rank, (code, out) in enumerate(zip(codes, outs)):
        assert code == 0, f"rank {rank} failed (exit {code}):\n{out[-4000:]}"
        assert "AUTOTUNE OK" in out


# ==========================================================================
# Disabled-mode guard (the telemetry/chaos/guardian contract)
# ==========================================================================

def test_disabled_mode_guard(hvd, monkeypatch):
    """HVDTPU_AUTOTUNE unset: init never built a ParameterManager and
    the coordinator's per-cycle cost is the one None check. The
    sentinel half proves the call site is live (a dead guard would
    also 'pass'), the bomb half proves nothing constructs a tuner on
    the hot path."""
    from horovod_tpu import basics
    from horovod_tpu import autotune as autotune_mod
    import jax.numpy as jnp

    rt = basics.runtime()
    assert rt.autotuner is None, \
        "HVDTPU_AUTOTUNE unset must leave runtime.autotuner None"

    class _Bomb:
        def __init__(self, *a, **k):
            raise AssertionError("ParameterManager constructed with "
                                 "HVDTPU_AUTOTUNE unset")

    monkeypatch.setattr(autotune_mod, "ParameterManager", _Bomb)
    out = hvd.allreduce(jnp.ones(8), op=hvd.Sum, name="autotune.guard")
    np.testing.assert_allclose(np.asarray(out)[0], float(hvd.size()))

    calls = []
    sentinel = types.SimpleNamespace(
        record_cycle=lambda: calls.append(1), enabled=True)
    monkeypatch.setattr(rt, "autotuner", sentinel)
    out = hvd.allreduce(jnp.ones(8), op=hvd.Sum, name="autotune.guard2")
    np.testing.assert_allclose(np.asarray(out)[0], float(hvd.size()))
    assert calls, "record_cycle call site is dead — the guard test is vacuous"


# ==========================================================================
# Score sources (autotune/score.py)
# ==========================================================================

def _ring_runtime(events):
    flight = types.SimpleNamespace(snapshot=lambda: list(events))
    tracer = types.SimpleNamespace(_flight=flight)
    return types.SimpleNamespace(tracer=tracer)


def _step_events(n_steps, names=("grad.0", "grad.1"), t0=1.0, step_s=1.0,
                 flight_s=0.25):
    """n_steps complete occurrence groups: every name submits at the
    step start and finishes flight_s later."""
    events = []
    for occ in range(n_steps):
        base = t0 + occ * step_s
        for i, n in enumerate(names):
            events.append({"e": "sub", "n": n, "o": occ,
                           "t": base + 0.01 * i})
            events.append({"e": "fin", "n": n, "o": occ,
                           "t": base + 0.01 * i + flight_s})
    return events


def test_window_stats_counts_complete_steps():
    from horovod_tpu.autotune import score
    stats = score.window_stats(_step_events(3), 0.0, 100.0)
    assert stats["steps"] == 3
    # span = first submit (1.0) -> last finish (3.0 + 0.01 + 0.25).
    assert stats["span_s"] == pytest.approx(2.26, abs=1e-6)
    assert stats["mean_step_s"] == pytest.approx(0.26, abs=1e-6)
    # Two collectives per step in flight together: union 0.26 of 0.50
    # total in-flight seconds -> 48% of collective time was overlapped.
    assert stats["overlap_fraction"] == pytest.approx(0.48, abs=1e-6)


def test_window_stats_excludes_dirty_and_incomplete_groups():
    from horovod_tpu.autotune import score
    events = _step_events(2)
    # occurrence 2: a finish whose submit predates the window (fell off
    # the ring) FOLLOWED by a clean in-window pair of the same
    # occurrence -> the whole occurrence is dirty; it must not be
    # counted as a (shorter) step off the late pair alone.
    events.append({"e": "fin", "n": "grad.0", "o": 2, "t": 50.0})
    events.append({"e": "sub", "n": "grad.1", "o": 2, "t": 60.0})
    events.append({"e": "fin", "n": "grad.1", "o": 2, "t": 60.2})
    # occurrence 3: submitted but never finished -> open, excluded.
    events.append({"e": "sub", "n": "grad.0", "o": 3, "t": 70.0})
    # occurrence 4: completed but err-flagged -> a fast-FAILING
    # collective must not score as a fast step.
    events.append({"e": "sub", "n": "grad.0", "o": 4, "t": 80.0})
    events.append({"e": "fin", "n": "grad.0", "o": 4, "t": 80.01,
                   "err": 1})
    stats = score.window_stats(events, 0.0, 100.0)
    assert stats["steps"] == 2

    # Fewer than MIN_STEPS complete groups -> no step structure.
    assert score.window_stats(_step_events(1), 0.0, 100.0) is None
    assert score.window_stats([], 0.0, 100.0) is None


def test_trace_score_steps_per_sec_and_bytes_fallback(logspy):
    from horovod_tpu.autotune import score
    events = _step_events(4)
    ts = score.make_source(_ring_runtime(events), "auto")
    ts.open_window()
    ts._t0 = 0.0   # window covers the synthetic timestamps
    window = ts.close_window([7.0, 9.0])
    assert window["steps"] == pytest.approx(4 / 3.26, rel=1e-6)
    # The bytes rate always rides along: mixed-unit rounds decide on it.
    assert window["bytes"] == 8.0

    # No ring -> bytes-only window, quietly under auto.
    bs = score.make_source(types.SimpleNamespace(), "auto")
    bs.open_window()
    window = bs.close_window([7.0, 9.0])
    assert window == {"bytes": 8.0, "steps": None}
    assert not logspy.grep("falls back")

    # strict (=steps) falls back too, but loudly and only once.
    ss = score.make_source(types.SimpleNamespace(), "steps")
    for _ in range(2):
        ss.open_window()
        window = ss.close_window([1.0])
        assert window["steps"] is None
    assert len(logspy.grep("falls back")) == 1


def test_trace_score_straggler_delay_stretches_span(metrics_on):
    from horovod_tpu.autotune import score
    events = _step_events(4)
    gauge = metrics_on.gauge("hvd_straggler_delay_seconds",
                             "test", labelnames=("rank",))
    gauge.labels(rank="0").set(0.0)
    ts = score.TraceScore(_ring_runtime(events), rank=0)
    ts.open_window()
    ts._t0 = 0.0
    base = ts.close_window([])["steps"]
    # A live analyzer attributes 2s of new straggler delay to this
    # rank mid-window: the same local throughput must score worse.
    gauge.labels(rank="0").set(0.0)
    ts.open_window()
    ts._t0 = 0.0
    gauge.labels(rank="0").set(2.0)
    delayed = ts.close_window([])["steps"]
    assert delayed == pytest.approx(4 / (3.26 + 2.0), rel=1e-6)
    assert delayed < base
    # Window gauges published for /metrics debuggability.
    assert _metric("hvd_autotune_step_seconds") == pytest.approx(0.26,
                                                                 abs=1e-6)
    assert _metric("hvd_autotune_window_overlap_fraction") \
        == pytest.approx(0.48, abs=1e-6)


def test_make_source_rejects_unknown_mode():
    from horovod_tpu.autotune import score
    with pytest.raises(ValueError, match="HVDTPU_AUTOTUNE_SCORE"):
        score.make_source(types.SimpleNamespace(), "bayesian")


# ==========================================================================
# Warm-start store (autotune/store.py)
# ==========================================================================

def _entry(fusion=3 * MIB, cycle=2.0, score=42.0, version="0", **cfg):
    from horovod_tpu.autotune import store
    config = {k: None for k in store.CONFIG_KEYS}
    config.update(fusion_threshold=fusion, cycle_time_ms=cycle, **cfg)
    return store.make_entry(config, score, "steps", "sig", 1, "int8",
                            version, [("host", 0, "x", score)])


def test_store_roundtrip_and_clear(tmp_path):
    from horovod_tpu.autotune import store
    path = str(tmp_path / "cache.json")
    assert store.load(path) == {}           # first run is not an error
    store.save_entry(path, "k1", _entry())
    store.save_entry(path, "k2", _entry(fusion=MIB))
    entries = store.load(path)
    assert set(entries) == {"k1", "k2"}
    assert store.validate_entry(entries["k1"]) is None
    assert entries["k1"]["config"]["fusion_threshold"] == 3 * MIB
    assert store.clear(path, key="k1") == 1
    assert set(store.load(path)) == {"k2"}
    assert store.clear(path, key="nope") == 0
    assert store.clear(path) == 1
    assert not os.path.exists(path)
    assert store.clear(path) == 0


def test_store_rejects_corrupt_and_stale_files(tmp_path):
    from horovod_tpu.autotune import store
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(store.StoreError, match="cannot parse"):
        store.load(str(bad))
    bad.write_text(json.dumps({"entries": {}, "format": 99}))
    with pytest.raises(store.StoreError, match="format"):
        store.load(str(bad))
    bad.write_text(json.dumps({"format": store.FORMAT}))
    with pytest.raises(store.StoreError, match="entries"):
        store.load(str(bad))
    # save_entry over a corrupt file IS the repair.
    bad.write_text("{not json")
    store.save_entry(str(bad), "k", _entry())
    assert set(store.load(str(bad))) == {"k"}


def test_validate_entry_reasons():
    from horovod_tpu.autotune import store
    assert store.validate_entry([]) == "entry is not an object"
    assert store.validate_entry({}) == "no config object"
    assert "missing" in store.validate_entry({"config": {}})
    e = _entry()
    e["config"]["cycle_time_ms"] = "fast"
    assert "not numeric" in store.validate_entry(e)


def test_model_signature_and_key():
    from horovod_tpu.autotune import store
    sig = store.model_signature(["grad.1", "grad.0", "grad.1",
                                 "hvdlint.order", None])
    assert sig.startswith("m")
    # Order/duplicate independent; guard-internal ops excluded.
    assert sig == store.model_signature(["grad.0", "grad.1"])
    assert sig != store.model_signature(["grad.0"])
    assert store.model_signature([]) == "default"
    assert store.make_key(sig, 8, "int8+q") == f"{sig}|w8|int8+q"


# ==========================================================================
# Overlay (autotune/overlay.py)
# ==========================================================================

def test_overlay_set_get_generation():
    from horovod_tpu.autotune import overlay
    from horovod_tpu.utils import envparse
    g0 = overlay.generation()
    assert overlay.get_int(envparse.BUCKET_BYTES) is None
    assert overlay.get_int(envparse.BUCKET_BYTES, 7) == 7
    overlay.set_int(envparse.BUCKET_BYTES, 4 * MIB)
    assert overlay.get_int(envparse.BUCKET_BYTES, 7) == 4 * MIB
    assert overlay.generation() == g0 + 1
    assert overlay.snapshot() == {envparse.BUCKET_BYTES: 4 * MIB}
    overlay.clear()
    assert overlay.get_int(envparse.BUCKET_BYTES) is None
    assert overlay.generation() == g0 + 2


# ==========================================================================
# ParameterManager: arms, warm start, re-validation, determinism
# ==========================================================================

def _rt(mode=None, rank=0, size=1, overlap=False, compression=False,
        min_bucket=None):
    """Fake runtime rich enough for every arm; see _fake_runtime for
    the minimal legacy shape."""
    from horovod_tpu import basics
    coord = types.SimpleNamespace(bytes_processed=0, fusion_threshold=0,
                                  cycle_time_s=0.001)
    if overlap:
        coord._overlap = True
        coord._bucket_bytes = 4 * MIB
    if compression:
        coord._compression = types.SimpleNamespace(
            policy=types.SimpleNamespace(rules=[], threshold=1024),
            _delegated=False)
    backend = types.SimpleNamespace(core=_FakeCore())
    if min_bucket is not None:
        backend.min_bucket = min_bucket
        backend._buckets = []
        backend.set_min_bucket = backend._buckets.append
    topology = types.SimpleNamespace(rank=rank, size=size)
    return types.SimpleNamespace(
        mode=mode if mode is not None else basics.MODE_SINGLE,
        coordinator=coord, backend=backend, topology=topology, size=size)


def _drive_fn(pm, rt, rate_fn, max_cycles=4000):
    """Feed synthetic byte deltas from rate_fn(pm) until convergence."""
    for _ in range(max_cycles):
        rt.coordinator.bytes_processed += rate_fn(pm)
        pm.record_cycle()
        if not pm.enabled:
            return
    raise AssertionError(f"did not converge (phase={pm._phase})")


def _tiny_grid(monkeypatch, warmup=1, budget=2):
    monkeypatch.setenv("HVDTPU_AUTOTUNE_FUSION_CANDIDATES_MIB", "1,2")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLE_CANDIDATES_MS", "0.5")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_WARMUP_CYCLES", str(warmup))
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLES_PER_CANDIDATE", str(budget))


def test_warm_start_hit_applies_before_first_scored_window(
        monkeypatch, tmp_path, metrics_on, logspy):
    """A populated cache + unchanged elastic version: the stored winner
    is applied at the end of warmup — before any scoring window opens —
    and the sweep never runs."""
    from horovod_tpu.autotune import ParameterManager, store
    _tiny_grid(monkeypatch)
    cache = str(tmp_path / "cache.json")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CACHE", cache)
    monkeypatch.setenv("HVDTPU_AUTOTUNE_SIGNATURE", "sig-a")
    rt = _rt()
    key = store.make_key("sig-a", 1, store.codec_signature(rt))
    store.save_entry(cache, key, _entry(fusion=3 * MIB, cycle=2.0))

    pm = ParameterManager(rt)
    assert pm.enabled
    rt.coordinator.bytes_processed += 10
    pm.record_cycle()           # warmup cycle 1 of 1 -> warm decision

    assert not pm.enabled, "cache hit must skip the sweep entirely"
    assert pm._round_scores == {} and pm._history == [], \
        "no scored window may precede a warm start"
    assert rt.coordinator.fusion_threshold == 3 * MIB
    assert rt.coordinator.cycle_time_s == pytest.approx(0.002)
    assert pm.best == (3 * MIB, 2.0, None)
    assert pm.best_config["fusion_threshold"] == 3 * MIB
    assert pm.applied == [("host", f"{3 * MIB}/2.0/None")]
    assert _metric("hvd_autotune_warm_start_total",
                   {"outcome": "hit"}) == 1
    assert _metric("hvd_autotune_converged") == 1
    assert logspy.grep("warm start")


def test_warm_start_miss_and_unset_cache_sweep(monkeypatch, tmp_path,
                                               metrics_on):
    """No cache entry for the key (and separately: no cache path at
    all) -> the full sweep runs as before."""
    from horovod_tpu.autotune import ParameterManager
    _tiny_grid(monkeypatch)
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CACHE", str(tmp_path / "c.json"))
    monkeypatch.setenv("HVDTPU_AUTOTUNE_SIGNATURE", "sig-miss")
    rt = _rt()
    pm = ParameterManager(rt)
    _drive_fn(pm, rt, lambda p: 10)
    assert pm.best is not None
    assert _metric("hvd_autotune_warm_start_total",
                   {"outcome": "miss"}) == 1
    # Convergence persisted the winner for the NEXT run.
    from horovod_tpu.autotune import store
    key = store.make_key("sig-miss", 1, store.codec_signature(rt))
    assert store.load(str(tmp_path / "c.json"))[key]["config"][
        "fusion_threshold"] == pm.best[0]


def test_corrupt_cache_degrades_to_fresh_sweep_loudly(
        monkeypatch, tmp_path, metrics_on, logspy):
    from horovod_tpu.autotune import ParameterManager, store
    _tiny_grid(monkeypatch)
    cache = tmp_path / "cache.json"
    cache.write_text("{definitely not json")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CACHE", str(cache))
    monkeypatch.setenv("HVDTPU_AUTOTUNE_SIGNATURE", "sig-c")
    rt = _rt()
    pm = ParameterManager(rt)
    assert pm._store_corrupt
    assert logspy.grep("warm-start cache unusable")
    assert _metric("hvd_autotune_warm_start_total",
                   {"outcome": "corrupt"}) == 1
    _drive_fn(pm, rt, lambda p: 10)
    assert pm.best is not None
    # Convergence rewrote the corrupt file atomically (save = repair).
    key = store.make_key("sig-c", 1, store.codec_signature(rt))
    assert key in store.load(str(cache))


def test_stale_entry_degrades_to_fresh_sweep_loudly(
        monkeypatch, tmp_path, metrics_on, logspy):
    """A schema-valid file whose entry fails validation (missing config
    keys) is stale, not fatal: loud warning + full sweep."""
    from horovod_tpu.autotune import ParameterManager, store
    _tiny_grid(monkeypatch)
    cache = str(tmp_path / "cache.json")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CACHE", cache)
    monkeypatch.setenv("HVDTPU_AUTOTUNE_SIGNATURE", "sig-s")
    rt = _rt()
    key = store.make_key("sig-s", 1, store.codec_signature(rt))
    store.save_entry(cache, key, {"config": {"fusion_threshold": 1}})
    pm = ParameterManager(rt)
    rt.coordinator.bytes_processed += 10
    pm.record_cycle()
    assert pm.enabled and pm._phase == "sweep"
    assert logspy.grep("is stale")
    assert _metric("hvd_autotune_warm_start_total",
                   {"outcome": "stale"}) == 1


def test_elastic_bump_revalidates_and_keeps_winner(
        monkeypatch, tmp_path, metrics_on, logspy):
    """Entry cached under elastic version 0, job now at version 2:
    one baseline window + one warm window; the warm config keeps its
    crown on a tie (noise must not trigger a re-sweep) and the store
    is rewritten under the new version."""
    from horovod_tpu.autotune import ParameterManager, store
    _tiny_grid(monkeypatch)
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CONFIRM_CYCLES", "2")
    monkeypatch.setenv("HVDTPU_ELASTIC_VERSION", "2")
    cache = str(tmp_path / "cache.json")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CACHE", cache)
    monkeypatch.setenv("HVDTPU_AUTOTUNE_SIGNATURE", "sig-r")
    rt = _rt()
    key = store.make_key("sig-r", 1, store.codec_signature(rt))
    store.save_entry(cache, key, _entry(fusion=3 * MIB, cycle=2.0,
                                        version="0"))
    pm = ParameterManager(rt)
    phases = []

    def rate(p):
        phases.append(p._phase)
        return 10   # identical rate either side: a tie

    _drive_fn(pm, rt, rate)
    assert "confirm_base" in phases and "confirm_warm" in phases
    assert "sweep" not in phases, "a tie must not trigger a re-sweep"
    assert pm.best == (3 * MIB, 2.0, None)
    assert _metric("hvd_autotune_warm_start_total",
                   {"outcome": "revalidate"}) == 1
    assert _metric("hvd_autotune_warm_start_total",
                   {"outcome": "revalidated"}) == 1
    assert logspy.grep("re-validated")
    updated = store.load(cache)[key]
    assert updated["elastic_version"] == "2"
    # The original converged sweep's history survives the rewrite —
    # this session ran confirm windows, not a sweep.
    assert updated["history"] == [["host", 0, "x", 42.0]], updated


def test_elastic_bump_regression_triggers_full_resweep(
        monkeypatch, tmp_path, metrics_on, logspy):
    """The stored winner scores far below the baseline window under the
    new cohort -> loud regression + the full sweep re-runs (and its
    winner, not the stale one, is persisted)."""
    from horovod_tpu.autotune import ParameterManager, store
    _tiny_grid(monkeypatch)
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CONFIRM_CYCLES", "2")
    monkeypatch.setenv("HVDTPU_ELASTIC_VERSION", "3")
    cache = str(tmp_path / "cache.json")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CACHE", cache)
    monkeypatch.setenv("HVDTPU_AUTOTUNE_SIGNATURE", "sig-g")
    rt = _rt()
    key = store.make_key("sig-g", 1, store.codec_signature(rt))
    store.save_entry(cache, key, _entry(fusion=3 * MIB, cycle=2.0,
                                        version="0"))
    pm = ParameterManager(rt)

    def rate(p):
        if p._phase == "confirm_warm":
            return 1          # the stored winner tanks
        return 100

    _drive_fn(pm, rt, rate)
    assert logspy.grep("REGRESSED")
    assert _metric("hvd_autotune_warm_start_total",
                   {"outcome": "regressed"}) == 1
    # The sweep ran after the failed confirmation and its winner stuck
    # (grid fusion values are 1/2 MiB — never the stale 3 MiB).
    assert pm._history, "full re-sweep must have scored candidates"
    assert pm.best[0] in (MIB, 2 * MIB)
    assert store.load(cache)[key]["config"]["fusion_threshold"] \
        == pm.best[0]
    assert store.load(cache)[key]["elastic_version"] == "3"


def test_min_bucket_gauge_seeded_from_backend_reality(
        monkeypatch, metrics_on):
    """Satellite fix: a scrape before the first bucket candidate must
    show the backend's CURRENT min bucket (and every other seeded
    plane gauge), not 0."""
    from horovod_tpu.autotune import ParameterManager
    _tiny_grid(monkeypatch)
    rt = _rt(overlap=True, compression=True, min_bucket=4096)
    rt.coordinator.fusion_threshold = 7 * MIB
    rt.coordinator.cycle_time_s = 0.004
    ParameterManager(rt)
    assert _metric("hvd_autotune_min_bucket") == 4096
    assert _metric("hvd_autotune_fusion_threshold_bytes") == 7 * MIB
    assert _metric("hvd_autotune_cycle_time_ms") == pytest.approx(4.0)
    assert _metric("hvd_autotune_bucket_bytes") == 4 * MIB
    assert _metric("hvd_autotune_compression_codec",
                   {"codec": "none"}) == 1
    assert _metric("hvd_autotune_compression_threshold") == 1024


def test_multi_arm_sweep_tunes_every_plane(monkeypatch, tmp_path):
    """host -> overlap -> compression -> zero coordinate descent: each
    arm converges on the candidate its synthetic rates favor, winners
    land on the live objects / the overlay, and the history log names
    every arm."""
    from horovod_tpu import basics
    from horovod_tpu.autotune import ParameterManager, overlay
    from horovod_tpu.utils import envparse
    monkeypatch.setenv("HVDTPU_AUTOTUNE_FUSION_CANDIDATES_MIB", "1")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLE_CANDIDATES_MS", "0.5")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_WARMUP_CYCLES", "1")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLES_PER_CANDIDATE", "2")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_BUCKET_BYTES_CANDIDATES_MIB",
                       "1,4")
    # Space after the comma on purpose: grid parsing strips items.
    monkeypatch.setenv("HVDTPU_AUTOTUNE_COMPRESSION_CANDIDATES",
                       "none, int8")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_ZERO_BUCKET_CANDIDATES_MIB",
                       "1,4")
    monkeypatch.setenv("HVDTPU_ZERO", "1")
    log = tmp_path / "tune.log"
    monkeypatch.setenv("HVDTPU_AUTOTUNE_LOG", str(log))
    rt = _rt(mode=basics.MODE_SINGLE, overlap=True, compression=True)
    pm = ParameterManager(rt)
    assert [a.name for a in pm._arms] == ["host", "overlap",
                                          "compression", "zero"]

    # Favor: overlap idx 1 (4 MiB), compression idx 1 (int8@1024),
    # zero idx 1 (4 MiB). Single-candidate arms converge on their own.
    wins = {"overlap": 1, "compression": 1, "zero": 1}

    def rate(p):
        if p._pos < 0:
            return 5
        arm = p._arms[p._arm_idx]
        return 90 if p._active[p._pos] == wins.get(arm.name, 0) else 10

    _drive_fn(pm, rt, rate)
    assert set(pm._winners) == {"host", "overlap", "compression", "zero"}
    assert rt.coordinator._bucket_bytes == 4 * MIB
    assert overlay.get_int(envparse.BUCKET_BYTES) == 4 * MIB
    assert overlay.get_int(envparse.ZERO_BUCKET_BYTES) == 4 * MIB
    assert pm._winners["compression"] == ("int8", 1024)
    assert rt.coordinator._compression.policy.rules == [("*", "int8")]
    assert pm.best_config["bucket_bytes"] == 4 * MIB
    assert pm.best_config["compression"] == "int8"
    assert pm.best_config["zero_bucket_bytes"] == 4 * MIB
    planes = {p for p, _ in pm.applied}
    assert planes == {"host", "overlap", "compression", "zero"}
    content = log.read_text()
    for arm in ("overlap", "compression", "zero"):
        assert f"{arm}=" in content, content


def _stub_source(pm, steps_by_cand):
    """Score-source stub: per-candidate steps value (None = the window
    saw no step structure), bytes riding along from the cycle rates."""
    class _Stub:
        name = "steps"

        def open_window(self):
            pass

        def close_window(self, rates):
            cand = pm._active[pm._pos]
            return {"bytes": sum(rates) / len(rates),
                    "steps": steps_by_cand.get(cand)}
    pm._source = _Stub()


def test_mixed_unit_round_decides_on_bytes(monkeypatch):
    """One candidate's windows fell back to bytes/sec: the round must
    compare EVERY candidate in bytes (which all windows carry) — a raw
    comparison would let any ~1e2 bytes rate beat any ~1e-3 steps rate
    regardless of actual step pacing."""
    from horovod_tpu.autotune import ParameterManager
    _tiny_grid(monkeypatch, warmup=1, budget=2)
    rt = _rt()
    pm = ParameterManager(rt)
    assert len(pm._grid) == 2
    # cand 0: no step structure, modest bytes. cand 1: tiny steps value
    # but DOUBLE the bytes rate.
    _stub_source(pm, {0: None, 1: 0.001})
    _drive_fn(pm, rt, lambda p: (100 if (p._pos >= 0
                                         and p._active[p._pos] == 1)
                                 else 50))
    assert pm._score_label == "bytes"
    assert pm.best == (2 * MIB, 0.5, None), \
        "mixed-unit round must decide on the common bytes unit"


def test_all_steps_round_decides_on_steps(monkeypatch):
    """Every window has step structure: steps/sec decides, even when
    the bytes rates disagree (the whole point of the trace score — a
    config that moves more bytes but finishes fewer steps loses)."""
    from horovod_tpu.autotune import ParameterManager
    _tiny_grid(monkeypatch, warmup=1, budget=2)
    rt = _rt()
    pm = ParameterManager(rt)
    # cand 0: more bytes, fewer steps. cand 1: fewer bytes, more steps.
    _stub_source(pm, {0: 5.0, 1: 9.0})
    _drive_fn(pm, rt, lambda p: (100 if (p._pos >= 0
                                         and p._active[p._pos] == 0)
                                 else 50))
    assert pm._score_label == "steps"
    assert pm.best == (2 * MIB, 0.5, None), \
        "steps/sec must out-vote the bytes proxy when available"


def test_apply_config_zero_overlay_respects_spmd_gate(monkeypatch):
    """A cached zero_bucket_bytes must obey the same single-controller
    gate the zero ARM does: in SPMD the per-process step loops would
    observe the overlay bump at different step indices and re-plan
    onto divergent shard geometries."""
    from horovod_tpu import basics
    from horovod_tpu.autotune import ParameterManager, overlay
    from horovod_tpu.utils import envparse
    _tiny_grid(monkeypatch)
    monkeypatch.setenv("HVDTPU_ZERO", "1")
    cfg = {"zero_bucket_bytes": 2 * MIB}

    rt0, rt1 = _spmd_pair()
    pm = ParameterManager(rt0)
    pm._apply_config(cfg)
    assert overlay.get_int(envparse.ZERO_BUCKET_BYTES) is None, \
        "SPMD warm start must not move the ZeRO overlay"

    pm = ParameterManager(_rt(mode=basics.MODE_SINGLE))
    pm._apply_config(cfg)
    assert overlay.get_int(envparse.ZERO_BUCKET_BYTES) == 2 * MIB


def test_apply_config_keeps_zero_compression_threshold(monkeypatch):
    """Threshold 0 (= compress everything) is a legitimate tuned value;
    the warm-start apply must not 'or' it away to the live plane's."""
    from horovod_tpu.autotune import ParameterManager
    _tiny_grid(monkeypatch)
    rt = _rt(compression=True)
    pm = ParameterManager(rt)
    pm._apply_config({"compression": "int8", "compression_threshold": 0})
    assert rt.coordinator._compression.policy.threshold == 0
    assert pm._current["compression_threshold"] == 0


def test_overlay_resolve_int_precedence(monkeypatch):
    """resolve_int: overlay > raw env > default — the one resolution
    every construction-time reader goes through."""
    from horovod_tpu.autotune import overlay
    from horovod_tpu.utils import envparse
    assert overlay.resolve_int(envparse.BUCKET_BYTES, 7) == 7
    monkeypatch.setenv("HVDTPU_BUCKET_BYTES", str(2 * MIB))
    assert overlay.resolve_int(envparse.BUCKET_BYTES, 7) == 2 * MIB
    overlay.set_int(envparse.BUCKET_BYTES, 4 * MIB)
    assert overlay.resolve_int(envparse.BUCKET_BYTES, 7) == 4 * MIB


def test_compression_arm_dedupes_none_thresholds(monkeypatch):
    """'none' ignores the threshold; crossing it with every threshold
    would burn a scoring window per identical duplicate."""
    from horovod_tpu.autotune import ParameterManager
    _tiny_grid(monkeypatch)
    monkeypatch.setenv("HVDTPU_AUTOTUNE_COMPRESSION_CANDIDATES",
                       "none,int8")
    monkeypatch.setenv(
        "HVDTPU_AUTOTUNE_COMPRESSION_THRESHOLD_CANDIDATES",
        "1024,16384")
    rt = _rt(compression=True)
    pm = ParameterManager(rt)
    comp = {a.name: a for a in pm._arms}["compression"]
    assert comp.candidates == [("none", 1024), ("int8", 1024),
                               ("int8", 16384)]


def test_cli_clear_unwritable_path_exits_2(tmp_path, capsys,
                                           monkeypatch):
    """An unwritable store (OSError from remove/rename) is the
    documented exit-2 failure, not a traceback. Simulated via
    monkeypatch: the test process runs as root, where chmod can't
    produce a real EACCES."""
    from horovod_tpu.autotune import cli, store
    cache = str(tmp_path / "cache.json")
    store.save_entry(cache, "k", _entry())

    def boom(path, key=None):
        raise OSError(30, "Read-only file system", path)

    monkeypatch.setattr(cli.store, "clear", boom)
    assert _cli(["clear", "--cache", cache]) == 2
    assert "hvd-autotune:" in capsys.readouterr().err


def test_compression_arm_never_overwrites_per_glob_rules(monkeypatch):
    """A user policy with per-glob rules is not the tuner's to rewrite:
    no compression arm is built over it."""
    from horovod_tpu.autotune import ParameterManager
    _tiny_grid(monkeypatch)
    rt = _rt(compression=True)
    rt.coordinator._compression.policy.rules = [("emb.*", "int8"),
                                                ("*", "none")]
    pm = ParameterManager(rt)
    assert [a.name for a in pm._arms] == ["host"]


# ==========================================================================
# Cross-rank determinism (the acceptance pin)
# ==========================================================================

class _Chan:
    """Rank 0 -> rank 1 broadcast FIFO; lockstep driving keeps the
    send/receive order aligned the way the real data plane's
    negotiated cycles do."""

    def __init__(self):
        self.fifo = []

    def bind(self, rt, rank):
        def broadcast(tensors, root, process_set):
            assert root == 0
            if rank == 0:
                self.fifo.append([np.array(t, copy=True)
                                  for t in tensors])
                return tensors
            assert self.fifo, \
                "rank 1 reached a broadcast before rank 0 (lockstep broken)"
            return self.fifo.pop(0)
        rt.backend.broadcast = broadcast


def _spmd_pair(**kw):
    from horovod_tpu import basics
    chan = _Chan()
    rts = []
    for rank in (0, 1):
        rt = _rt(mode=basics.MODE_SPMD, rank=rank, size=2, **kw)
        chan.bind(rt, rank)
        rts.append(rt)
    return rts


def test_divergent_rank_local_scores_identical_applied_sequence(
        monkeypatch):
    """THE determinism pin: rank 1's local scores prefer the opposite
    corner of the grid, yet both ranks apply the identical knob
    sequence and converge on rank 0's winner (survivors broadcast at
    every round boundary)."""
    from horovod_tpu.autotune import ParameterManager
    monkeypatch.setenv("HVDTPU_AUTOTUNE_FUSION_CANDIDATES_MIB", "1,2")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLE_CANDIDATES_MS", "0.5,1.0")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_WARMUP_CYCLES", "2")
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CYCLES_PER_CANDIDATE", "4")
    rt0, rt1 = _spmd_pair()
    pm0, pm1 = ParameterManager(rt0), ParameterManager(rt1)
    rates0 = {0: 10, 1: 20, 2: 99, 3: 30}     # rank 0 prefers cand 2
    rates1 = {0: 99, 1: 30, 2: 10, 3: 20}     # rank 1 prefers cand 0

    for _ in range(2000):
        for pm, rt, rates in ((pm0, rt0, rates0), (pm1, rt1, rates1)):
            cand = pm._active[pm._pos] if pm._pos >= 0 else None
            rt.coordinator.bytes_processed += rates.get(cand, 5)
            pm.record_cycle()
        if not pm0.enabled and not pm1.enabled:
            break
    assert not pm0.enabled and not pm1.enabled, "did not converge"

    assert pm0.applied == pm1.applied, \
        "ranks diverged on the applied-knob sequence"
    assert len(pm0.applied) >= 4
    assert pm0.best == pm1.best == (2 * MIB, 0.5, None), \
        "rank 0's preference must win on both ranks"
    assert (rt1.coordinator.fusion_threshold
            == rt0.coordinator.fusion_threshold == 2 * MIB)


def test_divergent_cache_files_follow_rank0_warm_decision(
        monkeypatch, tmp_path, logspy):
    """SPMD warm start with per-host cache drift: rank 0 has a valid
    entry, rank 1's file is empty. Rank 0's decision AND config
    broadcast — both ranks warm-start identically instead of rank 1
    forking into a sweep (divergent phases = divergent collective
    schedules)."""
    from horovod_tpu.autotune import ParameterManager, store
    _tiny_grid(monkeypatch)
    monkeypatch.setenv("HVDTPU_AUTOTUNE_SIGNATURE", "sig-d")
    rt0, rt1 = _spmd_pair()
    cache0 = str(tmp_path / "cache.rank0.json")
    cache1 = str(tmp_path / "cache.rank1.json")   # never populated
    key = store.make_key("sig-d", 2, store.codec_signature(rt0))
    store.save_entry(cache0, key, _entry(fusion=3 * MIB, cycle=2.0))

    monkeypatch.setenv("HVDTPU_AUTOTUNE_CACHE", cache0)
    pm0 = ParameterManager(rt0)
    monkeypatch.setenv("HVDTPU_AUTOTUNE_CACHE", cache1)
    pm1 = ParameterManager(rt1)

    for rt, pm in ((rt0, pm0), (rt1, pm1)):
        rt.coordinator.bytes_processed += 10
        pm.record_cycle()

    assert not pm0.enabled and not pm1.enabled
    assert pm0.applied == pm1.applied
    assert pm1.best == (3 * MIB, 2.0, None)
    assert rt1.coordinator.fusion_threshold == 3 * MIB
    # Rank 1 warm-started on the broadcast: its rank-LOCAL miss must
    # not be logged/counted as the run's outcome.
    assert not logspy.grep("no cache entry")


# ==========================================================================
# Knob registry
# ==========================================================================

def test_autotune_knobs_registered():
    from horovod_tpu.utils import envparse
    for name in ("AUTOTUNE_CACHE", "AUTOTUNE_SIGNATURE",
                 "AUTOTUNE_SCORE", "AUTOTUNE_CONFIRM_CYCLES",
                 "AUTOTUNE_BUCKET_BYTES_CANDIDATES_MIB",
                 "AUTOTUNE_COMPRESSION_CANDIDATES",
                 "AUTOTUNE_COMPRESSION_THRESHOLD_CANDIDATES",
                 "AUTOTUNE_ZERO_BUCKET_CANDIDATES_MIB"):
        assert name in envparse.KNOBS, name


# ==========================================================================
# hvd-autotune CLI
# ==========================================================================

def _cli(argv):
    from horovod_tpu.autotune import cli
    return cli.main(argv)


def test_cli_show_history_diff_clear(tmp_path, capsys):
    from horovod_tpu.autotune import store
    cache = str(tmp_path / "cache.json")
    old = str(tmp_path / "old.json")
    store.save_entry(old, "k1", _entry(fusion=MIB, score=10.0))
    store.save_entry(cache, "k1", _entry(fusion=3 * MIB, score=42.0))
    store.save_entry(cache, "k2", _entry(fusion=MIB))

    assert _cli(["show", "--cache", cache]) == 0
    out = capsys.readouterr().out
    assert "k1" in out and f"fusion_threshold={3 * MIB}" in out

    assert _cli(["show", "--cache", cache, "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert set(data) == {"k1", "k2"}

    assert _cli(["history", "--cache", cache, "--key", "k1"]) == 0
    out = capsys.readouterr().out
    assert "host" in out and "42.0" in out

    # Two entries and no --key: refuse rather than guess.
    with pytest.raises(SystemExit) as exc:
        _cli(["history", "--cache", cache])
    assert exc.value.code == 1

    assert _cli(["diff", old, cache]) == 0
    out = capsys.readouterr().out
    assert "+ k2" in out
    assert f"fusion_threshold: {MIB} -> {3 * MIB}" in out
    assert "score: 10.0 -> 42.0" in out

    assert _cli(["clear", "--cache", cache, "--key", "k2"]) == 0
    capsys.readouterr()
    assert set(store.load(cache)) == {"k1"}
    assert _cli(["clear", "--cache", cache]) == 0
    capsys.readouterr()
    assert not os.path.exists(cache)


def test_cli_empty_corrupt_and_missing_path(tmp_path, capsys,
                                            monkeypatch):
    monkeypatch.delenv("HVDTPU_AUTOTUNE_CACHE", raising=False)
    with pytest.raises(SystemExit) as exc:
        _cli(["show"])
    assert exc.value.code == 1

    empty = str(tmp_path / "missing.json")
    assert _cli(["show", "--cache", empty]) == 0
    assert "(empty store)" in capsys.readouterr().out

    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    with pytest.raises(SystemExit) as exc:
        _cli(["show", "--cache", str(bad)])
    assert exc.value.code == 2
