"""Chaos subsystem + control-plane hardening unit tests.

Covers the HVDTPU_CHAOS spec grammar, the injection engine's firing
semantics (counters, markers, matchers) and its disabled-mode no-op
guard (the same acceptance contract as telemetry's), the KV client's
retry/backoff/classification, the wait_for_kv transient-error fix, the
heartbeat lease + driver liveness detection, the SIGTERM→SIGKILL
escalation in the driver's stopping reaper, graceful-preemption flag
plumbing, and the hvd-chaos CLI. Whole-job chaos scenarios live in
tests/test_chaos_matrix.py (slow lane).
"""

import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error

import pytest

from conftest import clean_spawn_env
from horovod_tpu import chaos
from horovod_tpu.chaos.spec import ChaosSpecError, parse_spec
from horovod_tpu.exceptions import ChaosInjectedError, HorovodInternalError
from horovod_tpu.runner import http_client
from horovod_tpu.runner.elastic_driver import (ElasticDriver,
                                               ElasticSettings, _Worker)
from horovod_tpu.runner.heartbeat import HeartbeatThread, LivenessTracker
from horovod_tpu.runner.http_server import KVStoreServer
from horovod_tpu.runner.job import Settings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_chaos():
    """Each test resolves HVDTPU_CHAOS from ITS env: clear the cached
    plan (and any firing state) around every test."""
    chaos.reset()
    yield
    chaos.reset()


def _arm(monkeypatch, spec):
    monkeypatch.setenv("HVDTPU_CHAOS", spec)
    chaos.reset()


# ==========================================================================
# Spec grammar
# ==========================================================================
class TestSpecParsing:
    def test_issue_examples_parse(self):
        rules = parse_spec(
            "kv_get:fail:n=3;kv_put:delay:ms=500;worker:hang:rank=1;"
            "worker:preempt:rank=2:after_commits=3;"
            "collective:fail:name=grad_*:once")
        assert [r.point for r in rules] == [
            "kv_get", "kv_put", "worker", "worker", "collective"]
        assert rules[0].n == 3
        assert rules[1].ms == 500
        assert rules[2].rank == 1
        assert rules[3].after_commits == 3
        assert rules[4].name == "grad_*" and rules[4].n == 1  # once

    def test_colon_in_value_rejoined(self):
        (rule,) = parse_spec("worker:hang:wid=localhost:1")
        assert rule.wid == "localhost:1"

    def test_empty_spec_and_separators(self):
        assert parse_spec("") == []
        assert len(parse_spec(" kv_get:fail ; ; kv_put:fail ")) == 2

    @pytest.mark.parametrize("bad", [
        "kv_get",                        # no action
        "nosuchpoint:fail",              # unknown point
        "kv_get:explode",                # unknown action
        "kv_get:fail:bogus=1",           # unknown param
        "kv_get:fail:n=three",           # non-integer
        "kv_get:fail:p=2.0",             # p out of range
        "kv_get:fail:err=nuke",          # unknown error kind
        "kv_get:fail:n=3:once",          # ambiguous budget
        "kv_get:fail:once:n=3",          # ambiguous budget, either order
    ])
    def test_invalid_specs_raise(self, bad):
        with pytest.raises(ChaosSpecError):
            parse_spec(bad)

    def test_malformed_env_spec_fails_loud_at_injection(self, monkeypatch):
        _arm(monkeypatch, "kv_get:explode")
        with pytest.raises(ChaosSpecError):
            chaos.inject("kv_get", scope="s", key="k")

    def test_signal_actions_parse_at_their_points(self):
        rules = parse_spec(
            "collective:mismatch:rank=1:name=step2;"
            "collective:stall:name=grad_*;"
            "backend_submit:stall:kind=allreduce;"
            "checkpoint:corrupt:name=step_4")
        assert [r.action for r in rules] == [
            "mismatch", "stall", "stall", "corrupt"]

    @pytest.mark.parametrize("bad", [
        "kv_get:mismatch",        # digest corruption has no KV meaning
        "worker:stall",           # commit boundaries can't swallow ops
        "collective:corrupt",     # corruption is a checkpoint effect
        "checkpoint:stall",       # saves aren't negotiated submissions
    ])
    def test_signal_actions_rejected_at_foreign_points(self, bad):
        with pytest.raises(ChaosSpecError, match="only valid at"):
            parse_spec(bad)

    def test_signal_actions_raise_chaos_signal_at_inject(self,
                                                         monkeypatch):
        _arm(monkeypatch, "collective:stall:name=ghost")
        with pytest.raises(chaos.ChaosSignal) as ei:
            chaos.inject("collective", name="ghost", kind="allreduce")
        assert ei.value.action == "stall"
        # Non-matching context: no signal.
        chaos.inject("collective", name="fine", kind="allreduce")


# ==========================================================================
# Disabled mode: the no-op guard (acceptance criterion)
# ==========================================================================
class TestDisabledGuard:
    def test_unset_resolves_to_shared_null_plan(self, monkeypatch):
        monkeypatch.delenv("HVDTPU_CHAOS", raising=False)
        chaos.reset()
        assert chaos.plan() is chaos.NULL_PLAN
        assert not chaos.enabled()
        # Injection points are no-ops: no exception, no state, and the
        # resolved plan is the shared singleton (nothing accumulates).
        chaos.inject("kv_get", scope="s", key="k")
        chaos.inject("collective", name="grad_w")
        chaos.inject("worker", commits=99)
        assert chaos.plan() is chaos.NULL_PLAN
        assert chaos.plan().rules == ()

    def test_hot_paths_cache_disabled_flag(self, hvd):
        import horovod_tpu.basics as basics
        assert not chaos.enabled()
        coord = basics.runtime().coordinator
        assert coord._chaos_on is False


# ==========================================================================
# Injection engine: counting, matchers, determinism
# ==========================================================================
class TestInjection:
    def test_fail_counts_down_then_stops(self, monkeypatch):
        _arm(monkeypatch, "kv_get:fail:n=2")
        for _ in range(2):
            with pytest.raises(urllib.error.URLError):
                chaos.inject("kv_get", scope="s", key="k")
        chaos.inject("kv_get", scope="s", key="k")  # budget spent

    def test_after_skips_first_matches(self, monkeypatch):
        _arm(monkeypatch, "kv_get:fail:after=1:n=1")
        chaos.inject("kv_get", scope="s", key="k")  # skipped
        with pytest.raises(urllib.error.URLError):
            chaos.inject("kv_get", scope="s", key="k")
        chaos.inject("kv_get", scope="s", key="k")  # n spent

    def test_name_glob_matcher(self, monkeypatch):
        _arm(monkeypatch, "collective:fail:name=grad_*:once")
        chaos.inject("collective", name="loss")
        with pytest.raises(HorovodInternalError):
            chaos.inject("collective", name="grad_w")
        chaos.inject("collective", name="grad_w")  # once

    def test_scope_matcher(self, monkeypatch):
        _arm(monkeypatch, "kv_get:fail:scope=elastic:n=1")
        chaos.inject("kv_get", scope="peers.0", key="0")
        with pytest.raises(urllib.error.URLError):
            chaos.inject("kv_get", scope="elastic", key="version")

    def test_rank_matcher_reads_env(self, monkeypatch):
        monkeypatch.setenv("HVDTPU_RANK", "1")
        _arm(monkeypatch, "worker:fail:rank=1")
        with pytest.raises(ChaosInjectedError):
            chaos.inject("worker", commits=1)
        monkeypatch.setenv("HVDTPU_RANK", "0")
        chaos.reset()
        chaos.inject("worker", commits=1)  # wrong rank: no fire

    def test_after_commits_matcher(self, monkeypatch):
        _arm(monkeypatch, "worker:fail:after_commits=2")
        chaos.inject("worker", commits=1)
        chaos.inject("worker", commits=2)
        with pytest.raises(ChaosInjectedError):
            chaos.inject("worker", commits=3)

    def test_delay_sleeps(self, monkeypatch):
        _arm(monkeypatch, "kv_put:delay:ms=60")
        t0 = time.monotonic()
        chaos.inject("kv_put", scope="s", key="k")
        assert time.monotonic() - t0 >= 0.05

    def test_marker_fires_once_per_job(self, monkeypatch, tmp_path):
        marker = tmp_path / "fired.marker"
        _arm(monkeypatch, f"kv_put:fail:marker={marker}")
        with pytest.raises(urllib.error.URLError):
            chaos.inject("kv_put", scope="s", key="k")
        assert marker.exists()
        # A "respawned process" (fresh firing state) sees the marker and
        # skips — the cross-process fire-once lease.
        chaos.reset()
        chaos.inject("kv_put", scope="s", key="k")

    def test_err_kinds_shape_the_exception(self, monkeypatch):
        _arm(monkeypatch, "kv_get:fail:err=timeout:n=1")
        with pytest.raises(TimeoutError):
            chaos.inject("kv_get", scope="s", key="k")
        _arm(monkeypatch, "kv_get:fail:err=refused:n=1")
        with pytest.raises(urllib.error.URLError) as ei:
            chaos.inject("kv_get", scope="s", key="k")
        assert isinstance(ei.value.reason, ConnectionRefusedError)

    def test_chaos_log_records_firings(self, monkeypatch, tmp_path):
        log = tmp_path / "chaos.log"
        monkeypatch.setenv("HVDTPU_CHAOS_LOG", str(log))
        _arm(monkeypatch, "kv_get:fail:n=2")
        for _ in range(2):
            with pytest.raises(urllib.error.URLError):
                chaos.inject("kv_get", scope="s", key="k")
        lines = log.read_text().splitlines()
        assert len(lines) == 2
        assert all("kv_get fail" in line for line in lines)

    def test_seeded_sampling_is_deterministic(self, monkeypatch):
        def outcomes():
            _arm(monkeypatch, "kv_get:fail:p=0.5:seed=7")
            fired = []
            for _ in range(16):
                try:
                    chaos.inject("kv_get", scope="s", key="k")
                    fired.append(False)
                except urllib.error.URLError:
                    fired.append(True)
            return fired
        first, second = outcomes(), outcomes()
        assert first == second
        assert any(first) and not all(first)


# ==========================================================================
# KV client: retry/backoff/classification (tentpole part 2)
# ==========================================================================
@pytest.fixture
def kv_server():
    server = KVStoreServer()
    server.start()
    yield server
    server.stop()


class TestKVRetry:
    def test_recovers_through_transient_failures(self, monkeypatch,
                                                 kv_server):
        http_client.put_kv("127.0.0.1", kv_server.port, "s", "k", "v")
        _arm(monkeypatch, "kv_get:fail:n=3")
        assert http_client.get_kv("127.0.0.1", kv_server.port,
                                  "s", "k") == b"v"

    def test_exhaustion_is_a_timeout_error(self):
        # Nothing listens on this freshly released port: connection
        # refused, classified retryable, budget exhausts.
        probe = KVStoreServer()
        dead_port = probe.start()
        probe.stop()
        with pytest.raises(http_client.KVRetryExhaustedError) as ei:
            http_client.get_kv("127.0.0.1", dead_port, "s", "k",
                               retries=1, backoff=0.01, deadline=0.5)
        # The classification contract elastic._retry_reset relies on.
        assert isinstance(ei.value, TimeoutError)
        assert "get s/k" in str(ei.value)

    def test_fatal_auth_error_names_scope_key_and_skips_retry(self):
        server = KVStoreServer(job_token="sekrit")
        port = server.start()
        try:
            t0 = time.monotonic()
            with pytest.raises(http_client.KVFatalError) as ei:
                http_client.delete_kv("127.0.0.1", port, "scopeX",
                                      "keyY", token="wrong")
            assert time.monotonic() - t0 < 1.0  # no backoff ladder
            msg = str(ei.value)
            assert "delete scopeX/keyY" in msg and "403" in msg
            assert ei.value.code == 403
        finally:
            server.stop()

    def test_404_returns_none_without_retry(self, kv_server):
        t0 = time.monotonic()
        assert http_client.get_kv("127.0.0.1", kv_server.port,
                                  "s", "absent") is None
        assert time.monotonic() - t0 < 1.0

    def test_retry_outcomes_feed_telemetry(self, monkeypatch, kv_server):
        from horovod_tpu import telemetry
        monkeypatch.setenv("HOROVOD_TPU_METRICS", "1")
        telemetry.reset()
        try:
            http_client.put_kv("127.0.0.1", kv_server.port, "s", "k", "v")
            _arm(monkeypatch, "kv_get:fail:n=2")
            assert http_client.get_kv("127.0.0.1", kv_server.port,
                                      "s", "k") == b"v"
            fam = telemetry.registry().families()["hvd_kv_retries_total"]
            counts = {s["labels"]["outcome"]: s["value"]
                      for s in fam.samples()
                      if s["labels"]["op"] == "get"}
            assert counts.get("retried") == 2
            assert counts.get("recovered") == 1
            inj = telemetry.registry().families()[
                "hvd_chaos_injections_total"]
            assert inj.labels(point="kv_get", action="fail").value == 2
        finally:
            monkeypatch.delenv("HOROVOD_TPU_METRICS", raising=False)
            telemetry.reset()


class TestWaitForKV:
    def test_transient_errors_mid_poll_do_not_abort(self, monkeypatch,
                                                    kv_server):
        """Satellite fix: a transport error during the poll must be
        swallowed until deadline_s — even a whole inner retry budget
        exhausting (retries=0 makes every injected failure exhaust)."""
        monkeypatch.setenv("HVDTPU_KV_RETRIES", "0")
        _arm(monkeypatch, "kv_get:fail:n=6")
        port = kv_server.port

        def publish():
            time.sleep(0.25)
            kv_server.put("s", "late", b"arrived")

        t = threading.Thread(target=publish)
        t.start()
        try:
            value = http_client.wait_for_kv("127.0.0.1", port, "s",
                                            "late", deadline_s=10,
                                            poll_s=0.02)
        finally:
            t.join()
        assert value == b"arrived"

    def test_deadline_expiry_reports_last_transport_error(
            self, monkeypatch, kv_server):
        monkeypatch.setenv("HVDTPU_KV_RETRIES", "0")
        _arm(monkeypatch, "kv_get:fail")  # unlimited blackout
        with pytest.raises(TimeoutError) as ei:
            http_client.wait_for_kv("127.0.0.1", kv_server.port, "s",
                                    "never", deadline_s=0.3, poll_s=0.02)
        assert "last transport error" in str(ei.value)

    def test_kv_wait_fail_injection_is_swallowed(self, monkeypatch,
                                                 kv_server):
        """A kv_wait:fail injection is a transient transport error like
        any other: it must not abort the wait before its deadline."""
        _arm(monkeypatch, "kv_wait:fail:n=3")
        kv_server.put("s", "k", b"v")
        assert http_client.wait_for_kv("127.0.0.1", kv_server.port,
                                       "s", "k", deadline_s=5,
                                       poll_s=0.02) == b"v"

    def test_fatal_errors_still_propagate(self, monkeypatch):
        server = KVStoreServer(job_token="sekrit")
        port = server.start()
        try:
            with pytest.raises(http_client.KVFatalError):
                http_client.wait_for_kv("127.0.0.1", port, "s", "k",
                                        token="wrong", deadline_s=5)
        finally:
            server.stop()


# ==========================================================================
# Heartbeat lease + liveness tracking (tentpole part 3)
# ==========================================================================
class TestHeartbeat:
    def test_worker_thread_beats_and_values_change(self, kv_server):
        hb = HeartbeatThread("127.0.0.1", kv_server.port, "", "w0",
                             interval_s=0.05).start()
        try:
            deadline = time.monotonic() + 5
            while (kv_server.get("heartbeat", "w0") is None
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            v1 = kv_server.get("heartbeat", "w0")
            assert v1 is not None
            deadline = time.monotonic() + 5
            while (kv_server.get("heartbeat", "w0") == v1
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert kv_server.get("heartbeat", "w0") != v1
        finally:
            hb.stop()

    def test_beats_survive_injected_failures(self, monkeypatch,
                                             kv_server):
        _arm(monkeypatch, "heartbeat:fail:n=2")
        hb = HeartbeatThread("127.0.0.1", kv_server.port, "", "w1",
                             interval_s=0.05).start()
        try:
            deadline = time.monotonic() + 5
            while (kv_server.get("heartbeat", "w1") is None
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            # The first two beats were injected away; the thread kept
            # going and the lease still landed.
            assert kv_server.get("heartbeat", "w1") is not None
        finally:
            hb.stop()

    def test_liveness_tracker_change_detection(self):
        t = LivenessTracker(0.1)
        now = 100.0
        assert not t.observe("w", b"a", now)          # first sight
        assert not t.observe("w", b"a", now + 0.05)   # within timeout
        assert t.observe("w", b"a", now + 0.25)       # expired
        assert not t.observe("w", b"b", now + 0.3)    # changed: fresh
        t.forget("w")
        assert not t.observe("w", b"b", now + 9.0)    # forgotten: fresh


class _FakeProc:
    def __init__(self):
        self.terminated = False
        self.killed = False

    def poll(self):
        return None

    def wait(self, *a):
        return 0

    def terminate(self):
        self.terminated = True

    def kill(self):
        self.killed = True


def _fake_spawn(driver):
    def spawn(worker_id, host, idx):
        driver.workers[worker_id] = _Worker(worker_id, host, idx,
                                            _FakeProc())
    return spawn


class TestDriverLiveness:
    def _driver(self, monkeypatch, **kw):
        es = ElasticSettings(Settings(num_proc=2), min_np=1, **kw)
        driver = ElasticDriver(es, ["true"])
        monkeypatch.setattr(driver, "_spawn", _fake_spawn(driver))
        driver._reconcile(driver._discover_targets())
        return driver

    def test_stale_lease_fails_worker_via_stopping_path(self,
                                                        monkeypatch):
        driver = self._driver(monkeypatch, heartbeat_timeout=0.15,
                              sigkill_deadline=0.2)
        try:
            driver.server.put("heartbeat", "localhost:0", "7:1")
            # First observation only starts the clock.
            assert driver._check_heartbeats() is False
            assert "localhost:0" in driver.workers
            time.sleep(0.2)
            assert driver._check_heartbeats() is True
            assert "localhost:0" not in driver.workers
            assert "localhost:1" in driver.workers  # never beat: exempt
            (w, _), = driver.stopping
            assert w.worker_id == "localhost:0"
            assert w.proc.terminated
            assert driver.fail_counts["localhost"] == 1
            # Lease key retired so a respawn starts clean.
            assert driver.server.get("heartbeat", "localhost:0") is None
            # Slot is re-requested on the next reconcile.
            driver._reconcile(driver._discover_targets())
            assert "localhost:0" in driver.workers
        finally:
            driver.server.stop()

    def test_changing_lease_is_live(self, monkeypatch):
        driver = self._driver(monkeypatch, heartbeat_timeout=0.15)
        try:
            driver.server.put("heartbeat", "localhost:0", "7:1")
            driver._check_heartbeats()
            time.sleep(0.2)
            driver.server.put("heartbeat", "localhost:0", "7:2")
            assert driver._check_heartbeats() is False
            assert "localhost:0" in driver.workers
        finally:
            driver.server.stop()

    def test_reaped_stopping_worker_lease_is_retired(self, monkeypatch):
        """A SIGTERM-trapping worker can re-publish its lease between
        the stop request and its commit-boundary exit; the reaper must
        retire the orphan so a respawn of the same slot is judged by
        its own beats (the never-beaten exemption holds)."""
        driver = self._driver(monkeypatch, heartbeat_timeout=0.15)
        try:
            w = driver.workers.pop("localhost:0")
            driver.server.put("heartbeat", "localhost:0", "9:42")
            w.proc.poll = lambda: 83  # exited after the re-publish
            driver.stopping = [(w, time.monotonic() + 5)]
            driver._reap_stopping()
            assert driver.stopping == []
            assert driver.server.get("heartbeat",
                                     "localhost:0") is None
        finally:
            driver.server.stop()

    def test_reaped_lease_kept_when_slot_already_respawned(self,
                                                           monkeypatch):
        """If the slot was respawned before the predecessor was reaped,
        the lease now belongs to the live successor — reaping must not
        delete it (that would blind hung-worker detection until the
        successor's next beat)."""
        driver = self._driver(monkeypatch, heartbeat_timeout=10)
        try:
            old = _Worker("localhost:0", "localhost", 0, _FakeProc())
            old.proc.poll = lambda: 83
            # Successor already running under the same wid, beating.
            assert "localhost:0" in driver.workers
            driver.server.put("heartbeat", "localhost:0", "new:1")
            driver.stopping = [(old, time.monotonic() + 5)]
            driver._reap_stopping()
            assert driver.stopping == []
            assert driver.server.get("heartbeat",
                                     "localhost:0") == b"new:1"
        finally:
            driver.server.stop()

    def test_heartbeat_config_sanity_warning(self):
        from horovod_tpu.runner.elastic_driver import \
            _check_heartbeat_config
        # Worker env interval above half the timeout: misconfigured.
        assert _check_heartbeat_config(
            30.0, {"HVDTPU_HEARTBEAT_INTERVAL": "60"})
        # Sane pairing, and disabled timeout, stay quiet.
        assert not _check_heartbeat_config(
            30.0, {"HVDTPU_HEARTBEAT_INTERVAL": "2"})
        assert not _check_heartbeat_config(
            0, {"HVDTPU_HEARTBEAT_INTERVAL": "60"})

    def test_timeout_zero_disables_liveness(self, monkeypatch):
        driver = self._driver(monkeypatch, heartbeat_timeout=0)
        try:
            driver.server.put("heartbeat", "localhost:0", "7:1")
            driver._check_heartbeats()
            time.sleep(0.05)
            assert driver._check_heartbeats() is False
            assert len(driver.workers) == 2
        finally:
            driver.server.stop()


# ==========================================================================
# SIGTERM→SIGKILL escalation (satellite: _reap_stopping coverage)
# ==========================================================================
class _ShimProc:
    """SlotProcess-shaped wrapper over a raw Popen (process-group
    signalling like the real thing)."""

    def __init__(self, proc):
        self.proc = proc

    def poll(self):
        return self.proc.poll()

    def wait(self, timeout=None):
        return self.proc.wait(timeout)

    def terminate(self):
        if self.proc.poll() is None:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)

    def kill(self):
        if self.proc.poll() is None:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)


def test_reap_stopping_escalates_to_sigkill(monkeypatch):
    """A worker that ignores SIGTERM must be SIGKILLed once its
    sigkill_deadline passes, and its slot must be re-requested."""
    code = ("import signal, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "print('ready', flush=True)\n"
            "time.sleep(60)\n")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            start_new_session=True)
    assert proc.stdout.readline().strip() == b"ready"
    es = ElasticSettings(Settings(num_proc=1), min_np=1,
                         sigkill_deadline=0.4)
    driver = ElasticDriver(es, ["true"])
    try:
        w = _Worker("localhost:0", "localhost", 0, _ShimProc(proc))
        w.proc.terminate()
        driver.stopping = [(w, time.monotonic() + 0.4)]
        driver._reap_stopping()
        time.sleep(0.15)
        assert proc.poll() is None  # SIGTERM ignored, still alive
        deadline = time.monotonic() + 10
        while driver.stopping and time.monotonic() < deadline:
            driver._reap_stopping()
            time.sleep(0.05)
        assert driver.stopping == []
        assert proc.poll() == -signal.SIGKILL
        # The freed slot is re-requested by the next reconcile.
        monkeypatch.setattr(driver, "_spawn", _fake_spawn(driver))
        driver._reconcile([("localhost:0", "localhost", 0)])
        assert "localhost:0" in driver.workers
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stdout.close()
        driver.server.stop()


# ==========================================================================
# Graceful preemption plumbing (whole-job flow in test_chaos_matrix.py)
# ==========================================================================
class TestPreemption:
    def test_commit_boundary_converts_flag_to_interrupt(self):
        from horovod_tpu import elastic
        from horovod_tpu.exceptions import HostsUpdatedInterrupt
        st = elastic.ObjectState(x=1)
        st.commit()  # flag unset: plain commit
        elastic._PREEMPT["requested"] = True
        try:
            with pytest.raises(HostsUpdatedInterrupt) as ei:
                st.commit()
            assert ei.value.skip_sync
        finally:
            elastic._reset_preempt_state()

    def test_handler_not_installed_without_elastic_env(self, monkeypatch):
        from horovod_tpu import elastic
        monkeypatch.delenv("HVDTPU_ELASTIC", raising=False)
        before = signal.getsignal(signal.SIGTERM)

        class S(elastic.State):
            def save(self):
                pass

            def restore(self):
                pass

            def sync(self):
                pass

        wrapped = elastic.run_fn(lambda state: "ok", reset=lambda: None)
        assert wrapped(S()) == "ok"
        assert signal.getsignal(signal.SIGTERM) is before

    def test_preempt_exit_code_is_not_a_failure(self, monkeypatch):
        """Driver side: PREEMPT_EXIT_CODE changes membership without a
        fail count (no blacklist pressure on a graceful exit)."""
        from horovod_tpu.exceptions import PREEMPT_EXIT_CODE
        es = ElasticSettings(Settings(num_proc=2), min_np=1)
        driver = ElasticDriver(es, ["true"])
        try:
            monkeypatch.setattr(driver, "_spawn", _fake_spawn(driver))
            driver._reconcile(driver._discover_targets())
            w = driver.workers["localhost:0"]
            w.proc.poll = lambda: PREEMPT_EXIT_CODE
            assert driver._sweep_exits() is True
            assert "localhost:0" not in driver.workers
            assert driver.fail_counts == {}
            assert driver.blacklist == set()
            # A preemption during wind-down must not read as a crash
            # either (the rc-83 branch is unconditional on completing).
            driver.completing = True
            w1 = driver.workers["localhost:1"]
            w1.proc.poll = lambda: PREEMPT_EXIT_CODE
            driver._sweep_exits()
            assert driver.fail_counts == {}
        finally:
            driver.server.stop()


# ==========================================================================
# hvd-chaos CLI (console entry behavior via python -m)
# ==========================================================================
def _run_cli(*args, env_extra=None):
    env = clean_spawn_env(
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("HVDTPU_CHAOS", None)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.chaos.cli", *args],
        env=env, capture_output=True, text=True, timeout=120)


def test_cli_validates_good_spec():
    proc = _run_cli("validate",
                    "kv_get:fail:n=3;worker:preempt:rank=1:after_commits=2")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "2 rule(s)" in proc.stdout
    assert "kv_get:fail" in proc.stdout


def test_cli_rejects_bad_spec():
    proc = _run_cli("validate", "kv_get:explode")
    assert proc.returncode == 2
    assert "explode" in proc.stderr


def test_cli_validates_env_spec():
    proc = _run_cli("validate",
                    env_extra={"HVDTPU_CHAOS": "kv_put:delay:ms=500"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "kv_put:delay" in proc.stdout


def test_cli_lists_points():
    proc = _run_cli("points")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for point in ("kv_get", "collective", "worker", "heartbeat"):
        assert point in proc.stdout
    for action in ("fail", "delay", "hang", "preempt", "exit"):
        assert action in proc.stdout
