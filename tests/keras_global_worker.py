"""Keras-on-jax over the jax.distributed global mesh: one rank of an
N-process job where set_data_parallel spans every process's devices and
model.fit's jitted train step is one global-SPMD program (the multi-host
TPU deployment shape; launched by test_xla_global.py with
HVDTPU_CPU_OPERATIONS=xla)."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["KERAS_BACKEND"] = "jax"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

# The axon TPU plugin force-selects itself regardless of JAX_PLATFORMS;
# must precede backend init AND jax.distributed.initialize.
jax.config.update("jax_platforms", "cpu")

import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.keras as hk  # noqa: E402


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    import keras

    # Data is pre-sharded per process (the hvdrun idiom), so keras's
    # multi-worker auto-sharding is off; the global mesh still shards
    # each jitted step's batch across every device of every process.
    hk.set_data_parallel(auto_shard_dataset=False)
    n_local = int(os.environ.get("XGW_LOCAL_DEVICES", "2"))
    assert len(jax.devices()) == size * n_local

    keras.utils.set_random_seed(0)
    model = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(1),
    ])
    model.compile(
        optimizer=hk.DistributedOptimizer(keras.optimizers.SGD(0.05)),
        loss="mse")

    rng = np.random.RandomState(7)
    X = rng.randn(64, 8).astype(np.float32)
    y = (X @ rng.randn(8, 1)).astype(np.float32)
    per = 64 // size
    Xl, yl = X[rank * per:(rank + 1) * per], y[rank * per:(rank + 1) * per]
    hist = model.fit(Xl, yl, batch_size=per // 2, epochs=2, shuffle=False,
                     verbose=0)
    losses = hist.history["loss"]
    assert losses[-1] < losses[0], losses

    # The global-SPMD step keeps weights replicated: every rank holds
    # the identical trained model.
    from horovod_tpu.functions import allgather_object
    w = [np.asarray(x) for x in model.get_weights()]
    all_w = allgather_object(w)
    for rank_w in all_w[1:]:
        for a, b in zip(rank_w, all_w[0]):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    print(f"rank {rank}/{size}: KERAS-GLOBAL OK", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
