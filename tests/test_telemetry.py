"""telemetry/: metrics core, spans, exposition, aggregation, /metrics
route, hvd-metrics CLI, and the timeline flush/stop fixes.

The disabled path is a load-bearing contract (near-zero cost, nothing
accumulates), so it gets its own guard tests against the session
runtime; the enabled path runs end-to-end in a fresh subprocess (the
session fixture initializes without HOROVOD_TPU_METRICS).
"""

import json
import os
import subprocess
import sys
import threading
import time
import types
import urllib.error
import urllib.request

import jax  # noqa: F401  (backend pinned to the CPU mesh by conftest)
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import clean_spawn_env
from horovod_tpu import telemetry
from horovod_tpu.telemetry import aggregate, core, exposition
from horovod_tpu.runner.http_server import AUTH_HEADER, KVStoreServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def metrics_on(monkeypatch):
    """Force-enable the metrics plane for one test, on a fresh registry;
    restore the disabled default (and a clean registry) afterwards."""
    monkeypatch.setenv("HOROVOD_TPU_METRICS", "1")
    telemetry.reset()
    assert telemetry.enabled()
    yield telemetry
    monkeypatch.delenv("HOROVOD_TPU_METRICS", raising=False)
    telemetry.reset()


# ==========================================================================
# Core: histogram bucket edges, label plumbing, registry semantics
# ==========================================================================
class TestHistogramBuckets:
    def test_bucket_boundary_edges(self, metrics_on):
        h = telemetry.histogram("hvd_test_edges", buckets=[1.0, 2.0, 4.0])
        child = h.labels()
        for v in (0.0, 0.5, 1.0):     # le="1" is inclusive
            child.observe(v)
        child.observe(1.0000001)      # first value past an edge
        child.observe(4.0)            # exactly the last finite bound
        child.observe(4.1)            # overflows into +Inf
        buckets = dict(child.bucket_counts())
        assert buckets[1.0] == 3
        assert buckets[2.0] == 4      # cumulative
        assert buckets[4.0] == 5
        assert buckets[float("inf")] == 6
        assert child.count == 6
        assert child.sum == pytest.approx(0.5 + 1.0 + 1.0000001 + 4.0
                                          + 4.1)

    def test_log_buckets_cover_range(self):
        bounds = core.log_buckets(1e-5, 80.0)
        assert bounds[0] == 1e-5
        assert bounds[-1] >= 80.0
        ratios = [b / a for a, b in zip(bounds, bounds[1:])]
        assert all(r == pytest.approx(2.0) for r in ratios)

    def test_labels_and_registry_reuse(self, metrics_on):
        c1 = telemetry.counter("hvd_test_ops", labelnames=("kind",))
        c2 = telemetry.counter("hvd_test_ops", labelnames=("kind",))
        assert c1 is c2  # get-or-create across modules
        c1.labels(kind="a").inc(2)
        c1.labels(kind="b").inc()
        sample_values = {s["labels"]["kind"]: s["value"]
                         for s in c1.samples()}
        assert sample_values == {"a": 2, "b": 1}
        with pytest.raises(ValueError):
            telemetry.counter("hvd_test_ops", labelnames=("other",))
        with pytest.raises(ValueError):
            c1.labels(wrong="x")


# ==========================================================================
# Exposition: Prometheus v0.0.4 golden text
# ==========================================================================
GOLDEN = """\
# HELP hvd_test_depth Depth
# TYPE hvd_test_depth gauge
hvd_test_depth 2.5
# HELP hvd_test_lat_seconds Lat
# TYPE hvd_test_lat_seconds histogram
hvd_test_lat_seconds_bucket{le="0.1"} 1
hvd_test_lat_seconds_bucket{le="1"} 1
hvd_test_lat_seconds_bucket{le="+Inf"} 2
hvd_test_lat_seconds_sum 5.05
hvd_test_lat_seconds_count 2
# HELP hvd_test_ops_total Ops
# TYPE hvd_test_ops_total counter
hvd_test_ops_total{kind="allreduce"} 3
"""


def test_prometheus_exposition_golden():
    reg = core.Registry()
    reg.gauge("hvd_test_depth", "Depth").set(2.5)
    h = reg.histogram("hvd_test_lat_seconds", "Lat", buckets=[0.1, 1.0])
    h.observe(0.05)
    h.observe(5.0)
    reg.counter("hvd_test_ops_total", "Ops",
                labelnames=("kind",)).labels(kind="allreduce").inc(3)
    assert exposition.render_prometheus(reg.snapshot()) == GOLDEN


def test_prometheus_label_escaping_and_parse():
    reg = core.Registry()
    g = reg.gauge("hvd_test_esc", labelnames=("path",))
    g.labels(path='a"b\\c\nd').set(1)
    text = exposition.render_prometheus(reg.snapshot())
    assert '{path="a\\"b\\\\c\\nd"}' in text
    parsed = exposition.parse_prometheus(text)
    assert list(parsed) == ["hvd_test_esc"]
    assert list(parsed["hvd_test_esc"].values()) == [1.0]


# ==========================================================================
# Spans
# ==========================================================================
class _FakeTimeline:
    def __init__(self):
        self.events = []

    def begin(self, names, activity):
        self.events.append(("B", tuple(names), activity))

    def end(self, names, activity):
        self.events.append(("E", tuple(names), activity))


def test_span_feeds_histogram_and_timeline():
    reg = core.Registry()
    hist = reg.histogram("hvd_test_span_seconds", buckets=[10.0])
    tl = _FakeTimeline()
    with telemetry.span(["x", "y"], "ACT", timeline=tl, histogram=hist):
        pass
    assert tl.events == [("B", ("x", "y"), "ACT"),
                         ("E", ("x", "y"), "ACT")]
    assert hist.labels().count == 1


def test_span_failure_leaves_timeline_open_but_observes():
    reg = core.Registry()
    hist = reg.histogram("hvd_test_span_fail_seconds", buckets=[10.0])
    tl = _FakeTimeline()
    with pytest.raises(RuntimeError):
        with telemetry.span(["x"], "ACT", timeline=tl, histogram=hist):
            raise RuntimeError("boom")
    assert tl.events == [("B", ("x",), "ACT")]  # no end on failure
    assert hist.labels().count == 1

def test_span_null_when_both_sinks_absent():
    assert telemetry.span(["a"], "X") is telemetry.NULL_SPAN
    assert telemetry.span(["a"], "X",
                          histogram=telemetry.NULL) is telemetry.NULL_SPAN
    assert telemetry.span(
        ["a"], "X", timeline=_FakeTimeline()) is not telemetry.NULL_SPAN


# ==========================================================================
# Disabled mode: the no-op guard (acceptance criterion)
# ==========================================================================
class TestDisabledGuard:
    def test_factories_return_shared_null(self, hvd):
        assert not telemetry.enabled()
        c = telemetry.counter("hvd_guard_should_not_exist")
        assert c is telemetry.NULL
        assert c.labels(kind="x") is telemetry.NULL
        c.inc()
        c.observe(1.0)
        c.set(2.0)
        assert telemetry.registry().families() == {}

    def test_hot_path_accumulates_nothing(self, hvd, n_devices):
        import horovod_tpu.basics as basics
        coord = basics.runtime().coordinator
        assert coord._m_cycle_s is telemetry.NULL
        assert coord._metrics_on is False
        out = hvd.allreduce(jnp.ones((n_devices, 2)), op=hvd.Sum,
                            name="telemetry.guard.allreduce")
        assert np.asarray(out).shape == (n_devices, 2)
        assert telemetry.registry().families() == {}
        snap = hvd.metrics_snapshot()
        assert snap["families"] == {}
        assert snap["rank"] == hvd.rank()


# ==========================================================================
# Cluster aggregation
# ==========================================================================
def _counter_snap(value):
    return {"ts": 0.0, "families": {"hvd_x_total": {
        "type": "counter", "help": "x", "labelnames": [],
        "samples": [{"labels": {}, "value": value}]}}}


def test_quantile_from_buckets():
    buckets = [(1.0, 50), (2.0, 90), (4.0, 100), (float("inf"), 100)]
    assert aggregate.quantile_from_buckets(buckets, 0.50) == 1.0
    assert aggregate.quantile_from_buckets(buckets, 0.95) == 4.0
    assert aggregate.quantile_from_buckets(buckets, 0.99) == 4.0
    assert aggregate.quantile_from_buckets([], 0.99) == 0.0


def test_scalar_rollup_min_max_mean():
    rolled = aggregate.aggregate({0: _counter_snap(1.0),
                                  1: _counter_snap(3.0)})
    fam = rolled["families"]["hvd_x_total_cluster"]
    stats = {s["labels"]["stat"]: s["value"] for s in fam["samples"]}
    assert stats == {"min": 1.0, "max": 3.0, "mean": 2.0, "sum": 4.0}
    text = exposition.render_prometheus(rolled)
    assert 'hvd_x_total_cluster{stat="mean"} 2' in text


def test_histogram_rollup_merges_buckets():
    def snap(cum):
        return {"ts": 0.0, "families": {"hvd_h_seconds": {
            "type": "histogram", "help": "", "labelnames": [],
            "samples": [{"labels": {}, "sum": 1.0, "count": cum[-1][1],
                         "buckets": cum}]}}}
    rolled = aggregate.aggregate({
        0: snap([[1.0, 90], [float("inf"), 100]]),
        1: snap([[1.0, 100], [float("inf"), 100]])})
    fam = rolled["families"]["hvd_h_seconds_cluster"]
    stats = {s["labels"]["stat"]: s["value"] for s in fam["samples"]}
    assert stats["count"] == 200
    assert stats["p50"] == 1.0
    # p99 target (198 of 200) falls in +Inf: reported as the last
    # finite bound, not infinity.
    assert stats["p99"] == pytest.approx(1.0)


def test_push_and_scrape_store(metrics_on):
    telemetry.counter("hvd_push_total").inc(7)
    srv = KVStoreServer(job_token="tok")
    port = srv.start()
    try:
        aggregate.push_snapshot("127.0.0.1", port, "tok", 3)
        snaps = aggregate.store_snapshots(srv)
        assert 3 in snaps
        value = snaps[3]["families"]["hvd_push_total"]["samples"][0]
        assert value["value"] == 7
    finally:
        srv.stop()


# ==========================================================================
# /metrics route (auth + content)
# ==========================================================================
def _get(port, path, token=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    if token:
        req.add_header(AUTH_HEADER, token)
    return urllib.request.urlopen(req, timeout=10)


class TestMetricsRoute:
    def test_token_required(self):
        srv = KVStoreServer(job_token="s3cret")
        port = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(port, "/metrics")
            assert err.value.code == 403
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(port, "/metrics.json", token="wrong")
            assert err.value.code == 403
        finally:
            srv.stop()

    def test_route_serves_prometheus_and_rollup(self, metrics_on):
        telemetry.counter("hvd_route_total").inc(5)
        srv = KVStoreServer(job_token="tok")
        port = srv.start()
        try:
            with _get(port, "/metrics", token="tok") as resp:
                assert resp.headers["Content-Type"].startswith(
                    "text/plain; version=0.0.4")
                text = resp.read().decode()
            parsed = exposition.parse_prometheus(text)
            assert parsed["hvd_route_total"] == {(): 5.0}
            # pushed rank snapshots appear as the cluster roll-up
            aggregate.push_snapshot("127.0.0.1", port, "tok", 0)
            aggregate.push_snapshot("127.0.0.1", port, "tok", 1)
            with _get(port, "/metrics", token="tok") as resp:
                text = resp.read().decode()
            assert 'hvd_route_total_cluster{stat="mean"}' in text
            with _get(port, "/metrics.json", token="tok") as resp:
                payload = json.loads(resp.read())
            assert sorted(payload["ranks"]) == ["0", "1"]
            assert "hvd_route_total" in payload["local"]["families"]
        finally:
            srv.stop()


# ==========================================================================
# End-to-end: coordinator/backend/elastic/autotune families on the CPU
# backend, snapshot + exposition, HVDTPU_METRICS_DUMP (fresh process —
# the session runtime initialized with metrics off)
# ==========================================================================
E2E_SCRIPT = """
import json, sys
import horovod_tpu as hvd
import jax, jax.numpy as jnp
hvd.init()
n = len(jax.devices())
for i in range(4):
    hvd.allreduce(jnp.ones((n, 8)), op=hvd.Sum, name=f"m.{i}")
hvd.allgather(jnp.ones((n, 2)), name="m.ag")
hvd.broadcast(jnp.ones((n, 2)), root_rank=0, name="m.bc")
import horovod_tpu.elastic as elastic
state = elastic.ObjectState(step=1)
state.commit()
snap = hvd.metrics_snapshot()
from horovod_tpu import telemetry
text = telemetry.render_prometheus(snap)
assert telemetry.parse_prometheus(text), "unparseable exposition"
print("FAMILIES=" + json.dumps(sorted(snap["families"])))
hvd.shutdown()
print("E2E-OK")
"""


def test_e2e_counters_cpu_backend(tmp_path):
    dump = tmp_path / "metrics.json"
    env = clean_spawn_env(
        PYTHONPATH=REPO,
        HOROVOD_TPU_METRICS="1",
        HVDTPU_AUTOTUNE="1",
        HVDTPU_METRICS_DUMP=str(dump),
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.run([sys.executable, "-c", E2E_SCRIPT], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "E2E-OK" in proc.stdout
    families = json.loads(
        proc.stdout.split("FAMILIES=")[1].splitlines()[0])
    # Every instrumented layer reports (acceptance criterion).
    for needle in ("hvd_coordinator_ops_total",
                   "hvd_coordinator_cycle_seconds",
                   "hvd_coordinator_fused_bytes_total",
                   "hvd_backend_collective_seconds",
                   "hvd_backend_collective_bytes_total",
                   "hvd_elastic_commits_total",
                   "hvd_autotune_fusion_threshold_bytes",
                   "hvd_autotune_cycle_time_ms"):
        assert needle in families, (needle, families)
    # Shutdown wrote the HVDTPU_METRICS_DUMP snapshot.
    dumped = json.loads(dump.read_text())
    assert "hvd_coordinator_ops_total" in dumped["families"]
    ops = {s["labels"]["kind"]: s["value"]
           for s in dumped["families"]
           ["hvd_coordinator_ops_total"]["samples"]}
    assert ops.get("allreduce", 0) >= 4
    assert ops.get("allgather", 0) >= 1
    assert ops.get("broadcast", 0) >= 1
    eff = dumped["families"]["hvd_coordinator_fusion_efficiency"]
    assert 0.0 < eff["samples"][0]["value"] <= 1.0


# ==========================================================================
# hvd-metrics CLI
# ==========================================================================
def _run_cli(*args):
    env = clean_spawn_env(PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.telemetry.cli", *args],
        env=env, capture_output=True, text=True, timeout=120)


def test_cli_dump_and_diff(tmp_path):
    before = tmp_path / "a.json"
    after = tmp_path / "b.json"
    before.write_text(json.dumps(_counter_snap(3.0)))
    after.write_text(json.dumps(_counter_snap(10.0)))
    dump = _run_cli("dump", str(before))
    assert dump.returncode == 0, dump.stderr
    assert "hvd_x_total 3" in dump.stdout
    dump_json = _run_cli("dump", str(before), "--format", "json")
    assert json.loads(dump_json.stdout)["families"]
    diff = _run_cli("diff", str(before), str(after))
    assert diff.returncode == 0, diff.stderr
    assert "(+7)" in diff.stdout
    assert "1 series changed" in diff.stdout


def test_cli_usage_errors():
    assert _run_cli("dump").returncode == 2
    assert _run_cli("dump", "/nonexistent.json").returncode == 2


# ==========================================================================
# Timeline satellites: flush once per drain, race-free stop
# ==========================================================================
def test_timeline_flushes_once_per_drain(tmp_path):
    from horovod_tpu.timeline import Timeline
    path = tmp_path / "trace.json"
    tl = Timeline(str(path))
    hold = threading.Event()
    first = [True]
    orig = tl._emit_item

    def gated(file, item, *rest):
        if first[0]:
            first[0] = False
            hold.wait(10)
        orig(file, item, *rest)

    tl._emit_item = gated
    tl.start()
    flushes = [0]
    orig_flush = tl._file.flush

    def counting_flush():
        flushes[0] += 1
        orig_flush()

    tl._file.flush = counting_flush
    for i in range(100):
        tl.marker(f"m{i}")
    hold.set()
    tl.stop()
    events = json.loads(path.read_text())
    assert len(events) == 100
    # One drain (plus at most a straggler) — not one flush per event.
    assert flushes[0] <= 3, flushes[0]


def test_timeline_stop_race_free_when_join_times_out(tmp_path):
    """stop() must NOT close the file while the writer is still
    draining (the pre-fix ValueError-on-closed-file race); the writer
    closes it after the sentinel."""
    from horovod_tpu.timeline import Timeline
    path = tmp_path / "trace.json"
    tl = Timeline(str(path))
    hold = threading.Event()
    orig = tl._emit_item

    def blocked(file, item, *rest):
        hold.wait(10)
        orig(file, item, *rest)

    tl._emit_item = blocked
    tl.start()
    tl.marker("m0")
    time.sleep(0.05)  # writer is now blocked inside _emit_item
    real_thread = tl._thread
    tl._thread = types.SimpleNamespace(join=lambda timeout=None: None)
    tl.stop()  # simulated join timeout: returns with the writer alive
    assert not tl._file.closed
    hold.set()
    real_thread.join(5)
    assert tl._file.closed
    events = json.loads(path.read_text())
    assert [e["name"] for e in events] == ["m0"]


def test_timeline_restart_while_old_writer_straggles(tmp_path):
    """A start() after a timed-out stop() gets a FRESH queue and file:
    the straggling writer keeps its own queue/file (finishing cleanly)
    and cannot steal the new session's events, sentinel, or comma
    placement."""
    from horovod_tpu.timeline import Timeline
    old_path = tmp_path / "old.json"
    tl = Timeline(str(old_path))
    hold = threading.Event()
    orig = tl._emit_item

    def blocked(file, item, *rest):
        hold.wait(10)
        orig(file, item, *rest)

    tl._emit_item = blocked
    tl.start()
    tl.marker("old0")
    time.sleep(0.05)
    old_thread = tl._thread
    tl._thread = types.SimpleNamespace(join=lambda timeout=None: None)
    tl.stop()  # old writer still blocked; its sentinel is queued

    tl.path = str(tmp_path / "new.json")
    tl._emit_item = orig  # new session writes unblocked
    tl.start()
    for i in range(3):
        tl.marker(f"new{i}")
    hold.set()           # let the straggler finish its own session
    old_thread.join(5)
    tl.stop()
    old_events = json.loads(old_path.read_text())
    assert [e["name"] for e in old_events] == ["old0"]
    new_events = json.loads((tmp_path / "new.json").read_text())
    assert [e["name"] for e in new_events] == ["new0", "new1", "new2"]
