"""Elastic integration worker (launched by test_elastic.py).

The analog of the reference's per-framework elastic train scripts
(reference: test/integration/data/elastic_torch_main.py): train EPOCHS
epochs, commit state each epoch, append ``worker_id epoch rank size`` lines
to a shared log so the test can assert rank reassignment and recovery.
Optionally hard-exits once at a configured (worker, epoch) to simulate a
preempted host.
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import elastic  # noqa: E402

LOG = os.environ["ELASTIC_TEST_LOG"]
EPOCHS = int(os.environ.get("ELASTIC_TEST_EPOCHS", "6"))
EPOCH_SLEEP = float(os.environ.get("ELASTIC_TEST_EPOCH_SLEEP", "0.3"))
KILL_WORKER = os.environ.get("ELASTIC_TEST_KILL_WORKER", "")
KILL_EPOCH = int(os.environ.get("ELASTIC_TEST_KILL_EPOCH", "-1"))

WID = os.environ.get("HVDTPU_WORKER_ID", "static:?")
KILL_MARKER = LOG + ".killed"

# Sparse chaos row (ISSUE 11): the per-epoch collective is a
# sparse_allreduce of an embedding-table gradient instead of the dense
# allreduce — deterministic in (epoch, rank), so recovery re-runs an
# epoch to the same numbers and a dense-path run (HVDTPU_SPARSE unset)
# is the exact reference for the gather-path run.
SPARSE_MODE = os.environ.get("ELASTIC_TEST_SPARSE") == "1"
SPARSE_ROWS, SPARSE_WIDTH, SPARSE_NNZ = 64, 4, 6

# Autotune chaos row (ISSUE 12): after training, drive a FIXED number
# of extra allreduces so the post-recovery cohort's tuner converges,
# then log the applied-knob sequence for the cross-rank divergence
# assertion. The drive count is fixed (not `while tuner.enabled`) on
# purpose: the convergence flag flips on the cycle thread, so a
# condition-driven loop could make one rank submit a collective its
# peer never does — a fixed count keeps the submission schedules
# identical by construction.
AUTOTUNE_MODE = os.environ.get("ELASTIC_TEST_AUTOTUNE") == "1"
AUTOTUNE_DRIVE_STEPS = int(os.environ.get("ELASTIC_TEST_AUTOTUNE_STEPS",
                                          "60"))


def _sparse_grad(epoch, rank):
    rng = np.random.RandomState(1000 * epoch + rank)
    idx = rng.choice(SPARSE_ROWS, size=SPARSE_NNZ,
                     replace=True).astype(np.int32)
    vals = rng.randn(SPARSE_NNZ, SPARSE_WIDTH).astype(np.float32)
    return hvd.SparseGradient(idx, vals, (SPARSE_ROWS, SPARSE_WIDTH))


def log_line(msg):
    with open(LOG, "a") as f:
        f.write(f"{WID} {msg}\n")


@elastic.run
def train(state):
    while state.epoch < EPOCHS:
        if SPARSE_MODE:
            sg = _sparse_grad(state.epoch, hvd.rank())
            out = np.asarray(hvd.sparse_allreduce(
                sg, op=hvd.Sum, name=f"step{state.epoch}"))
            # Every rank can rebuild the oracle: the sum of every
            # cohort member's densified gradient for this epoch.
            expect = np.zeros((SPARSE_ROWS, SPARSE_WIDTH), np.float32)
            for r in range(hvd.size()):
                expect += np.asarray(
                    _sparse_grad(state.epoch, r).densify())
            np.testing.assert_allclose(out, expect, rtol=1e-4,
                                       atol=1e-5)
            state.table = state.table + out
            state.total = state.total + float(np.abs(out).sum())
        else:
            out = hvd.allreduce(jnp.ones(4), op=hvd.Sum,
                                name=f"step{state.epoch}")
            # rtol loose enough for the int8-quantized wire format the
            # compression chaos row runs under (ones quantize exactly
            # up to one f32 ulp per rank).
            np.testing.assert_allclose(np.asarray(out),
                                       float(hvd.size()), rtol=1e-5)
            state.total = state.total + float(np.asarray(out)[0])

        if (WID == KILL_WORKER and state.epoch == KILL_EPOCH
                and not os.path.exists(KILL_MARKER)):
            open(KILL_MARKER, "w").close()
            log_line(f"KILLED epoch={state.epoch}")
            os._exit(17)

        log_line(f"epoch={state.epoch} rank={hvd.rank()} "
                 f"size={hvd.size()}")
        state.epoch += 1
        state.commit()
        time.sleep(EPOCH_SLEEP)
    return state.epoch


def main():
    hvd.init()
    state = elastic.ObjectState(
        epoch=0, total=0.0,
        table=np.zeros((SPARSE_ROWS, SPARSE_WIDTH), np.float32))
    final_epoch = train(state)
    # Compression engagement evidence for the chaos matrix row: name
    # the plane state so the test can assert the quantized path (and
    # its residual store) actually ran, not silently fell back.
    from horovod_tpu import basics
    plane = basics.runtime().coordinator._compression
    if plane is not None:
        log_line(f"COMPRESSION residuals={len(plane.residuals)}")
    if SPARSE_MODE:
        # Sparse engagement evidence + the recovered embedding table
        # itself (the chaos row compares it against the dense-path
        # recovery run).
        sp = basics.runtime().coordinator._sparse
        if sp is not None:
            log_line("SPARSE paths=gather:%d,dense:%d"
                     % (sp.path_counts["gather"],
                        sp.path_counts["dense"]))
        np.save(f"{LOG}.table.rank{hvd.rank()}.npy", state.table)
    if AUTOTUNE_MODE:
        import json as _json
        tuner = basics.runtime().autotuner
        assert tuner is not None, "HVDTPU_AUTOTUNE=1 must create the tuner"
        for i in range(AUTOTUNE_DRIVE_STEPS):
            out = hvd.allreduce(jnp.ones(4), op=hvd.Sum,
                                name=f"tune{i % 3}")
            np.testing.assert_allclose(np.asarray(out)[0],
                                       float(hvd.size()), rtol=1e-5)
        log_line("AUTOTUNE converged=%d best=%s applied=%s"
                 % (0 if tuner.enabled else 1, tuner.best,
                    _json.dumps(tuner.applied)))
    log_line(f"DONE epoch={final_epoch} rank={hvd.rank()} "
             f"size={hvd.size()} total={state.total}")


if __name__ == "__main__":
    main()
