"""2D (data × tensor) training (parallel/twod.py; docs/resharding.md).

Pins the ISSUE 17 acceptance contract: a composed dp × tp train step —
sharding.py tensor layouts + ZeRO legs over dp on ONE mesh — is
bit-exact against the same-mesh data-parallel oracle (psum +
replicated inner state), its elastic reshard (dp 4→2 on 8 devices) and
train→serve transform are both planner-emitted and bit-exact, the
moment bytes survive the transition exactly, and every emitted program
proves HVD501/HVD502-clean under hvd-sim.
"""

import numpy as np

import jax
import jax.numpy as jnp
import optax
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import resharding
from horovod_tpu.parallel import twod
from horovod_tpu.utils.jax_compat import shard_map as _shard_map

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


def _params(seed=1):
    rng = np.random.default_rng(seed)
    return {
        "mlp_in": {
            "kernel": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32),
            "bias": jnp.asarray(rng.normal(size=(4,)), jnp.float32)},
        "scale": jnp.asarray(rng.normal(size=(8,)), jnp.float32)}


def _batch(seed=2):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)


def _loss_fn(p, b):
    """Column-decomposable toy loss: with mlp_in.kernel tensor-sharded
    on its output dim, each tp rank's partial is exact for its slice
    and the partials sum to the full loss."""
    h = b * p["scale"]
    y = h @ p["mlp_in"]["kernel"] + p["mlp_in"]["bias"]
    return jnp.sum(y * y)


def _oracle(tz, inner):
    """Same-mesh data-parallel reference: tp-sharded params, psum'd
    gradients (tp-sum for replicated leaves first — the shared-param
    contract), one REPLICATED (unsharded) inner state per rank."""
    mesh, specs = tz.mesh, tz.param_specs
    params0 = _params()
    pspec_leaves = jax.tree.leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    local_shapes = [tz._local_shape(l.shape, sp) for l, sp in
                    zip(jax.tree.leaves(params0), pspec_leaves)]
    ostate_shape = jax.eval_shape(inner.init, jax.tree.map(
        lambda l, sp: jax.ShapeDtypeStruct(
            tz._local_shape(l.shape, sp), l.dtype),
        params0, specs, is_leaf=lambda x: hasattr(x, "shape")))
    flat_state, tdef = jax.tree_util.tree_flatten(ostate_shape)
    sspec_leaves = [P() if l.ndim == 0 else
                    pspec_leaves[local_shapes.index(tuple(l.shape))]
                    for l in flat_state]
    state_specs = jax.tree_util.tree_unflatten(tdef, sspec_leaves)

    def o_body(p, s, b):
        loss, grads = jax.value_and_grad(_loss_fn)(p, b)
        gl = list(jax.tree.leaves(grads))
        for i, sp in enumerate(pspec_leaves):
            if tz._tp_replicated(sp):
                gl[i] = lax.psum(gl[i], tz.tp_axis)
        grads = jax.tree.unflatten(jax.tree.structure(grads), gl)
        grads = jax.tree.map(
            lambda g: lax.psum(g, tz.dp_axis) / tz.dp, grads)
        updates, s2 = inner.update(grads, s, p)
        p2 = jax.tree.map(lambda q, u: q + u.astype(q.dtype), p,
                          updates)
        return p2, s2, lax.psum(lax.psum(loss, tz.dp_axis),
                                tz.tp_axis)

    o_init = jax.jit(_shard_map(
        lambda p: inner.init(p), mesh=mesh, in_specs=(specs,),
        out_specs=state_specs, check_vma=False))
    o_step = jax.jit(_shard_map(
        o_body, mesh=mesh,
        in_specs=(specs, state_specs, P(tz.dp_axis)),
        out_specs=(specs, state_specs, P()), check_vma=False))
    return o_init, o_step, state_specs


def _place(tree, mesh, specs):
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(
            np.asarray(leaf), NamedSharding(mesh, spec)),
        tree, specs, is_leaf=lambda x: hasattr(x, "shape"))


def _moment_vecs(state, tz):
    """(bucket k, (dp, tp, shard_len) view) per vector state leaf."""
    out = []
    for k, bs in enumerate(state[0]):
        for leaf in jax.tree_util.tree_leaves(bs):
            if np.ndim(leaf) >= 1:
                out.append((k, np.asarray(leaf).reshape(
                    tz.dp, tz.tp, -1)))
    return out


class TestTwoDStep:
    def test_two_steps_bit_exact_vs_oracle(self):
        inner = optax.adam(1e-2)
        mesh = twod.make_mesh_2d(4, 2)
        tz = twod.TwoDZero(inner, mesh)
        params, batch = _params(), _batch()
        state = tz.init_state(params)
        step = tz.make_step(_loss_fn)
        o_init, o_step, _ = _oracle(tz, inner)
        op, ost = params, o_init(params)
        p, s = params, state
        for _ in range(2):
            p, s, loss = step(p, s, batch)
            op, ost, oloss = o_step(op, ost, batch)
            assert float(loss) == float(oloss)
            for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(op)):
                assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_state_is_born_sharded(self):
        inner = optax.adam(1e-2)
        tz = twod.TwoDZero(inner, twod.make_mesh_2d(4, 2))
        state = tz.init_state(_params())
        for k, bs in enumerate(state[0]):
            for leaf in jax.tree_util.tree_leaves(bs):
                if np.ndim(leaf) >= 1:
                    n = tz.plan.shards[k].shard_len
                    assert leaf.shape == (tz.dp * tz.tp * n,)

    def test_sum_op_supported_and_adasum_rejected(self):
        from horovod_tpu.ops import reduce_ops
        inner = optax.sgd(1e-2)
        mesh = twod.make_mesh_2d(2, 2)
        twod.TwoDZero(inner, mesh, op=reduce_ops.Sum)
        with pytest.raises(ValueError):
            twod.TwoDZero(inner, mesh, op=reduce_ops.Adasum)


class TestElasticReshard2D:
    def _train(self, steps=2):
        inner = optax.adam(1e-2)
        mesh4 = twod.make_mesh_2d(4, 2)
        tz4 = twod.TwoDZero(inner, mesh4)
        params, batch = _params(), _batch()
        state = tz4.init_state(params)
        step = tz4.make_step(_loss_fn)
        p, s = params, state
        for _ in range(steps):
            p, s, _ = step(p, s, batch)
        return inner, tz4, p, s, batch, step

    def test_reshard_4_to_2_then_step_bit_exact_vs_oracle(self):
        inner, tz4, p, s, batch, _ = self._train()
        o_init4, o_step4, _ = _oracle(tz4, inner)
        op, ost = _params(), o_init4(_params())
        for _ in range(2):
            op, ost, _ = o_step4(op, ost, batch)

        mesh2 = twod.make_mesh_2d(2, 2)
        tz2 = twod.TwoDZero(inner, mesh2)
        s2 = twod.reshard_2d(s, tz4, tz2, p)
        tz2.ensure_plan(p)
        p2 = _place(p, mesh2, tz2.param_specs)
        pa, sa, la = tz2.make_step(_loss_fn)(p2, s2, batch)

        _, o_step2, sspecs2 = _oracle(tz2, inner)
        opa, osta, ola = o_step2(
            _place(op, mesh2, tz2.param_specs),
            _place(ost, mesh2, sspecs2), batch)
        assert float(la) == float(ola)
        for a, b in zip(jax.tree.leaves(pa), jax.tree.leaves(opa)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_moments_survive_bit_exact_and_round_trip(self):
        inner, tz4, p, s, _, _ = self._train()
        tz2 = twod.TwoDZero(inner, twod.make_mesh_2d(2, 2))
        s2 = twod.reshard_2d(s, tz4, tz2, p)
        v4 = _moment_vecs(s, tz4)
        for (k, a), (_, b) in zip(v4, _moment_vecs(s2, tz2)):
            size = tz4.plan.shards[k].size
            for t in range(tz4.tp):
                assert np.array_equal(
                    a[:, t].reshape(-1)[:size],
                    b[:, t].reshape(-1)[:size])
        s4b = twod.reshard_2d(s2, tz2, tz4, p)
        for (k, a), (_, b) in zip(v4, _moment_vecs(s4b, tz4)):
            size = tz4.plan.shards[k].size
            for t in range(tz4.tp):
                assert np.array_equal(
                    a[:, t].reshape(-1)[:size],
                    b[:, t].reshape(-1)[:size])

    def test_reshard_program_proves_clean(self):
        inner, tz4, p, _, _, _ = self._train(steps=1)
        tz2 = twod.TwoDZero(inner, twod.make_mesh_2d(2, 2))
        tz2.ensure_plan(p)
        meta = [(tuple(l.shape), str(l.dtype))
                for l in jax.tree.leaves(p)]
        program = resharding.plan_redistribution(
            tz4.spec_2d(p), tz2.spec_2d(p), meta)
        assert program.prove() == []
        assert program.bytes_moved() > 0


class TestTrainToServe2D:
    def test_replicated_and_rows_bit_exact(self):
        inner = optax.adam(1e-2)
        tz = twod.TwoDZero(inner, twod.make_mesh_2d(2, 2))
        params, batch = _params(), _batch()
        state = tz.init_state(params)
        p, _, _ = tz.make_step(_loss_fn)(params, state, batch)
        full = tz.to_serving(p, serving_world=1, serving_rank=0)
        for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(p)):
            assert np.array_equal(np.asarray(a),
                                  np.asarray(jax.device_get(b)))
        rows = [tz.to_serving(p, serving_world=2, serving_rank=r,
                              layout="rows") for r in (0, 1)]
        for r0, r1, leaf in zip(jax.tree.leaves(rows[0]),
                                jax.tree.leaves(rows[1]),
                                jax.tree.leaves(p)):
            cat = np.concatenate(
                [np.asarray(r0), np.asarray(r1)], axis=0)
            assert np.array_equal(cat,
                                  np.asarray(jax.device_get(leaf)))

    def test_serve_program_proves_clean(self):
        inner = optax.adam(1e-2)
        tz = twod.TwoDZero(inner, twod.make_mesh_2d(2, 2))
        p = _params()
        tz.ensure_plan(p)
        meta = [(tuple(l.shape), str(l.dtype))
                for l in jax.tree.leaves(p)]
        src = resharding.Spec({"dp": 2, "tp": 2}, tz.tensor_layouts())
        dst = resharding.replicated_spec(len(meta), {"s": 1})
        program = resharding.plan_redistribution(src, dst, meta)
        assert program.prove() == []
