"""Model-zoo unit tests: stem/remat variants preserve semantics."""

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models.resnet import (ResNet18,
                                       stem_weights_to_space_to_depth)


def test_space_to_depth_stem_equivalent():
    """The space-to-depth stem is EXACTLY the 7x7/s2 conv under the
    weight transform (zero-pad to 8x8, fold the 2x2 phase into input
    channels) — checkpoints trained with either stem interconvert."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1, 64, 64, 3), jnp.float32)
    m1 = ResNet18(num_classes=10, dtype=jnp.float32)
    v1 = m1.init(jax.random.PRNGKey(0), x)
    m2 = ResNet18(num_classes=10, dtype=jnp.float32,
                  stem="space_to_depth")
    p1 = v1["params"]
    p2 = jax.tree.map(lambda t: t, p1)
    p2["conv_init"] = {"kernel": jnp.asarray(
        stem_weights_to_space_to_depth(p1["conv_init"]["kernel"]))}
    o1 = m1.apply({"params": p1, "batch_stats": v1["batch_stats"]},
                  x, train=False)
    o2 = m2.apply({"params": p2, "batch_stats": v1["batch_stats"]},
                  x, train=False)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               atol=1e-4)
    # The s2d stem's own init produces the transformed kernel shape.
    v2 = m2.init(jax.random.PRNGKey(1), x)
    assert v2["params"]["conv_init"]["kernel"].shape == (4, 4, 12, 64)


def test_resnet_remat_variants_run():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    for remat in (True, "dots"):
        m = ResNet18(num_classes=10, remat=remat)
        v = m.init(jax.random.PRNGKey(0), x)
        out, _ = m.apply(v, x, mutable=["batch_stats"])
        assert out.shape == (2, 10)


def test_transformer_remat_variants_run():
    from horovod_tpu.models import TransformerConfig, TransformerLM
    tokens = jnp.zeros((2, 16), jnp.int32)
    for remat in (True, "dots"):
        cfg = TransformerConfig(vocab_size=64, hidden=32, layers=2,
                                heads=2, max_len=16, causal=True,
                                use_rope=True, remat=remat)
        model = TransformerLM(cfg)
        params = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 16, 64)
        assert logits.dtype == jnp.float32
