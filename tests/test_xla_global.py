"""XLA-global data plane tests over 2x4 and 4x2 process-by-device
topologies — the compiled multi-host story the driver's dryrun validates
single-process (VERDICT round-1 item 4: prove the SPMD data plane is XLA,
not sockets)."""

import os
import socket
import sys

import pytest

from test_spmd import launch

HERE = os.path.dirname(os.path.abspath(__file__))
XLA_WORKER = os.path.join(HERE, "xla_global_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("size,local", [(2, 4), (4, 2)])
def test_xla_global_static(size, local):
    """Static peers (env-fed) + explicit coordinator address, at 2x4 and
    4x2 process-by-device topologies."""
    extra = {
        "HVDTPU_CPU_OPERATIONS": "xla",
        "HVDTPU_XLA_COORD": f"127.0.0.1:{_free_port()}",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={local}",
        "XGW_LOCAL_DEVICES": str(local),
    }
    codes, outs = launch(size, script=XLA_WORKER, extra_env=extra,
                         timeout=300)
    for rank, (code, out) in enumerate(zip(codes, outs)):
        assert code == 0, f"rank {rank} failed (exit {code}):\n{out[-4000:]}"
        assert f"rank {rank}/{size}: XLA-GLOBAL OK" in out


def test_xla_global_kill_rank_fails_fast():
    """Peer death on the delegated plane: survivors must terminate
    promptly, never hang inside a jitted collective missing a
    participant. Two legitimate fail-fast paths race: the native TCP
    control plane surfaces HorovodInternalError (survivor exits 0 after
    handling it), or the JAX coordination service detects the death
    first and terminates the process (the NCCL-abort analog)."""
    extra = {
        "HVDTPU_CPU_OPERATIONS": "xla",
        "HVDTPU_XLA_COORD": f"127.0.0.1:{_free_port()}",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "XGW_LOCAL_DEVICES": "2",
        "XGW_MODE": "kill",
    }
    codes, outs = launch(3, script=XLA_WORKER, extra_env=extra,
                         timeout=120)
    assert codes[2] == 17, f"rank 2 should die(17), got {codes[2]}"
    for rank in (0, 1):
        handled = ("XLA-GLOBAL-KILL OK" in outs[rank]
                   and codes[rank] == 0)
        terminated = ("JAX distributed service detected fatal errors"
                      in outs[rank] and codes[rank] not in (None, 0))
        assert handled or terminated, (
            f"rank {rank} neither handled the death nor was terminated "
            f"(exit {codes[rank]}):\n{outs[rank][-4000:]}")


def test_xla_global_through_hvdrun():
    """Launcher-rendezvoused: the JAX coordinator address is brokered
    through the hvdrun KV store (the NCCL-unique-id-over-controller
    analog), no hand-fed env at all."""
    from horovod_tpu.runner import run_command
    pythonpath = os.pathsep.join(
        [os.path.dirname(HERE), HERE, os.environ.get("PYTHONPATH", "")])
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": pythonpath,
        "HVDTPU_CPU_OPERATIONS": "xla",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "XGW_LOCAL_DEVICES": "4",
    }
    rc = run_command([sys.executable, XLA_WORKER], num_proc=2, env=env,
                     start_timeout=180)
    assert rc == 0


def test_keras_compiled_over_global_mesh():
    """Keras model.fit with set_data_parallel spanning 2 processes x 2
    devices over jax.distributed — the multi-host on-chip keras shape.
    Each rank feeds its pre-sharded data; the jitted step is one
    global-SPMD program; weights stay replicated across ranks."""
    pytest.importorskip("keras")
    extra = {
        "HVDTPU_CPU_OPERATIONS": "xla",
        "HVDTPU_XLA_COORD": f"127.0.0.1:{_free_port()}",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "XGW_LOCAL_DEVICES": "2",
    }
    codes, outs = launch(2, script=os.path.join(HERE,
                                                "keras_global_worker.py"),
                         extra_env=extra, timeout=420)
    for rank, (code, out) in enumerate(zip(codes, outs)):
        assert code == 0, f"rank {rank} failed (exit {code}):\n{out[-4000:]}"
        assert f"rank {rank}/2: KERAS-GLOBAL OK" in out


def test_elastic_accepts_xla_plane():
    """Elastic + xla-global initializes (exit-restart resets make the
    combination legal — elastic.py "Exit-restart reset"); the reset path
    itself is covered by test_elastic.py's xla-plane kill test."""
    import subprocess
    from conftest import clean_spawn_env
    env = clean_spawn_env()
    env.update({
        "PYTHONPATH": os.path.dirname(HERE),
        "HVDTPU_CPU_OPERATIONS": "xla",
        "HVDTPU_ELASTIC": "1",
        "HVDTPU_RANK": "0", "HVDTPU_SIZE": "1",
    })
    proc = subprocess.run(
        [sys.executable, "-c",
         "import horovod_tpu as hvd; hvd.init(); "
         "print('ELASTIC-XLA OK', hvd.size())"],
        env=env, capture_output=True, timeout=120)
    assert proc.returncode == 0, proc.stderr.decode()[-3000:]
    assert b"ELASTIC-XLA OK 1" in proc.stdout
