"""Keras-3-on-JAX on-chip training path.

The round-4 headline: keras model.fit math compiled onto the device mesh
(here the 8-device virtual CPU mesh; same code path as a TPU slice).
Covers set_data_parallel (one XLA program, sharded batch, native gradient
reduction), parity with the plain single-device path, and the graph-safe
backward_passes_per_step delegation to keras's accumulation engine.
Reference test analog: test/parallel/test_keras.py + the xla-ops suite
(reference: test/parallel/test_xla.py).
"""

import os
import sys

import numpy as np
import pytest

os.environ["KERAS_BACKEND"] = "jax"

keras = pytest.importorskip("keras")
if keras.backend.backend() != "jax":
    pytest.skip("keras already imported with a non-jax backend",
                allow_module_level=True)

import horovod_tpu.keras as hvd  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_distribution():
    yield
    keras.distribution.set_distribution(None)


def _make_model(seed=7):
    keras.utils.set_random_seed(seed)
    return keras.Sequential([
        keras.layers.Input((16,)),
        keras.layers.Dense(32, activation="relu"),
        keras.layers.Dense(1),
    ])


def _data(n=256, seed=11):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 16).astype(np.float32)
    w = rng.randn(16, 1).astype(np.float32)
    y = (X @ w + 0.1 * rng.randn(n, 1)).astype(np.float32)
    return X, y


def test_data_parallel_fit_on_mesh(hvd_init, n_devices):
    """model.fit under set_data_parallel: jitted, sharded, loss falls."""
    dist = hvd.set_data_parallel()
    assert keras.distribution.distribution() is dist
    model = _make_model()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.05))
    model.compile(optimizer=opt, loss="mse")
    X, y = _data()
    hist = model.fit(X, y, epochs=3, batch_size=8 * n_devices, verbose=0)
    losses = hist.history["loss"]
    assert losses[-1] < losses[0], losses
    # Variables really live replicated on the mesh (not single-device).
    kernel = model.layers[0].kernel.value
    assert len(kernel.sharding.device_set) == n_devices


def test_data_parallel_parity_with_single_device(hvd_init, n_devices):
    """Sharded-mesh training == single-device training, same data/weights.

    The gradient under DataParallel is the full-batch gradient computed
    distributively (XLA inserts the reduction); with SGD the updates must
    match the unsharded run to float tolerance."""
    X, y = _data(n=64 * n_devices)
    bs = 16 * n_devices

    hvd.set_data_parallel()
    model_a = _make_model(seed=3)
    w0 = [np.array(w) for w in model_a.get_weights()]
    model_a.compile(optimizer=hvd.DistributedOptimizer(
        keras.optimizers.SGD(0.05)), loss="mse")
    model_a.fit(X, y, epochs=2, batch_size=bs, shuffle=False, verbose=0)
    w_mesh = [np.array(w) for w in model_a.get_weights()]

    keras.distribution.set_distribution(None)
    model_b = _make_model(seed=3)
    model_b.set_weights(w0)
    model_b.compile(optimizer=keras.optimizers.SGD(0.05), loss="mse")
    model_b.fit(X, y, epochs=2, batch_size=bs, shuffle=False, verbose=0)
    w_single = [np.array(w) for w in model_b.get_weights()]

    for a, b in zip(w_mesh, w_single):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_backward_passes_per_step_aggregates(hvd_init):
    """k micro-batches of size B == one batch of size k*B (SGD).

    Exercises the keras-native accumulation engine the wrapper delegates
    to (reference semantics: horovod/tensorflow/gradient_aggregation.py:16
    — update applied every k-th pass with the averaged aggregate)."""
    X, y = _data(n=64)
    k, bs = 2, 32

    model_a = _make_model(seed=5)
    w0 = [np.array(w) for w in model_a.get_weights()]
    opt_a = hvd.DistributedOptimizer(keras.optimizers.SGD(0.05),
                                     backward_passes_per_step=k)
    assert opt_a.gradient_accumulation_steps == k
    model_a.compile(optimizer=opt_a, loss="mse")
    model_a.fit(X, y, epochs=1, batch_size=bs, shuffle=False, verbose=0)
    w_agg = [np.array(w) for w in model_a.get_weights()]

    model_b = _make_model(seed=5)
    model_b.set_weights(w0)
    model_b.compile(optimizer=keras.optimizers.SGD(0.05), loss="mse")
    model_b.fit(X, y, epochs=1, batch_size=k * bs, shuffle=False, verbose=0)
    w_big = [np.array(w) for w in model_b.get_weights()]

    for a, b in zip(w_agg, w_big):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_backward_passes_per_step_unaveraged(hvd_init):
    """average_aggregated_gradients=False applies the micro-batch SUM:
    the weight delta is k times the averaged variant's."""
    X, y = _data(n=64)
    k, bs = 2, 32

    deltas = []
    for averaged in (True, False):
        model = _make_model(seed=9)
        w0 = [np.array(w) for w in model.get_weights()]
        opt = hvd.DistributedOptimizer(
            keras.optimizers.SGD(0.05), backward_passes_per_step=k,
            average_aggregated_gradients=averaged)
        model.compile(optimizer=opt, loss="mse")
        model.fit(X, y, epochs=1, batch_size=bs, shuffle=False, verbose=0)
        w1 = [np.array(w) for w in model.get_weights()]
        deltas.append([b - a for a, b in zip(w0, w1)])

    for d_avg, d_sum in zip(*deltas):
        np.testing.assert_allclose(d_sum, k * d_avg, rtol=2e-4, atol=2e-5)


def test_apply_gradients_entry_point_not_double_prepared(hvd_init):
    """keras BaseOptimizer.apply_gradients delegates to self.apply — the
    wrapper must prepare only once on that path (the custom-training-loop
    idiom). Regression for the k^2 prescale bug."""
    k = 2
    v = keras.Variable(np.zeros((), np.float32))
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(1.0),
                                   backward_passes_per_step=k,
                                   average_aggregated_gradients=False)
    opt.build([v])
    for _ in range(k):
        opt.apply_gradients([(keras.ops.ones(()), v)])
    # unaveraged sum of k unit grads with lr 1.0 -> v = -k (not -k^2)
    np.testing.assert_allclose(np.asarray(v.value), -float(k), rtol=1e-6)


def test_backward_passes_validation(hvd_init):
    with pytest.raises(ValueError, match="Adasum"):
        hvd.DistributedOptimizer(keras.optimizers.SGD(0.01),
                                 backward_passes_per_step=2, op=hvd.Adasum)

    built = keras.optimizers.SGD(0.01)
    built.build([keras.Variable(np.zeros((2, 2), np.float32))])
    with pytest.raises(ValueError, match="before it is built"):
        hvd.DistributedOptimizer(built, backward_passes_per_step=2)

    conflicted = keras.optimizers.SGD(0.01, gradient_accumulation_steps=3)
    with pytest.raises(ValueError, match="conflicting"):
        hvd.DistributedOptimizer(conflicted, backward_passes_per_step=2)


def test_keras_applications_model_on_mesh(hvd_init, n_devices):
    """A real keras.applications model family (MobileNetV3: depthwise
    convs, hard-swish, BN, squeeze-excite) compiles and trains through
    model.fit on the mesh — the 'switch your keras model, keep your
    code' contract."""
    hvd.set_data_parallel()
    model = keras.applications.MobileNetV3Small(
        input_shape=(32, 32, 3), weights=None, classes=10,
        include_top=True)
    model.compile(
        optimizer=hvd.DistributedOptimizer(keras.optimizers.SGD(0.01)),
        loss=keras.losses.SparseCategoricalCrossentropy())
    x = np.random.RandomState(0).rand(64, 32, 32, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, size=(64,))
    hist = model.fit(x, y, batch_size=32, epochs=2, verbose=0)
    assert all(np.isfinite(v) for v in hist.history["loss"])
    assert len(model.weights[0].value.sharding.device_set) == n_devices


def test_set_data_parallel_requires_jax_backend(hvd_init, monkeypatch):
    monkeypatch.setattr(keras.backend, "backend", lambda: "torch")
    with pytest.raises(RuntimeError, match="jax keras backend"):
        hvd.set_data_parallel()


@pytest.fixture(scope="module")
def hvd_init():
    hvd.init()
    return hvd
