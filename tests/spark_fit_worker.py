"""Worker for the estimator training-loop test: runs fit_on_parquet as
one rank of an np=2 job (launched by test_spark_estimator.py). The same
function body is what KerasEstimator.fit executes inside Spark barrier
tasks — this harness proves the loop needs no Spark."""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("KERAS_BACKEND", "tensorflow")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import keras
    import numpy as np

    from horovod_tpu.spark.keras import fit_on_parquet

    keras.utils.set_random_seed(int(os.environ["HVDTPU_RANK"]) + 1)
    # Deliberately rank-divergent init: BroadcastGlobalVariablesCallback
    # must sync rank 0's weights before step 1.
    model = keras.Sequential([keras.layers.Dense(1, input_shape=(4,))])
    from horovod_tpu.spark.keras import serialize_model

    from horovod_tpu.ops.compression import Compression

    history = fit_on_parquet(
        store_prefix=os.environ["STORE_PREFIX"],
        run_id="testrun",
        model_bytes=serialize_model(model),
        feature_cols=["features"],
        label_cols=["label"],
        batch_size=16,
        epochs=5,
        optimizer={"class_name": "Adam",
                   "config": {"learning_rate": 0.05}},
        loss="mse",
        validation=0.25,
        # Estimator-level wire compression (reference estimator param).
        compression=Compression.bf16,
    )
    assert history["loss"][-1] < history["loss"][0], history
    assert "val_loss" in history, list(history)
    print("HISTORY " + json.dumps(history), flush=True)


if __name__ == "__main__":
    main()
