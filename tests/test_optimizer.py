"""Distributed optimizer + end-to-end training tests (reference analog:
DistributedOptimizer tests in test/parallel/test_torch.py and the MNIST
example smoke runs in CI, .buildkite/gen-pipeline.sh:155-279)."""

import jax
from horovod_tpu.utils.jax_compat import shard_map
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd_mod
import horovod_tpu.jax as hvd_jax
from horovod_tpu.models import MLP


@pytest.fixture(autouse=True)
def _init(hvd):
    pass


def _loss_fn(model):
    def loss(params, batch):
        x, y = batch
        logits = model.apply(params, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
    return loss


def _make_data(n, batch_per_rank, key=0):
    rng = np.random.RandomState(key)
    x = rng.uniform(size=(n * batch_per_rank, 8, 8, 1)).astype(np.float32)
    y = rng.randint(0, 10, size=(n * batch_per_rank,))
    return jnp.asarray(x), jnp.asarray(y)


def test_train_step_loss_decreases(hvd, n_devices):
    model = MLP(features=(32,), num_classes=10)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8, 8, 1)))
    opt = hvd_jax.DistributedOptimizer(optax.adam(1e-2))
    step = hvd_jax.make_train_step(_loss_fn(model), opt)
    opt_state = opt.init(params)
    batch = _make_data(n_devices, 16)
    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses


def test_grads_reduced_identically(hvd, n_devices):
    """After a step, params on every replica must be identical (the
    defining property of DP allreduce training)."""
    model = MLP(features=(16,), num_classes=4)
    params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 4, 4, 1)))
    opt = hvd_jax.DistributedOptimizer(optax.sgd(0.1))
    step = hvd_jax.make_train_step(_loss_fn(model), opt, donate=False)
    opt_state = opt.init(params)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.uniform(size=(n_devices * 4, 4, 4, 1)),
                    dtype=jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, size=(n_devices * 4,)))
    new_params, _, _ = step(params, opt_state, (x, y))
    # Replicated output: sharding must report full replication.
    leaf = jax.tree.leaves(new_params)[0]
    assert leaf.sharding.is_fully_replicated


def test_train_step_matches_single_device_sgd(hvd, n_devices):
    """Sharded training must be numerically equivalent to one big-batch
    SGD step on a single device (grad of mean over full batch)."""
    model = MLP(features=(8,), num_classes=3)
    params = model.init(jax.random.PRNGKey(2), jnp.zeros((1, 2, 2, 1)))
    loss = _loss_fn(model)
    batch = _make_data(n_devices, 8, key=9)
    batch = (batch[0][:, :2, :2, :], batch[1] % 3)

    opt = hvd_jax.DistributedOptimizer(optax.sgd(0.5))
    step = hvd_jax.make_train_step(loss, opt, donate=False)
    opt_state = opt.init(params)
    dist_params, _, dist_loss = step(params, opt_state, batch)

    ref_grads = jax.grad(loss)(params, batch)
    ref_params = jax.tree.map(lambda p, g: p - 0.5 * g, params, ref_grads)
    for a, b in zip(jax.tree.leaves(dist_params),
                    jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_backward_passes_per_step(hvd, n_devices):
    """Local gradient aggregation: updates apply only every k-th step
    (reference: horovod/tensorflow/gradient_aggregation.py)."""
    model = MLP(features=(8,), num_classes=3)
    params = model.init(jax.random.PRNGKey(4), jnp.zeros((1, 2, 2, 1)))
    opt = hvd_jax.DistributedOptimizer(optax.sgd(0.1),
                                       backward_passes_per_step=2)
    step = hvd_jax.make_train_step(_loss_fn(model), opt, donate=False)
    opt_state = opt.init(params)
    batch = _make_data(n_devices, 4, key=5)
    batch = (batch[0][:, :2, :2, :], batch[1] % 3)

    p1, s1, _ = step(params, opt_state, batch)
    # First micro-batch: no update applied yet.
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    p2, s2, _ = step(p1, s1, batch)
    # Second micro-batch: aggregated update applied.
    changed = any(not np.allclose(np.asarray(a), np.asarray(b))
                  for a, b in zip(jax.tree.leaves(p2),
                                  jax.tree.leaves(params)))
    assert changed


def test_adasum_optimizer_runs(hvd, n_devices):
    model = MLP(features=(8,), num_classes=3)
    params = model.init(jax.random.PRNGKey(6), jnp.zeros((1, 2, 2, 1)))
    opt = hvd_jax.DistributedAdasumOptimizer(optax.sgd(0.1))
    step = hvd_jax.make_train_step(_loss_fn(model), opt, donate=False)
    opt_state = opt.init(params)
    batch = _make_data(n_devices, 4, key=7)
    batch = (batch[0][:, :2, :2, :], batch[1] % 3)
    p, s, loss = step(params, opt_state, batch)
    assert np.isfinite(float(loss))
    changed = any(not np.allclose(np.asarray(a), np.asarray(b))
                  for a, b in zip(jax.tree.leaves(p),
                                  jax.tree.leaves(params)))
    assert changed


def test_compression_bf16_training(hvd, n_devices):
    model = MLP(features=(8,), num_classes=3)
    params = model.init(jax.random.PRNGKey(8), jnp.zeros((1, 2, 2, 1)))
    opt = hvd_jax.DistributedOptimizer(
        optax.sgd(0.1), compression=hvd_mod.Compression.bf16)
    step = hvd_jax.make_train_step(_loss_fn(model), opt, donate=False)
    opt_state = opt.init(params)
    batch = _make_data(n_devices, 4, key=11)
    batch = (batch[0][:, :2, :2, :], batch[1] % 3)
    p, s, loss = step(params, opt_state, batch)
    assert np.isfinite(float(loss))


def test_zero_train_step_matches_regular(hvd, n_devices):
    """ZeRO-1 step == regular step numerically; optimizer moments live
    sharded 1/N per device (global state leaves are flat vectors padded
    to N x shard_len)."""
    import optax

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(13, 5), jnp.float32),
              "b": jnp.zeros(5)}

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    x = jnp.asarray(rng.randn(2 * n_devices, 13), jnp.float32)
    y = jnp.asarray(rng.randn(2 * n_devices, 5), jnp.float32)

    opt = hvd_jax.DistributedOptimizer(optax.adam(1e-2))
    step = hvd_jax.make_train_step(loss_fn, opt, donate=False)
    s = opt.init(params)
    zopt = hvd_jax.DistributedOptimizer(optax.adam(1e-2))
    zstep, zinit = hvd_jax.make_zero_train_step(loss_fn, zopt,
                                                donate=False)
    zs = zinit(params)

    n_elem = sum(int(np.prod(v.shape)) for v in params.values())
    padded = n_elem + (-n_elem) % n_devices
    vec_shapes = {np.shape(t) for t in jax.tree.leaves(zs)
                  if np.ndim(t) >= 1}
    assert vec_shapes == {(padded,)}, vec_shapes

    pp, zpp = params, params
    for i in range(4):
        pp, s, loss = step(pp, s, (x, y))
        zpp, zs, zloss = zstep(zpp, zs, (x, y))
        assert abs(float(loss) - float(zloss)) < 1e-5, i
    np.testing.assert_allclose(np.asarray(pp["w"]),
                               np.asarray(zpp["w"]), atol=1e-4)


def test_zero_train_step_rejects_unsupported(hvd):
    import optax

    def loss_fn(p, b):
        return jnp.sum(p["w"])

    for bad in (dict(op=hvd_mod.Sum),
                dict(backward_passes_per_step=2),
                dict(compression=hvd_mod.Compression.bf16)):
        opt = hvd_jax.DistributedOptimizer(optax.sgd(0.1), **bad)
        with pytest.raises(ValueError, match="make_zero_train_step"):
            hvd_jax.make_zero_train_step(loss_fn, opt)


def test_broadcast_variables_single_mode_identity(hvd):
    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros((3,))}
    out = hvd_jax.broadcast_parameters(params, root_rank=0)
    assert out is params


def test_broadcast_object_single_mode(hvd):
    obj = {"epoch": 3, "lr": 0.1}
    assert hvd_jax.broadcast_object(obj) == obj
    assert hvd_jax.allgather_object(obj) == [obj]


from horovod_tpu.ops.adasum import adasum_vhdd_np as _np_vhdd  # noqa: E402


def test_adasum_axis_matches_pairwise_vhdd_oracle(hvd, n_devices):
    """adasum_axis (ppermute VHDD inside shard_map — the compiled data
    plane) is allclose to the numpy pairwise recursion, mirroring the
    host-plane oracle (tests/spmd_worker.py)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.ops.adasum import adasum_axis

    n = n_devices
    rng = np.random.RandomState(3)
    # scale the ranks very differently: Adasum's whole point is scale
    # awareness, and mismatched norms exercise both coefficients
    stacked = np.stack([
        rng.normal(size=(4, 5)).astype(np.float32) * (10.0 ** (i % 3 - 1))
        for i in range(n)])
    mesh = Mesh(np.array(jax.devices()[:n]), ("r",))

    out = jax.jit(shard_map(
        lambda x: adasum_axis(x[0], "r")[None],
        mesh=mesh, in_specs=P("r"), out_specs=P("r")))(jnp.asarray(stacked))
    expect = _np_vhdd(stacked)
    for i in range(n):
        np.testing.assert_allclose(np.asarray(out)[i], expect,
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"rank {i} diverges from the "
                                           "pairwise VHDD recursion")


def test_adasum_optimizer_matches_tree_oracle(hvd, n_devices):
    """One DistributedAdasumOptimizer step equals a manual SGD update
    with the numpy-VHDD combination of the per-shard gradients — the
    compiled analog of the host plane's oracle-tested VhddAdasum."""
    model = MLP(features=(8,), num_classes=3)
    params = model.init(jax.random.PRNGKey(6), jnp.zeros((1, 2, 2, 1)))
    opt = hvd_jax.DistributedAdasumOptimizer(optax.sgd(0.1))
    step = hvd_jax.make_train_step(_loss_fn(model), opt, donate=False)
    opt_state = opt.init(params)
    batch = _make_data(n_devices, 4, key=7)
    batch = (batch[0][:, :2, :2, :], batch[1] % 3)
    p, s, loss = step(params, opt_state, batch)
    assert np.isfinite(float(loss))

    per = batch[0].shape[0] // n_devices
    loss_fn = _loss_fn(model)
    shard_grads = []
    for i in range(n_devices):
        shard = (batch[0][i * per:(i + 1) * per],
                 batch[1][i * per:(i + 1) * per])
        shard_grads.append(jax.grad(loss_fn)(params, shard))
    leaves = [jax.tree.leaves(g) for g in shard_grads]
    flat_params = jax.tree.leaves(params)
    flat_new = jax.tree.leaves(p)
    for leaf_idx, (p0, p1) in enumerate(zip(flat_params, flat_new)):
        combined = _np_vhdd([np.asarray(leaves[i][leaf_idx])
                             for i in range(n_devices)])
        expected = np.asarray(p0, np.float64) - 0.1 * combined
        np.testing.assert_allclose(np.asarray(p1), expected,
                                   rtol=2e-4, atol=2e-5)
