"""Drop-in ``horovod`` namespace: every ``horovod.X`` import resolves to
the SAME module object as ``horovod_tpu.X``.

This is what lets verbatim reference training scripts
(``import horovod.tensorflow as hvd``, ``import horovod.torch as hvd``,
``import horovod.keras as hvd`` — reference:
examples/tensorflow2/tensorflow2_mnist.py:17,
examples/pytorch/pytorch_mnist.py:12, examples/keras/keras_mnist.py:9)
run unmodified against this framework
(tests/test_reference_examples.py). A meta-path finder — not a second
package tree — so there is exactly ONE runtime: process sets, the
native core, and jax state are shared no matter which name a module
was imported under.
"""

import importlib
import importlib.abc
import importlib.util
import sys

import horovod_tpu as _impl
from horovod_tpu import *  # noqa: F401,F403 — top-level API surface

__version__ = _impl.__version__


class _AliasLoader(importlib.abc.Loader):
    def __init__(self, module):
        self._module = module

    def create_module(self, spec):
        return self._module

    def exec_module(self, module):
        """No-op: the horovod_tpu module is already fully executed."""


class _AliasFinder(importlib.abc.MetaPathFinder):
    """Resolve ``horovod.a.b`` to the already-imported (or importable)
    ``horovod_tpu.a.b`` module object."""

    def find_spec(self, fullname, path=None, target=None):
        if not fullname.startswith("horovod."):
            return None
        real = "horovod_tpu." + fullname[len("horovod."):]
        try:
            module = importlib.import_module(real)
        except ImportError:
            return None
        spec = importlib.util.spec_from_loader(
            fullname, _AliasLoader(module), origin=getattr(
                module, "__file__", None))
        if getattr(module, "__path__", None) is not None:
            spec.submodule_search_locations = list(module.__path__)
        return spec


if not any(isinstance(f, _AliasFinder) for f in sys.meta_path):
    sys.meta_path.insert(0, _AliasFinder())


def __getattr__(name):
    """`import horovod; horovod.tensorflow` attribute-style access."""
    try:
        module = importlib.import_module(f"horovod_tpu.{name}")
    except ImportError as e:
        # PEP 562: missing attributes must raise AttributeError —
        # hasattr/getattr-with-default and star-import __all__ probes
        # depend on it.
        raise AttributeError(
            f"module 'horovod' has no attribute {name!r}") from e
    sys.modules[f"horovod.{name}"] = module
    return module
