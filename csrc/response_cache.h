// LRU response cache with cross-rank bitvector coordination.
//
// Steady-state training enqueues the same named tensors with the same
// parameters every step; the reference short-circuits the full coordinator
// negotiation with an LRU of previously-negotiated responses plus two
// bitwise-AND allreduces over a bit vector (reference:
// horovod/common/response_cache.h:45-169, used controller.cc:88-251).
// This is the same design: a hit list every rank agrees on is executed in
// deterministic cache order with zero coordinator round-trips.
#ifndef HVDCORE_RESPONSE_CACHE_H_
#define HVDCORE_RESPONSE_CACHE_H_

#include <cstdint>
#include <list>
#include <map>
#include <string>
#include <vector>

#include "message.h"

namespace hvdcore {

class ResponseCache {
 public:
  enum class CacheState { kMiss, kHit, kInvalid };

  explicit ResponseCache(size_t capacity) : capacity_(capacity) {}

  // Does `req` match a cached response bit-for-bit (same type/op/dtype/
  // shape/root/scales)? kInvalid = name cached with different params, which
  // forces eviction + renegotiation (reference: response_cache.cc cache
  // invalidation on parameter change).
  CacheState Lookup(const Request& req) const;

  size_t Put(const Request& req, const Response& resp);
  void Erase(const std::string& name);

  // Bit position of a cached name (stable across ranks because insertion
  // order is driven by identical coordinator responses on every rank).
  bool BitFor(const std::string& name, size_t* bit) const;
  const Response& Get(size_t bit) const;
  const Request& CachedRequest(size_t bit) const;
  void Touch(size_t bit);  // LRU bump
  size_t NumEntries() const { return entries_.size(); }
  size_t capacity() const { return capacity_; }

  // All cache bits in most-recently-used-last order — the deterministic
  // execution order for hit lists (reference: controller.cc:240-247 requires
  // identical ordering on all ranks).
  std::vector<size_t> BitsInInsertionOrder() const;

 private:
  struct Entry {
    Request req;
    Response resp;
    uint64_t seq;  // insertion sequence for deterministic ordering
  };
  size_t capacity_;
  uint64_t next_seq_ = 0;
  std::vector<Entry> entries_;             // slot index == bit position
  std::list<size_t> lru_;                  // front = least recent
  std::map<std::string, size_t> by_name_;  // name -> slot
};

}  // namespace hvdcore

#endif  // HVDCORE_RESPONSE_CACHE_H_
