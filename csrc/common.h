// Core types shared across the native runtime.
//
// TPU-native rethink of the reference's common layer (reference:
// horovod/common/common.h). The native core owns the *host-side* machinery —
// negotiation, fusion planning, CPU data plane, timeline — while the TPU data
// plane lives in compiled XLA programs on the Python side.
#ifndef HVDCORE_COMMON_H_
#define HVDCORE_COMMON_H_

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace hvdcore {

// Matches numpy dtype kinds the Python binding marshals
// (reference dtype enum: horovod/common/common.h DataType / message.h).
enum class DataType : uint8_t {
  kUint8 = 0,
  kInt8 = 1,
  kInt32 = 2,
  kInt64 = 3,
  kFloat16 = 4,
  kFloat32 = 5,
  kFloat64 = 6,
  kBool = 7,
  kBFloat16 = 8,
};

inline size_t DataTypeSize(DataType t) {
  switch (t) {
    case DataType::kUint8:
    case DataType::kInt8:
    case DataType::kBool:
      return 1;
    case DataType::kFloat16:
    case DataType::kBFloat16:
      return 2;
    case DataType::kInt32:
    case DataType::kFloat32:
      return 4;
    case DataType::kInt64:
    case DataType::kFloat64:
      return 8;
  }
  return 1;
}

inline const char* DataTypeName(DataType t) {
  switch (t) {
    case DataType::kUint8: return "uint8";
    case DataType::kInt8: return "int8";
    case DataType::kInt32: return "int32";
    case DataType::kInt64: return "int64";
    case DataType::kFloat16: return "float16";
    case DataType::kFloat32: return "float32";
    case DataType::kFloat64: return "float64";
    case DataType::kBool: return "bool";
    case DataType::kBFloat16: return "bfloat16";
  }
  return "?";
}

// Collective kinds (reference Request::RequestType, horovod/common/message.h).
enum class ReqType : uint8_t {
  kAllreduce = 0,
  kAllgather = 1,
  kBroadcast = 2,
  kAlltoall = 3,
  kReducescatter = 4,
  kBarrier = 5,
  kJoin = 6,
};

// Reduction ops (reference ReduceOp: Average is Sum + postscale on the
// Python side, reference: horovod/common/operations.cc:1408-1424).
enum class RedOp : uint8_t {
  kSum = 0,
  kMin = 1,
  kMax = 2,
  kProd = 3,
  kAdasum = 4,  // VHDD adaptive summation (collectives.cc VhddAdasum)
};

enum class StatusCode : uint8_t {
  kOk = 0,
  kUnknownError = 1,
  kPreconditionError = 2,
  kAborted = 3,
  kInvalidArgument = 4,
  kInProgress = 5,
};

struct Status {
  StatusCode code = StatusCode::kOk;
  std::string reason;
  static Status OK() { return Status{}; }
  static Status Error(StatusCode c, std::string r) { return Status{c, std::move(r)}; }
  bool ok() const { return code == StatusCode::kOk; }
};

// Simple leveled logging to stderr with rank prefix (reference:
// horovod/common/logging.h LOG(level, rank)).
enum class LogLevel : int { kTrace = 0, kDebug = 1, kInfo = 2, kWarn = 3, kError = 4, kNone = 5 };
LogLevel GlobalLogLevel();
void LogMsg(LogLevel level, int rank, const std::string& msg);

}  // namespace hvdcore

#endif  // HVDCORE_COMMON_H_
