// Chrome-trace timeline writer.
//
// Reference: horovod/common/timeline.h — a writer thread drains an SPSC
// queue of events and emits chrome://tracing JSON; tensors move through
// NEGOTIATING -> TOP_LEVEL -> ACTIVITY states. Here the queue is a
// mutex-guarded deque drained by a dedicated writer thread (contention is
// negligible at negotiation rates), and the same three-phase structure is
// emitted: NEGOTIATE_<OP>, the top-level op span, and per-op activities
// (e.g. RING_ALLREDUCE, MEMCPY_IN_FUSION_BUFFER).
#ifndef HVDCORE_TIMELINE_H_
#define HVDCORE_TIMELINE_H_

#include <condition_variable>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>

namespace hvdcore {

class Timeline {
 public:
  // pid: rank, so multi-process traces merge into one view.
  Timeline(const std::string& path, int pid);
  ~Timeline();

  bool ok() const { return file_ != nullptr; }

  void NegotiateStart(const std::string& tensor);
  void NegotiateEnd(const std::string& tensor);
  void OpStart(const std::string& tensor, const std::string& op);
  void OpEnd(const std::string& tensor);
  void ActivityStart(const std::string& tensor, const std::string& activity);
  void ActivityEnd(const std::string& tensor);
  // One-shot marker (cycle boundaries, autotune decisions).
  void Marker(const std::string& name);

 private:
  struct Event {
    char phase;  // 'B' begin, 'E' end, 'i' instant
    std::string tid;   // per-tensor lane
    std::string name;  // event label (empty for 'E')
    int64_t us;
  };
  void Push(char phase, const std::string& tid, const std::string& name);
  void WriterLoop();

  std::FILE* file_ = nullptr;
  int pid_;
  bool first_ = true;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Event> queue_;
  bool stop_ = false;
  std::thread writer_;
};

}  // namespace hvdcore

#endif  // HVDCORE_TIMELINE_H_
