// CPU data-plane collectives over a point-to-point Transport.
//
// This is the native core's gloo-analog op set (reference:
// horovod/common/ops/gloo_operations.cc, mpi_operations.cc). Algorithms are
// the classic bandwidth-optimal ones: ring allreduce (reduce-scatter +
// allgather), ring allgatherv, binomial-tree broadcast, pairwise alltoallv,
// dissemination barrier. On TPU the data plane is XLA/ICI (Python side);
// these run the same negotiated schedule for CPU-only SPMD jobs and tests.
#ifndef HVDCORE_COLLECTIVES_H_
#define HVDCORE_COLLECTIVES_H_

#include <cstdint>
#include <vector>

#include "common.h"
#include "transport.h"

namespace hvdcore {

// In-place allreduce over `count` elements of `dtype` at `buf`.
Status RingAllreduce(Transport* t, void* buf, int64_t count, DataType dtype,
                     RedOp op);

// Rank-subset adapter: expose members of a parent transport as a dense
// 0..k-1 transport (the SubsetTransport of the hierarchical algorithms;
// the mux-channel twin for process sets is core.h ChannelView).
class SubsetTransport : public Transport {
 public:
  // members: parent ranks in subset order; my_index: this rank's slot.
  SubsetTransport(Transport* base, std::vector<int> members, int my_index)
      : base_(base), members_(std::move(members)), my_index_(my_index) {}
  int rank() const override { return my_index_; }
  int size() const override { return static_cast<int>(members_.size()); }
  Status Send(int to, const void* data, size_t len) override {
    return base_->Send(members_[to], data, len);
  }
  Status Recv(int from, std::vector<uint8_t>* out) override {
    return base_->Recv(members_[from], out);
  }
  Status SendRecv(int to, const void* sdata, size_t slen, int from,
                  std::vector<uint8_t>* out) override {
    return base_->SendRecv(members_[to], sdata, slen, members_[from], out);
  }
  void Close() override {}

 private:
  Transport* base_;
  std::vector<int> members_;
  int my_index_;
};

// Two-level allreduce for multi-host topologies (reference:
// horovod/common/ops/nccl_operations.cc:267 NCCLHierarchicalAllreduce /
// mpi_operations.cc:331 shared-mem hierarchical allgather): intra-host
// reduce-scatter to spread the load, cross-host ring allreduce among the
// per-host shards, intra-host allgather. host_of[r] = host index of
// transport rank r. Cross-host traffic drops from N ring peers to
// num_hosts, which is the win once per-host rank counts grow.
Status HierarchicalAllreduce(Transport* t, void* buf, int64_t count,
                             DataType dtype, RedOp op,
                             const std::vector<int>& host_of);

// Two-level allgatherv (reference: mpi_operations.cc:331 shared-mem
// hierarchical allgather): gather to the host leader, ring-allgather
// per-host bundles among leaders only, leader scatters blocks to rank
// offsets and broadcasts locally. Cross-host connections drop from
// all-pairs to leaders-only; works for ANY host grouping (no equal
// ranks-per-host requirement — bundles are variable-size).
Status HierarchicalAllgatherv(Transport* t, const void* sendbuf,
                              void* recvbuf,
                              const std::vector<int64_t>& counts,
                              DataType dtype,
                              const std::vector<int>& host_of);

// Gather variable-size blocks: rank r contributes counts[r] elements from
// sendbuf; recvbuf (sum(counts) elements) receives blocks ordered by rank.
Status RingAllgatherv(Transport* t, const void* sendbuf, void* recvbuf,
                      const std::vector<int64_t>& counts, DataType dtype);

// In-place Adasum allreduce via vector-halving distance-doubling
// (reference spec: adasum/adasum.h:194-343 FusedAllreduce + the pairwise
// rule a <- (1 - dot/2|a|^2) a + (1 - dot/2|b|^2) b at :397-407).
// Float dtypes only; transport size must be a power of two.
Status VhddAdasum(Transport* t, void* buf, int64_t count, DataType dtype);

// Binomial-tree broadcast of `count` elements from `root`.
Status TreeBroadcast(Transport* t, void* buf, int64_t count, DataType dtype,
                     int root);

// Pairwise exchange: send_splits[r] elements go to rank r (in rank order in
// sendbuf); recv_splits[r] elements arrive from rank r (in rank order in
// recvbuf). Splits are element counts of `dtype`.
Status PairwiseAlltoallv(Transport* t, const void* sendbuf, void* recvbuf,
                         const std::vector<int64_t>& send_splits,
                         const std::vector<int64_t>& recv_splits,
                         DataType dtype);

// Reduce then scatter: input `count` = sum(recv_counts) elements in sendbuf;
// this rank's reduced block (recv_counts[rank] elements, offset = prefix sum)
// lands in recvbuf.
Status RingReducescatter(Transport* t, const void* sendbuf, void* recvbuf,
                         const std::vector<int64_t>& recv_counts,
                         DataType dtype, RedOp op);

Status DisseminationBarrier(Transport* t);

// Elementwise accumulate src into dst (used by fusion + tests).
void ReduceInto(void* dst, const void* src, int64_t count, DataType dtype,
                RedOp op);

// Scale buffer elementwise by `factor` (pre/postscale application,
// reference: ScaleBufferCPUImpl, horovod/common/ops/collective_operations.h:91).
void ScaleBuffer(void* buf, int64_t count, DataType dtype, double factor);

// Fill `count` elements with the identity of `op` (0 for sum, +max for min,
// lowest for max, 1 for prod) — the contribution of a joined/entry-less rank
// to a fused reduction (the reference restricts Join to sum, where zero is
// the identity; using the true identity extends it to min/max/prod).
void FillReduceIdentity(void* buf, int64_t count, DataType dtype, RedOp op);

}  // namespace hvdcore

#endif  // HVDCORE_COLLECTIVES_H_
