#include "transport.h"

#include <arpa/inet.h>
#include <cerrno>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <thread>

namespace hvdcore {

LogLevel GlobalLogLevel() {
  static LogLevel level = [] {
    const char* env = std::getenv("HVDTPU_LOG_LEVEL");
    if (!env) env = std::getenv("HOROVOD_LOG_LEVEL");
    if (!env) return LogLevel::kWarn;
    std::string s(env);
    if (s == "trace") return LogLevel::kTrace;
    if (s == "debug") return LogLevel::kDebug;
    if (s == "info") return LogLevel::kInfo;
    if (s == "warning" || s == "warn") return LogLevel::kWarn;
    if (s == "error") return LogLevel::kError;
    return LogLevel::kNone;
  }();
  return level;
}

void LogMsg(LogLevel level, int rank, const std::string& msg) {
  if (level < GlobalLogLevel()) return;
  static const char* names[] = {"TRACE", "DEBUG", "INFO", "WARN", "ERROR", ""};
  std::fprintf(stderr, "[hvdcore %s rank %d] %s\n",
               names[static_cast<int>(level)], rank, msg.c_str());
}

// --- LocalTransport --------------------------------------------------------

// Mailboxes for one in-process job: box[from * size + to] holds messages in
// flight from `from` to `to`.
class LocalHub {
 public:
  explicit LocalHub(int size) : size_(size), boxes_(size * size) {}

  void Push(int from, int to, const void* data, size_t len) {
    auto& box = boxes_[from * size_ + to];
    {
      std::lock_guard<std::mutex> g(mu_);
      box.emplace_back(static_cast<const uint8_t*>(data),
                       static_cast<const uint8_t*>(data) + len);
    }
    cv_.notify_all();
  }

  Status Pop(int from, int to, std::vector<uint8_t>* out) {
    auto& box = boxes_[from * size_ + to];
    std::unique_lock<std::mutex> g(mu_);
    if (!cv_.wait_for(g, std::chrono::seconds(300),
                      [&] { return !box.empty() || closed_; })) {
      return Status::Error(StatusCode::kUnknownError, "local recv timeout");
    }
    if (box.empty() && closed_)
      return Status::Error(StatusCode::kAborted, "transport closed");
    *out = std::move(box.front());
    box.pop_front();
    return Status::OK();
  }

  void CloseAll() {
    { std::lock_guard<std::mutex> g(mu_); closed_ = true; }
    cv_.notify_all();
  }

  static std::shared_ptr<LocalHub> Get(const std::string& job, int size) {
    static std::mutex reg_mu;
    static std::map<std::string, std::weak_ptr<LocalHub>> registry;
    std::lock_guard<std::mutex> g(reg_mu);
    auto it = registry.find(job);
    if (it != registry.end()) {
      if (auto hub = it->second.lock()) return hub;
    }
    auto hub = std::make_shared<LocalHub>(size);
    registry[job] = hub;
    return hub;
  }

 private:
  int size_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::vector<std::deque<std::vector<uint8_t>>> boxes_;
  bool closed_ = false;
};

std::unique_ptr<LocalTransport> LocalTransport::Create(const std::string& job,
                                                       int rank, int size) {
  return std::unique_ptr<LocalTransport>(
      new LocalTransport(LocalHub::Get(job, size), rank, size));
}

LocalTransport::LocalTransport(std::shared_ptr<LocalHub> hub, int rank,
                               int size)
    : hub_(std::move(hub)), rank_(rank), size_(size) {}

LocalTransport::~LocalTransport() = default;

Status LocalTransport::Send(int to, const void* data, size_t len) {
  hub_->Push(rank_, to, data, len);
  return Status::OK();
}

Status LocalTransport::Recv(int from, std::vector<uint8_t>* out) {
  return hub_->Pop(from, rank_, out);
}

Status LocalTransport::SendRecv(int to, const void* sdata, size_t slen,
                                int from, std::vector<uint8_t>* out) {
  hub_->Push(rank_, to, sdata, slen);
  return hub_->Pop(from, rank_, out);
}

void LocalTransport::Close() { hub_->CloseAll(); }

// --- TcpTransport ----------------------------------------------------------

namespace {

Status ParseHostPort(const std::string& hp, std::string* host, int* port) {
  size_t colon = hp.rfind(':');
  if (colon == std::string::npos)
    return Status::Error(StatusCode::kInvalidArgument, "bad address " + hp);
  *host = hp.substr(0, colon);
  *port = std::atoi(hp.c_str() + colon + 1);
  return Status::OK();
}

void SetSockOpts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// Write exactly len bytes (blocking fd).
Status WriteAll(int fd, const void* data, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return Status::Error(StatusCode::kUnknownError,
                           std::string("send: ") + std::strerror(errno));
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return Status::OK();
}

Status ReadAll(int fd, void* data, size_t len) {
  uint8_t* p = static_cast<uint8_t*>(data);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n < 0) {
      if (errno == EINTR) continue;
      return Status::Error(StatusCode::kUnknownError,
                           std::string("recv: ") + std::strerror(errno));
    }
    if (n == 0)
      return Status::Error(StatusCode::kAborted, "peer closed connection");
    p += n;
    len -= static_cast<size_t>(n);
  }
  return Status::OK();
}

}  // namespace

// Ports reserved by ReserveListenPort(), keyed by port number. The fd stays
// bound+listening from reservation until TcpTransport::Create consumes it.
namespace {
std::mutex g_reserved_mu;
std::map<int, int> g_reserved_listeners;  // port -> listening fd
}  // namespace

int ReserveListenPort() {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = 0;  // ephemeral
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  if (::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) < 0) {
    ::close(fd);
    return -1;
  }
  int port = ntohs(addr.sin_port);
  std::lock_guard<std::mutex> lock(g_reserved_mu);
  auto it = g_reserved_listeners.find(port);
  if (it != g_reserved_listeners.end()) ::close(it->second);
  g_reserved_listeners[port] = fd;
  return port;
}

namespace {
int TakeReservedListenFd(int port) {
  std::lock_guard<std::mutex> lock(g_reserved_mu);
  auto it = g_reserved_listeners.find(port);
  if (it == g_reserved_listeners.end()) return -1;
  int fd = it->second;
  g_reserved_listeners.erase(it);
  return fd;
}
}  // namespace

Status TcpTransport::Create(int rank, const std::vector<std::string>& peers,
                            double timeout_s,
                            std::unique_ptr<TcpTransport>* out) {
  const int size = static_cast<int>(peers.size());
  std::vector<int> fds(size, -1);

  std::string host;
  int port = 0;
  Status st = ParseHostPort(peers[rank], &host, &port);
  if (!st.ok()) return st;

  // Listen socket for this rank: prefer a socket reserved at rendezvous
  // time (already bound + listening, no steal window); otherwise bind to
  // all interfaces at our assigned port.
  int listen_fd = TakeReservedListenFd(port);
  if (listen_fd < 0) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0)
      return Status::Error(StatusCode::kUnknownError, "socket() failed");
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) < 0) {
      ::close(listen_fd);
      return Status::Error(
          StatusCode::kUnknownError,
          "bind " + peers[rank] + ": " + std::strerror(errno));
    }
    if (::listen(listen_fd, size) < 0) {
      ::close(listen_fd);
      return Status::Error(StatusCode::kUnknownError, "listen failed");
    }
  }

  // Connector thread: dial every lower rank (with retries — peers may not
  // be listening yet). Handshake = our rank as u32.
  Status connect_status = Status::OK();
  std::thread connector([&] {
    for (int peer = 0; peer < rank; ++peer) {
      std::string phost;
      int pport = 0;
      Status s = ParseHostPort(peers[peer], &phost, &pport);
      if (!s.ok()) { connect_status = s; return; }
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      if (getaddrinfo(phost.c_str(), nullptr, &hints, &res) != 0 || !res) {
        connect_status = Status::Error(StatusCode::kUnknownError,
                                       "getaddrinfo " + phost);
        return;
      }
      sockaddr_in peer_addr = *reinterpret_cast<sockaddr_in*>(res->ai_addr);
      peer_addr.sin_port = htons(static_cast<uint16_t>(pport));
      freeaddrinfo(res);

      auto deadline = std::chrono::steady_clock::now() +
                      std::chrono::duration<double>(timeout_s);
      int fd = -1;
      while (true) {
        fd = ::socket(AF_INET, SOCK_STREAM, 0);
        if (::connect(fd, reinterpret_cast<sockaddr*>(&peer_addr),
                      sizeof(peer_addr)) == 0)
          break;
        ::close(fd);
        fd = -1;
        if (std::chrono::steady_clock::now() > deadline) {
          connect_status = Status::Error(
              StatusCode::kUnknownError,
              "connect to rank " + std::to_string(peer) + " (" + peers[peer] +
                  ") timed out");
          return;
        }
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
      }
      SetSockOpts(fd);
      uint32_t my_rank = static_cast<uint32_t>(rank);
      Status w = WriteAll(fd, &my_rank, sizeof(my_rank));
      if (!w.ok()) { connect_status = w; ::close(fd); return; }
      fds[peer] = fd;
    }
  });

  // Accept every higher rank.
  Status accept_status = Status::OK();
  for (int need = size - 1 - rank; need > 0; --need) {
    pollfd pfd{listen_fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(timeout_s * 1000));
    if (pr <= 0) {
      accept_status = Status::Error(StatusCode::kUnknownError,
                                    "timed out waiting for peer connections");
      break;
    }
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) {
      accept_status = Status::Error(StatusCode::kUnknownError, "accept failed");
      break;
    }
    SetSockOpts(fd);
    uint32_t peer_rank = 0;
    Status r = ReadAll(fd, &peer_rank, sizeof(peer_rank));
    if (!r.ok() || peer_rank >= static_cast<uint32_t>(size)) {
      ::close(fd);
      accept_status = Status::Error(StatusCode::kUnknownError,
                                    "bad handshake from peer");
      break;
    }
    fds[peer_rank] = fd;
  }

  connector.join();
  ::close(listen_fd);
  if (!connect_status.ok() || !accept_status.ok()) {
    for (int fd : fds)
      if (fd >= 0) ::close(fd);
    return connect_status.ok() ? accept_status : connect_status;
  }
  out->reset(new TcpTransport(rank, std::move(fds)));
  return Status::OK();
}

TcpTransport::~TcpTransport() { Close(); }

void TcpTransport::Close() {
  for (int& fd : fds_) {
    if (fd >= 0) {
      ::close(fd);
      fd = -1;
    }
  }
}

Status TcpTransport::Send(int to, const void* data, size_t len) {
  if (to == rank_)
    return Status::Error(StatusCode::kInvalidArgument, "send to self");
  uint64_t frame = len;
  Status st = WriteAll(fds_[to], &frame, sizeof(frame));
  if (!st.ok()) return st;
  return WriteAll(fds_[to], data, len);
}

Status TcpTransport::Recv(int from, std::vector<uint8_t>* out) {
  uint64_t frame = 0;
  Status st = ReadAll(fds_[from], &frame, sizeof(frame));
  if (!st.ok()) return st;
  out->resize(frame);
  return frame ? ReadAll(fds_[from], out->data(), frame) : Status::OK();
}

// Full-duplex exchange: drive both directions with poll() so neither side
// blocks on a full socket buffer (classic ring-allreduce requirement).
Status TcpTransport::SendRecv(int to, const void* sdata, size_t slen, int from,
                              std::vector<uint8_t>* out) {
  if (to == rank_ && from == rank_) {
    out->assign(static_cast<const uint8_t*>(sdata),
                static_cast<const uint8_t*>(sdata) + slen);
    return Status::OK();
  }
  // Compose framed send buffer.
  std::vector<uint8_t> sbuf(sizeof(uint64_t) + slen);
  uint64_t frame = slen;
  std::memcpy(sbuf.data(), &frame, sizeof(frame));
  std::memcpy(sbuf.data() + sizeof(frame), sdata, slen);

  size_t sent = 0;
  size_t rcvd = 0;
  bool have_frame = false;
  uint64_t rframe = 0;
  std::vector<uint8_t> hdr(sizeof(uint64_t));

  int sfd = fds_[to];
  int rfd = fds_[from];
  while (sent < sbuf.size() || !have_frame || rcvd < rframe) {
    pollfd pfds[2];
    int n = 0;
    int send_idx = -1, recv_idx = -1;
    if (sent < sbuf.size()) {
      pfds[n] = {sfd, POLLOUT, 0};
      send_idx = n++;
    }
    if (!have_frame || rcvd < rframe) {
      pfds[n] = {rfd, POLLIN, 0};
      recv_idx = n++;
    }
    int pr = ::poll(pfds, n, 300000);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return Status::Error(StatusCode::kUnknownError, "poll failed");
    }
    if (pr == 0)
      return Status::Error(StatusCode::kUnknownError, "sendrecv timeout");
    if (send_idx >= 0 && (pfds[send_idx].revents & (POLLOUT | POLLERR))) {
      // MSG_DONTWAIT: a blocking send() would sleep until the peer drains
      // its receive buffer — with every rank in the ring sending at once
      // that deadlocks as soon as the payload exceeds sndbuf+rcvbuf. A
      // partial nonblocking write keeps the recv direction serviced.
      ssize_t w = ::send(sfd, sbuf.data() + sent, sbuf.size() - sent,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w < 0 && errno != EINTR && errno != EAGAIN)
        return Status::Error(StatusCode::kUnknownError,
                             std::string("send: ") + std::strerror(errno));
      if (w > 0) sent += static_cast<size_t>(w);
    }
    if (recv_idx >= 0 && (pfds[recv_idx].revents & (POLLIN | POLLHUP))) {
      if (!have_frame) {
        ssize_t r = ::recv(rfd, hdr.data() + rcvd, hdr.size() - rcvd, 0);
        if (r == 0)
          return Status::Error(StatusCode::kAborted, "peer closed");
        if (r < 0 && errno != EINTR && errno != EAGAIN)
          return Status::Error(StatusCode::kUnknownError, "recv failed");
        if (r > 0) {
          rcvd += static_cast<size_t>(r);
          if (rcvd == hdr.size()) {
            std::memcpy(&rframe, hdr.data(), sizeof(rframe));
            out->resize(rframe);
            have_frame = true;
            rcvd = 0;
          }
        }
      } else if (rcvd < rframe) {
        ssize_t r = ::recv(rfd, out->data() + rcvd, rframe - rcvd, 0);
        if (r == 0)
          return Status::Error(StatusCode::kAborted, "peer closed");
        if (r < 0 && errno != EINTR && errno != EAGAIN)
          return Status::Error(StatusCode::kUnknownError, "recv failed");
        if (r > 0) rcvd += static_cast<size_t>(r);
      }
    }
  }
  return Status::OK();
}

}  // namespace hvdcore
