// C API exported to the Python binding via ctypes.
//
// The reference exposes a ctypes-visible C API from its shared library
// (reference: horovod/common/operations.cc:887-1353 horovod_* functions,
// loaded by horovod/common/basics.py:48). Same pattern here: opaque context
// handle + flat-argument entry points. All functions are thread-safe w.r.t.
// the single cycle-driver thread plus any number of enqueueing threads.
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "core.h"

using namespace hvdcore;

namespace {
// Error strings returned to Python must outlive the call; keep them in a
// per-context slot guarded by a mutex.
struct Ctx {
  std::unique_ptr<Core> core;
  std::mutex err_mu;
  std::string last_error;
};

void SetErr(Ctx* c, const std::string& e) {
  std::lock_guard<std::mutex> g(c->err_mu);
  c->last_error = e;
}
}  // namespace

extern "C" {

// Returns an opaque context or nullptr (check hvd_core_last_error via a
// temporary context-less slot is impossible; errors at create go to stderr).
void* hvd_core_create(int rank, int size, const char* transport,
                      const char* peers, int64_t fusion_threshold,
                      int64_t cache_capacity, double stall_warning_s,
                      const char* timeline_path, int delegate_data_ops,
                      double stall_shutdown_s) {
  CoreOptions opts;
  if (fusion_threshold > 0) opts.controller.fusion_threshold = fusion_threshold;
  if (cache_capacity > 0)
    opts.controller.cache_capacity = static_cast<size_t>(cache_capacity);
  if (stall_warning_s > 0) opts.controller.stall_warning_s = stall_warning_s;
  if (stall_shutdown_s > 0) opts.controller.stall_shutdown_s = stall_shutdown_s;
  if (timeline_path) opts.timeline_path = timeline_path;
  opts.delegate_data_ops = delegate_data_ops != 0;
  auto ctx = std::make_unique<Ctx>();
  Status st = Core::Create(rank, size, transport ? transport : "tcp",
                           peers ? peers : "", opts, &ctx->core);
  if (!st.ok()) {
    LogMsg(LogLevel::kError, rank, "core create failed: " + st.reason);
    return nullptr;
  }
  return ctx.release();
}

void hvd_core_destroy(void* h) { delete static_cast<Ctx*>(h); }

// Autotune: apply an agreed fusion threshold at a cycle boundary.
void hvd_core_set_fusion_threshold(void* h, int64_t bytes) {
  static_cast<Ctx*>(h)->core->SetFusionThreshold(bytes);
}

// Host topology for hierarchical collectives: host_of[r] = host index of
// global rank r; threshold = min buffer bytes for the two-level path
// (0 disables).
void hvd_core_set_topology(void* h, const int32_t* host_of, int n,
                           int64_t threshold) {
  std::vector<int> hosts(host_of, host_of + n);
  static_cast<Ctx*>(h)->core->SetTopology(hosts, threshold);
}

// Rendezvous bootstrap: reserve (bind+listen) an ephemeral port that a
// later hvd_core_create consumes, closing the publish-then-rebind race.
int hvd_reserve_listen_port() { return ReserveListenPort(); }

// --- delegated execution (external XLA data plane) ------------------------

int64_t hvd_core_next_delegated(void* h) {
  return static_cast<Ctx*>(h)->core->NextDelegated();
}

// Fills the fixed-size fields; returns 0 on success, -1 for a bad token.
// sizes layout depends on type (allreduce: per-tensor flat sizes;
// allgather: [rows per rank..., row_elems]; broadcast: [count, root]).
int hvd_core_delegated_info(void* h, int64_t token, int32_t* ps_id,
                            int32_t* type, int32_t* dtype, int32_t* red_op,
                            double* prescale, double* postscale,
                            int32_t* ntensors, int32_t* nsizes) {
  const Core::Delegated* d =
      static_cast<Ctx*>(h)->core->GetDelegated(token);
  if (!d) return -1;
  *ps_id = d->ps_id;
  *type = static_cast<int32_t>(d->resp.type);
  *dtype = static_cast<int32_t>(d->resp.dtype);
  *red_op = static_cast<int32_t>(d->resp.op);
  *prescale = d->resp.prescale;
  *postscale = d->resp.postscale;
  *ntensors = static_cast<int32_t>(d->resp.names.size());
  *nsizes = static_cast<int32_t>(d->resp.sizes.size());
  return 0;
}

// handles_out: ntensors entries (-1 = entry-less); sizes_out: nsizes.
int hvd_core_delegated_meta(void* h, int64_t token, int64_t* handles_out,
                            int64_t* sizes_out) {
  const Core::Delegated* d =
      static_cast<Ctx*>(h)->core->GetDelegated(token);
  if (!d) return -1;
  for (size_t i = 0; i < d->handles.size(); ++i)
    handles_out[i] = d->handles[i];
  for (size_t i = 0; i < d->resp.sizes.size(); ++i)
    sizes_out[i] = d->resp.sizes[i];
  return 0;
}

int hvd_core_delegated_complete(void* h, int64_t handle, const void* data,
                                int64_t nbytes, const int64_t* shape,
                                int32_t ndim, const char* error) {
  return static_cast<Ctx*>(h)->core->CompleteDelegatedEntry(
             handle, data, static_cast<size_t>(nbytes), shape, ndim, error)
             ? 0
             : -1;
}

void hvd_core_delegated_finish(void* h, int64_t token) {
  static_cast<Ctx*>(h)->core->FinishDelegated(token);
}

int hvd_core_rank(void* h) { return static_cast<Ctx*>(h)->core->rank(); }
int hvd_core_size(void* h) { return static_cast<Ctx*>(h)->core->size(); }

int hvd_core_add_process_set(void* h, const int* ranks, int n) {
  std::vector<int> v(ranks, ranks + n);
  return static_cast<Ctx*>(h)->core->AddProcessSet(v);
}

int hvd_core_remove_process_set(void* h, int ps_id) {
  return static_cast<Ctx*>(h)->core->RemoveProcessSet(ps_id) ? 0 : -1;
}

// req_type / red_op / dtype match the enums in common.h. splits may be null.
// Returns handle >= 0, or a negative error code (-1 duplicate name, -2 bad
// arguments, -3 shut down, -4 not a member of the process set).
int64_t hvd_core_enqueue(void* h, int ps_id, const char* name, int req_type,
                         int red_op, int dtype, const void* data,
                         const int64_t* shape, int ndim, int root_rank,
                         double prescale, double postscale,
                         const int32_t* splits, int nsplits) {
  Ctx* c = static_cast<Ctx*>(h);
  Request req;
  req.type = static_cast<ReqType>(req_type);
  req.op = static_cast<RedOp>(red_op);
  req.dtype = static_cast<DataType>(dtype);
  req.name = name ? name : "";
  req.root_rank = root_rank;
  req.prescale = prescale;
  req.postscale = postscale;
  if (shape && ndim > 0) req.shape.assign(shape, shape + ndim);
  if (splits && nsplits > 0) req.splits.assign(splits, splits + nsplits);
  size_t nbytes = 0;
  if (req.type != ReqType::kBarrier && req.type != ReqType::kJoin) {
    int64_t n = 1;
    for (int64_t d : req.shape) n *= d;
    nbytes = static_cast<size_t>(n) * DataTypeSize(req.dtype);
  }
  return c->core->Enqueue(ps_id, req, data, nbytes);
}

// Returns completed count this cycle; -1 once shut down; -2 on transport
// failure (all in-flight handles are failed).
int hvd_core_run_cycle(void* h) {
  return static_cast<Ctx*>(h)->core->RunCycle();
}

void hvd_core_request_shutdown(void* h) {
  static_cast<Ctx*>(h)->core->RequestShutdown();
}

int hvd_core_shutdown_complete(void* h) {
  return static_cast<Ctx*>(h)->core->ShutdownComplete() ? 1 : 0;
}

// 0 = in progress, 1 = done, 2 = error (see hvd_core_handle_error).
int hvd_core_poll(void* h, int64_t handle) {
  std::string err;
  return static_cast<int>(static_cast<Ctx*>(h)->core->Poll(handle, &err));
}

int hvd_core_wait(void* h, int64_t handle, double timeout_s) {
  Ctx* c = static_cast<Ctx*>(h);
  Status st = c->core->Wait(handle, timeout_s);
  if (!st.ok()) {
    SetErr(c, st.reason);
    return -1;
  }
  return 0;
}

const char* hvd_core_handle_error(void* h, int64_t handle) {
  Ctx* c = static_cast<Ctx*>(h);
  std::string err;
  c->core->Poll(handle, &err);
  SetErr(c, err);
  std::lock_guard<std::mutex> g(c->err_mu);
  return c->last_error.c_str();
}

// Output access: ndim/shape/bytes. Copy the payload out before Release.
int hvd_core_output_ndim(void* h, int64_t handle) {
  const Entry* e = static_cast<Ctx*>(h)->core->Get(handle);
  return e ? static_cast<int>(e->out_shape.size()) : -1;
}

int hvd_core_output_shape(void* h, int64_t handle, int64_t* shape_out) {
  const Entry* e = static_cast<Ctx*>(h)->core->Get(handle);
  if (!e) return -1;
  for (size_t i = 0; i < e->out_shape.size(); ++i)
    shape_out[i] = e->out_shape[i];
  return static_cast<int>(e->out_shape.size());
}

int64_t hvd_core_output_nbytes(void* h, int64_t handle) {
  const Entry* e = static_cast<Ctx*>(h)->core->Get(handle);
  return e ? static_cast<int64_t>(e->output.size()) : -1;
}

int hvd_core_output_copy(void* h, int64_t handle, void* dst,
                         int64_t dst_bytes) {
  const Entry* e = static_cast<Ctx*>(h)->core->Get(handle);
  if (!e || dst_bytes < static_cast<int64_t>(e->output.size())) return -1;
  std::memcpy(dst, e->output.data(), e->output.size());
  return 0;
}

int hvd_core_recv_splits(void* h, int64_t handle, int32_t* out, int n) {
  const Entry* e = static_cast<Ctx*>(h)->core->Get(handle);
  if (!e || static_cast<int>(e->recv_splits.size()) > n) return -1;
  for (size_t i = 0; i < e->recv_splits.size(); ++i) out[i] = e->recv_splits[i];
  return static_cast<int>(e->recv_splits.size());
}

void hvd_core_release(void* h, int64_t handle) {
  static_cast<Ctx*>(h)->core->Release(handle);
}

uint64_t hvd_core_cycles(void* h) {
  return static_cast<Ctx*>(h)->core->cycles();
}

uint64_t hvd_core_bytes_processed(void* h) {
  return static_cast<Ctx*>(h)->core->bytes_processed();
}

}  // extern "C"
