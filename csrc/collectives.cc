#include "collectives.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <limits>
#include <map>

namespace hvdcore {
namespace {

// --- half-precision conversion (IEEE fp16 and bfloat16) --------------------

float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t mant = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while ((mant & 0x400) == 0) {
        mant <<= 1;
        --exp;
      }
      mant &= 0x3FF;
      bits = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (mant << 13);
  } else {
    bits = sign | ((exp + 127 - 15) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

uint16_t FloatToHalf(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xFF) - 127 + 15;
  uint32_t mant = bits & 0x7FFFFF;
  // NaN must stay NaN (not clamp to Inf) so numerical errors upstream of an
  // fp16 reduction are not silently masked.
  if (((bits >> 23) & 0xFF) == 0xFF && mant != 0)
    return static_cast<uint16_t>(sign | 0x7E00);
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half_mant = mant >> shift;
    // round-to-nearest-even
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t halfway = 1u << (shift - 1);
    if (rem > halfway || (rem == halfway && (half_mant & 1))) ++half_mant;
    return static_cast<uint16_t>(sign | half_mant);
  }
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7C00);
  uint32_t half_mant = mant >> 13;
  uint32_t rem = mant & 0x1FFF;
  if (rem > 0x1000 || (rem == 0x1000 && (half_mant & 1))) {
    ++half_mant;
    if (half_mant == 0x400) {
      half_mant = 0;
      ++exp;
      if (exp >= 31) return static_cast<uint16_t>(sign | 0x7C00);
    }
  }
  return static_cast<uint16_t>(sign | (static_cast<uint32_t>(exp) << 10) |
                               half_mant);
}

float BF16ToFloat(uint16_t b) {
  uint32_t bits = static_cast<uint32_t>(b) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

uint16_t FloatToBF16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  // NaN must stay NaN: round-to-nearest-even below can carry a NaN mantissa
  // into the exponent (0x7FFFFFFF -> -0.0, sNaN -> Inf), silently masking
  // upstream numerical errors — same guard as FloatToHalf above.
  if ((bits & 0x7F800000u) == 0x7F800000u && (bits & 0x7FFFFFu) != 0)
    return static_cast<uint16_t>(((bits >> 16) & 0x8000u) | 0x7FC0u);
  // round-to-nearest-even on the truncated 16 bits
  uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

template <typename T>
void ReduceTyped(T* dst, const T* src, int64_t n, RedOp op) {
  switch (op) {
    case RedOp::kSum:
      for (int64_t i = 0; i < n; ++i) dst[i] = static_cast<T>(dst[i] + src[i]);
      break;
    case RedOp::kMin:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::min(dst[i], src[i]);
      break;
    case RedOp::kMax:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::max(dst[i], src[i]);
      break;
    case RedOp::kProd:
      for (int64_t i = 0; i < n; ++i) dst[i] = static_cast<T>(dst[i] * src[i]);
      break;
    default:
      break;  // kAdasum never reaches elementwise reduction (VhddAdasum)
  }
}

template <uint16_t (*ToBits)(float), float (*FromBits)(uint16_t)>
void ReduceHalfLike(uint16_t* dst, const uint16_t* src, int64_t n, RedOp op) {
  for (int64_t i = 0; i < n; ++i) {
    float a = FromBits(dst[i]);
    float b = FromBits(src[i]);
    float r;
    switch (op) {
      case RedOp::kSum: r = a + b; break;
      case RedOp::kMin: r = std::min(a, b); break;
      case RedOp::kMax: r = std::max(a, b); break;
      case RedOp::kProd: r = a * b; break;
      default: r = a; break;
    }
    dst[i] = ToBits(r);
  }
}

void ReduceBool(uint8_t* dst, const uint8_t* src, int64_t n, RedOp op) {
  // Sum/Max => logical OR, Min/Prod => logical AND (reference maps bool
  // allreduce onto MPI LOR/LAND semantics).
  if (op == RedOp::kSum || op == RedOp::kMax) {
    for (int64_t i = 0; i < n; ++i) dst[i] = dst[i] || src[i];
  } else {
    for (int64_t i = 0; i < n; ++i) dst[i] = dst[i] && src[i];
  }
}

}  // namespace

void ReduceInto(void* dst, const void* src, int64_t count, DataType dtype,
                RedOp op) {
  switch (dtype) {
    case DataType::kUint8:
      ReduceTyped(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src),
                  count, op);
      break;
    case DataType::kInt8:
      ReduceTyped(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src),
                  count, op);
      break;
    case DataType::kInt32:
      ReduceTyped(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src),
                  count, op);
      break;
    case DataType::kInt64:
      ReduceTyped(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src),
                  count, op);
      break;
    case DataType::kFloat32:
      ReduceTyped(static_cast<float*>(dst), static_cast<const float*>(src),
                  count, op);
      break;
    case DataType::kFloat64:
      ReduceTyped(static_cast<double*>(dst), static_cast<const double*>(src),
                  count, op);
      break;
    case DataType::kFloat16:
      ReduceHalfLike<FloatToHalf, HalfToFloat>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
          count, op);
      break;
    case DataType::kBFloat16:
      ReduceHalfLike<FloatToBF16, BF16ToFloat>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
          count, op);
      break;
    case DataType::kBool:
      ReduceBool(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src),
                 count, op);
      break;
  }
}

void ScaleBuffer(void* buf, int64_t count, DataType dtype, double factor) {
  if (factor == 1.0) return;
  switch (dtype) {
    case DataType::kFloat32: {
      float* p = static_cast<float*>(buf);
      float f = static_cast<float>(factor);
      for (int64_t i = 0; i < count; ++i) p[i] *= f;
      break;
    }
    case DataType::kFloat64: {
      double* p = static_cast<double*>(buf);
      for (int64_t i = 0; i < count; ++i) p[i] *= factor;
      break;
    }
    case DataType::kFloat16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      float f = static_cast<float>(factor);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToHalf(HalfToFloat(p[i]) * f);
      break;
    }
    case DataType::kBFloat16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      float f = static_cast<float>(factor);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToBF16(BF16ToFloat(p[i]) * f);
      break;
    }
    case DataType::kInt32: {
      int32_t* p = static_cast<int32_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<int32_t>(std::llround(p[i] * factor));
      break;
    }
    case DataType::kInt64: {
      int64_t* p = static_cast<int64_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<int64_t>(std::llround(p[i] * factor));
      break;
    }
    default:
      break;  // uint8/int8/bool: scaling not meaningful
  }
}

namespace {

template <typename T>
void FillTyped(void* buf, int64_t count, T value) {
  T* p = static_cast<T*>(buf);
  for (int64_t i = 0; i < count; ++i) p[i] = value;
}

}  // namespace

void FillReduceIdentity(void* buf, int64_t count, DataType dtype, RedOp op) {
  if (op == RedOp::kSum || op == RedOp::kAdasum) {
    std::memset(buf, 0, static_cast<size_t>(count) * DataTypeSize(dtype));
    return;
  }
  float fval = op == RedOp::kProd
                   ? 1.0f
                   : (op == RedOp::kMin ? std::numeric_limits<float>::infinity()
                                        : -std::numeric_limits<float>::infinity());
  switch (dtype) {
    case DataType::kFloat32:
      FillTyped<float>(buf, count, fval);
      break;
    case DataType::kFloat64:
      FillTyped<double>(buf, count, static_cast<double>(fval));
      break;
    case DataType::kFloat16:
      FillTyped<uint16_t>(buf, count, FloatToHalf(fval));
      break;
    case DataType::kBFloat16:
      FillTyped<uint16_t>(buf, count, FloatToBF16(fval));
      break;
    case DataType::kInt32:
      FillTyped<int32_t>(buf, count,
                         op == RedOp::kProd ? 1
                         : op == RedOp::kMin
                             ? std::numeric_limits<int32_t>::max()
                             : std::numeric_limits<int32_t>::lowest());
      break;
    case DataType::kInt64:
      FillTyped<int64_t>(buf, count,
                         op == RedOp::kProd ? 1
                         : op == RedOp::kMin
                             ? std::numeric_limits<int64_t>::max()
                             : std::numeric_limits<int64_t>::lowest());
      break;
    case DataType::kUint8:
      FillTyped<uint8_t>(buf, count,
                         op == RedOp::kProd ? 1
                         : op == RedOp::kMin
                             ? std::numeric_limits<uint8_t>::max()
                             : std::numeric_limits<uint8_t>::lowest());
      break;
    case DataType::kInt8:
      FillTyped<int8_t>(buf, count,
                        op == RedOp::kProd ? 1
                        : op == RedOp::kMin ? std::numeric_limits<int8_t>::max()
                                            : std::numeric_limits<int8_t>::lowest());
      break;
    case DataType::kBool:
      // min/prod identity = 1 (true), max identity = 0 (false)
      FillTyped<uint8_t>(buf, count, op == RedOp::kMax ? 0 : 1);
      break;
  }
}

namespace {

// Chunk boundaries for the ring: chunk i covers [offsets[i], offsets[i+1]).
std::vector<int64_t> EvenOffsets(int64_t count, int size) {
  std::vector<int64_t> offsets(size + 1, 0);
  int64_t base = count / size, rem = count % size;
  for (int i = 0; i < size; ++i)
    offsets[i + 1] = offsets[i] + base + (i < rem ? 1 : 0);
  return offsets;
}

std::vector<int64_t> PrefixOffsets(const std::vector<int64_t>& counts) {
  std::vector<int64_t> offsets(counts.size() + 1, 0);
  for (size_t i = 0; i < counts.size(); ++i)
    offsets[i + 1] = offsets[i] + counts[i];
  return offsets;
}

// Ring reduce-scatter on buf with chunk layout `offsets`. After this, chunk
// (rank+1) % size in buf holds the fully reduced values.
Status RingReduceScatterPhase(Transport* t, uint8_t* buf,
                              const std::vector<int64_t>& offsets,
                              DataType dtype, RedOp op) {
  const int size = t->size();
  const int rank = t->rank();
  const size_t esize = DataTypeSize(dtype);
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  std::vector<uint8_t> incoming;
  for (int s = 0; s < size - 1; ++s) {
    int send_chunk = (rank - s + size) % size;
    int recv_chunk = (rank - s - 1 + size) % size;
    uint8_t* send_ptr = buf + offsets[send_chunk] * esize;
    int64_t send_n = offsets[send_chunk + 1] - offsets[send_chunk];
    Status st = t->SendRecv(right, send_ptr, send_n * esize, left, &incoming);
    if (!st.ok()) return st;
    int64_t recv_n = offsets[recv_chunk + 1] - offsets[recv_chunk];
    if (incoming.size() != static_cast<size_t>(recv_n) * esize)
      return Status::Error(StatusCode::kUnknownError, "ring size mismatch");
    ReduceInto(buf + offsets[recv_chunk] * esize, incoming.data(), recv_n,
               dtype, op);
  }
  return Status::OK();
}

}  // namespace

Status RingAllreduce(Transport* t, void* vbuf, int64_t count, DataType dtype,
                     RedOp op) {
  const int size = t->size();
  if (size == 1 || count == 0) return Status::OK();
  uint8_t* buf = static_cast<uint8_t*>(vbuf);
  const size_t esize = DataTypeSize(dtype);
  auto offsets = EvenOffsets(count, size);
  const int rank = t->rank();
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;

  Status st = RingReduceScatterPhase(t, buf, offsets, dtype, op);
  if (!st.ok()) return st;

  // Allgather phase: circulate reduced chunks. After reduce-scatter, this
  // rank owns fully-reduced chunk (rank+1) % size.
  std::vector<uint8_t> incoming;
  for (int s = 0; s < size - 1; ++s) {
    int send_chunk = (rank + 1 - s + size) % size;
    int recv_chunk = (rank - s + size) % size;
    uint8_t* send_ptr = buf + offsets[send_chunk] * esize;
    int64_t send_n = offsets[send_chunk + 1] - offsets[send_chunk];
    st = t->SendRecv(right, send_ptr, send_n * esize, left, &incoming);
    if (!st.ok()) return st;
    int64_t recv_n = offsets[recv_chunk + 1] - offsets[recv_chunk];
    if (incoming.size() != static_cast<size_t>(recv_n) * esize)
      return Status::Error(StatusCode::kUnknownError, "ring size mismatch");
    std::memcpy(buf + offsets[recv_chunk] * esize, incoming.data(),
                incoming.size());
  }
  return Status::OK();
}

Status RingAllgatherv(Transport* t, const void* sendbuf, void* recvbuf,
                      const std::vector<int64_t>& counts, DataType dtype) {
  const int size = t->size();
  const int rank = t->rank();
  const size_t esize = DataTypeSize(dtype);
  auto offsets = PrefixOffsets(counts);
  uint8_t* out = static_cast<uint8_t*>(recvbuf);
  if (counts[rank] > 0)
    std::memcpy(out + offsets[rank] * esize, sendbuf, counts[rank] * esize);
  if (size == 1) return Status::OK();
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  std::vector<uint8_t> incoming;
  for (int s = 0; s < size - 1; ++s) {
    int send_block = (rank - s + size) % size;
    int recv_block = (rank - s - 1 + size) % size;
    Status st = t->SendRecv(right, out + offsets[send_block] * esize,
                            counts[send_block] * esize, left, &incoming);
    if (!st.ok()) return st;
    if (incoming.size() != static_cast<size_t>(counts[recv_block]) * esize)
      return Status::Error(StatusCode::kUnknownError, "allgather size mismatch");
    std::memcpy(out + offsets[recv_block] * esize, incoming.data(),
                incoming.size());
  }
  return Status::OK();
}

Status TreeBroadcast(Transport* t, void* vbuf, int64_t count, DataType dtype,
                     int root) {
  const int size = t->size();
  if (size == 1) return Status::OK();
  const int rank = t->rank();
  const size_t nbytes = static_cast<size_t>(count) * DataTypeSize(dtype);
  const int vrank = (rank - root + size) % size;

  // Receive once from the parent, then forward to children: standard
  // binomial tree on virtual ranks.
  int mask = 1;
  while (mask < size && (vrank & mask) == 0) mask <<= 1;
  if (vrank != 0) {
    int parent = ((vrank & ~mask) + root) % size;
    std::vector<uint8_t> data;
    Status st = t->Recv(parent, &data);
    if (!st.ok()) return st;
    if (data.size() != nbytes)
      return Status::Error(StatusCode::kUnknownError, "broadcast size mismatch");
    std::memcpy(vbuf, data.data(), nbytes);
  }
  // Children: vrank + m for m in descending powers of two below mask.
  for (int m = mask >> 1; m >= 1; m >>= 1) {
    int child_v = vrank + m;
    if (child_v < size) {
      Status st = t->Send((child_v + root) % size, vbuf, nbytes);
      if (!st.ok()) return st;
    }
  }
  return Status::OK();
}

Status PairwiseAlltoallv(Transport* t, const void* sendbuf, void* recvbuf,
                         const std::vector<int64_t>& send_splits,
                         const std::vector<int64_t>& recv_splits,
                         DataType dtype) {
  const int size = t->size();
  const int rank = t->rank();
  const size_t esize = DataTypeSize(dtype);
  auto soff = PrefixOffsets(send_splits);
  auto roff = PrefixOffsets(recv_splits);
  const uint8_t* in = static_cast<const uint8_t*>(sendbuf);
  uint8_t* out = static_cast<uint8_t*>(recvbuf);
  std::memcpy(out + roff[rank] * esize, in + soff[rank] * esize,
              send_splits[rank] * esize);
  std::vector<uint8_t> incoming;
  for (int d = 1; d < size; ++d) {
    int to = (rank + d) % size;
    int from = (rank - d + size) % size;
    Status st = t->SendRecv(to, in + soff[to] * esize,
                            send_splits[to] * esize, from, &incoming);
    if (!st.ok()) return st;
    if (incoming.size() != static_cast<size_t>(recv_splits[from]) * esize)
      return Status::Error(StatusCode::kUnknownError, "alltoall size mismatch");
    std::memcpy(out + roff[from] * esize, incoming.data(), incoming.size());
  }
  return Status::OK();
}

Status RingReducescatter(Transport* t, const void* sendbuf, void* recvbuf,
                         const std::vector<int64_t>& recv_counts,
                         DataType dtype, RedOp op) {
  const int size = t->size();
  const int rank = t->rank();
  const size_t esize = DataTypeSize(dtype);
  auto offsets = PrefixOffsets(recv_counts);
  const int64_t total = offsets[size];
  // Work on a scratch copy: the reduce-scatter phase mutates the full buffer.
  std::vector<uint8_t> scratch(static_cast<size_t>(total) * esize);
  std::memcpy(scratch.data(), sendbuf, scratch.size());
  if (size > 1) {
    Status st = RingReduceScatterPhase(t, scratch.data(), offsets, dtype, op);
    if (!st.ok()) return st;
  }
  // RingReduceScatterPhase leaves chunk (rank+1)%size fully reduced at this
  // rank... but reducescatter semantics say this rank gets chunk `rank`.
  // One extra neighbor exchange aligns them — unless size == 1.
  if (size == 1) {
    std::memcpy(recvbuf, scratch.data() + offsets[rank] * esize,
                recv_counts[rank] * esize);
    return Status::OK();
  }
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  int owned = (rank + 1) % size;  // chunk index this rank holds reduced
  std::vector<uint8_t> incoming;
  Status st = t->SendRecv(right, scratch.data() + offsets[owned] * esize,
                          recv_counts[owned] * esize, left, &incoming);
  if (!st.ok()) return st;
  if (incoming.size() != static_cast<size_t>(recv_counts[rank]) * esize)
    return Status::Error(StatusCode::kUnknownError, "reducescatter mismatch");
  std::memcpy(recvbuf, incoming.data(), incoming.size());
  return Status::OK();
}

// --- Adasum (VHDD) ---------------------------------------------------------
// Vector-halving distance-doubling adaptive summation (reference:
// adasum/adasum.h:194-343). The whole reduction runs in double precision:
// the convergence-preserving property rests on the dot-product
// coefficients, and fp16/bf16 partial dots would defeat it.

namespace {

Status ToDoubleVec(const void* buf, int64_t count, DataType dtype,
                   std::vector<double>* out) {
  out->resize(static_cast<size_t>(count));
  switch (dtype) {
    case DataType::kFloat32: {
      const float* p = static_cast<const float*>(buf);
      for (int64_t i = 0; i < count; ++i) (*out)[i] = p[i];
      return Status::OK();
    }
    case DataType::kFloat64:
      std::memcpy(out->data(), buf, static_cast<size_t>(count) * 8);
      return Status::OK();
    case DataType::kFloat16: {
      const uint16_t* p = static_cast<const uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i) (*out)[i] = HalfToFloat(p[i]);
      return Status::OK();
    }
    case DataType::kBFloat16: {
      const uint16_t* p = static_cast<const uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i) (*out)[i] = BF16ToFloat(p[i]);
      return Status::OK();
    }
    default:
      return Status::Error(StatusCode::kInvalidArgument,
                           "Adasum requires a floating-point dtype");
  }
}

void FromDoubleVec(const std::vector<double>& in, void* buf, DataType dtype) {
  const int64_t count = static_cast<int64_t>(in.size());
  switch (dtype) {
    case DataType::kFloat32: {
      float* p = static_cast<float*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<float>(in[i]);
      break;
    }
    case DataType::kFloat64:
      std::memcpy(buf, in.data(), static_cast<size_t>(count) * 8);
      break;
    case DataType::kFloat16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToHalf(static_cast<float>(in[i]));
      break;
    }
    case DataType::kBFloat16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToBF16(static_cast<float>(in[i]));
      break;
    }
    default:
      break;  // unreachable: ToDoubleVec validated the dtype
  }
}

struct AdasumLevel {
  int partner;
  int64_t kept_start, kept_count;   // span kept after halving
  int64_t sent_start, sent_count;   // span handed to the partner
};

}  // namespace

Status VhddAdasum(Transport* t, void* vbuf, int64_t count, DataType dtype) {
  const int size = t->size();
  const int rank = t->rank();
  if (size == 1) return Status::OK();
  if ((size & (size - 1)) != 0)
    return Status::Error(
        StatusCode::kInvalidArgument,
        "Adasum requires a power-of-two rank count (reference restriction, "
        "horovod/tensorflow/__init__.py:138-154); got " +
            std::to_string(size));
  if (dtype != DataType::kFloat32 && dtype != DataType::kFloat64 &&
      dtype != DataType::kFloat16 && dtype != DataType::kBFloat16)
    return Status::Error(StatusCode::kInvalidArgument,
                         "Adasum requires a floating-point dtype");

  // Spans travel in the tensor's NATIVE dtype (the reference exchanges
  // native buffers too — fp64 on the wire would double/quadruple
  // traffic); only the dot accumulators and the combine run in double.
  const size_t esize = DataTypeSize(dtype);
  uint8_t* buf = static_cast<uint8_t*>(vbuf);

  std::vector<AdasumLevel> levels;
  std::vector<uint8_t> incoming;
  std::vector<double> mine_d, theirs_d;
  int64_t start = 0, seg = count;
  Status st;

  // Forward: halve the vector, double the distance.
  for (int d = 1; d < size; d <<= 1) {
    const int partner = rank ^ d;
    const bool low = rank < partner;  // low keeps the left half
    const int64_t left = seg - seg / 2;
    const int64_t right = seg / 2;
    AdasumLevel lv;
    lv.partner = partner;
    if (low) {
      lv.kept_start = start;
      lv.kept_count = left;
      lv.sent_start = start + left;
      lv.sent_count = right;
    } else {
      lv.kept_start = start + left;
      lv.kept_count = right;
      lv.sent_start = start;
      lv.sent_count = left;
    }
    st = t->SendRecv(partner, buf + lv.sent_start * esize,
                     static_cast<size_t>(lv.sent_count) * esize, partner,
                     &incoming);
    if (!st.ok()) return st;
    if (incoming.size() != static_cast<size_t>(lv.kept_count) * esize)
      return Status::Error(StatusCode::kUnknownError, "adasum size mismatch");

    st = ToDoubleVec(buf + lv.kept_start * esize, lv.kept_count, dtype,
                     &mine_d);
    if (!st.ok()) return st;
    st = ToDoubleVec(incoming.data(), lv.kept_count, dtype, &theirs_d);
    if (!st.ok()) return st;

    // Partial dot products over the kept span; `a` is always the lower
    // sub-block's logical vector so every rank applies the same formula.
    const bool i_hold_a = (rank & d) == 0;
    double aa = 0, bb = 0, ab = 0;
    for (int64_t i = 0; i < lv.kept_count; ++i) {
      const double m = mine_d[i], th = theirs_d[i];
      ab += m * th;
      if (i_hold_a) {
        aa += m * m;
        bb += th * th;
      } else {
        aa += th * th;
        bb += m * m;
      }
    }
    // Sum the three scalars over the 2d ranks holding pieces of (a, b):
    // recursive doubling with strides 1..d (reference: the distributed
    // dot-product reduction inside FusedAllreduce).
    double dots[3] = {aa, bb, ab};
    for (int s = 1; s <= d; s <<= 1) {
      const int p2 = rank ^ s;
      st = t->SendRecv(p2, dots, sizeof(dots), p2, &incoming);
      if (!st.ok()) return st;
      if (incoming.size() != sizeof(dots))
        return Status::Error(StatusCode::kUnknownError,
                             "adasum dot exchange mismatch");
      const double* other = reinterpret_cast<const double*>(incoming.data());
      dots[0] += other[0];
      dots[1] += other[1];
      dots[2] += other[2];
    }
    aa = dots[0];
    bb = dots[1];
    ab = dots[2];

    // a <- (1 - dot/2|a|^2) a + (1 - dot/2|b|^2) b; a zero-norm operand is
    // the Adasum identity (joined ranks contribute zeros), coefficient 1
    // on the other side (reference: adasum.h:397-407 with norm guards).
    const double acoef = aa > 0.0 ? 1.0 - ab / (2.0 * aa) : 1.0;
    const double bcoef = bb > 0.0 ? 1.0 - ab / (2.0 * bb) : 1.0;
    for (int64_t i = 0; i < lv.kept_count; ++i) {
      const double m = mine_d[i], th = theirs_d[i];
      mine_d[i] = i_hold_a ? acoef * m + bcoef * th
                           : acoef * th + bcoef * m;
    }
    FromDoubleVec(mine_d, buf + lv.kept_start * esize, dtype);

    levels.push_back(lv);
    start = lv.kept_start;
    seg = lv.kept_count;
  }

  // Reverse: distance-halving allgather reconstructs the full vector.
  for (auto it = levels.rbegin(); it != levels.rend(); ++it) {
    st = t->SendRecv(it->partner, buf + it->kept_start * esize,
                     static_cast<size_t>(it->kept_count) * esize,
                     it->partner, &incoming);
    if (!st.ok()) return st;
    if (incoming.size() != static_cast<size_t>(it->sent_count) * esize)
      return Status::Error(StatusCode::kUnknownError,
                           "adasum reconstruct mismatch");
    std::memcpy(buf + it->sent_start * esize, incoming.data(),
                incoming.size());
  }
  return Status::OK();
}

Status DisseminationBarrier(Transport* t) {
  const int size = t->size();
  const int rank = t->rank();
  uint8_t token = 1;
  std::vector<uint8_t> incoming;
  for (int mask = 1; mask < size; mask <<= 1) {
    int to = (rank + mask) % size;
    int from = (rank - mask + size) % size;
    Status st = t->SendRecv(to, &token, 1, from, &incoming);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

namespace {
// Group ranks by host in ONE pass (this runs per collective on the
// cycle thread — keep it O(size)). Rank order within a host defines
// the local order; hosts are numbered by first appearance, identically
// on every rank. Returns the groups + the index of `rank`'s group.
int GroupByHost(const std::vector<int>& host_of, int rank,
                std::vector<std::vector<int>>* by_host) {
  std::map<int, int> host_slot;  // host id -> dense host index
  const int size = static_cast<int>(host_of.size());
  for (int r = 0; r < size; ++r) {
    auto it = host_slot.find(host_of[r]);
    if (it == host_slot.end()) {
      it = host_slot.emplace(host_of[r],
                             static_cast<int>(by_host->size())).first;
      by_host->emplace_back();
    }
    (*by_host)[it->second].push_back(r);
  }
  return host_slot[host_of[rank]];
}
}  // namespace

Status HierarchicalAllreduce(Transport* t, void* vbuf, int64_t count,
                             DataType dtype, RedOp op,
                             const std::vector<int>& host_of) {
  const int size = t->size();
  const int rank = t->rank();
  if (static_cast<int>(host_of.size()) != size)
    return Status::Error(StatusCode::kInvalidArgument,
                         "host_of size != transport size");
  if (size == 1 || count == 0) return Status::OK();

  std::vector<std::vector<int>> by_host;
  const std::vector<int>& my_local =
      by_host[GroupByHost(host_of, rank, &by_host)];
  const int k = static_cast<int>(my_local.size());
  const int num_hosts = static_cast<int>(by_host.size());
  if (k == 1 || num_hosts == 1)
    return RingAllreduce(t, vbuf, count, dtype, op);
  // Chunk boundaries must agree across hosts: require homogeneous local
  // sizes (the reference's hierarchical paths assume the same).
  for (const auto& group : by_host) {
    if (static_cast<int>(group.size()) != k)
      return Status::Error(StatusCode::kInvalidArgument,
                           "hierarchical allreduce needs equal ranks per "
                           "host");
  }

  int li = 0;
  while (my_local[li] != rank) ++li;
  // Cross-host group: the rank holding local index li on every host
  // (first-appearance host order keeps it identical on every rank).
  std::vector<int> cross;
  cross.reserve(num_hosts);
  for (const auto& group : by_host) cross.push_back(group[li]);
  int ci = 0;
  while (cross[ci] != rank) ++ci;

  const size_t esize = DataTypeSize(dtype);
  auto offsets = EvenOffsets(count, k);
  std::vector<int64_t> counts(k);
  for (int i = 0; i < k; ++i) counts[i] = offsets[i + 1] - offsets[i];
  uint8_t* buf = static_cast<uint8_t*>(vbuf);

  // 1. Intra-host reduce-scatter: local rank li ends up owning the
  //    locally-reduced chunk li.
  SubsetTransport local(t, my_local, li);
  std::vector<uint8_t> shard(static_cast<size_t>(counts[li]) * esize);
  Status st = RingReducescatter(&local, buf, shard.data(), counts, dtype,
                                op);
  if (!st.ok()) return st;

  // 2. Cross-host ring allreduce of chunk li among the hosts' li-ranks —
  //    the only phase that touches the cross-host network, moving
  //    count/k elements instead of count.
  SubsetTransport xhost(t, cross, ci);
  st = RingAllreduce(&xhost, shard.data(), counts[li], dtype, op);
  if (!st.ok()) return st;

  // 3. Intra-host allgather of the fully-reduced chunks.
  return RingAllgatherv(&local, shard.data(), buf, counts, dtype);
}

Status HierarchicalAllgatherv(Transport* t, const void* sendbuf,
                              void* recvbuf,
                              const std::vector<int64_t>& counts,
                              DataType dtype,
                              const std::vector<int>& host_of) {
  const int size = t->size();
  const int rank = t->rank();
  if (static_cast<int>(host_of.size()) != size ||
      static_cast<int>(counts.size()) != size)
    return Status::Error(StatusCode::kInvalidArgument,
                         "host_of/counts size != transport size");
  if (size == 1) {
    if (counts[0] > 0)
      std::memcpy(recvbuf, sendbuf, counts[0] * DataTypeSize(dtype));
    return Status::OK();
  }

  std::vector<std::vector<int>> by_host;
  const std::vector<int>& my_local =
      by_host[GroupByHost(host_of, rank, &by_host)];
  const int num_hosts = static_cast<int>(by_host.size());
  const int k = static_cast<int>(my_local.size());
  if (num_hosts == 1 || k == size)
    return RingAllgatherv(t, sendbuf, recvbuf, counts, dtype);

  const size_t esize = DataTypeSize(dtype);
  auto offsets = PrefixOffsets(counts);  // rank-order output offsets
  uint8_t* out = static_cast<uint8_t*>(recvbuf);
  int li = 0;
  while (my_local[li] != rank) ++li;
  const int leader = my_local[0];

  // 1. Gather to the host leader (local members in local order).
  if (li != 0) {
    Status st = t->Send(leader, sendbuf, counts[rank] * esize);
    if (!st.ok()) return st;
  } else {
    // Leader builds this host's bundle: members' blocks back to back.
    std::vector<uint8_t> bundle;
    int64_t bundle_elems = 0;
    for (int r : my_local) bundle_elems += counts[r];
    bundle.reserve(static_cast<size_t>(bundle_elems) * esize);
    std::vector<uint8_t> incoming;
    for (int r : my_local) {
      if (r == rank) {
        const uint8_t* p = static_cast<const uint8_t*>(sendbuf);
        bundle.insert(bundle.end(), p, p + counts[r] * esize);
      } else {
        Status st = t->Recv(r, &incoming);
        if (!st.ok()) return st;
        if (incoming.size() != static_cast<size_t>(counts[r]) * esize)
          return Status::Error(StatusCode::kUnknownError,
                               "hier allgather bundle size mismatch");
        bundle.insert(bundle.end(), incoming.begin(), incoming.end());
      }
    }

    // 2. Ring allgatherv of bundles among leaders (cross-host plane).
    std::vector<int> leaders;
    std::vector<int64_t> bundle_counts;
    int ci = -1;
    for (const auto& group : by_host) {
      if (group[0] == rank) ci = static_cast<int>(leaders.size());
      leaders.push_back(group[0]);
      int64_t c = 0;
      for (int r : group) c += counts[r];
      bundle_counts.push_back(c);
    }
    SubsetTransport xhost(t, leaders, ci);
    auto boff = PrefixOffsets(bundle_counts);
    std::vector<uint8_t> all(static_cast<size_t>(boff[num_hosts]) * esize);
    Status st = RingAllgatherv(&xhost, bundle.data(), all.data(),
                               bundle_counts, dtype);
    if (!st.ok()) return st;

    // 3a. Scatter bundle blocks into rank-order output offsets.
    for (int h = 0; h < num_hosts; ++h) {
      size_t pos = static_cast<size_t>(boff[h]) * esize;
      for (int r : by_host[h]) {
        std::memcpy(out + offsets[r] * esize, all.data() + pos,
                    counts[r] * esize);
        pos += counts[r] * esize;
      }
    }
  }

  // 3b. Leader broadcasts the assembled output to local members.
  SubsetTransport local(t, my_local, li);
  return TreeBroadcast(&local, out, offsets[size], dtype, 0);
}

}  // namespace hvdcore
