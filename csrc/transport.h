// Point-to-point transports for the control plane and the CPU data plane.
//
// The reference's equivalents are the Gloo TCP context (reference:
// horovod/common/gloo/gloo_context.cc) for CPU jobs and MPI. On TPU-VMs
// there is no MPI; the native core talks plain TCP over DCN for host-side
// coordination, while tensor bytes on TPU ride XLA/ICI (Python side). This
// TCP layer doubles as the CPU-fallback data plane (the gloo analog).
//
// Two implementations:
//  - TcpTransport: full socket mesh between N processes.
//  - LocalTransport: in-process queues keyed by a job id, letting N threads
//    act as N ranks for unit tests (the reference tests its controller only
//    under real launchers; in-process ranks make the native core testable
//    from a single pytest process).
#ifndef HVDCORE_TRANSPORT_H_
#define HVDCORE_TRANSPORT_H_

#include <condition_variable>
#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "common.h"

namespace hvdcore {

class Transport {
 public:
  virtual ~Transport() = default;
  virtual int rank() const = 0;
  virtual int size() const = 0;

  // Blocking framed send/recv. Messages from a given peer arrive in order.
  virtual Status Send(int to, const void* data, size_t len) = 0;
  virtual Status Recv(int from, std::vector<uint8_t>* out) = 0;
  // Simultaneous exchange (ring steps would deadlock two blocking Sends
  // whose socket buffers fill; this primitive multiplexes with poll()).
  virtual Status SendRecv(int to, const void* sdata, size_t slen, int from,
                          std::vector<uint8_t>* out) = 0;
  virtual void Close() = 0;
};

// Rendezvous bootstrap support: bind + listen an ephemeral port NOW and
// keep the socket open so the address a worker publishes to the KV store
// cannot be stolen before TcpTransport::Create runs (a close-then-rebind
// dance would be a TOCTOU race). Create() consumes the reserved fd when
// peers[rank] names a reserved port. Returns -1 on failure.
int ReserveListenPort();

// --- LocalTransport --------------------------------------------------------

class LocalHub;  // shared mailbox registry for one in-process "job"

class LocalTransport : public Transport {
 public:
  // All ranks of `job` within this process share one hub.
  static std::unique_ptr<LocalTransport> Create(const std::string& job,
                                                int rank, int size);
  ~LocalTransport() override;

  int rank() const override { return rank_; }
  int size() const override { return size_; }
  Status Send(int to, const void* data, size_t len) override;
  Status Recv(int from, std::vector<uint8_t>* out) override;
  Status SendRecv(int to, const void* sdata, size_t slen, int from,
                  std::vector<uint8_t>* out) override;
  void Close() override;

 private:
  LocalTransport(std::shared_ptr<LocalHub> hub, int rank, int size);
  std::shared_ptr<LocalHub> hub_;
  int rank_, size_;
};

// --- TcpTransport ----------------------------------------------------------

class TcpTransport : public Transport {
 public:
  // peers[i] = "host:port" where rank i listens. Establishes the full mesh:
  // listens on peers[rank], connects to lower ranks, accepts higher ranks.
  static Status Create(int rank, const std::vector<std::string>& peers,
                       double timeout_s, std::unique_ptr<TcpTransport>* out);
  ~TcpTransport() override;

  int rank() const override { return rank_; }
  int size() const override { return static_cast<int>(fds_.size()); }
  Status Send(int to, const void* data, size_t len) override;
  Status Recv(int from, std::vector<uint8_t>* out) override;
  Status SendRecv(int to, const void* sdata, size_t slen, int from,
                  std::vector<uint8_t>* out) override;
  void Close() override;

 private:
  TcpTransport(int rank, std::vector<int> fds) : rank_(rank), fds_(std::move(fds)) {}
  int rank_;
  std::vector<int> fds_;  // fds_[peer] = connected socket, -1 for self
};

}  // namespace hvdcore

#endif  // HVDCORE_TRANSPORT_H_
