// Core runtime context: tensor queues, per-process-set controllers,
// fusion-buffer execution, async handles.
//
// Native rethink of the reference's HorovodGlobalState + background loop
// (reference: horovod/common/operations.cc:385 BackgroundThreadLoop, :706
// RunLoopOnce, :257 PerformOperation; process-set table:
// horovod/common/process_set.h:89-171). Differences by design:
//  - The cycle is *driven from outside* (the Python coordinator thread calls
//    RunCycle) instead of owning a thread: on TPU the heavy data plane is
//    compiled XLA programs dispatched from Python, so the native core slots
//    under the same driver thread rather than competing with it.
//  - Each process set is a channel over one multiplexed transport; a set's
//    controller, response cache, queue, and fusion buffer are private to the
//    channel, mirroring the reference's per-set controller+queue.
#ifndef HVDCORE_CORE_H_
#define HVDCORE_CORE_H_

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <vector>

#include "collectives.h"
#include "controller.h"
#include "message.h"
#include "timeline.h"
#include "transport.h"

namespace hvdcore {

// Channel-multiplexed wrapper over one base transport: every message gets a
// u32 channel header; out-of-channel frames are parked in per-(channel,peer)
// inboxes. Lets N process sets share one socket mesh (the reference gives
// each process set its own communicator; one mesh + channels is the
// TCP-native equivalent).
class MuxTransport {
 public:
  explicit MuxTransport(std::unique_ptr<Transport> base)
      : base_(std::move(base)) {}
  int rank() const { return base_->rank(); }
  int size() const { return base_->size(); }
  Status Send(uint32_t ch, int to, const void* data, size_t len);
  Status Recv(uint32_t ch, int from, std::vector<uint8_t>* out);
  Status SendRecv(uint32_t ch, int to, const void* sdata, size_t slen,
                  int from, std::vector<uint8_t>* out);
  void Close() { base_->Close(); }

 private:
  Status TakeFromInbox(uint32_t ch, int from, std::vector<uint8_t>* out,
                       bool* found);
  std::unique_ptr<Transport> base_;
  std::map<std::pair<uint32_t, int>, std::vector<std::vector<uint8_t>>> inbox_;
};

// Adapts (channel, member-rank-list) to the Transport interface consumed by
// the controller and the ring collectives.
class ChannelView : public Transport {
 public:
  ChannelView(MuxTransport* mux, uint32_t ch, std::vector<int> members,
              int my_index)
      : mux_(mux), ch_(ch), members_(std::move(members)), my_index_(my_index) {}
  int rank() const override { return my_index_; }
  int size() const override { return static_cast<int>(members_.size()); }
  Status Send(int to, const void* data, size_t len) override {
    return mux_->Send(ch_, members_[to], data, len);
  }
  Status Recv(int from, std::vector<uint8_t>* out) override {
    return mux_->Recv(ch_, members_[from], out);
  }
  Status SendRecv(int to, const void* sdata, size_t slen, int from,
                  std::vector<uint8_t>* out) override {
    return mux_->SendRecv(ch_, members_[to], sdata, slen, members_[from], out);
  }
  void Close() override {}

 private:
  MuxTransport* mux_;
  uint32_t ch_;
  std::vector<int> members_;
  int my_index_;
};

enum class HandleState : int { kInProgress = 0, kDone = 1, kError = 2 };

struct Entry {
  Request req;
  std::vector<uint8_t> input;    // copied at enqueue (owner-safe)
  std::vector<uint8_t> output;
  std::vector<int64_t> out_shape;
  std::vector<int32_t> recv_splits;  // alltoall only
  HandleState state = HandleState::kInProgress;
  std::string error;
};

struct CoreOptions {
  ControllerOptions controller;
  std::string timeline_path;  // empty = disabled
  // Delegate data-op execution to the embedding runtime: negotiation and
  // fusion ordering stay native, but agreed allreduce/allgather/broadcast/
  // reducescatter responses queue for external execution (the XLA/ICI data
  // plane) instead of running the TCP ring collectives. The analog of the
  // reference's NCCL-executes/controller-negotiates split
  // (reference: horovod/common/ops/nccl_operations.cc:80-119 — the NCCL
  // data plane bootstraps and orders through the MPI/Gloo controller).
  bool delegate_data_ops = false;
};

class Core {
 public:
  // transport_kind: "tcp" (peers = "host:port,...") or "local"
  // (peers = job name for the in-process hub).
  static Status Create(int rank, int size, const std::string& transport_kind,
                       const std::string& peers, const CoreOptions& opts,
                       std::unique_ptr<Core>* out);

  // Must be called collectively in the same order on every member rank.
  // Returns the new process-set id (>0; 0 is the global set).
  int AddProcessSet(const std::vector<int>& ranks);
  bool RemoveProcessSet(int ps_id);

  // Thread-safe enqueue; returns handle >= 0 or negative error code
  // (-1 duplicate name, -2 bad args, -3 shutting down, -4 not a member).
  int64_t Enqueue(int ps_id, const Request& req, const void* data,
                  size_t nbytes);

  // One negotiation+execution cycle over every process set this rank
  // belongs to. Returns completed-handle count, or -1 after shutdown.
  int RunCycle();

  // Apply an autotuned fusion threshold to every process-set controller.
  void SetFusionThreshold(int64_t bytes);

  // Host topology for hierarchical collectives: host_of[r] = host index
  // of global rank r; threshold = minimum buffer bytes before the
  // two-level path engages (0 disables). Settable at runtime (autotune).
  void SetTopology(const std::vector<int>& host_of, int64_t threshold);

  void RequestShutdown() { shutdown_requested_.store(true); }
  bool ShutdownComplete() const { return shutdown_complete_.load(); }

  HandleState Poll(int64_t handle, std::string* error);
  Status Wait(int64_t handle, double timeout_s);
  const Entry* Get(int64_t handle);
  void Release(int64_t handle);

  // --- delegated execution (external data plane; delegate_data_ops) ---
  struct Delegated {
    int ps_id = 0;
    Response resp;                 // the negotiated (possibly fused) bucket
    std::vector<int64_t> handles;  // parallel to resp.names; -1 entry-less
  };
  // Pop the next delegated response token (FIFO) or 0 when none pending.
  int64_t NextDelegated();
  // Valid until FinishDelegated(token).
  const Delegated* GetDelegated(int64_t token);
  void FinishDelegated(int64_t token);
  // Write the externally computed result into the entry and complete its
  // handle; empty/NULL error means success. False if the handle is gone.
  bool CompleteDelegatedEntry(int64_t handle, const void* data,
                              size_t nbytes, const int64_t* shape, int ndim,
                              const char* error);

  int rank() const { return mux_->rank(); }
  int size() const { return mux_->size(); }
  uint64_t cycles() const { return cycles_; }
  uint64_t bytes_processed() const { return bytes_processed_; }
  Timeline* timeline() { return timeline_.get(); }

 private:
  Core(std::unique_ptr<Transport> base, const CoreOptions& opts);

  struct PsState {
    uint32_t channel;
    std::vector<int> members;           // global ranks, sorted
    int my_index;                       // -1 if not a member
    bool active = false;  // cycled only after cross-rank activation
    std::unique_ptr<ChannelView> view;
    std::unique_ptr<Controller> controller;
    std::vector<std::pair<Request, int64_t>> queue;  // pending (req, handle)
    std::map<std::string, int64_t> inflight;         // name -> handle
    std::vector<uint8_t> fusion_buffer;              // persistent
  };

  void ExecuteResponse(PsState& ps, const Response& resp, int* completed);
  void DelegateResponse(int ps_id, PsState& ps, const Response& resp);
  void CompleteHandle(int64_t handle, HandleState state,
                      const std::string& error);
  // Hierarchical-collective gate: process-set-local host indices when the
  // two-level path should engage for a buffer of `nbytes` (empty vector =
  // stay flat). Snapshots topology under mu_ (SetTopology is
  // runtime-settable).
  std::vector<int> HierViewHosts(const PsState& ps, int64_t nbytes);

  CoreOptions opts_;
  std::unique_ptr<MuxTransport> mux_;
  std::unique_ptr<Timeline> timeline_;
  std::vector<int> host_of_;            // empty = flat topology
  int64_t hierarchical_threshold_ = 0;  // bytes; 0 = disabled

  std::mutex mu_;  // guards handles_ + queues + process-set table
  std::condition_variable cv_;
  std::map<int, std::unique_ptr<PsState>> process_sets_;
  // Creation/removal is staged locally and applied only once every rank has
  // staged the same change (MIN-consensus through the global set's phase-A
  // exchange; see controller.h PsConsensus). Both lists are consumed FIFO,
  // which is why every rank must stage changes in the same order.
  std::vector<int> staged_adds_;      // ps ids awaiting activation
  std::vector<int> staged_removals_;  // ps ids awaiting removal
  int next_ps_id_ = 1;
  uint32_t next_channel_ = 1;
  std::map<int64_t, std::unique_ptr<Entry>> handles_;
  // Entries pinned by an in-flight ExecuteResponse (raw Entry* held without
  // mu_ during network execution). Release() defers destruction of pinned
  // entries into zombies_, freed when the response finishes.
  std::set<int64_t> executing_handles_;
  std::vector<std::unique_ptr<Entry>> zombies_;
  int64_t next_handle_ = 0;
  std::map<int64_t, Delegated> delegated_;  // token -> record
  std::deque<int64_t> delegated_order_;     // unclaimed tokens, FIFO
  int64_t next_token_ = 1;
  std::atomic<bool> shutdown_requested_{false};
  std::atomic<bool> shutdown_complete_{false};
  uint64_t cycles_ = 0;
  uint64_t bytes_processed_ = 0;
};

}  // namespace hvdcore

#endif  // HVDCORE_CORE_H_
