// Native-core unit test: N in-process threads act as N ranks over the
// LocalTransport hub (see transport.h). Covers the negotiation protocol,
// cache fast path, fusion, every collective, validation errors, process
// sets, and join. Exits non-zero on failure.
//
// (The reference only exercises its controller under real launchers in
// test/parallel/; in-process ranks make the same protocol testable from one
// binary.)
#include <atomic>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

#include "core.h"

using namespace hvdcore;

namespace {

std::atomic<int> failures{0};

#define CHECK(cond, msg)                                         \
  do {                                                           \
    if (!(cond)) {                                               \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, msg); \
      failures.fetch_add(1);                                     \
    }                                                            \
  } while (0)

void RunUntilDone(Core* core, int64_t handle) {
  std::string err;
  while (core->Poll(handle, &err) == HandleState::kInProgress) {
    int rc = core->RunCycle();
    if (rc < 0) break;
  }
}

Request MakeReq(ReqType type, const std::string& name, DataType dtype,
                std::vector<int64_t> shape, RedOp op = RedOp::kSum,
                int root = -1, double pre = 1.0, double post = 1.0,
                std::vector<int32_t> splits = {}) {
  Request r;
  r.type = type;
  r.name = name;
  r.dtype = dtype;
  r.shape = std::move(shape);
  r.op = op;
  r.root_rank = root;
  r.prescale = pre;
  r.postscale = post;
  r.splits = std::move(splits);
  return r;
}

void RankMain(int rank, int size, const std::string& job) {
  CoreOptions opts;
  opts.controller.fusion_threshold = 1 << 20;
  std::unique_ptr<Core> core;
  Status st = Core::Create(rank, size, "local", job, opts, &core);
  CHECK(st.ok(), st.reason.c_str());
  if (!st.ok()) return;

  // --- allreduce sum, three steady-state steps (exercises cache path) ----
  for (int step = 0; step < 3; ++step) {
    std::vector<float> data(37);
    for (size_t i = 0; i < data.size(); ++i)
      data[i] = static_cast<float>(rank + 1) * (i + 1);
    int64_t h = core->Enqueue(
        0, MakeReq(ReqType::kAllreduce, "t.allreduce", DataType::kFloat32,
                   {37}),
        data.data(), data.size() * 4);
    CHECK(h >= 0, "enqueue allreduce");
    RunUntilDone(core.get(), h);
    std::string err;
    CHECK(core->Poll(h, &err) == HandleState::kDone, err.c_str());
    const Entry* e = core->Get(h);
    float expect_factor = size * (size + 1) / 2.0f;
    const float* out = reinterpret_cast<const float*>(e->output.data());
    bool good = true;
    for (size_t i = 0; i < data.size(); ++i)
      if (std::fabs(out[i] - expect_factor * (i + 1)) > 1e-3) good = false;
    CHECK(good, "allreduce values");
    core->Release(h);
  }

  // --- fused grouped allreduce: two tensors in one cycle -----------------
  {
    std::vector<double> a(16, rank + 1.0), b(8, 2.0 * rank);
    int64_t ha = core->Enqueue(
        0, MakeReq(ReqType::kAllreduce, "fuse.a", DataType::kFloat64, {16}),
        a.data(), a.size() * 8);
    int64_t hb = core->Enqueue(
        0, MakeReq(ReqType::kAllreduce, "fuse.b", DataType::kFloat64, {8}),
        b.data(), b.size() * 8);
    CHECK(ha >= 0 && hb >= 0, "enqueue fused");
    RunUntilDone(core.get(), ha);
    RunUntilDone(core.get(), hb);
    const Entry* ea = core->Get(ha);
    const Entry* eb = core->Get(hb);
    double sum_ranks = size * (size + 1) / 2.0;      // sum of (rank+1)
    double sum_2ranks = size * (size - 1.0);          // sum of 2*rank
    CHECK(std::fabs(reinterpret_cast<const double*>(ea->output.data())[0] -
                    sum_ranks) < 1e-9,
          "fused a");
    CHECK(std::fabs(reinterpret_cast<const double*>(eb->output.data())[7] -
                    sum_2ranks) < 1e-9,
          "fused b");
    core->Release(ha);
    core->Release(hb);
  }

  // --- allreduce min/max --------------------------------------------------
  {
    std::vector<int32_t> v(5, rank * 10);
    int64_t h = core->Enqueue(
        0, MakeReq(ReqType::kAllreduce, "t.max", DataType::kInt32, {5},
                   RedOp::kMax),
        v.data(), v.size() * 4);
    RunUntilDone(core.get(), h);
    const Entry* e = core->Get(h);
    CHECK(reinterpret_cast<const int32_t*>(e->output.data())[0] ==
              (size - 1) * 10,
          "max value");
    core->Release(h);
  }

  // --- ragged allgather ---------------------------------------------------
  {
    int64_t rows = rank + 1;
    std::vector<float> v(rows * 2);
    for (int64_t i = 0; i < rows * 2; ++i) v[i] = rank * 100.0f + i;
    int64_t h = core->Enqueue(
        0, MakeReq(ReqType::kAllgather, "t.allgather", DataType::kFloat32,
                   {rows, 2}),
        v.data(), v.size() * 4);
    CHECK(h >= 0, "enqueue allgather");
    RunUntilDone(core.get(), h);
    std::string err;
    CHECK(core->Poll(h, &err) == HandleState::kDone, err.c_str());
    const Entry* e = core->Get(h);
    int64_t total_rows = 0;
    for (int r = 0; r < size; ++r) total_rows += r + 1;
    CHECK(e->out_shape.size() == 2 && e->out_shape[0] == total_rows,
          "allgather shape");
    const float* out = reinterpret_cast<const float*>(e->output.data());
    // Block for rank r begins after sum_{q<r}(q+1) rows.
    int64_t off_rows = 0;
    bool good = true;
    for (int r = 0; r < size; ++r) {
      for (int64_t i = 0; i < (r + 1) * 2; ++i)
        if (std::fabs(out[off_rows * 2 + i] - (r * 100.0f + i)) > 1e-3)
          good = false;
      off_rows += r + 1;
    }
    CHECK(good, "allgather values");
    core->Release(h);
  }

  // --- broadcast from root 1 ---------------------------------------------
  {
    std::vector<int64_t> v(9, rank == 1 ? 4242 : -1);
    int64_t h = core->Enqueue(
        0, MakeReq(ReqType::kBroadcast, "t.bcast", DataType::kInt64, {9},
                   RedOp::kSum, /*root=*/1),
        v.data(), v.size() * 8);
    RunUntilDone(core.get(), h);
    const Entry* e = core->Get(h);
    CHECK(reinterpret_cast<const int64_t*>(e->output.data())[8] == 4242,
          "broadcast value");
    core->Release(h);
  }

  // --- alltoall with uneven splits ----------------------------------------
  {
    // Rank r sends (d+1) rows to destination d; row payload = r*1000+d.
    std::vector<int32_t> splits(size);
    int64_t rows = 0;
    for (int d = 0; d < size; ++d) {
      splits[d] = d + 1;
      rows += d + 1;
    }
    std::vector<float> v(rows * 3);
    int64_t row = 0;
    for (int d = 0; d < size; ++d)
      for (int k = 0; k < d + 1; ++k, ++row)
        for (int c = 0; c < 3; ++c) v[row * 3 + c] = rank * 1000.0f + d;
    int64_t h = core->Enqueue(
        0, MakeReq(ReqType::kAlltoall, "t.alltoall", DataType::kFloat32,
                   {rows, 3}, RedOp::kSum, -1, 1.0, 1.0, splits),
        v.data(), v.size() * 4);
    CHECK(h >= 0, "enqueue alltoall");
    RunUntilDone(core.get(), h);
    std::string err;
    CHECK(core->Poll(h, &err) == HandleState::kDone, err.c_str());
    const Entry* e = core->Get(h);
    // Every source sends us (rank+1) rows stamped src*1000+rank.
    CHECK(e->out_shape[0] == static_cast<int64_t>(size) * (rank + 1),
          "alltoall rows");
    const float* out = reinterpret_cast<const float*>(e->output.data());
    bool good = true;
    for (int src = 0; src < size; ++src)
      for (int k = 0; k < rank + 1; ++k) {
        int64_t r2 = static_cast<int64_t>(src) * (rank + 1) + k;
        if (std::fabs(out[r2 * 3] - (src * 1000.0f + rank)) > 1e-3)
          good = false;
      }
    CHECK(good, "alltoall values");
    CHECK(e->recv_splits.size() == static_cast<size_t>(size) &&
              e->recv_splits[0] == rank + 1,
          "alltoall recv splits");
    core->Release(h);
  }

  // --- reducescatter ------------------------------------------------------
  {
    int64_t rows = 2 * size + 1;  // uneven split
    std::vector<float> v(rows * 2, 1.0f + rank);
    int64_t h = core->Enqueue(
        0, MakeReq(ReqType::kReducescatter, "t.rs", DataType::kFloat32,
                   {rows, 2}),
        v.data(), v.size() * 4);
    RunUntilDone(core.get(), h);
    std::string err;
    CHECK(core->Poll(h, &err) == HandleState::kDone, err.c_str());
    const Entry* e = core->Get(h);
    int64_t expect_rows = rows / size + (rank < rows % size ? 1 : 0);
    CHECK(e->out_shape[0] == expect_rows, "reducescatter shape");
    float expect = size * (size + 1) / 2.0f;
    CHECK(std::fabs(reinterpret_cast<const float*>(e->output.data())[0] -
                    expect) < 1e-3,
          "reducescatter value");
    core->Release(h);
  }

  // --- barrier ------------------------------------------------------------
  {
    int64_t h = core->Enqueue(
        0, MakeReq(ReqType::kBarrier, "t.barrier", DataType::kUint8, {}),
        nullptr, 0);
    RunUntilDone(core.get(), h);
    std::string err;
    CHECK(core->Poll(h, &err) == HandleState::kDone, "barrier");
    core->Release(h);
  }

  // --- validation error: mismatched dtype ---------------------------------
  {
    std::vector<uint8_t> v(8 * 4, 0);
    Request req = rank == 0
                      ? MakeReq(ReqType::kAllreduce, "t.bad", DataType::kInt32,
                                {8})
                      : MakeReq(ReqType::kAllreduce, "t.bad",
                                DataType::kFloat32, {8});
    int64_t h = core->Enqueue(0, req, v.data(), 8 * 4);
    RunUntilDone(core.get(), h);
    std::string err;
    CHECK(core->Poll(h, &err) == HandleState::kError, "mismatch should fail");
    CHECK(err.find("data types") != std::string::npos, err.c_str());
    core->Release(h);
  }

  // --- process set {0, size-1} -------------------------------------------
  {
    std::vector<int> members = {0, size - 1};
    int ps = core->AddProcessSet(members);
    CHECK(ps > 0, "add process set");
    bool member = rank == 0 || rank == size - 1;
    if (member) {
      std::vector<float> v(4, static_cast<float>(rank));
      int64_t h = core->Enqueue(
          ps, MakeReq(ReqType::kAllreduce, "ps.t", DataType::kFloat32, {4}),
          v.data(), 16);
      CHECK(h >= 0, "enqueue on subset");
      RunUntilDone(core.get(), h);
      const Entry* e = core->Get(h);
      CHECK(std::fabs(reinterpret_cast<const float*>(e->output.data())[0] -
                      (0.0f + size - 1)) < 1e-4,
            "subset allreduce");
      core->Release(h);
    } else {
      int64_t h = core->Enqueue(
          ps, MakeReq(ReqType::kAllreduce, "ps.t", DataType::kFloat32, {4}),
          nullptr, 16);
      CHECK(h == -4, "non-member enqueue rejected");
    }
    CHECK(core->RemoveProcessSet(ps), "remove process set");
  }

  // --- join ---------------------------------------------------------------
  {
    // Odd ranks join immediately; even ranks allreduce one more tensor
    // (joined ranks contribute zeros), then join.
    if (rank % 2 == 1) {
      int64_t hj = core->Enqueue(
          0, MakeReq(ReqType::kJoin, "__join__", DataType::kUint8, {}),
          nullptr, 0);
      RunUntilDone(core.get(), hj);
      std::string err;
      CHECK(core->Poll(hj, &err) == HandleState::kDone, "join done");
      core->Release(hj);
    } else {
      std::vector<float> v(6, 1.0f);
      int64_t h = core->Enqueue(
          0, MakeReq(ReqType::kAllreduce, "t.joined", DataType::kFloat32,
                     {6}),
          v.data(), 24);
      RunUntilDone(core.get(), h);
      std::string err;
      CHECK(core->Poll(h, &err) == HandleState::kDone, err.c_str());
      const Entry* e = core->Get(h);
      int evens = (size + 1) / 2;
      CHECK(std::fabs(reinterpret_cast<const float*>(e->output.data())[0] -
                      static_cast<float>(evens)) < 1e-4,
            "join-padded allreduce");
      core->Release(h);
      int64_t hj = core->Enqueue(
          0, MakeReq(ReqType::kJoin, "__join__", DataType::kUint8, {}),
          nullptr, 0);
      RunUntilDone(core.get(), hj);
      core->Release(hj);
    }
  }

  // --- coordinated shutdown ----------------------------------------------
  core->RequestShutdown();
  while (!core->ShutdownComplete()) {
    if (core->RunCycle() < 0) break;
  }
  CHECK(core->ShutdownComplete(), "shutdown consensus");
}

}  // namespace

int main() {
  for (int size : {2, 4}) {
    std::string job = "test_core_job_" + std::to_string(size);
    std::vector<std::thread> threads;
    for (int r = 0; r < size; ++r)
      threads.emplace_back(RankMain, r, size, job);
    for (auto& t : threads) t.join();
    std::printf("size=%d: %s\n", size,
                failures.load() == 0 ? "OK" : "FAILURES");
  }
  if (failures.load()) {
    std::printf("test_core: %d failure(s)\n", failures.load());
    return 1;
  }
  std::printf("test_core: all passed\n");
  return 0;
}
