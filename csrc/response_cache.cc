#include "response_cache.h"

#include <algorithm>

namespace hvdcore {

namespace {
bool SameParams(const Request& a, const Request& b) {
  return a.type == b.type && a.op == b.op && a.dtype == b.dtype &&
         a.root_rank == b.root_rank && a.prescale == b.prescale &&
         a.postscale == b.postscale && a.shape == b.shape &&
         a.splits == b.splits;
}
}  // namespace

ResponseCache::CacheState ResponseCache::Lookup(const Request& req) const {
  auto it = by_name_.find(req.name);
  if (it == by_name_.end()) return CacheState::kMiss;
  const Entry& e = entries_[it->second];
  return SameParams(e.req, req) ? CacheState::kHit : CacheState::kInvalid;
}

size_t ResponseCache::Put(const Request& req, const Response& resp) {
  auto it = by_name_.find(req.name);
  if (it != by_name_.end()) {
    size_t slot = it->second;
    entries_[slot].req = req;
    entries_[slot].resp = resp;
    entries_[slot].seq = next_seq_++;
    Touch(slot);
    return slot;
  }
  size_t slot;
  if (entries_.size() < capacity_) {
    slot = entries_.size();
    entries_.push_back(Entry{req, resp, next_seq_++});
  } else {
    // Evict least-recently-used. Deterministic across ranks because every
    // rank performs the identical Put/Touch sequence (responses are
    // coordinator-broadcast; touches happen only on cross-rank-agreed hits).
    slot = lru_.front();
    lru_.pop_front();
    by_name_.erase(entries_[slot].resp.names.empty()
                       ? entries_[slot].req.name
                       : entries_[slot].req.name);
    entries_[slot] = Entry{req, resp, next_seq_++};
  }
  by_name_[req.name] = slot;
  lru_.push_back(slot);
  return slot;
}

void ResponseCache::Erase(const std::string& name) {
  auto it = by_name_.find(name);
  if (it == by_name_.end()) return;
  size_t slot = it->second;
  by_name_.erase(it);
  lru_.remove(slot);
  // Leave the slot allocated but unnamed; it is reused only via LRU reuse
  // of capacity slots. Mark unusable by clearing the name.
  entries_[slot].req.name.clear();
  entries_[slot].resp = Response{};
  // Push to front so the dead slot is first to be recycled.
  lru_.push_front(slot);
}

bool ResponseCache::BitFor(const std::string& name, size_t* bit) const {
  auto it = by_name_.find(name);
  if (it == by_name_.end()) return false;
  *bit = it->second;
  return true;
}

const Response& ResponseCache::Get(size_t bit) const {
  return entries_[bit].resp;
}

const Request& ResponseCache::CachedRequest(size_t bit) const {
  return entries_[bit].req;
}

void ResponseCache::Touch(size_t bit) {
  lru_.remove(bit);
  lru_.push_back(bit);
}

std::vector<size_t> ResponseCache::BitsInInsertionOrder() const {
  std::vector<size_t> bits;
  bits.reserve(entries_.size());
  for (size_t i = 0; i < entries_.size(); ++i)
    if (!entries_[i].req.name.empty()) bits.push_back(i);
  std::sort(bits.begin(), bits.end(), [this](size_t a, size_t b) {
    return entries_[a].seq < entries_[b].seq;
  });
  return bits;
}

}  // namespace hvdcore
