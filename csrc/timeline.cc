#include "timeline.h"

#include <chrono>

namespace hvdcore {

namespace {
int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Minimal JSON string escaping for tensor names.
std::string Escape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    if (c == '"' || c == '\\') out.push_back('\\');
    if (static_cast<unsigned char>(c) >= 0x20) out.push_back(c);
  }
  return out;
}
}  // namespace

Timeline::Timeline(const std::string& path, int pid) : pid_(pid) {
  file_ = std::fopen(path.c_str(), "w");
  if (!file_) return;
  std::fputs("[\n", file_);
  writer_ = std::thread(&Timeline::WriterLoop, this);
}

Timeline::~Timeline() {
  if (!file_) return;
  {
    std::lock_guard<std::mutex> g(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  writer_.join();
  std::fputs("\n]\n", file_);
  std::fclose(file_);
}

void Timeline::Push(char phase, const std::string& tid,
                    const std::string& name) {
  if (!file_) return;
  {
    std::lock_guard<std::mutex> g(mu_);
    queue_.push_back(Event{phase, tid, name, NowUs()});
  }
  cv_.notify_one();
}

void Timeline::WriterLoop() {
  std::unique_lock<std::mutex> g(mu_);
  while (true) {
    cv_.wait(g, [&] { return stop_ || !queue_.empty(); });
    while (!queue_.empty()) {
      Event e = std::move(queue_.front());
      queue_.pop_front();
      g.unlock();
      if (!first_) std::fputs(",\n", file_);
      first_ = false;
      if (e.phase == 'i') {
        std::fprintf(file_,
                     "{\"ph\":\"i\",\"name\":\"%s\",\"pid\":%d,\"ts\":%lld,"
                     "\"s\":\"p\"}",
                     Escape(e.name).c_str(), pid_,
                     static_cast<long long>(e.us));
      } else {
        // Chrome-trace tids are numeric: lane = stable hash of tensor name.
        unsigned long tid =
            std::hash<std::string>{}(e.tid) % 1000000ul;
        if (e.phase == 'B') {
          std::fprintf(file_,
                       "{\"ph\":\"B\",\"name\":\"%s\",\"pid\":%d,"
                       "\"tid\":%lu,\"ts\":%lld}",
                       Escape(e.name).c_str(), pid_, tid,
                       static_cast<long long>(e.us));
        } else {
          std::fprintf(file_,
                       "{\"ph\":\"E\",\"pid\":%d,\"tid\":%lu,\"ts\":%lld}",
                       pid_, tid, static_cast<long long>(e.us));
        }
      }
      g.lock();
    }
    if (stop_ && queue_.empty()) break;
  }
  std::fflush(file_);
}

void Timeline::NegotiateStart(const std::string& tensor) {
  Push('B', tensor, "NEGOTIATE");
}
void Timeline::NegotiateEnd(const std::string& tensor) { Push('E', tensor, ""); }
void Timeline::OpStart(const std::string& tensor, const std::string& op) {
  Push('B', tensor, op);
}
void Timeline::OpEnd(const std::string& tensor) { Push('E', tensor, ""); }
void Timeline::ActivityStart(const std::string& tensor,
                             const std::string& activity) {
  Push('B', tensor, activity);
}
void Timeline::ActivityEnd(const std::string& tensor) {
  Push('E', tensor, "");
}
void Timeline::Marker(const std::string& name) { Push('i', "", name); }

}  // namespace hvdcore
