#include "core.h"

#include <map>

#include <algorithm>
#include <chrono>
#include <cstring>

namespace hvdcore {

// --- MuxTransport ----------------------------------------------------------

Status MuxTransport::Send(uint32_t ch, int to, const void* data, size_t len) {
  std::vector<uint8_t> framed(sizeof(uint32_t) + len);
  std::memcpy(framed.data(), &ch, sizeof(uint32_t));
  std::memcpy(framed.data() + sizeof(uint32_t), data, len);
  return base_->Send(to, framed.data(), framed.size());
}

Status MuxTransport::TakeFromInbox(uint32_t ch, int from,
                                   std::vector<uint8_t>* out, bool* found) {
  auto it = inbox_.find({ch, from});
  if (it != inbox_.end() && !it->second.empty()) {
    *out = std::move(it->second.front());
    it->second.erase(it->second.begin());
    *found = true;
  } else {
    *found = false;
  }
  return Status::OK();
}

namespace {
Status StripChannel(std::vector<uint8_t>* frame, uint32_t* ch) {
  if (frame->size() < sizeof(uint32_t))
    return Status::Error(StatusCode::kUnknownError, "short mux frame");
  std::memcpy(ch, frame->data(), sizeof(uint32_t));
  frame->erase(frame->begin(), frame->begin() + sizeof(uint32_t));
  return Status::OK();
}
}  // namespace

Status MuxTransport::Recv(uint32_t ch, int from, std::vector<uint8_t>* out) {
  bool found = false;
  TakeFromInbox(ch, from, out, &found);
  while (!found) {
    std::vector<uint8_t> frame;
    Status st = base_->Recv(from, &frame);
    if (!st.ok()) return st;
    uint32_t got = 0;
    st = StripChannel(&frame, &got);
    if (!st.ok()) return st;
    if (got == ch) {
      *out = std::move(frame);
      found = true;
    } else {
      inbox_[{got, from}].push_back(std::move(frame));
    }
  }
  return Status::OK();
}

Status MuxTransport::SendRecv(uint32_t ch, int to, const void* sdata,
                              size_t slen, int from,
                              std::vector<uint8_t>* out) {
  std::vector<uint8_t> framed(sizeof(uint32_t) + slen);
  std::memcpy(framed.data(), &ch, sizeof(uint32_t));
  std::memcpy(framed.data() + sizeof(uint32_t), sdata, slen);

  bool found = false;
  TakeFromInbox(ch, from, out, &found);
  if (found) return base_->Send(to, framed.data(), framed.size());

  std::vector<uint8_t> frame;
  Status st = base_->SendRecv(to, framed.data(), framed.size(), from, &frame);
  if (!st.ok()) return st;
  while (true) {
    uint32_t got = 0;
    st = StripChannel(&frame, &got);
    if (!st.ok()) return st;
    if (got == ch) {
      *out = std::move(frame);
      return Status::OK();
    }
    inbox_[{got, from}].push_back(std::move(frame));
    st = base_->Recv(from, &frame);
    if (!st.ok()) return st;
  }
}

// --- Core ------------------------------------------------------------------

Core::Core(std::unique_ptr<Transport> base, const CoreOptions& opts)
    : opts_(opts), mux_(new MuxTransport(std::move(base))) {
  if (!opts_.timeline_path.empty())
    timeline_.reset(new Timeline(opts_.timeline_path, mux_->rank()));
}

Status Core::Create(int rank, int size, const std::string& transport_kind,
                    const std::string& peers, const CoreOptions& opts,
                    std::unique_ptr<Core>* out) {
  std::unique_ptr<Transport> base;
  if (transport_kind == "local") {
    base = LocalTransport::Create(peers, rank, size);
  } else if (transport_kind == "tcp") {
    std::vector<std::string> addrs;
    size_t pos = 0;
    while (pos <= peers.size()) {
      size_t comma = peers.find(',', pos);
      if (comma == std::string::npos) comma = peers.size();
      if (comma > pos) addrs.push_back(peers.substr(pos, comma - pos));
      pos = comma + 1;
    }
    if (static_cast<int>(addrs.size()) != size)
      return Status::Error(StatusCode::kInvalidArgument,
                           "peer list size != world size");
    std::unique_ptr<TcpTransport> tcp;
    Status st = TcpTransport::Create(rank, addrs, 60.0, &tcp);
    if (!st.ok()) return st;
    base = std::move(tcp);
  } else {
    return Status::Error(StatusCode::kInvalidArgument,
                         "unknown transport " + transport_kind);
  }
  std::unique_ptr<Core> core(new Core(std::move(base), opts));
  // Global process set (id 0) spans all ranks (reference: process set 0,
  // horovod/common/process_set.cc).
  std::vector<int> all(size);
  for (int i = 0; i < size; ++i) all[i] = i;
  {
    std::lock_guard<std::mutex> g(core->mu_);
    auto ps = std::make_unique<PsState>();
    ps->channel = 0;
    ps->members = all;
    ps->my_index = rank;
    ps->active = true;
    ps->view.reset(
        new ChannelView(core->mux_.get(), 0, ps->members, ps->my_index));
    ps->controller.reset(new Controller(ps->view.get(), opts.controller,
                                        core->timeline_.get()));
    core->process_sets_[0] = std::move(ps);
  }
  *out = std::move(core);
  return Status::OK();
}

int Core::AddProcessSet(const std::vector<int>& ranks) {
  std::vector<int> members = ranks;
  std::sort(members.begin(), members.end());
  members.erase(std::unique(members.begin(), members.end()), members.end());
  std::lock_guard<std::mutex> g(mu_);
  int ps_id = next_ps_id_++;
  auto ps = std::make_unique<PsState>();
  ps->channel = next_channel_++;
  ps->members = members;
  auto it = std::find(members.begin(), members.end(), mux_->rank());
  ps->my_index = it == members.end()
                     ? -1
                     : static_cast<int>(it - members.begin());
  if (ps->my_index >= 0) {
    ps->view.reset(new ChannelView(mux_.get(), ps->channel, ps->members,
                                   ps->my_index));
    ps->controller.reset(
        new Controller(ps->view.get(), opts_.controller, timeline_.get()));
  }
  process_sets_[ps_id] = std::move(ps);
  staged_adds_.push_back(ps_id);  // activates once all ranks staged it
  return ps_id;
}

bool Core::RemoveProcessSet(int ps_id) {
  std::lock_guard<std::mutex> g(mu_);
  if (ps_id == 0) return false;
  auto it = process_sets_.find(ps_id);
  if (it == process_sets_.end()) return false;
  staged_removals_.push_back(ps_id);  // applied once all ranks staged it
  return true;
}

int64_t Core::Enqueue(int ps_id, const Request& req, const void* data,
                      size_t nbytes) {
  std::lock_guard<std::mutex> g(mu_);
  if (shutdown_complete_.load()) return -3;
  auto it = process_sets_.find(ps_id);
  if (it == process_sets_.end() || it->second->my_index < 0) return -4;
  PsState& ps = *it->second;
  if (ps.inflight.count(req.name)) return -1;  // DUPLICATE_NAME_ERROR analog
  int64_t expect = 1;
  for (int64_t d : req.shape) expect *= d;
  if (req.type != ReqType::kBarrier && req.type != ReqType::kJoin &&
      nbytes != static_cast<size_t>(expect) * DataTypeSize(req.dtype))
    return -2;

  int64_t handle = next_handle_++;
  auto entry = std::make_unique<Entry>();
  entry->req = req;
  entry->req.rank = ps.my_index;
  if (nbytes) {
    entry->input.resize(nbytes);
    std::memcpy(entry->input.data(), data, nbytes);
  }
  handles_[handle] = std::move(entry);
  ps.inflight[req.name] = handle;
  ps.queue.emplace_back(handles_[handle]->req, handle);
  return handle;
}

void Core::SetFusionThreshold(int64_t bytes) {
  std::lock_guard<std::mutex> g(mu_);
  opts_.controller.fusion_threshold = bytes;  // future process sets
  for (auto& kv : process_sets_)
    if (kv.second->controller)
      kv.second->controller->set_fusion_threshold(bytes);
}

void Core::SetTopology(const std::vector<int>& host_of, int64_t threshold) {
  std::lock_guard<std::mutex> g(mu_);
  host_of_ = host_of;
  hierarchical_threshold_ = threshold;
}

std::vector<int> Core::HierViewHosts(const PsState& ps, int64_t nbytes) {
  std::vector<int> topo;
  {
    std::lock_guard<std::mutex> g(mu_);
    // Scalar checks first: the common small-tensor path must not pay an
    // O(world) vector copy just to discover the gate is closed.
    if (hierarchical_threshold_ <= 0 || nbytes < hierarchical_threshold_ ||
        host_of_.empty())
      return {};
    topo = host_of_;
  }
  std::vector<int> view_hosts;
  view_hosts.reserve(ps.members.size());
  for (int g : ps.members) {
    if (g < 0 || g >= static_cast<int>(topo.size())) return {};
    view_hosts.push_back(topo[g]);
  }
  // Only worth engaging (and only honest to timeline as HIERARCHICAL_*)
  // when the view spans >1 host AND some host holds >1 rank.
  std::map<int, int> counts;
  for (int h : view_hosts) ++counts[h];
  if (counts.size() < 2 || counts.size() == view_hosts.size()) return {};
  return view_hosts;
}

void Core::CompleteHandle(int64_t handle, HandleState state,
                          const std::string& error) {
  auto it = handles_.find(handle);
  if (it == handles_.end()) return;
  it->second->state = state;
  it->second->error = error;
  cv_.notify_all();
}

int Core::RunCycle() {
  if (shutdown_complete_.load()) return -1;
  int completed = 0;
  bool want_shutdown = shutdown_requested_.load();
  bool all_shutdown = false;

  // One process set's negotiation + execution. Returns false on transport
  // failure (everything in flight is failed; the elastic layer turns this
  // into restore+reinit, reference: horovod/common/elastic.py:151).
  auto cycle_ps = [&](int ps_id, PsState* ps, const PsConsensus& staged,
                      PsConsensus* agreed) -> bool {
    std::vector<std::pair<Request, int64_t>> pending;
    {
      std::lock_guard<std::mutex> g(mu_);
      pending.swap(ps->queue);
    }
    std::vector<Request> reqs;
    reqs.reserve(pending.size());
    for (auto& p : pending) reqs.push_back(p.first);

    CycleResult result;
    // Only the global set carries shutdown + process-set consensus (the
    // reference ties both to the global state, operations.cc RunLoopOnce).
    Status st = ps->controller->ComputeResponseList(
        std::move(reqs), ps_id == 0 && want_shutdown, staged, &result);
    if (!st.ok()) {
      std::lock_guard<std::mutex> g(mu_);
      for (auto& kv : ps->inflight)
        CompleteHandle(kv.second, HandleState::kError, st.reason);
      ps->inflight.clear();
      shutdown_complete_.store(true);
      return false;
    }
    // Requeue cache hits some rank has not caught up to yet.
    if (!result.requeue.empty()) {
      std::lock_guard<std::mutex> g(mu_);
      std::map<std::string, int64_t> handles_by_name;
      for (auto& p : pending) handles_by_name[p.first.name] = p.second;
      for (Request& r : result.requeue)
        ps->queue.emplace_back(r, handles_by_name[r.name]);
    }
    for (const Response& resp : result.to_execute.responses) {
      // Data ops can be delegated to the external (XLA) data plane; error
      // responses, alltoall (uneven splits need the TCP plane), barrier,
      // and join always run natively.
      bool delegatable =
          opts_.delegate_data_ops && resp.error.empty() &&
          resp.op != RedOp::kAdasum &&  // VHDD stays on the host plane
          (resp.type == ReqType::kAllreduce ||
           resp.type == ReqType::kAllgather ||
           resp.type == ReqType::kBroadcast ||
           resp.type == ReqType::kReducescatter);
      if (delegatable)
        DelegateResponse(ps_id, *ps, resp);
      else
        ExecuteResponse(*ps, resp, &completed);
    }
    if (ps_id == 0 && result.shutdown) all_shutdown = true;
    if (agreed) *agreed = result.agreed_ps;
    ++cycles_;
    return true;
  };

  // Phase 1: global set — always active, carries the consensus counters.
  PsConsensus staged, agreed;
  {
    std::lock_guard<std::mutex> g(mu_);
    staged.adds = static_cast<uint32_t>(staged_adds_.size());
    staged.removals = static_cast<uint32_t>(staged_removals_.size());
  }
  if (!cycle_ps(0, process_sets_.at(0).get(), staged, &agreed)) return -2;

  // Apply agreed process-set changes: every rank activates/removes the same
  // FIFO prefix this cycle, so channel schedules stay aligned.
  std::vector<int> active_ids;
  {
    std::lock_guard<std::mutex> g(mu_);
    for (uint32_t i = 0; i < agreed.adds && !staged_adds_.empty(); ++i) {
      int id = staged_adds_.front();
      staged_adds_.erase(staged_adds_.begin());
      auto it = process_sets_.find(id);
      if (it != process_sets_.end()) it->second->active = true;
    }
    for (uint32_t i = 0; i < agreed.removals && !staged_removals_.empty();
         ++i) {
      int id = staged_removals_.front();
      staged_removals_.erase(staged_removals_.begin());
      auto it = process_sets_.find(id);
      if (it == process_sets_.end()) continue;
      for (auto& kv : it->second->inflight)
        CompleteHandle(kv.second, HandleState::kError, "process set removed");
      process_sets_.erase(it);
    }
    for (auto& kv : process_sets_)
      if (kv.first != 0 && kv.second->active && kv.second->my_index >= 0)
        active_ids.push_back(kv.first);
  }

  // Phase 2: the other active sets, in id order on every rank.
  for (int ps_id : active_ids) {
    PsState* ps;
    {
      std::lock_guard<std::mutex> g(mu_);
      auto it = process_sets_.find(ps_id);
      if (it == process_sets_.end()) continue;
      ps = it->second.get();
    }
    if (!cycle_ps(ps_id, ps, PsConsensus{}, nullptr)) return -2;
  }
  if (all_shutdown) shutdown_complete_.store(true);
  return completed;
}

void Core::ExecuteResponse(PsState& ps, const Response& resp, int* completed) {
  Transport* view = ps.view.get();
  const size_t esize = DataTypeSize(resp.dtype);

  // Resolve the entries this rank owns for the response's names.
  std::vector<Entry*> entries(resp.names.size(), nullptr);
  std::vector<int64_t> handles(resp.names.size(), -1);
  {
    std::lock_guard<std::mutex> g(mu_);
    for (size_t i = 0; i < resp.names.size(); ++i) {
      auto it = ps.inflight.find(resp.names[i]);
      if (it == ps.inflight.end()) continue;
      auto hit = handles_.find(it->second);
      if (hit == handles_.end()) {
        // Handle was Released while still negotiating (caller gave up);
        // drop the stale in-flight name and participate entry-less, like a
        // joined rank.
        ps.inflight.erase(it);
        continue;
      }
      handles[i] = it->second;
      entries[i] = hit->second.get();
      executing_handles_.insert(it->second);
    }
  }
  auto finish = [&](size_t i, HandleState state, const std::string& err) {
    if (handles[i] < 0) return;
    std::lock_guard<std::mutex> g(mu_);
    // Only drop the inflight mapping if it still points at the handle we
    // resolved — a Release + same-name resubmit mid-flight installs a new
    // handle that must keep its mapping.
    auto it = ps.inflight.find(resp.names[i]);
    if (it != ps.inflight.end() && it->second == handles[i])
      ps.inflight.erase(it);
    CompleteHandle(handles[i], state, err);
    ++*completed;
  };
  auto fail_all = [&](const std::string& err) {
    for (size_t i = 0; i < resp.names.size(); ++i)
      finish(i, HandleState::kError, err);
  };
  auto unpin = [&] {
    std::lock_guard<std::mutex> g(mu_);
    for (int64_t h : handles)
      if (h >= 0) executing_handles_.erase(h);
    if (executing_handles_.empty()) zombies_.clear();
  };

  if (!resp.error.empty()) {
    fail_all(resp.error);
    unpin();
    return;
  }
  if (timeline_ && !resp.names.empty())
    timeline_->OpStart(resp.names[0], "EXEC");

  Status st = Status::OK();
  switch (resp.type) {
    case ReqType::kAllreduce: {
      int64_t total = 0;
      for (int64_t n : resp.sizes) total += n;
      uint8_t* buf = nullptr;
      bool fused = resp.names.size() > 1 || entries[0] == nullptr;
      if (fused) {
        if (timeline_)
          timeline_->ActivityStart(resp.names[0], "MEMCPY_IN_FUSION_BUFFER");
        ps.fusion_buffer.resize(static_cast<size_t>(total) * esize);
        size_t off = 0;
        for (size_t i = 0; i < resp.names.size(); ++i) {
          size_t n = static_cast<size_t>(resp.sizes[i]) * esize;
          if (entries[i]) {
            std::memcpy(ps.fusion_buffer.data() + off,
                        entries[i]->input.data(), n);
            // Prescale applies to contributed data only; the identity
            // slots below must stay exact (0.5 * 1.0 would corrupt prod).
            if (resp.prescale != 1.0)
              ScaleBuffer(ps.fusion_buffer.data() + off, resp.sizes[i],
                          resp.dtype, resp.prescale);
          } else {
            // Joined/entry-less rank: contribute the op's identity element
            // (zeros would corrupt min/max/prod results).
            FillReduceIdentity(ps.fusion_buffer.data() + off, resp.sizes[i],
                               resp.dtype, resp.op);
          }
          off += n;
        }
        buf = ps.fusion_buffer.data();
        if (timeline_) timeline_->ActivityEnd(resp.names[0]);
      } else {
        buf = entries[0]->input.data();
      }
      if (!fused && resp.prescale != 1.0)
        ScaleBuffer(buf, total, resp.dtype, resp.prescale);
      // Two-level path: engaged for large buffers on a known multi-host
      // topology (SetTopology; see HierViewHosts).
      std::vector<int> view_hosts;
      if (resp.op != RedOp::kAdasum)
        view_hosts =
            HierViewHosts(ps, static_cast<int64_t>(total * esize));
      const bool hier = !view_hosts.empty();
      if (timeline_)
        timeline_->ActivityStart(resp.names[0],
                                 resp.op == RedOp::kAdasum
                                     ? "VHDD_ADASUM"
                                     : (hier ? "HIERARCHICAL_ALLREDUCE"
                                             : "RING_ALLREDUCE"));
      if (resp.op == RedOp::kAdasum) {
        st = VhddAdasum(view, buf, total, resp.dtype);
      } else if (hier) {
        st = HierarchicalAllreduce(view, buf, total, resp.dtype, resp.op,
                                   view_hosts);
        // Heterogeneous local sizes are detected inside; fall back flat.
        if (!st.ok() && st.code == StatusCode::kInvalidArgument)
          st = RingAllreduce(view, buf, total, resp.dtype, resp.op);
      } else {
        st = RingAllreduce(view, buf, total, resp.dtype, resp.op);
      }
      if (timeline_) timeline_->ActivityEnd(resp.names[0]);
      if (st.ok() && resp.postscale != 1.0)
        ScaleBuffer(buf, total, resp.dtype, resp.postscale);
      if (st.ok()) {
        size_t off = 0;
        for (size_t i = 0; i < resp.names.size(); ++i) {
          size_t n = static_cast<size_t>(resp.sizes[i]) * esize;
          if (entries[i]) {
            if (fused) {
              entries[i]->output.assign(buf + off, buf + off + n);
            } else {
              entries[i]->output = std::move(entries[i]->input);
            }
            entries[i]->out_shape = entries[i]->req.shape;
            finish(i, HandleState::kDone, "");
          }
          off += n;
        }
        bytes_processed_ += static_cast<uint64_t>(total) * esize;
      }
      break;
    }
    case ReqType::kAllgather: {
      // sizes = [rows per rank..., row_elems]
      const int n = view->size();
      if (static_cast<int>(resp.sizes.size()) != n + 1) {
        st = Status::Error(StatusCode::kUnknownError, "bad allgather sizes");
        break;
      }
      int64_t row_elems = resp.sizes[n];
      std::vector<int64_t> counts(n);
      int64_t total = 0, total_rows = 0;
      for (int i = 0; i < n; ++i) {
        counts[i] = resp.sizes[i] * row_elems;
        total += counts[i];
        total_rows += resp.sizes[i];
      }
      Entry* e = entries[0];
      std::vector<uint8_t> out(static_cast<size_t>(total) * esize);
      std::vector<uint8_t> scratch;
      const void* sendbuf = e ? e->input.data() : nullptr;
      if (!e && counts[view->rank()] > 0) {
        // Negotiation listed this rank with rows but the entry is gone
        // (released mid-flight): contribute zeros so peers don't hang.
        scratch.assign(static_cast<size_t>(counts[view->rank()]) * esize, 0);
        sendbuf = scratch.data();
      }
      // Two-level path mirrors the allreduce gate (see HierViewHosts).
      std::vector<int> view_hosts =
          HierViewHosts(ps, static_cast<int64_t>(total * esize));
      if (!view_hosts.empty()) {
        if (timeline_)
          timeline_->ActivityStart(resp.names[0],
                                   "HIERARCHICAL_ALLGATHER");
        st = HierarchicalAllgatherv(view, sendbuf, out.data(), counts,
                                    resp.dtype, view_hosts);
        if (timeline_) timeline_->ActivityEnd(resp.names[0]);
      } else {
        st = RingAllgatherv(view, sendbuf, out.data(), counts,
                            resp.dtype);
      }
      if (st.ok() && e) {
        e->output = std::move(out);
        e->out_shape = e->req.shape;
        if (!e->out_shape.empty()) e->out_shape[0] = total_rows;
        bytes_processed_ += static_cast<uint64_t>(total) * esize;
        finish(0, HandleState::kDone, "");
      }
      break;
    }
    case ReqType::kBroadcast: {
      int64_t count = resp.sizes.empty() ? 0 : resp.sizes[0];
      int root = resp.sizes.size() > 1 ? static_cast<int>(resp.sizes[1]) : 0;
      Entry* e = entries[0];
      std::vector<uint8_t> scratch;
      uint8_t* buf;
      if (e) {
        buf = e->input.data();
      } else {
        scratch.resize(static_cast<size_t>(count) * esize);
        buf = scratch.data();
      }
      st = TreeBroadcast(view, buf, count, resp.dtype, root);
      if (st.ok() && e) {
        e->output = std::move(e->input);
        e->out_shape = e->req.shape;
        bytes_processed_ += static_cast<uint64_t>(count) * esize;
        finish(0, HandleState::kDone, "");
      }
      break;
    }
    case ReqType::kAlltoall: {
      const int n = view->size();
      const int me = view->rank();
      Entry* e = entries[0];
      if (!e || static_cast<int>(resp.sizes.size()) != n * n) {
        st = Status::Error(StatusCode::kUnknownError, "bad alltoall state");
        break;
      }
      int64_t row_elems = 1;
      for (size_t d = 1; d < e->req.shape.size(); ++d)
        row_elems *= e->req.shape[d];
      std::vector<int64_t> send_splits(n), recv_splits(n);
      int64_t recv_total = 0, recv_rows = 0;
      for (int d = 0; d < n; ++d) {
        send_splits[d] = resp.sizes[static_cast<size_t>(me) * n + d] * row_elems;
        recv_splits[d] = resp.sizes[static_cast<size_t>(d) * n + me] * row_elems;
        recv_total += recv_splits[d];
        recv_rows += resp.sizes[static_cast<size_t>(d) * n + me];
      }
      e->output.resize(static_cast<size_t>(recv_total) * esize);
      st = PairwiseAlltoallv(view, e->input.data(), e->output.data(),
                             send_splits, recv_splits, resp.dtype);
      if (st.ok()) {
        e->out_shape = e->req.shape;
        if (!e->out_shape.empty()) e->out_shape[0] = recv_rows;
        e->recv_splits.resize(n);
        for (int d = 0; d < n; ++d)
          e->recv_splits[d] =
              static_cast<int32_t>(resp.sizes[static_cast<size_t>(d) * n + me]);
        bytes_processed_ += static_cast<uint64_t>(recv_total) * esize;
        finish(0, HandleState::kDone, "");
      }
      break;
    }
    case ReqType::kReducescatter: {
      const int n = view->size();
      const int me = view->rank();
      Entry* e = entries[0];
      if (!e) {
        st = Status::Error(StatusCode::kUnknownError,
                           "reducescatter with no local entry");
        break;
      }
      int64_t rows = e->req.shape.empty() ? 1 : e->req.shape[0];
      int64_t row_elems = 1;
      for (size_t d = 1; d < e->req.shape.size(); ++d)
        row_elems *= e->req.shape[d];
      // First dim split evenly, remainder to lower ranks (reference:
      // reducescatter output sizing in collective_operations.cc).
      std::vector<int64_t> recv_counts(n);
      int64_t base = rows / n, rem = rows % n;
      for (int i = 0; i < n; ++i)
        recv_counts[i] = (base + (i < rem ? 1 : 0)) * row_elems;
      int64_t my_rows = base + (me < rem ? 1 : 0);
      e->output.resize(static_cast<size_t>(recv_counts[me]) * esize);
      st = RingReducescatter(view, e->input.data(), e->output.data(),
                             recv_counts, resp.dtype, resp.op);
      if (st.ok()) {
        if (resp.postscale != 1.0)
          ScaleBuffer(e->output.data(), recv_counts[me], resp.dtype,
                      resp.postscale);
        e->out_shape = e->req.shape;
        if (!e->out_shape.empty()) e->out_shape[0] = my_rows;
        bytes_processed_ +=
            static_cast<uint64_t>(recv_counts[me]) * esize;
        finish(0, HandleState::kDone, "");
      }
      break;
    }
    case ReqType::kBarrier: {
      st = DisseminationBarrier(view);
      if (st.ok()) finish(0, HandleState::kDone, "");
      break;
    }
    case ReqType::kJoin: {
      Entry* e = entries[0];
      if (e) {
        e->output.resize(sizeof(int32_t));
        int32_t last = resp.last_joined_rank;
        std::memcpy(e->output.data(), &last, sizeof(int32_t));
        e->out_shape.clear();
        finish(0, HandleState::kDone, "");
      }
      break;
    }
  }
  if (!st.ok()) fail_all(st.reason);
  if (timeline_ && !resp.names.empty()) timeline_->OpEnd(resp.names[0]);
  unpin();
}

void Core::DelegateResponse(int ps_id, PsState& ps, const Response& resp) {
  Delegated d;
  d.ps_id = ps_id;
  d.resp = resp;
  d.handles.assign(resp.names.size(), -1);
  std::lock_guard<std::mutex> g(mu_);
  for (size_t i = 0; i < resp.names.size(); ++i) {
    auto it = ps.inflight.find(resp.names[i]);
    if (it == ps.inflight.end()) continue;
    if (handles_.find(it->second) == handles_.end()) {
      // Released while negotiating: participate entry-less.
      ps.inflight.erase(it);
      continue;
    }
    d.handles[i] = it->second;
    // The name frees once execution starts (reference: the entry is popped
    // from the tensor queue at PerformOperation); completion later is by
    // handle, not name.
    ps.inflight.erase(it);
  }
  // Queue even with zero local entries: a joined rank is still a member of
  // the external collective and must contribute identity data.
  int64_t token = next_token_++;
  delegated_order_.push_back(token);
  delegated_.emplace(token, std::move(d));
}

int64_t Core::NextDelegated() {
  std::lock_guard<std::mutex> g(mu_);
  if (delegated_order_.empty()) return 0;
  int64_t token = delegated_order_.front();
  delegated_order_.pop_front();
  return token;
}

const Core::Delegated* Core::GetDelegated(int64_t token) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = delegated_.find(token);
  return it == delegated_.end() ? nullptr : &it->second;
}

void Core::FinishDelegated(int64_t token) {
  std::lock_guard<std::mutex> g(mu_);
  delegated_.erase(token);
}

bool Core::CompleteDelegatedEntry(int64_t handle, const void* data,
                                  size_t nbytes, const int64_t* shape,
                                  int ndim, const char* error) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end()) return false;  // released while delegated
  Entry* e = it->second.get();
  if (error && error[0]) {
    e->state = HandleState::kError;
    e->error = error;
  } else {
    e->output.assign(static_cast<const uint8_t*>(data),
                     static_cast<const uint8_t*>(data) + nbytes);
    e->out_shape.assign(shape, shape + ndim);
    e->input.clear();
    e->input.shrink_to_fit();
    e->state = HandleState::kDone;
    bytes_processed_ += nbytes;
  }
  cv_.notify_all();
  return true;
}

HandleState Core::Poll(int64_t handle, std::string* error) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end()) {
    if (error) *error = "unknown handle";
    return HandleState::kError;
  }
  if (error) *error = it->second->error;
  return it->second->state;
}

Status Core::Wait(int64_t handle, double timeout_s) {
  std::unique_lock<std::mutex> g(mu_);
  auto done = [&] {
    auto it = handles_.find(handle);
    return it == handles_.end() ||
           it->second->state != HandleState::kInProgress;
  };
  if (!cv_.wait_for(g, std::chrono::duration<double>(timeout_s), done))
    return Status::Error(StatusCode::kUnknownError, "wait timed out");
  return Status::OK();
}

const Entry* Core::Get(int64_t handle) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = handles_.find(handle);
  return it == handles_.end() ? nullptr : it->second.get();
}

void Core::Release(int64_t handle) {
  std::lock_guard<std::mutex> g(mu_);
  // Drop any in-flight name still pointing at this handle so a later
  // response does not resolve to a dead entry.
  for (auto& kv : process_sets_) {
    auto& inflight = kv.second->inflight;
    for (auto it = inflight.begin(); it != inflight.end();) {
      if (it->second == handle)
        it = inflight.erase(it);
      else
        ++it;
    }
  }
  auto h = handles_.find(handle);
  if (h != handles_.end()) {
    // The cycle thread may hold a raw Entry* for this handle mid-collective
    // (mu_ is dropped during network execution) — defer destruction until
    // the response finishes instead of freeing under its feet.
    if (executing_handles_.count(handle)) zombies_.push_back(std::move(h->second));
    handles_.erase(h);
  }
}

}  // namespace hvdcore
