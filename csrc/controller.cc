#include "controller.h"

#include <algorithm>
#include <chrono>
#include <cstring>
#include <sstream>

#include "timeline.h"

namespace hvdcore {
namespace {

double NowSeconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Cache-coordination exchange payload: flags + process-set consensus
// counters + two bit vectors.
struct CacheWire {
  uint64_t flags = 0;  // bit0 = has_uncached, bit1 = shutdown_requested
  uint32_t staged_adds = 0;      // pending process-set creations (min-fold)
  uint32_t staged_removals = 0;  // pending process-set removals (min-fold)
  std::vector<uint64_t> hits;
  std::vector<uint64_t> invalid;
};

constexpr uint64_t kFlagUncached = 1ull;
constexpr uint64_t kFlagShutdown = 2ull;

void EncodeCacheWire(const CacheWire& w, std::vector<uint8_t>* out) {
  out->clear();
  uint32_t nwords = static_cast<uint32_t>(w.hits.size());
  out->resize(sizeof(uint64_t) + 3 * sizeof(uint32_t) +
              2 * nwords * sizeof(uint64_t));
  uint8_t* p = out->data();
  std::memcpy(p, &w.flags, sizeof(uint64_t));
  p += sizeof(uint64_t);
  std::memcpy(p, &w.staged_adds, sizeof(uint32_t));
  p += sizeof(uint32_t);
  std::memcpy(p, &w.staged_removals, sizeof(uint32_t));
  p += sizeof(uint32_t);
  std::memcpy(p, &nwords, sizeof(uint32_t));
  p += sizeof(uint32_t);
  std::memcpy(p, w.hits.data(), nwords * sizeof(uint64_t));
  p += nwords * sizeof(uint64_t);
  std::memcpy(p, w.invalid.data(), nwords * sizeof(uint64_t));
}

bool DecodeCacheWire(const std::vector<uint8_t>& in, CacheWire* w) {
  if (in.size() < sizeof(uint64_t) + 3 * sizeof(uint32_t)) return false;
  const uint8_t* p = in.data();
  std::memcpy(&w->flags, p, sizeof(uint64_t));
  p += sizeof(uint64_t);
  std::memcpy(&w->staged_adds, p, sizeof(uint32_t));
  p += sizeof(uint32_t);
  std::memcpy(&w->staged_removals, p, sizeof(uint32_t));
  p += sizeof(uint32_t);
  uint32_t nwords = 0;
  std::memcpy(&nwords, p, sizeof(uint32_t));
  p += sizeof(uint32_t);
  if (in.size() != sizeof(uint64_t) + 3 * sizeof(uint32_t) +
                       2ull * nwords * sizeof(uint64_t))
    return false;
  w->hits.resize(nwords);
  std::memcpy(w->hits.data(), p, nwords * sizeof(uint64_t));
  p += nwords * sizeof(uint64_t);
  w->invalid.resize(nwords);
  std::memcpy(w->invalid.data(), p, nwords * sizeof(uint64_t));
  return true;
}

std::string RanksToString(const std::vector<int>& ranks) {
  std::ostringstream os;
  for (size_t i = 0; i < ranks.size(); ++i)
    os << (i ? ", " : "") << ranks[i];
  return os.str();
}

int64_t NumElements(const std::vector<int64_t>& shape) {
  int64_t n = 1;
  for (int64_t d : shape) n *= d;
  return n;
}

bool Cacheable(const Response& r) {
  return r.error.empty() &&
         (r.type == ReqType::kAllreduce || r.type == ReqType::kAllgather ||
          r.type == ReqType::kBroadcast || r.type == ReqType::kAlltoall ||
          r.type == ReqType::kReducescatter);
}

}  // namespace

Controller::Controller(Transport* transport, const ControllerOptions& opts,
                       Timeline* timeline)
    : transport_(transport),
      opts_(opts),
      timeline_(timeline),
      cache_(opts.cache_capacity) {}

Status Controller::CoordinateCache(const std::vector<size_t>& hit_bits,
                                   const std::vector<size_t>& invalid_bits,
                                   bool has_uncached, bool request_shutdown,
                                   const PsConsensus& staged,
                                   std::vector<size_t>* agreed_bits,
                                   bool* any_uncached, bool* all_shutdown,
                                   PsConsensus* agreed_ps) {
  const size_t nwords = (opts_.cache_capacity + 63) / 64;
  CacheWire mine;
  mine.hits.assign(nwords, 0);
  mine.invalid.assign(nwords, 0);
  for (size_t b : hit_bits) mine.hits[b / 64] |= 1ull << (b % 64);
  for (size_t b : invalid_bits) mine.invalid[b / 64] |= 1ull << (b % 64);
  if (has_uncached) mine.flags |= kFlagUncached;
  if (request_shutdown) mine.flags |= kFlagShutdown;
  mine.staged_adds = staged.adds;
  mine.staged_removals = staged.removals;

  CacheWire global = mine;
  const int size = transport_->size();
  if (size > 1) {
    std::vector<uint8_t> buf;
    if (is_coordinator()) {
      // Fold every worker's vector: AND hits, OR invalid, OR uncached,
      // AND shutdown (reference: CrossRankBitwiseAnd/Or,
      // mpi_controller.cc:117-127).
      for (int r = 1; r < size; ++r) {
        Status st = transport_->Recv(r, &buf);
        if (!st.ok()) return st;
        CacheWire theirs;
        if (!DecodeCacheWire(buf, &theirs) ||
            theirs.hits.size() != nwords)
          return Status::Error(StatusCode::kUnknownError,
                               "bad cache-coordination message");
        for (size_t i = 0; i < nwords; ++i) {
          global.hits[i] &= theirs.hits[i];
          global.invalid[i] |= theirs.invalid[i];
        }
        uint64_t uncached =
            (global.flags | theirs.flags) & kFlagUncached;
        uint64_t shut = (global.flags & theirs.flags) & kFlagShutdown;
        global.flags = uncached | shut;
        global.staged_adds = std::min(global.staged_adds, theirs.staged_adds);
        global.staged_removals =
            std::min(global.staged_removals, theirs.staged_removals);
      }
      std::vector<uint8_t> enc;
      EncodeCacheWire(global, &enc);
      for (int r = 1; r < size; ++r) {
        Status st = transport_->Send(r, enc.data(), enc.size());
        if (!st.ok()) return st;
      }
    } else {
      std::vector<uint8_t> enc;
      EncodeCacheWire(mine, &enc);
      Status st = transport_->Send(0, enc.data(), enc.size());
      if (!st.ok()) return st;
      st = transport_->Recv(0, &buf);
      if (!st.ok()) return st;
      if (!DecodeCacheWire(buf, &global) || global.hits.size() != nwords)
        return Status::Error(StatusCode::kUnknownError,
                             "bad cache-coordination reply");
    }
  }

  bool any_invalid = false;
  agreed_bits->clear();
  for (size_t w = 0; w < nwords; ++w) {
    uint64_t agreed = global.hits[w] & ~global.invalid[w];
    if (global.invalid[w]) any_invalid = true;
    for (int b = 0; b < 64; ++b)
      if (agreed & (1ull << b)) agreed_bits->push_back(w * 64 + b);
    // Cross-rank-invalidated entries are erased on EVERY rank so bit
    // layouts stay identical (reference: cache invalidation coordination).
    uint64_t inv = global.invalid[w];
    for (int b = 0; b < 64; ++b)
      if (inv & (1ull << b)) {
        size_t bit = w * 64 + b;
        if (bit < cache_.NumEntries())
          cache_.Erase(cache_.CachedRequest(bit).name);
      }
  }
  *any_uncached = (global.flags & kFlagUncached) != 0 || any_invalid;
  *all_shutdown = (global.flags & kFlagShutdown) != 0;
  if (agreed_ps) {
    agreed_ps->adds = global.staged_adds;
    agreed_ps->removals = global.staged_removals;
  }
  return Status::OK();
}

void Controller::AddRequestToTable(const Request& req, int from_rank) {
  if (req.type == ReqType::kJoin) {
    joined_ranks_.insert(from_rank);
    return;
  }
  auto& entry = message_table_[req.name];
  const double now = NowSeconds();
  if (entry.ranks.empty()) entry.first_seen = now;
  entry.last_update = now;
  if (entry.ranks.insert(from_rank).second)
    entry.requests.push_back(req);
}

bool Controller::TableEntryReady(const std::string& name) const {
  auto it = message_table_.find(name);
  if (it == message_table_.end()) return false;
  // Ready when every rank has either submitted the tensor or joined
  // (reference: IncrementTensorCount counts joined ranks as ready,
  // controller.cc:977).
  std::set<int> covered = it->second.ranks;
  covered.insert(joined_ranks_.begin(), joined_ranks_.end());
  return static_cast<int>(covered.size()) == transport_->size();
}

Response Controller::ConstructResponse(const std::string& name) {
  // Validation mirroring the reference's cross-rank consistency checks
  // (reference: controller.cc:495-778) — errors name offending ranks.
  TableEntry entry = std::move(message_table_[name]);
  message_table_.erase(name);
  std::sort(entry.requests.begin(), entry.requests.end(),
            [](const Request& a, const Request& b) { return a.rank < b.rank; });
  const Request& first = entry.requests.front();

  Response resp;
  resp.type = first.type;
  resp.op = first.op;
  resp.dtype = first.dtype;
  resp.names.push_back(name);
  resp.prescale = first.prescale;
  resp.postscale = first.postscale;
  if (!joined_ranks_.empty())
    resp.last_joined_rank = *joined_ranks_.rbegin();

  auto fail = [&](const std::string& why) {
    resp.error = "Tensor " + name + ": " + why;
    return resp;
  };

  std::vector<int> bad;
  for (const Request& r : entry.requests)
    if (r.type != first.type) bad.push_back(r.rank);
  if (!bad.empty())
    return fail("mismatched collective types; rank " +
                std::to_string(first.rank) + " vs ranks " + RanksToString(bad));
  bad.clear();
  for (const Request& r : entry.requests)
    if (r.dtype != first.dtype) bad.push_back(r.rank);
  if (!bad.empty())
    return fail(std::string("mismatched data types; expected ") +
                DataTypeName(first.dtype) + ", differing ranks " +
                RanksToString(bad));

  switch (first.type) {
    case ReqType::kAllreduce:
    case ReqType::kReducescatter: {
      if (first.type == ReqType::kReducescatter && !joined_ranks_.empty())
        return fail("reducescatter cannot run while ranks have joined");
      if (first.type == ReqType::kReducescatter &&
          first.op == RedOp::kAdasum)
        return fail("Adasum is not defined for reducescatter");
      for (const Request& r : entry.requests) {
        if (r.shape != first.shape) bad.push_back(r.rank);
        if (r.op != first.op || r.prescale != first.prescale ||
            r.postscale != first.postscale)
          bad.push_back(r.rank);
      }
      if (!bad.empty())
        return fail("mismatched shapes or reduction parameters on ranks " +
                    RanksToString(bad));
      resp.sizes.push_back(NumElements(first.shape));
      break;
    }
    case ReqType::kBroadcast: {
      for (const Request& r : entry.requests) {
        if (r.root_rank != first.root_rank) bad.push_back(r.rank);
        if (r.shape != first.shape) bad.push_back(r.rank);
      }
      if (!bad.empty())
        return fail("mismatched root rank or shapes on ranks " +
                    RanksToString(bad));
      if (first.root_rank < 0 || first.root_rank >= transport_->size())
        return fail("root rank " + std::to_string(first.root_rank) +
                    " out of range");
      // sizes = [element count, root index] so ranks without a local entry
      // (joined) can still participate in the broadcast tree.
      resp.sizes.push_back(NumElements(first.shape));
      resp.sizes.push_back(first.root_rank);
      break;
    }
    case ReqType::kAllgather: {
      // Shapes must agree on all dims but the first (reference: allgather
      // displacement logic, collective_operations.h:129-179). sizes[r] =
      // rank r's first-dim extent; joined ranks contribute 0 rows.
      for (const Request& r : entry.requests) {
        if (r.shape.size() != first.shape.size() ||
            !std::equal(r.shape.begin() + 1, r.shape.end(),
                        first.shape.begin() + 1))
          bad.push_back(r.rank);
      }
      if (!bad.empty())
        return fail("mismatched trailing dimensions on ranks " +
                    RanksToString(bad));
      resp.sizes.assign(transport_->size(), 0);
      for (const Request& r : entry.requests)
        resp.sizes[r.rank] = r.shape.empty() ? 1 : r.shape[0];
      // Trailing extra entry: row element count, so ranks without a local
      // entry can size their ring buffers.
      {
        int64_t row_elems = 1;
        for (size_t d = 1; d < first.shape.size(); ++d)
          row_elems *= first.shape[d];
        resp.sizes.push_back(row_elems);
      }
      break;
    }
    case ReqType::kAlltoall: {
      // sizes = row-count matrix [src][dst] (reference: alltoall recv-split
      // negotiation, AlltoallOp::PrepareOutputAndParams,
      // collective_operations.h:195-273).
      const int size = transport_->size();
      resp.sizes.assign(static_cast<size_t>(size) * size, 0);
      for (const Request& r : entry.requests) {
        if (static_cast<int>(r.splits.size()) != size) {
          bad.push_back(r.rank);
          continue;
        }
        int64_t total = 0;
        for (int32_t s : r.splits) total += s;
        int64_t rows = r.shape.empty() ? 0 : r.shape[0];
        if (total != rows) bad.push_back(r.rank);
        for (int d = 0; d < size; ++d)
          resp.sizes[static_cast<size_t>(r.rank) * size + d] = r.splits[d];
      }
      if (!bad.empty())
        return fail("invalid alltoall splits on ranks " + RanksToString(bad));
      if (!joined_ranks_.empty())
        return fail("alltoall cannot run while ranks have joined");
      break;
    }
    case ReqType::kBarrier:
      break;
    case ReqType::kJoin:
      break;
  }
  return resp;
}

void Controller::CheckForStalledTensors() {
  // Coordinator-side stall inspection (reference:
  // horovod/common/stall_inspector.cc:26 CheckForStalledTensors; warn after
  // 60s listing which ranks are missing which tensors).
  const double now = NowSeconds();
  if (now - last_stall_check_ < 5.0) return;
  last_stall_check_ = now;
  for (auto& kv : message_table_) {
    double age = now - kv.second.first_seen;
    // The shutdown threshold stands on its own: a user may set it below
    // the (default 60s) warning threshold. Quiescence guard: a healthy
    // rank whose cache-hit submissions are still escalating refreshes
    // last_update when its request lands, deferring the fatal verdict —
    // without it a transiently-slow but alive rank could be declared
    // missing in the escalation window.
    if (opts_.stall_shutdown_s > 0 && age >= opts_.stall_shutdown_s &&
        now - kv.second.last_update >= EffectiveStallThreshold())
      stalled_fatal_.insert(kv.first);
    if (age < opts_.stall_warning_s) continue;
    LogMsg(LogLevel::kWarn, transport_->rank(),
           "Tensor '" + kv.first + "' stalled for " +
               std::to_string(static_cast<int>(age)) +
               "s; waiting on ranks [" +
               RanksToString(MissingRanks(kv.second)) + "]");
  }
}

double Controller::EffectiveStallThreshold() const {
  // Escalation and the fatal quiescence window MUST use the same value:
  // the quiescence guard assumes a healthy rank escalates within it.
  double t = opts_.stall_warning_s;
  if (opts_.stall_shutdown_s > 0) t = std::min(t, opts_.stall_shutdown_s);
  return t;
}

std::vector<int> Controller::MissingRanks(const TableEntry& entry) const {
  std::vector<int> missing;
  for (int r = 0; r < transport_->size(); ++r)
    if (!entry.ranks.count(r) && !joined_ranks_.count(r))
      missing.push_back(r);
  return missing;
}

ResponseList Controller::FuseResponses(std::vector<Response> responses) {
  // Greedy fusion with lookahead over the deterministic response order
  // (reference: FuseResponses, controller.cc:808-948): allreduce responses
  // sharing (dtype, op, scale factors) merge until the byte threshold.
  ResponseList out;
  std::vector<bool> used(responses.size(), false);
  for (size_t i = 0; i < responses.size(); ++i) {
    if (used[i]) continue;
    Response& r = responses[i];
    used[i] = true;
    // kAdasum never fuses: the dot-product coefficients are per-tensor
    // (a concatenated buffer would couple unrelated layers' scale
    // adaptation and make results depend on fusion timing).
    if (r.type == ReqType::kAllreduce && r.error.empty() &&
        r.op != RedOp::kAdasum) {
      int64_t bytes = 0;
      for (int64_t n : r.sizes) bytes += n * DataTypeSize(r.dtype);
      for (size_t j = i + 1; j < responses.size(); ++j) {
        if (used[j]) continue;
        const Response& c = responses[j];
        if (c.type != ReqType::kAllreduce || !c.error.empty() ||
            c.dtype != r.dtype || c.op != r.op ||
            c.prescale != r.prescale || c.postscale != r.postscale ||
            c.last_joined_rank != r.last_joined_rank)
          continue;
        int64_t cbytes = 0;
        for (int64_t n : c.sizes) cbytes += n * DataTypeSize(c.dtype);
        if (bytes + cbytes > opts_.fusion_threshold) continue;
        bytes += cbytes;
        r.names.insert(r.names.end(), c.names.begin(), c.names.end());
        r.sizes.insert(r.sizes.end(), c.sizes.begin(), c.sizes.end());
        used[j] = true;
      }
    }
    out.responses.push_back(std::move(r));
  }
  return out;
}

Status Controller::ComputeResponseList(std::vector<Request> pending,
                                       bool request_shutdown,
                                       const PsConsensus& staged,
                                       CycleResult* out) {
  // Classify local pending requests against the response cache.
  std::vector<size_t> hit_bits, invalid_bits;
  std::vector<Request> uncached;
  std::map<size_t, Request> hit_candidates;
  for (Request& req : pending) {
    if (req.type == ReqType::kBarrier || req.type == ReqType::kJoin) {
      if (req.type == ReqType::kJoin) local_joined_ = true;
      uncached.push_back(std::move(req));
      continue;
    }
    switch (cache_.Lookup(req)) {
      case ResponseCache::CacheState::kHit: {
        // A hit that keeps failing cross-rank agreement (some rank has
        // stopped submitting) is invisible to the stall inspector: it
        // loops through the requeue path and never reaches the
        // coordinator's message table. Past the warning threshold,
        // escalate it to the slow path so stall warning/shutdown apply
        // to cached steady-state tensors too.
        const double now_hit = NowSeconds();
        auto emplaced = hit_pending_since_.try_emplace(req.name, now_hit);
        if (now_hit - emplaced.first->second >= EffectiveStallThreshold()) {
          hit_pending_since_.erase(emplaced.first);
          uncached.push_back(std::move(req));
          break;
        }
        size_t bit = 0;
        cache_.BitFor(req.name, &bit);
        hit_bits.push_back(bit);
        hit_candidates[bit] = std::move(req);
        break;
      }
      case ResponseCache::CacheState::kInvalid: {
        size_t bit = 0;
        cache_.BitFor(req.name, &bit);
        invalid_bits.push_back(bit);
        hit_pending_since_.erase(req.name);
        uncached.push_back(std::move(req));
        break;
      }
      case ResponseCache::CacheState::kMiss:
        hit_pending_since_.erase(req.name);
        uncached.push_back(std::move(req));
        break;
    }
  }

  if (local_joined_) {
    // A joined rank submits nothing; report every cache bit as a hit so the
    // training ranks' AND-agreement still succeeds. Cached non-allreduce
    // responses carry per-rank sizes that are stale once this rank joins —
    // invalidate them ONCE at the join transition so they renegotiate
    // join-aware; anything re-cached after that is already join-aware, and
    // re-invalidating every cycle would force slow-path negotiation for the
    // whole joined period.
    hit_bits.clear();
    for (size_t bit : cache_.BitsInInsertionOrder()) {  // live slots only
      if (joined_cache_flushed_ ||
          cache_.Get(bit).type == ReqType::kAllreduce)
        hit_bits.push_back(bit);
      else
        invalid_bits.push_back(bit);
    }
    joined_cache_flushed_ = true;
  }

  if (timeline_)
    for (const auto& kv : hit_candidates)
      timeline_->NegotiateStart(kv.second.name);
  for (const Request& r : uncached)
    if (timeline_ && r.type != ReqType::kBarrier && r.type != ReqType::kJoin)
      timeline_->NegotiateStart(r.name);

  std::vector<size_t> agreed_bits;
  bool any_uncached = false, all_shutdown = false;
  // Pending stall-shutdown errors must reach every rank; forcing the slow
  // path gives the coordinator a response broadcast to carry them.
  bool has_uncached_local =
      !uncached.empty() || (is_coordinator() && !stalled_fatal_.empty());
  Status st = CoordinateCache(hit_bits, invalid_bits, has_uncached_local,
                              request_shutdown, staged, &agreed_bits,
                              &any_uncached, &all_shutdown, &out->agreed_ps);
  if (!st.ok()) return st;

  // Agreed hits resolve straight from cache; unagreed hits requeue locally
  // for a later cycle (some rank has not submitted the tensor yet).
  std::set<size_t> agreed(agreed_bits.begin(), agreed_bits.end());
  std::vector<Response> ready_responses;
  std::vector<size_t> my_agreed;  // agreed bits this rank actually requested
  for (auto& kv : hit_candidates) {
    if (agreed.count(kv.first)) {
      my_agreed.push_back(kv.first);
      hit_pending_since_.erase(kv.second.name);
    } else if (cache_.Lookup(kv.second) ==
               ResponseCache::CacheState::kMiss) {
      // Invalidated cross-rank during coordination: renegotiate.
      hit_pending_since_.erase(kv.second.name);
      uncached.push_back(std::move(kv.second));
    } else {
      out->requeue.push_back(std::move(kv.second));
    }
  }
  // Deterministic cross-rank execution order for the fast path: cache
  // insertion order (reference: controller.cc:240-247 — identical bit order
  // on all ranks is a correctness requirement).
  std::sort(my_agreed.begin(), my_agreed.end());
  std::vector<size_t> order = cache_.BitsInInsertionOrder();
  for (size_t bit : order) {
    if (!agreed.count(bit)) continue;
    cache_.Touch(bit);
    // A joined rank executes every agreed cached response entry-less (ring
    // collectives need all ranks); others execute only what they requested.
    if (local_joined_ ||
        std::binary_search(my_agreed.begin(), my_agreed.end(), bit))
      ready_responses.push_back(cache_.Get(bit));
  }

  // Slow path: full negotiation through the coordinator.
  if (any_uncached) {
    // Remember what this rank submitted: negotiated responses are cached
    // under the *submitted* request (shape, root, scales) so the next
    // identical submit is a cache hit.
    std::map<std::string, Request> submitted;
    for (const Request& r : uncached)
      if (r.type != ReqType::kBarrier && r.type != ReqType::kJoin)
        submitted[r.name] = r;
    ResponseList negotiated;
    if (is_coordinator()) {
      for (const Request& r : uncached) AddRequestToTable(r, transport_->rank());
      std::vector<uint8_t> buf;
      for (int r = 1; r < transport_->size(); ++r) {
        Status s = transport_->Recv(r, &buf);
        if (!s.ok()) return s;
        RequestList rl;
        if (!Deserialize(buf.data(), buf.size(), &rl))
          return Status::Error(StatusCode::kUnknownError,
                               "bad request list from rank " +
                                   std::to_string(r));
        for (const Request& req : rl.requests) AddRequestToTable(req, r);
      }
      // Construct responses for every tensor now ready on all ranks, in
      // deterministic (name-sorted) order.
      std::vector<std::string> ready;
      for (const auto& kv : message_table_)
        if (TableEntryReady(kv.first)) ready.push_back(kv.first);
      std::sort(ready.begin(), ready.end());
      bool barrier_ready = false;
      for (const std::string& name : ready) {
        if (message_table_[name].requests.front().type == ReqType::kBarrier) {
          message_table_.erase(name);
          Response b;
          b.type = ReqType::kBarrier;
          b.names.push_back(name);
          negotiated.responses.push_back(std::move(b));
          barrier_ready = true;
          continue;
        }
        negotiated.responses.push_back(ConstructResponse(name));
      }
      (void)barrier_ready;
      // Stall shutdown: fail tensors past the threshold with an error
      // response naming the missing ranks.
      for (auto it = stalled_fatal_.begin(); it != stalled_fatal_.end();) {
        auto te = message_table_.find(*it);
        if (te == message_table_.end()) {  // became ready in the meantime
          it = stalled_fatal_.erase(it);
          continue;
        }
        Response err;
        err.type = te->second.requests.front().type;
        err.names.push_back(*it);
        // "STALLED:" is a stable machine-readable marker (the Python layer
        // classifies the exception type by it; wording after it is free).
        err.error = "STALLED: tensor '" + *it +
                    "' stalled beyond the stall-shutdown threshold; "
                    "missing ranks [" +
                    RanksToString(MissingRanks(te->second)) + "]";
        negotiated.responses.push_back(std::move(err));
        message_table_.erase(te);
        it = stalled_fatal_.erase(it);
      }
      // All ranks joined => emit the join-done response and reset.
      if (!joined_ranks_.empty() &&
          static_cast<int>(joined_ranks_.size()) == transport_->size()) {
        Response j;
        j.type = ReqType::kJoin;
        j.names.push_back("__join__");
        j.last_joined_rank = *joined_ranks_.rbegin();
        negotiated.responses.push_back(std::move(j));
        joined_ranks_.clear();
      }
      std::vector<uint8_t> enc;
      Serialize(negotiated, &enc);
      for (int r = 1; r < transport_->size(); ++r) {
        Status s = transport_->Send(r, enc.data(), enc.size());
        if (!s.ok()) return s;
      }
    } else {
      RequestList rl;
      rl.requests = uncached;
      rl.shutdown = request_shutdown;
      std::vector<uint8_t> enc;
      Serialize(rl, &enc);
      Status s = transport_->Send(0, enc.data(), enc.size());
      if (!s.ok()) return s;
      std::vector<uint8_t> buf;
      s = transport_->Recv(0, &buf);
      if (!s.ok()) return s;
      if (!Deserialize(buf.data(), buf.size(), &negotiated))
        return Status::Error(StatusCode::kUnknownError,
                             "bad response list from coordinator");
    }
    // Every rank caches the negotiated responses in identical order so
    // cache-bit layouts agree next cycle.
    for (const Response& r : negotiated.responses) {
      if (r.type == ReqType::kJoin) {
        local_joined_ = false;  // all joined
        joined_cache_flushed_ = false;
      }
      if (!Cacheable(r) || r.names.size() != 1) {
        ready_responses.push_back(r);
        continue;
      }
      {
        auto sub = submitted.find(r.names[0]);
        Request key;
        if (sub != submitted.end()) {
          key = sub->second;  // this rank's exact submission
        } else {
          // This rank never submitted the tensor (it joined). The cache
          // MUST still be updated — insertion order is a pure function of
          // the broadcast response list so bit layouts stay identical on
          // every rank. Store a reconstructed key; a later real submit
          // mismatches it and renegotiates (coordinated invalidation),
          // which is correct, just not fast-pathed.
          key.name = r.names[0];
          key.type = r.type;
          key.op = r.op;
          key.dtype = r.dtype;
          key.prescale = r.prescale;
          key.postscale = r.postscale;
          if (r.type == ReqType::kAllreduce ||
              r.type == ReqType::kBroadcast ||
              r.type == ReqType::kReducescatter) {
            key.shape.assign(1, 0);
            for (int64_t n : r.sizes) key.shape[0] += n;
          }
        }
        cache_.Put(key, r);
      }
      ready_responses.push_back(r);
    }
  }

  if (timeline_)
    for (const Response& r : ready_responses)
      for (const std::string& n : r.names) timeline_->NegotiateEnd(n);

  if (is_coordinator()) CheckForStalledTensors();

  out->to_execute = FuseResponses(std::move(ready_responses));
  out->shutdown = all_shutdown;
  return Status::OK();
}

}  // namespace hvdcore
