// Coordinator/worker negotiation controller.
//
// Native rethink of the reference controller (reference:
// horovod/common/controller.cc:73 ComputeResponseList, :495 ConstructResponse,
// :808 FuseResponses, :977 IncrementTensorCount; protocol documented at
// controller.h:77-108). Per cycle:
//
//   1. Cache coordination (always): every rank exchanges a bit vector of
//      response-cache hits plus flags; agreed hits (bitwise AND) execute
//      straight from the cache with no coordinator round-trip — the
//      steady-state fast path (reference: response_cache.h:131-168).
//   2. Slow path (only when any rank holds uncached requests): workers send
//      their RequestList to rank 0; the coordinator counts per-name
//      readiness across ranks, validates shape/dtype/op agreement naming
//      offending ranks in errors, constructs responses, and broadcasts the
//      ResponseList.
//   3. Both rank-agreed cache hits and fresh responses are fused into
//      buckets up to the fusion threshold (identical, deterministic order
//      on every rank) and returned for execution.
#ifndef HVDCORE_CONTROLLER_H_
#define HVDCORE_CONTROLLER_H_

#include <cstdint>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

#include "message.h"
#include "response_cache.h"
#include "transport.h"

namespace hvdcore {

class Timeline;

struct ControllerOptions {
  size_t cache_capacity = 1024;     // HOROVOD_CACHE_CAPACITY analog
  int64_t fusion_threshold = 128 << 20;  // bytes (reference: operations.cc:491)
  double stall_warning_s = 60.0;    // reference: stall_inspector.h
  double stall_shutdown_s = 0.0;    // 0 = never force-error stalled tensors
};

// Pending process-set changes folded through the phase-A exchange with MIN:
// a staged set/removal activates only once every rank has staged it — the
// analog of the reference's synchronized process-set initialization in the
// background loop (reference: horovod/common/operations.cc:725-741).
struct PsConsensus {
  uint32_t adds = 0;
  uint32_t removals = 0;
};

// Outcome of one negotiation cycle.
struct CycleResult {
  ResponseList to_execute;          // fused, identical order on all ranks
  std::vector<Request> requeue;     // cache hits not yet agreed by all ranks
  bool shutdown = false;            // every rank requested shutdown
  PsConsensus agreed_ps;            // process-set changes agreed this cycle
};

class Controller {
 public:
  Controller(Transport* transport, const ControllerOptions& opts,
             Timeline* timeline);

  // Runs one full negotiation cycle. `pending` = requests popped from the
  // local tensor queue this cycle; `request_shutdown` = this rank wants out;
  // `staged` = this rank's pending process-set adds/removals (global set
  // controller only; pass {} elsewhere).
  Status ComputeResponseList(std::vector<Request> pending,
                             bool request_shutdown, const PsConsensus& staged,
                             CycleResult* out);

  int rank() const { return transport_->rank(); }
  int size() const { return transport_->size(); }
  int joined_size() const { return static_cast<int>(joined_ranks_.size()); }

  // Runtime autotune knob (reference: SynchronizeParameters applies the
  // parameter manager's winners, controller.cc:39-53). Callers must set
  // the same value on every rank at the same cycle boundary.
  void set_fusion_threshold(int64_t bytes) { opts_.fusion_threshold = bytes; }

 private:
  bool is_coordinator() const { return transport_->rank() == 0; }

  // Cache coordination: returns agreed-hit bits; fills `any_uncached` /
  // `all_shutdown` / `agreed_ps`; erases cross-rank-invalidated entries.
  Status CoordinateCache(const std::vector<size_t>& hit_bits,
                         const std::vector<size_t>& invalid_bits,
                         bool has_uncached, bool request_shutdown,
                         const PsConsensus& staged,
                         std::vector<size_t>* agreed_bits, bool* any_uncached,
                         bool* all_shutdown, PsConsensus* agreed_ps);

  // Slow path pieces (coordinator side).
  void AddRequestToTable(const Request& req, int from_rank);
  bool TableEntryReady(const std::string& name) const;
  Response ConstructResponse(const std::string& name);
  void CheckForStalledTensors();

  ResponseList FuseResponses(std::vector<Response> responses);

  struct TableEntry;
  std::vector<int> MissingRanks(const TableEntry& entry) const;
  double EffectiveStallThreshold() const;

  Transport* transport_;
  ControllerOptions opts_;
  Timeline* timeline_;
  ResponseCache cache_;

  // Coordinator state persisting across cycles (workers may submit the same
  // tensor on different cycles): name -> per-rank requests.
  struct TableEntry {
    std::vector<Request> requests;
    std::set<int> ranks;
    double first_seen;   // monotonic seconds, for the stall inspector
    double last_update;  // refreshed per insert; fatal needs quiescence
  };
  std::map<std::string, TableEntry> message_table_;
  // Names past the stall-shutdown threshold: the next slow-path round
  // broadcasts an error response for them (reference: the stall
  // inspector's optional shutdown, stall_inspector.h:78-83 — failing the
  // stalled tensor with a rank-naming error beats killing the job).
  std::set<std::string> stalled_fatal_;
  // First time a cache-hit failed cross-rank agreement, per name (stall
  // escalation for cached steady-state tensors).
  std::map<std::string, double> hit_pending_since_;
  std::set<int> joined_ranks_;
  // True between this rank submitting a Join and the all-joined response.
  // A joined rank submits nothing, so it must (a) report every cache bit as
  // a hit so the bitwise-AND agreement can still succeed for the training
  // ranks (reference: joined ranks record all cache bits,
  // horovod/common/controller.cc:129-133), and (b) execute agreed cached
  // responses entry-less so ring collectives do not hang on it.
  bool local_joined_ = false;
  // Whether the one-time join-transition cache invalidation already ran
  // (stale non-allreduce sizes renegotiate once, then cache hits resume).
  bool joined_cache_flushed_ = false;
  double last_stall_check_ = 0.0;
};

}  // namespace hvdcore

#endif  // HVDCORE_CONTROLLER_H_
