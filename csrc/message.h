// Wire format for coordinator negotiation.
//
// The reference serializes Request/Response lists with flatbuffers
// (reference: horovod/common/wire/message.fbs, message.cc). We use a compact
// hand-rolled little-endian binary format instead: the messages are small,
// fixed in structure, and a zero-dependency encoder keeps the native core
// self-contained.
#ifndef HVDCORE_MESSAGE_H_
#define HVDCORE_MESSAGE_H_

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvdcore {

// One named-tensor request from one rank (reference: Request table,
// horovod/common/wire/message.fbs:44-67).
struct Request {
  int32_t rank = 0;
  ReqType type = ReqType::kAllreduce;
  RedOp op = RedOp::kSum;
  DataType dtype = DataType::kFloat32;
  std::string name;
  int32_t root_rank = -1;
  int32_t group_id = -1;  // grouped-allreduce atomic fusion (group_table.cc)
  double prescale = 1.0;
  double postscale = 1.0;
  std::vector<int64_t> shape;
  std::vector<int32_t> splits;  // alltoall send splits
};

// Coordinator's fused verdict (reference: Response table, message.fbs:78+).
struct Response {
  ReqType type = ReqType::kAllreduce;
  RedOp op = RedOp::kSum;
  DataType dtype = DataType::kFloat32;
  std::vector<std::string> names;    // >1 => fused bucket
  std::string error;                 // non-empty => error response
  double prescale = 1.0;
  double postscale = 1.0;
  // Allgather/alltoall: first-dim sizes per rank, flattened per tensor
  // (reference: Response::tensor_sizes).
  std::vector<int64_t> sizes;
  int32_t last_joined_rank = -1;
};

struct RequestList {
  std::vector<Request> requests;
  bool shutdown = false;
};

struct ResponseList {
  std::vector<Response> responses;
  bool shutdown = false;
};

void Serialize(const RequestList& in, std::vector<uint8_t>* out);
bool Deserialize(const uint8_t* data, size_t len, RequestList* out);
void Serialize(const ResponseList& in, std::vector<uint8_t>* out);
bool Deserialize(const uint8_t* data, size_t len, ResponseList* out);

}  // namespace hvdcore

#endif  // HVDCORE_MESSAGE_H_
