#include "message.h"

#include <cstring>

namespace hvdcore {
namespace {

// Little-endian append/read helpers. All hosts we target are LE; a static
// assert guards the assumption rather than paying for byte swaps.
template <typename T>
void Put(std::vector<uint8_t>* out, T v) {
  static_assert(std::is_trivially_copyable<T>::value, "POD only");
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
  out->insert(out->end(), p, p + sizeof(T));
}

void PutStr(std::vector<uint8_t>* out, const std::string& s) {
  Put<uint32_t>(out, static_cast<uint32_t>(s.size()));
  out->insert(out->end(), s.begin(), s.end());
}

template <typename T>
void PutVec(std::vector<uint8_t>* out, const std::vector<T>& v) {
  Put<uint32_t>(out, static_cast<uint32_t>(v.size()));
  for (const T& x : v) Put<T>(out, x);
}

class Reader {
 public:
  Reader(const uint8_t* data, size_t len) : data_(data), len_(len) {}
  bool ok() const { return ok_; }

  template <typename T>
  T Get() {
    T v{};
    if (pos_ + sizeof(T) > len_) { ok_ = false; return v; }
    std::memcpy(&v, data_ + pos_, sizeof(T));
    pos_ += sizeof(T);
    return v;
  }

  std::string GetStr() {
    uint32_t n = Get<uint32_t>();
    if (!ok_ || pos_ + n > len_) { ok_ = false; return ""; }
    std::string s(reinterpret_cast<const char*>(data_ + pos_), n);
    pos_ += n;
    return s;
  }

  template <typename T>
  std::vector<T> GetVec() {
    uint32_t n = Get<uint32_t>();
    std::vector<T> v;
    if (!ok_ || pos_ + static_cast<size_t>(n) * sizeof(T) > len_) {
      ok_ = false;
      return v;
    }
    v.reserve(n);
    for (uint32_t i = 0; i < n; ++i) v.push_back(Get<T>());
    return v;
  }

 private:
  const uint8_t* data_;
  size_t len_;
  size_t pos_ = 0;
  bool ok_ = true;
};

constexpr uint32_t kReqMagic = 0x48565251;   // "HVRQ"
constexpr uint32_t kRespMagic = 0x48565250;  // "HVRP"

}  // namespace

void Serialize(const RequestList& in, std::vector<uint8_t>* out) {
  out->clear();
  Put<uint32_t>(out, kReqMagic);
  Put<uint8_t>(out, in.shutdown ? 1 : 0);
  Put<uint32_t>(out, static_cast<uint32_t>(in.requests.size()));
  for (const Request& r : in.requests) {
    Put<int32_t>(out, r.rank);
    Put<uint8_t>(out, static_cast<uint8_t>(r.type));
    Put<uint8_t>(out, static_cast<uint8_t>(r.op));
    Put<uint8_t>(out, static_cast<uint8_t>(r.dtype));
    PutStr(out, r.name);
    Put<int32_t>(out, r.root_rank);
    Put<int32_t>(out, r.group_id);
    Put<double>(out, r.prescale);
    Put<double>(out, r.postscale);
    PutVec<int64_t>(out, r.shape);
    PutVec<int32_t>(out, r.splits);
  }
}

bool Deserialize(const uint8_t* data, size_t len, RequestList* out) {
  Reader rd(data, len);
  if (rd.Get<uint32_t>() != kReqMagic) return false;
  out->shutdown = rd.Get<uint8_t>() != 0;
  uint32_t n = rd.Get<uint32_t>();
  out->requests.clear();
  out->requests.reserve(n);
  for (uint32_t i = 0; i < n && rd.ok(); ++i) {
    Request r;
    r.rank = rd.Get<int32_t>();
    r.type = static_cast<ReqType>(rd.Get<uint8_t>());
    r.op = static_cast<RedOp>(rd.Get<uint8_t>());
    r.dtype = static_cast<DataType>(rd.Get<uint8_t>());
    r.name = rd.GetStr();
    r.root_rank = rd.Get<int32_t>();
    r.group_id = rd.Get<int32_t>();
    r.prescale = rd.Get<double>();
    r.postscale = rd.Get<double>();
    r.shape = rd.GetVec<int64_t>();
    r.splits = rd.GetVec<int32_t>();
    out->requests.push_back(std::move(r));
  }
  return rd.ok();
}

void Serialize(const ResponseList& in, std::vector<uint8_t>* out) {
  out->clear();
  Put<uint32_t>(out, kRespMagic);
  Put<uint8_t>(out, in.shutdown ? 1 : 0);
  Put<uint32_t>(out, static_cast<uint32_t>(in.responses.size()));
  for (const Response& r : in.responses) {
    Put<uint8_t>(out, static_cast<uint8_t>(r.type));
    Put<uint8_t>(out, static_cast<uint8_t>(r.op));
    Put<uint8_t>(out, static_cast<uint8_t>(r.dtype));
    Put<uint32_t>(out, static_cast<uint32_t>(r.names.size()));
    for (const std::string& s : r.names) PutStr(out, s);
    PutStr(out, r.error);
    Put<double>(out, r.prescale);
    Put<double>(out, r.postscale);
    PutVec<int64_t>(out, r.sizes);
    Put<int32_t>(out, r.last_joined_rank);
  }
}

bool Deserialize(const uint8_t* data, size_t len, ResponseList* out) {
  Reader rd(data, len);
  if (rd.Get<uint32_t>() != kRespMagic) return false;
  out->shutdown = rd.Get<uint8_t>() != 0;
  uint32_t n = rd.Get<uint32_t>();
  out->responses.clear();
  out->responses.reserve(n);
  for (uint32_t i = 0; i < n && rd.ok(); ++i) {
    Response r;
    r.type = static_cast<ReqType>(rd.Get<uint8_t>());
    r.op = static_cast<RedOp>(rd.Get<uint8_t>());
    r.dtype = static_cast<DataType>(rd.Get<uint8_t>());
    uint32_t nn = rd.Get<uint32_t>();
    for (uint32_t j = 0; j < nn && rd.ok(); ++j) r.names.push_back(rd.GetStr());
    r.error = rd.GetStr();
    r.prescale = rd.Get<double>();
    r.postscale = rd.Get<double>();
    r.sizes = rd.GetVec<int64_t>();
    r.last_joined_rank = rd.Get<int32_t>();
    out->responses.push_back(std::move(r));
  }
  return rd.ok();
}

}  // namespace hvdcore
