"""Parallelism strategies over TPU device meshes.

The reference is a data-parallel product whose extension point for hybrid
schemes is process sets (reference: horovod/common/process_sets.py,
SURVEY.md §2.6 — TP/PP/SP/EP are explicitly absent there). This package is
the TPU-native strategy layer built on that substrate: every strategy is a
mesh axis, every data exchange is an XLA collective over ICI.

- mesh:            N-D mesh construction + axis bookkeeping (dp/fsdp/tp/pp)
- ring_attention:  context parallelism — blockwise attention with k/v blocks
                   rotating over the 'sp' axis via ppermute
- ulysses:         sequence parallelism via head-scatter all_to_all
- sharding:        parameter/activation PartitionSpec rules (tp + fsdp)
- pipeline:        pipeline parallelism via shard_map + microbatch streaming
- moe:             expert parallelism — top-k gating + all_to_all dispatch
"""

from .mesh import MeshConfig, make_mesh  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .sharding import (  # noqa: F401
    transformer_param_rules, make_param_specs, shard_params,
    constrain, batch_spec,
)
from .pipeline import pipeline_apply  # noqa: F401
from .moe import MoELayer, moe_apply  # noqa: F401
