"""Parameter/activation sharding rules for the GSPMD (jit) path.

Megatron-style tensor parallelism expressed as PartitionSpecs: annotate the
parameter tree + batch, jit the step, and XLA's SPMD partitioner inserts the
tp collectives (the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives). The manual shard_map compositions live in
ring_attention.py / ulysses.py / pipeline.py / moe.py; this module is the
annotation route, which is what most users want for tp/fsdp.

Rules are (path-regex → PartitionSpec) pairs matched against the flax param
path joined with '/'. First match wins; unmatched params replicate.
"""

import re

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def transformer_param_rules(tp_axis="tp", fsdp_axis=None):
    """Sharding rules for the models in horovod_tpu.models.

    Column-parallel (shard output features): qkv projections, mlp_in.
    Row-parallel (shard input features): attention out-proj, mlp_out.
    Embeddings/lm_head: shard the vocab dimension.
    With ``fsdp_axis``, the remaining major dimension is sharded ZeRO-3
    style and XLA all-gathers parameters at use.
    """
    f = fsdp_axis

    return [
        # DenseGeneral qkv kernel: (hidden, 3, heads, head_dim) — shard heads.
        (r".*attn/qkv/kernel", P(f, None, tp_axis, None)),
        (r".*attn/qkv/bias", P(None, tp_axis, None)),
        # DenseGeneral proj kernel: (heads, head_dim, hidden) — shard heads.
        (r".*attn/proj/kernel", P(tp_axis, None, f)),
        (r".*attn/proj/bias", P()),
        (r".*mlp_in/kernel", P(f, tp_axis)),
        (r".*mlp_in/bias", P(tp_axis)),
        (r".*mlp_out/kernel", P(tp_axis, f)),
        (r".*mlp_out/bias", P()),
        # MoE expert weights: (experts, d, f) — experts over the data axes
        # (expert parallelism), features over tp.
        (r".*moe/w_in", P(("dp",) if f is None else ("dp", f), None,
                          tp_axis)),
        (r".*moe/w_out", P(("dp",) if f is None else ("dp", f), tp_axis,
                           None)),
        (r".*moe/w_gate", P()),
        (r".*embed/embedding", P(tp_axis, f)),
        (r".*lm_head/kernel", P(f, tp_axis)),
        (r".*mlm_head/kernel", P(f, tp_axis)),
    ]


def _path_str(path):
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _spec_fits(spec, shape, mesh):
    """A spec only applies if every named dimension divides evenly."""
    if len(spec) > len(shape):
        return False
    for dim, names in zip(shape, spec):
        if names is None:
            continue
        names = names if isinstance(names, tuple) else (names,)
        if any(n not in mesh.shape for n in names):
            return False  # mesh lacks this axis → fall back to replication
        k = int(np.prod([mesh.shape[n] for n in names]))
        if dim % k:
            return False
    return True


def make_param_specs(params, mesh, rules=None):
    """Map a param pytree to PartitionSpecs via the rules; params whose
    shapes don't divide the mesh axes fall back to replication."""
    if rules is None:
        rules = transformer_param_rules(
            fsdp_axis="fsdp" if mesh.shape.get("fsdp", 1) > 1 else None)
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def assign(path, leaf):
        name = _path_str(path)
        for pat, spec in compiled:
            if pat.fullmatch(name):
                if _spec_fits(spec, leaf.shape, mesh):
                    return spec
                return P()
        return P()

    return jax.tree_util.tree_map_with_path(assign, params)


def shard_params(params, mesh, specs=None):
    """device_put the param tree onto the mesh per the specs."""
    if specs is None:
        specs = make_param_specs(params, mesh)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)


def constrain(x, mesh, spec):
    """with_sharding_constraint under an explicit mesh."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def batch_spec(extra_dims=0, data_axes=("dp", "fsdp")):
    """PartitionSpec for a batch-leading array: batch over the data axes."""
    return P(data_axes, *([None] * extra_dims))
