"""Ring attention: context parallelism over the 'sp' mesh axis.

Long-context training shards the sequence across devices; each device holds
a contiguous query chunk and the k/v chunks rotate around the ring via
``lax.ppermute`` (one ICI hop per step) while flash-attention partials are
merged with the online-softmax rule. Communication overlaps compute: XLA
schedules the next ppermute concurrently with the current chunk's kernel.

The reference framework has no sequence-axis scaling at all (SURVEY.md §5.7)
— this module is the TPU rebuild's first-class long-context story. Causality
is handled in *global* coordinates by the flash kernel's chunk offsets, so
fully-future chunks contribute zero (lse = -inf) and merge away; no
host-side control flow depends on the ring step.

Differentiability: the ring is an unrolled loop of differentiable pieces
(flash custom-VJP, ppermute, softmax-merge), so JAX autodiff produces the
reverse ring schedule automatically.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import jax_compat

from ..ops.flash_attention import (
    _NEG_INF, flash_attention, reference_attention)


def _merge(o1, lse1, o2, lse2):
    """Merge two normalized attention partials via their log-sum-exps.
    Accumulates in fp32 — the ring loop casts back to the input dtype only
    after the final merge (avoids n-1 bf16 rounding round-trips)."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    denom = w1 + w2
    safe = jnp.where(denom == 0.0, 1.0, denom)
    o = (o1.astype(jnp.float32) * w1[..., None]
         + o2.astype(jnp.float32) * w2[..., None]) / safe[..., None]
    lse = jnp.where(denom == 0.0, _NEG_INF, m + jnp.log(safe))
    return o, lse


def ring_attention(q, k, v, axis_name="sp", *, causal=True, sm_scale=None,
                   impl="flash", block_q=256, block_k=256):
    """Blockwise ring attention (call inside shard_map over ``axis_name``).

    Args:
      q, k, v: local chunks (batch, heads, seq_local, head_dim); the global
        sequence is ``axis_size * seq_local``, device i holding positions
        [i*seq_local, (i+1)*seq_local).
      impl: 'flash' (pallas kernel) or 'einsum' (oracle fallback for tiny
        shapes).
    Returns the local output chunk (batch, heads, seq_local, head_dim).
    """
    n = jax_compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    s_local = q.shape[2]
    q_off = idx * s_local
    perm = [(i, (i + 1) % n) for i in range(n)]

    def partial_attn(kc, vc, k_off):
        if impl == "flash":
            return flash_attention(
                q, kc, vc, causal=causal, sm_scale=sm_scale,
                q_offset=q_off, k_offset=k_off,
                block_q=block_q, block_k=block_k, with_lse=True)
        return reference_attention(q, kc, vc, causal=causal,
                                   sm_scale=sm_scale, q_offset=q_off,
                                   k_offset=k_off, with_lse=True)

    o = lse = None
    kc, vc = k, v
    for t in range(n):
        src = (idx - t) % n
        k_off = src * s_local
        if t < n - 1:
            # Launch the rotation before consuming the chunk so XLA can
            # overlap the ICI transfer with the attention kernel.
            kn = lax.ppermute(kc, axis_name, perm)
            vn = lax.ppermute(vc, axis_name, perm)
        o_t, lse_t = partial_attn(kc, vc, k_off)
        if o is None:
            o, lse = o_t.astype(jnp.float32), lse_t
        else:
            o, lse = _merge(o, lse, o_t, lse_t)
        if t < n - 1:
            kc, vc = kn, vn
    return o.astype(q.dtype)
