"""Ulysses-style sequence parallelism: head-scatter all_to_all attention.

The second sequence-parallel flavor (DeepSpeed-Ulysses): instead of rotating
k/v chunks (ring_attention), one all_to_all re-shards the activations from
sequence-sharded to head-sharded, attention runs over the *full* sequence
with a subset of heads per device, and a second all_to_all restores sequence
sharding. Two collectives total — cheaper than a ring when
heads >= axis_size and sequence fits per-device memory; the ring wins for
extreme sequence lengths. Both compose with tp/dp via the mesh (mesh.py).
"""

from jax import lax

from ..utils import jax_compat

from ..ops.flash_attention import flash_attention, reference_attention


def ulysses_attention(q, k, v, axis_name="sp", *, causal=True, sm_scale=None,
                      impl="flash", block_q=256, block_k=256):
    """Sequence-parallel attention (call inside shard_map over ``axis_name``).

    Args:
      q, k, v: local chunks (batch, heads, seq_local, head_dim); heads must
        be divisible by the axis size.
    Returns the local output chunk (batch, heads, seq_local, head_dim).
    """
    n = jax_compat.axis_size(axis_name)
    heads = q.shape[1]
    if heads % n != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({heads}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring_attention otherwise")

    def scatter_heads(x):
        # (B, H, S/n, D) -> (B, H/n, S, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if impl == "flash":
        oh = flash_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale,
                             block_q=block_q, block_k=block_k)
    else:
        oh = reference_attention(qh, kh, vh, causal=causal,
                                 sm_scale=sm_scale)
    # (B, H/n, S, D) -> (B, H, S/n, D)
    return lax.all_to_all(oh, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
