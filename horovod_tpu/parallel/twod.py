"""2D (data × tensor) training: ``sharding.py`` layouts + ZeRO legs.

One mesh, two axes: parameters shard over ``tp`` per the Megatron-
style rules of :mod:`horovod_tpu.parallel.sharding`, gradients reduce
over ``dp`` through the ZeRO-1 legs of ``ops/zero.py`` — each (dp, tp)
rank owns 1/dp of the optimizer state for ITS tensor slice, so state
memory scales 1/(dp·tp). The composition is exactly the two-stage
layout the redistribution planner speaks (a :class:`ZeroFlat` stage
over ``dp`` stacked on :class:`Sharded` tensor stages over ``tp``),
which is what makes the elastic transitions planner-emitted instead of
hand-rolled:

- :func:`reshard_2d` — dp cohort change (4→2, 2→4, …) at fixed or
  changed tp: one ``plan_redistribution`` over the composed specs,
  executed host-side from windowed shard reads.
- :meth:`TwoDZero.to_serving` — train→serve: tensor-sharded params to
  the serving plane's replicated / near-even rows layout.

Numerics follow the ZeRO contract (tests/test_twod.py): with plain
fp32 Sum/Average the sharded update is bit-identical to the same-mesh
data-parallel oracle (psum + replicated update), because psum_scatter
reduces per element exactly like psum and the parameter add stays
adjacent to the optimizer multiply (``ops/zero.py`` ``_run``). Wire
codecs do not compose with the 2D path yet — gradients ride fp32.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import reduce_ops
from ..ops.bucketing import _unpack
from ..ops.zero import (DEFAULT_ZERO_BUCKET_BYTES, _pack_padded,
                        _validate_elementwise_state, plan_zero)
from ..utils.jax_compat import shard_map as _shard_map
from ..utils.logging_util import get_logger
from .sharding import make_param_specs, transformer_param_rules


def make_mesh_2d(dp, tp, devices=None):
    """A (dp, tp) mesh over the first ``dp*tp`` local devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = int(dp) * int(tp)
    if len(devices) < n:
        raise ValueError(f"need {n} devices for a ({dp}, {tp}) mesh, "
                         f"have {len(devices)}")
    return Mesh(np.array(devices[:n]).reshape(int(dp), int(tp)),
                ("dp", "tp"))


class TwoDZero:
    """One bound instance of (inner optimizer × 2D mesh × shard plan).

    The ZeRO plan is derived from the TENSOR-LOCAL leaf shapes (every
    tp rank's slice is the same shape — even division is the
    ``sharding._spec_fits`` contract), so all (dp, tp) ranks agree on
    the identical pad-and-split geometry; state vector leaves live as
    global ``(dp·tp·shard_len,)`` arrays sharded ``P((dp, tp))`` —
    rank-major flat shards, the exact buffer layout the redistribution
    planner's ``("bucket", k)`` keys address."""

    def __init__(self, inner, mesh, dp_axis="dp", tp_axis="tp",
                 op=reduce_ops.Average,
                 bucket_bytes=DEFAULT_ZERO_BUCKET_BYTES, rules=None):
        if op not in (reduce_ops.Average, reduce_ops.Sum):
            raise ValueError(
                "2D ZeRO supports Average/Sum gradient reductions "
                f"only (got {reduce_ops.op_name(op)})")
        self.inner = inner
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.tp_axis = tp_axis
        self.op = op
        self.dp = int(mesh.shape[dp_axis])
        self.tp = int(mesh.shape[tp_axis])
        self.bucket_bytes = int(bucket_bytes)
        self.rules = rules
        self.plan = None
        self.param_specs = None
        self.treedef = None

    # -- plan --------------------------------------------------------------
    def _local_shape(self, shape, spec):
        out = list(shape)
        for d, names in enumerate(tuple(spec)[:len(out)]):
            if names is None:
                continue
            names = names if isinstance(names, tuple) else (names,)
            k = int(np.prod([self.mesh.shape[n] for n in names]))
            out[d] //= k
        return tuple(out)

    def ensure_plan(self, params):
        leaves, treedef = jax.tree.flatten(params)
        if self.plan is None:
            self.param_specs = make_param_specs(
                params, self.mesh,
                self.rules if self.rules is not None
                else transformer_param_rules(tp_axis=self.tp_axis))
            spec_leaves = jax.tree.leaves(
                self.param_specs,
                is_leaf=lambda x: isinstance(x, P))
            local = [jax.ShapeDtypeStruct(
                self._local_shape(leaf.shape, spec), leaf.dtype)
                for leaf, spec in zip(leaves, spec_leaves)]
            self.plan = plan_zero(local, self.dp, self.bucket_bytes)
            self.treedef = treedef
            for b, s in zip(self.plan.buckets, self.plan.shards):
                _validate_elementwise_state(self.inner, s.shard_len,
                                            b.dtype)
        return self.plan

    def _spec_leaves(self):
        return jax.tree.leaves(self.param_specs,
                               is_leaf=lambda x: isinstance(x, P))

    # -- resharding specs --------------------------------------------------
    def tensor_layouts(self):
        """Per-leaf :class:`resharding.Sharded`/``Replicated`` tensor
        stages mirroring the param specs (first tp-named dim wins; the
        rules shard at most one dim over tp)."""
        from .. import resharding
        out = []
        for spec in self._spec_leaves():
            lay = resharding.Replicated()
            for d, names in enumerate(tuple(spec)):
                names = names if isinstance(names, tuple) else (names,)
                if self.tp_axis in names:
                    lay = resharding.Sharded(self.tp_axis, d)
                    break
            out.append(lay)
        return out

    def spec_2d(self, params):
        """The composed (ZeroFlat over dp) × (Sharded over tp) layout
        of this runtime's optimizer state, as a planner Spec."""
        from .. import resharding
        self.ensure_plan(params)
        return resharding.Spec(
            {self.dp_axis: self.dp, self.tp_axis: self.tp},
            self.tensor_layouts(),
            zero=resharding.ZeroFlat(self.dp_axis, self.plan))

    # -- state -------------------------------------------------------------
    def state_specs(self):
        specs = []
        for b, s in zip(self.plan.buckets, self.plan.shards):
            shape = jax.eval_shape(
                self.inner.init,
                jax.ShapeDtypeStruct((s.shard_len,), b.dtype))
            specs.append(jax.tree.map(
                lambda l: P((self.dp_axis, self.tp_axis))
                if l.ndim >= 1 else P(), shape))
        return (tuple(specs), (), ())

    def init_state(self, params):
        """Optimizer state born (dp × tp)-sharded — the replicated
        footprint never exists (same contract as
        ``ZeroRuntime.init_state``)."""
        plan = self.ensure_plan(params)

        def body(p):
            leaves = jax.tree.leaves(p)
            states = []
            for b, s in zip(plan.buckets, plan.shards):
                buf = _pack_padded(leaves, b, s.padded)
                p_shard = buf.reshape(self.dp, s.shard_len)[
                    lax.axis_index(self.dp_axis)]
                states.append(self.inner.init(p_shard))
            return tuple(states), (), ()

        return jax.jit(_shard_map(
            body, mesh=self.mesh, in_specs=(self.param_specs,),
            out_specs=self.state_specs(), check_vma=False))(params)

    # -- the 2D sharded update --------------------------------------------
    def _tp_replicated(self, spec):
        for names in tuple(spec):
            names = names if isinstance(names, tuple) else (names,)
            if self.tp_axis in names:
                return False
        return True

    def _update_body(self, grads, state, params):
        """tp-local, dp-replicated leaves in; ZeRO legs over dp.

        Gradients arrive as raw autodiff of the rank's LOCAL partial
        loss: tp-sharded leaves carry their exact slice gradient, but
        tp-REPLICATED leaves (norms, embeddings the rules leave whole)
        carry only this tp slice's contribution — sum those over tp
        first, or the shared parameter silently diverges across the
        tensor dimension."""
        plan = self.plan
        g_leaves = list(jax.tree.leaves(grads))
        if self.tp > 1:
            for idx, spec in enumerate(self._spec_leaves()):
                if self._tp_replicated(spec):
                    g_leaves[idx] = lax.psum(g_leaves[idx],
                                             self.tp_axis)
        p_leaves = jax.tree.leaves(params)
        bucket_states = state[0]
        out = [None] * len(g_leaves)
        new_states = []
        average = self.op == reduce_ops.Average
        for k, (b, s) in enumerate(zip(plan.buckets, plan.shards)):
            g = _pack_padded(g_leaves, b, s.padded)
            g_shard = lax.psum_scatter(g, self.dp_axis, tiled=True)
            if average:
                g_shard = g_shard / self.dp
            p = _pack_padded(p_leaves, b, s.padded)
            p_shard = p.reshape(self.dp, s.shard_len)[
                lax.axis_index(self.dp_axis)]
            u_shard, new_state_k = self.inner.update(
                g_shard, bucket_states[k], p_shard)
            new_states.append(new_state_k)
            new_p_shard = p_shard + u_shard.astype(p_shard.dtype)
            full = lax.all_gather(new_p_shard, self.dp_axis,
                                  tiled=True)
            if s.padded != s.size:
                full = lax.slice(full, (0,), (s.size,))
            _unpack(full, g_leaves, b, out)
        new_params = jax.tree.unflatten(self.treedef, out)
        return new_params, (tuple(new_states), (), ())

    def make_step(self, loss_fn):
        """Jitted 2D train step: ``step(params, state, batch) ->
        (new_params, new_state, loss)``. ``loss_fn(params, batch)``
        sees TENSOR-LOCAL params and the rank's dp batch shard and
        returns its local partial loss; the returned loss is the
        psum over both axes."""
        self_ref = self

        def body(p, s, b):
            loss, grads = jax.value_and_grad(loss_fn)(p, b)
            new_p, new_s = self_ref._update_body(grads, s, p)
            loss = lax.psum(lax.psum(loss, self_ref.dp_axis),
                            self_ref.tp_axis)
            return new_p, new_s, loss

        def step(params, state, batch):
            self_ref.ensure_plan(params)
            fn = jax.jit(_shard_map(
                body, mesh=self_ref.mesh,
                in_specs=(self_ref.param_specs,
                          self_ref.state_specs(),
                          P(self_ref.dp_axis)),
                out_specs=(self_ref.param_specs,
                           self_ref.state_specs(), P()),
                check_vma=False))
            return fn(params, state, batch)

        return step

    def apply_gradients(self, params, state, grads):
        """ZeRO-leg update from already-computed gradients (grads laid
        out exactly like params: tp-sharded, dp-replicated)."""
        self.ensure_plan(params)
        fn = jax.jit(_shard_map(
            lambda g, s, p: self._update_body(g, s, p),
            mesh=self.mesh,
            in_specs=(self.param_specs, self.state_specs(),
                      self.param_specs),
            out_specs=(self.param_specs, self.state_specs()),
            check_vma=False))
        return fn(grads, state, params)

    # -- train -> serve ----------------------------------------------------
    def to_serving(self, params, serving_world=1, serving_rank=0,
                   layout="replicated"):
        """Planner-emitted train→serve transform: the tensor-sharded
        params move to the serving plane's layout
        (``serving.state.REPLICATED`` / ``ROWS``) through a bounded-
        window program — never a full device_get of the tree."""
        from .. import resharding
        self.ensure_plan(params)
        meta = resharding.tree_meta_of(params)
        src = resharding.Spec(
            {self.dp_axis: self.dp, self.tp_axis: self.tp},
            self.tensor_layouts())
        if layout == "rows":
            dst = resharding.Spec(
                {"s": int(serving_world)},
                [resharding.Sharded("s", 0, even=False)
                 for _ in meta])
        elif layout == "replicated":
            dst = resharding.replicated_spec(len(meta),
                                             {"s": int(serving_world)})
        else:
            raise ValueError(f"unknown inference layout {layout!r}")
        program = resharding.plan_redistribution(src, dst, meta)
        program.verify_consistency()
        reader = _param_shard_reader(params, src, meta, self.mesh)
        results, _ = resharding.execute_host(
            program, reader, ranks=[int(serving_rank)])
        leaves = []
        for i, (shape, dtype) in enumerate(meta):
            flat = results[int(serving_rank)].get(
                ("leaf", i), np.zeros(0, np.dtype(dtype)))
            if layout == "rows" and len(shape) >= 1 and shape[0] >= 1:
                from ..serving.state import row_slice
                lo, hi = row_slice(shape[0], serving_world,
                                   serving_rank)
                out_shape = (hi - lo,) + tuple(shape[1:])
            else:
                out_shape = tuple(shape)
            leaves.append(flat.reshape(out_shape))
        return jax.tree.unflatten(jax.tree.structure(params), leaves)


def _param_shard_reader(params, spec, meta, mesh):
    """Windowed reads over tensor-sharded param leaves: resolve
    (rank, leaf) to the rank's addressable device shard, slice the
    window (one host-side shard cached at a time)."""
    devices = list(mesh.devices.flat)
    dev_rank = {id(d): r for r, d in enumerate(devices)}
    leaves = jax.tree.leaves(params)
    shard_by = []
    for leaf in leaves:
        if not getattr(leaf, "is_fully_addressable", True):
            raise RuntimeError(
                "twod: cannot read train-layout params in place — a "
                "leaf lives on non-addressable devices (multi-process "
                "global mesh). Checkpoint and load_from_shards on the "
                "serving hosts instead (docs/serving.md).")
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            shard_by.append(None)
        else:
            shard_by.append({dev_rank[id(sh.device)]: sh
                             for sh in shards
                             if id(sh.device) in dev_rank})
    cache = {}

    def read_window(rank, buf, start, length):
        _, i = buf
        key = (i, rank)
        if key not in cache:
            cache.clear()
            if shard_by[i] is None:
                cache[key] = np.asarray(leaves[i]).reshape(-1)
            else:
                cache[key] = np.asarray(
                    shard_by[i][rank].data).reshape(-1)
        return cache[key][start:start + length]

    return read_window


def reshard_2d(state, old, new, params):
    """Planner-emitted elastic reshard of the 2D optimizer state:
    ``old``/``new`` are :class:`TwoDZero` runtimes (dp and/or tp
    cohort sizes may differ; the new tp slicing must keep leaf shapes
    even). One redistribution program moves every moment slot; windows
    read from the old cohort's addressable shards. Mirrors
    ``ops.zero.reshard_state`` (residual-free state, pure data
    movement — moments survive bit-exactly)."""
    from .. import resharding
    old.ensure_plan(params)
    new_plan = new.ensure_plan(params)
    meta = [(tuple(leaf.shape), str(leaf.dtype))
            for leaf in jax.tree.leaves(params)]
    src_spec = old.spec_2d(params)
    dst_spec = new.spec_2d(params)
    program = resharding.plan_redistribution(src_spec, dst_spec, meta)
    program.verify_consistency()
    bucket_states = state[0]
    treedefs = [jax.tree.structure(bs) for bs in bucket_states]
    if any(td != treedefs[0] for td in treedefs[1:]):
        raise ValueError("per-bucket inner states diverge in structure")
    devices_old = list(old.mesh.devices.flat)
    dev_rank = {id(d): r for r, d in enumerate(devices_old)}
    new_devices = list(new.mesh.devices.flat)
    nw = new.dp * new.tp
    slot0 = jax.tree.leaves(bucket_states[0])
    nslots = len(slot0)
    new_flat = [[None] * nslots for _ in range(len(new_plan.buckets))]
    rep_sharding = NamedSharding(new.mesh, P())
    for j in range(nslots):
        if np.ndim(slot0[j]) == 0:
            scalar = np.asarray(slot0[j])
            for k in range(len(new_plan.buckets)):
                new_flat[k][j] = jax.device_put(scalar, rep_sharding)
            continue
        shard_by = {}
        for k, bs in enumerate(bucket_states):
            leaf = jax.tree.leaves(bs)[j]
            shard_by[k] = {dev_rank[id(sh.device)]: sh
                           for sh in leaf.addressable_shards
                           if id(sh.device) in dev_rank}
        cache = {}

        def read_window(rank, buf, start, length, _sb=shard_by,
                        _c=cache):
            _, k = buf
            key = (k, rank)
            if key not in _c:
                _c.clear()
                _c[key] = np.asarray(_sb[k][rank].data).reshape(-1)
            return _c[key][start:start + length]

        dtypes = {str(jax.tree.leaves(bs)[j].dtype)
                  for bs in bucket_states}
        override = dtypes.pop() if len(dtypes) == 1 else None
        results, _ = resharding.execute_host(program, read_window,
                                             dtype_override=override)
        for k, s in enumerate(new_plan.shards):
            vec_sharding = NamedSharding(
                new.mesh, P((new.dp_axis, new.tp_axis)))
            new_flat[k][j] = jax.make_array_from_single_device_arrays(
                (nw * s.shard_len,), vec_sharding,
                [jax.device_put(results[r][("bucket", k)], d)
                 for r, d in enumerate(new_devices)])
    get_logger().warning(
        "twod: optimizer state resharded (dp=%d, tp=%d) -> "
        "(dp=%d, tp=%d) via %s program (%d step(s), %d wire bytes)",
        old.dp, old.tp, new.dp, new.tp, program.strategy,
        len(program.steps), program.bytes_moved())
    return (tuple(jax.tree.unflatten(treedefs[0], flat)
                  for flat in new_flat), (), ())
