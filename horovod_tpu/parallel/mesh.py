"""N-D device mesh construction and axis bookkeeping.

The reference's GLOBAL/LOCAL/CROSS communicator triple (reference:
horovod/common/common.h:166-183, gloo_context.cc:216-228) is how it runs
hierarchical algorithms. On TPU the same idea is a named mesh: axes that ride
ICI (fast, within a slice) vs DCN (across slices). MeshConfig owns the axis
layout; strategies reference axes by name.

Axis convention (outer → inner, slowest → fastest wire):
  dp   — data parallelism (pure replication of params)
  fsdp — data parallelism with parameter sharding (ZeRO-3 style)
  pp   — pipeline stages
  sp   — sequence/context parallelism (ring attention / Ulysses)
  tp   — tensor parallelism (innermost: highest-bandwidth ICI neighbors)

``ep`` (expert parallelism) does not get its own wires: experts shard over
the ('dp','fsdp') axes (the standard mapping — expert dispatch all_to_all
rides the data-parallel axis), see moe.py.
"""

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np

AXIS_ORDER = ("dp", "fsdp", "pp", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each parallelism axis; -1 on dp = "use remaining devices"."""

    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        known = self.fsdp * self.pp * self.sp * self.tp
        dp = self.dp
        if dp == -1:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"fsdp*pp*sp*tp={known}")
            dp = n_devices // known
        if dp * known != n_devices:
            raise ValueError(
                f"mesh {dp}x{self.fsdp}x{self.pp}x{self.sp}x{self.tp} != "
                f"{n_devices} devices")
        return dataclasses.replace(self, dp=dp)

    @property
    def shape(self):
        return (self.dp, self.fsdp, self.pp, self.sp, self.tp)

    @property
    def data_axes(self):
        """Axes gradients are reduced over (batch is sharded over these)."""
        return ("dp", "fsdp")


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """Build the named mesh. Device order follows jax.devices(), which on
    TPU enumerates in physical-torus order so the innermost ('tp') axis
    lands on nearest ICI neighbors."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    config = (config or MeshConfig()).resolve(len(devices))
    arr = np.asarray(devices).reshape(config.shape)
    return jax.sharding.Mesh(arr, AXIS_ORDER)
