"""Expert parallelism: top-k gated mixture-of-experts with all_to_all
dispatch.

Experts shard over the data axes (the standard mapping: the dispatch
all_to_all rides the same wires the gradient allreduce uses, and dp ranks
already hold distinct tokens). Dispatch/combine use the dense one-hot
formulation — (tokens, experts, capacity) einsums — which XLA lowers to MXU
matmuls, avoiding gather/scatter (slow on TPU). Over-capacity tokens are
dropped (their combine weight is zero), standard Switch/GShard semantics.

Two entry points:
- ``moe_apply``: functional, callable inside shard_map with a named 'ep'
  axis (manual collectives), or with axis_name=None under plain jit where
  GSPMD partitions the expert dimension via the sharding rules
  (parallel/sharding.py: moe/w_in over ('dp','fsdp')).
- ``MoELayer``: flax module for the model zoo (GSPMD route).
"""

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils import jax_compat


def _top_k_dispatch(gate_logits, k, capacity):
    """Build dispatch/combine tensors from gate logits.

    Returns (dispatch (T,E,C) bool-ish float, combine (T,E,C) float,
    aux_loss scalar).
    """
    t, e = gate_logits.shape
    gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    masked = gate_logits.astype(jnp.float32)
    # Tokens already routed in earlier slots occupy expert capacity first.
    fill = jnp.zeros((e,), jnp.float32)
    density_sum = jnp.zeros((e,), jnp.float32)
    for _ in range(k):
        choice = jnp.argmax(masked, axis=-1)                  # (T,)
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # (T,E)
        density_sum = density_sum + onehot.mean(axis=0)
        # Position of each token within its chosen expert's buffer.
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) + fill[None, :]
        pos = jnp.sum(pos * onehot, axis=-1)                   # (T,)
        keep = pos < capacity
        pos = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
        slot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # (T,C)
        d = onehot[:, :, None] * slot[:, None, :]
        d = d * keep[:, None, None]
        dispatch = dispatch + d
        prob = jnp.sum(gates * onehot, axis=-1)                # (T,)
        combine = combine + d * prob[:, None, None]
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0)
        masked = jnp.where(onehot > 0, -1e30, masked)

    # Renormalize the kept top-k probabilities.
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = combine / jnp.where(denom == 0, 1.0, denom)
    # GShard load-balancing auxiliary loss.
    density = density_sum / k
    mean_gate = gates.mean(axis=0)
    aux = e * jnp.sum(density * mean_gate)
    return dispatch, combine, aux


def moe_apply(x, w_gate, w_in, w_out, *, axis_name=None, k=2,
              capacity_factor=1.25, activation=jax.nn.gelu):
    """Apply the MoE FFN to tokens.

    Args:
      x: (tokens, d_model) local tokens.
      w_gate: (d_model, n_experts_global).
      w_in: (experts_local, d_model, d_ff) — local experts when ``axis_name``
        is set, all experts otherwise.
      w_out: (experts_local, d_ff, d_model).
      axis_name: 'ep' mesh axis for expert parallelism (inside shard_map);
        None = single-program (GSPMD or single device).
    Returns (y (tokens, d_model), aux_loss scalar).
    """
    tokens, d = x.shape
    e_global = w_gate.shape[1]
    n = jax_compat.axis_size(axis_name) if axis_name is not None else 1
    e_local = w_in.shape[0]
    if e_local * n != e_global:
        raise ValueError(
            f"w_in holds {e_local} experts x {n} ranks != gate's {e_global}")
    capacity = int(np.ceil(k * tokens * capacity_factor / e_global))
    capacity = max(capacity, 1)

    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        w_gate.astype(jnp.float32))
    dispatch, combine, aux = _top_k_dispatch(logits, k, capacity)

    expert_in = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                           dispatch).astype(x.dtype)      # (E, C, d)
    if axis_name is not None:
        # Exchange: each rank keeps its local experts' buffers from every
        # rank: (E, C, d) -> (E_local, n*C, d).
        expert_in = lax.all_to_all(expert_in, axis_name, split_axis=0,
                                   concat_axis=1, tiled=True)
    h = jnp.einsum("ecd,edf->ecf", expert_in, w_in.astype(expert_in.dtype))
    h = activation(h)
    out = jnp.einsum("ecf,efd->ecd", h, w_out.astype(h.dtype))
    if axis_name is not None:
        # (E_local, n*C, d) -> (E, C, d): route results back to the ranks
        # whose tokens they are.
        out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0,
                             tiled=True)
    y = jnp.einsum("ecd,tec->td", out.astype(jnp.float32), combine)
    if axis_name is not None:
        # Load statistics are per-rank; average the aux loss across ranks.
        aux = lax.pmean(aux, axis_name)
    return y.astype(x.dtype), aux


class MoELayer(nn.Module):
    """Flax MoE FFN block (GSPMD route; param names match
    parallel/sharding.py rules under the 'moe' scope)."""

    n_experts: int
    d_ff: int
    k: int = 2
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        # x: (batch, seq, d); flatten tokens for dispatch.
        b, s, d = x.shape
        w_gate = self.param("w_gate", nn.initializers.lecun_normal(),
                            (d, self.n_experts))
        w_in = self.param("w_in", nn.initializers.lecun_normal(),
                          (self.n_experts, d, self.d_ff))
        w_out = self.param("w_out", nn.initializers.lecun_normal(),
                           (self.n_experts, self.d_ff, d))
        y, aux = moe_apply(x.reshape(b * s, d), w_gate, w_in, w_out,
                           k=self.k, capacity_factor=self.capacity_factor)
        self.sow("losses", "moe_aux_loss", aux)
        return y.reshape(b, s, d).astype(self.dtype)
