"""Pipeline parallelism: microbatch streaming over the 'pp' mesh axis.

GPipe-style schedule expressed as a differentiable lax.scan inside
shard_map: each pp rank holds one stage's parameters; every tick each rank
applies its stage and ppermutes the activation to the next rank, so after
the n_pp-1 warm-up ticks every stage is busy. Reverse-mode autodiff of the
scan yields the mirrored backward schedule (1F1B-shaped in steady state)
without any hand-written backward plumbing.

Bubble fraction is (n_pp-1)/(M+n_pp-1) for M microbatches — choose M >= 4x
the stage count for >80% utilization.
"""

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name="pp", *,
                   replicate_out=True):
    """Run microbatches through the pipeline (inside shard_map over
    ``axis_name``).

    Args:
      stage_fn: ``stage_fn(stage_params, x) -> y`` with y.shape == x.shape
        (a transformer stage: hidden states in, hidden states out).
      stage_params: THIS rank's stage parameters (the caller shards the
        stacked per-stage tree over 'pp' via shard_map in_specs).
      microbatches: (M, mb, ...) activations entering stage 0 (replicated
        across pp ranks; only rank 0 consumes them).
      replicate_out: psum the final outputs so every pp rank returns the
        full (M, mb, ...) result (needed when loss is computed under further
        dp reduction); if False, only the last rank's values are meaningful.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    ticks = m + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        state = carry
        mb_idx = jnp.clip(t, 0, m - 1)
        x0 = lax.dynamic_index_in_dim(microbatches, mb_idx, 0,
                                      keepdims=False)
        xin = jnp.where(idx == 0, x0, state)
        y = stage_fn(stage_params, xin)
        nxt = lax.ppermute(y, axis_name, perm)
        return nxt, y

    init = jnp.zeros_like(microbatches[0])
    try:  # scan carry must be typed pp-varying (it crosses ranks)
        init = lax.pcast(init, axis_name, to="varying")
    except (AttributeError, TypeError):
        init = lax.pvary(init, axis_name)
    _, ys = lax.scan(tick, init, jnp.arange(ticks))
    # On the last rank, tick t produced microbatch t-(n-1); slice the
    # steady-state window. (On other ranks this window is their stage's
    # intermediate activations — discarded.)
    outputs = ys[n - 1:]
    if replicate_out:
        outputs = lax.psum(
            jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
    return outputs


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage param trees along a new leading 'stage'
    axis — shard that axis over 'pp' in shard_map in_specs."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
