"""Pipeline parallelism: microbatch streaming over the 'pp' mesh axis.

GPipe-style schedule expressed as a differentiable lax.scan inside
shard_map: each pp rank holds its stages' parameters; every tick each rank
applies a stage and ppermutes the activation to the next rank, so after
the n_pp-1 warm-up ticks every stage is busy. Reverse-mode autodiff of the
scan yields the mirrored backward schedule (1F1B-shaped in steady state)
without any hand-written backward plumbing.

Round-4 realism upgrades over the original GPipe toy:

- **Input lives with its owner, not replicated.** ``microbatches`` is the
  rank-local shard of the global microbatch queue (batch m on rank
  m // per_rank). A one-hop-per-tick ppermute *shift register* streams
  each batch so it arrives at stage 0 exactly on its tick — comm cost one
  microbatch per tick, same order as the activation hop; no rank ever
  holds the full queue.
- **Heterogeneous ends.** ``first_fn`` (e.g. token embedding) runs where
  the queue feeds stage 0 and may change shape/dtype (tokens → hidden);
  ``last_fn`` (e.g. LM head) runs on the last stage's output (hidden →
  logits). The ring itself still carries one fixed hidden shape — that is
  what a static ppermute requires.
- **More stages than ranks** via ``rounds``: rank j holds ``rounds``
  stage-parameter slots; each circuit applies slot ro on every rank, so
  logical stage ro*n + j lives at rank j, slot ro — the interleaved
  placement. Circuits run back-to-back with a drain between them (outputs
  of circuit ro are re-sharded into circuit ro+1's queue), so the bubble
  is rounds*(n_pp-1) ticks; the schedule is circular-GPipe, not
  interleaved-1F1B (a 1F1B interleave cannot be expressed as one
  homogeneous scan — documented limitation). The drain between circuits
  replicates the (M, mb, hidden) outputs with a psum before each rank
  slices its block — ~n x the bytes a true scatter would move, but
  bounded at ~2 circuits' worth of activation-ppermute traffic per
  drain; acceptable until a last-rank scatter primitive exists.

Bubble fraction per circuit is (n_pp-1)/(M+n_pp-1) for M microbatches —
choose M >= 4x the stage count for >80% utilization.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import jax_compat

from ..utils.jax_compat import pvary as _pvary


def _circuit(stage_fn, params_ro, queue, axis_name, *, first=None,
             last=None, hidden_struct):
    """One full pass of every microbatch through the n ranks.

    queue: (per_rank, ...) rank-local input shard, batch m on rank
      m // per_rank.
    Returns (M, ...) per-tick outputs ys[n-1:] (meaningful on the last
    rank; caller masks/replicates).
    """
    n = jax_compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    per = queue.shape[0]
    m = per * n
    ticks = m + n - 1
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]
    perm_feed = [(i, (i - 1) % n) for i in range(n)]

    def tick(carry, s):
        state, feed = carry
        # Shift-register slot invariant: at tick s, rank j's slot holds
        # global batch s+j. A batch is loaded from the local queue at its
        # origin rank (tgt // per == idx) and then rides the feed permute
        # one hop per tick, arriving at rank 0 exactly at its tick.
        tgt = s + idx
        load = (tgt // per == idx) & (tgt < m)
        li = jnp.clip(tgt - idx * per, 0, per - 1)
        q = lax.dynamic_index_in_dim(queue, li, 0, keepdims=False)
        feed = jax.tree.map(
            lambda f, qq: jnp.where(load, qq, f), feed, q)
        x0 = first(feed) if first is not None else feed
        xin = jnp.where(idx == 0, x0, state)
        y = stage_fn(params_ro, xin)
        out = last(y) if last is not None else y
        nxt = lax.ppermute(y, axis_name, perm_fwd)
        feed_next = lax.ppermute(feed, axis_name, perm_feed)
        return (nxt, feed_next), out

    state0 = _pvary(jnp.zeros(hidden_struct.shape, hidden_struct.dtype),
                    axis_name)
    feed0 = _pvary(jnp.zeros_like(queue[0]), axis_name)
    (_, _), ys = lax.scan(tick, (state0, feed0), jnp.arange(ticks))
    # On the last rank, tick t produced microbatch t-(n-1); slice the
    # steady-state window. (On other ranks this window is their stage's
    # intermediate activations — discarded.)
    return ys[n - 1:]


def _replicate_from_last(outputs, axis_name):
    n = jax_compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    return lax.psum(
        jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name="pp", *,
                   first_fn=None, first_params=None,
                   last_fn=None, last_params=None,
                   rounds=1, replicate_out=True):
    """Run microbatches through the pipeline (inside shard_map over
    ``axis_name``).

    Args:
      stage_fn: ``stage_fn(stage_params, x) -> y`` with y.shape == x.shape
        (a transformer stage: hidden states in, hidden states out).
      stage_params: THIS rank's stage-parameter block with a leading
        ``rounds`` axis — build the stacked global tree with
        :func:`stack_stage_params` (which applies the interleaved
        placement) and shard its axis 0 over ``axis_name`` in the
        shard_map in_specs.
      microbatches: (M/n, mb, ...) rank-local input shard (global batch m
        on rank m // (M/n)); shard the global (M, mb, ...) queue's axis 0
        over ``axis_name``. Only stage 0 consumes values — they stream
        there through the feed register.
      first_fn / first_params: optional entry adapter applied where the
        queue feeds stage 0 (``first_fn(first_params, batch) -> hidden``,
        e.g. embedding). May change shape/dtype. Pass first_params
        replicated (P()).
      last_fn / last_params: optional exit adapter applied to the last
        stage's output (e.g. LM head).
      rounds: circuits around the ring; total logical stages =
        rounds * n_pp, stage ro*n+j living at rank j slot ro.
      replicate_out: psum the final outputs so every pp rank returns the
        full (M, mb, ...) result (needed when loss is computed under
        further dp reduction); if False, only the last rank's values are
        meaningful.
    """
    n = jax_compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    per = microbatches.shape[0]

    leaves = jax.tree.leaves(stage_params)
    if leaves and any(leaf.shape[0] != rounds for leaf in leaves):
        raise ValueError(
            f"stage_params leaves must carry a leading rounds={rounds} "
            f"axis (local slot block); got shapes "
            f"{[leaf.shape for leaf in leaves]}. Build the global tree "
            "with stack_stage_params(stages, n_ranks) and shard axis 0.")

    first = (lambda x: first_fn(first_params, x)) \
        if first_fn is not None else None
    last_wrapped = (lambda y: last_fn(last_params, y)) \
        if last_fn is not None else None

    queue = microbatches
    for ro in range(rounds):
        params_ro = jax.tree.map(lambda a: a[ro], stage_params)
        probe = queue[0]
        if first is not None and ro == 0:
            hidden_struct = jax.eval_shape(first, probe)
        else:
            hidden_struct = jax.eval_shape(lambda x: x, probe)
        outputs = _circuit(
            stage_fn, params_ro, queue, axis_name,
            first=first if ro == 0 else None,
            last=last_wrapped if ro == rounds - 1 else None,
            hidden_struct=hidden_struct)
        if ro < rounds - 1:
            # Drain: replicate the circuit's outputs, then each rank
            # slices its block as the next circuit's queue.
            full = _replicate_from_last(outputs, axis_name)
            queue = lax.dynamic_slice_in_dim(full, idx * per, per, 0)

    if replicate_out:
        outputs = _replicate_from_last(outputs, axis_name)
    return outputs


def stack_stage_params(per_stage_params, n_ranks=None):
    """Stack per-stage param trees (sequential order) into the pipeline's
    global layout.

    With ``n_ranks=None`` (or len(stages) == n_ranks): plain stacking —
    axis 0 index j = stage j; shard over 'pp'.

    With rounds = len(stages) / n_ranks > 1: interleaved placement —
    logical stage ro*n + j must land at rank j, slot ro, so axis 0 index
    j*rounds + ro holds stage ro*n + j. Shard axis 0 over 'pp' (giving
    each rank a contiguous (rounds, ...) block) and pass rounds= to
    :func:`pipeline_apply`.
    """
    stages = list(per_stage_params)
    if n_ranks is None or len(stages) == n_ranks:
        return jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    if len(stages) % n_ranks:
        raise ValueError(
            f"{len(stages)} stages not divisible by n_ranks={n_ranks}")
    rounds = len(stages) // n_ranks
    order = [ro * n_ranks + j
             for j in range(n_ranks) for ro in range(rounds)]
    arranged = [stages[i] for i in order]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *arranged)
