"""ctypes binding to the native C++ core runtime (csrc/ -> libhvdcore.so).

The reference loads its C++ runtime the same way — a shared library exposing
a flat C API consumed via ctypes (reference: horovod/common/basics.py:48
loads the per-framework mpi_lib and calls horovod_init/...). Our native core
owns the host-side machinery for multi-process SPMD jobs: coordinator/worker
negotiation with a bitvector-coordinated response cache, allreduce fusion,
the CPU ring-collective data plane over TCP, the chrome-trace timeline, and
the stall inspector (see csrc/*.cc for the component map).

The library is built lazily with ``make`` on first import if missing or
stale — the build environment always carries g++ (no wheels to ship).
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_PKG_DIR, "libhvdcore.so")
_CSRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_PKG_DIR)), "csrc")

_lib = None
_lib_lock = threading.Lock()

# Enum values must match csrc/common.h.
REQ_ALLREDUCE, REQ_ALLGATHER, REQ_BROADCAST, REQ_ALLTOALL = 0, 1, 2, 3
REQ_REDUCESCATTER, REQ_BARRIER, REQ_JOIN = 4, 5, 6
RED_SUM, RED_MIN, RED_MAX, RED_PROD, RED_ADASUM = 0, 1, 2, 3, 4

_DTYPE_TO_ENUM = {}


def _dtype_table():
    global _DTYPE_TO_ENUM
    if _DTYPE_TO_ENUM:
        return _DTYPE_TO_ENUM
    table = {
        np.dtype(np.uint8): 0,
        np.dtype(np.int8): 1,
        np.dtype(np.int32): 2,
        np.dtype(np.int64): 3,
        np.dtype(np.float16): 4,
        np.dtype(np.float32): 5,
        np.dtype(np.float64): 6,
        np.dtype(np.bool_): 7,
    }
    try:
        import ml_dtypes
        table[np.dtype(ml_dtypes.bfloat16)] = 8
    except ImportError:
        pass
    _DTYPE_TO_ENUM = table
    return table


def _build_library():
    if not os.path.isdir(_CSRC_DIR):
        raise ImportError(
            f"libhvdcore.so missing at {_LIB_PATH} and no csrc/ tree to "
            "build it from")
    subprocess.run(["make", "-s", "all"], cwd=_CSRC_DIR, check=True)


def _stale():
    if not os.path.exists(_LIB_PATH):
        return True
    if not os.path.isdir(_CSRC_DIR):
        return False
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for f in os.listdir(_CSRC_DIR):
        if f.endswith((".cc", ".h")) and not f.startswith("test_"):
            if os.path.getmtime(os.path.join(_CSRC_DIR, f)) > lib_mtime:
                return True
    return False


def load_library():
    """Load (building if needed) the native core library."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _stale():
            _build_library()
        lib = ctypes.CDLL(_LIB_PATH)

        lib.hvd_core_create.restype = ctypes.c_void_p
        lib.hvd_core_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_double, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_double]
        lib.hvd_core_destroy.argtypes = [ctypes.c_void_p]
        lib.hvd_reserve_listen_port.restype = ctypes.c_int
        lib.hvd_reserve_listen_port.argtypes = []
        lib.hvd_core_rank.argtypes = [ctypes.c_void_p]
        lib.hvd_core_size.argtypes = [ctypes.c_void_p]
        lib.hvd_core_add_process_set.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int]
        lib.hvd_core_remove_process_set.argtypes = [
            ctypes.c_void_p, ctypes.c_int]
        lib.hvd_core_enqueue.restype = ctypes.c_int64
        lib.hvd_core_enqueue.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_double,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
        lib.hvd_core_run_cycle.argtypes = [ctypes.c_void_p]
        lib.hvd_core_request_shutdown.argtypes = [ctypes.c_void_p]
        lib.hvd_core_shutdown_complete.argtypes = [ctypes.c_void_p]
        lib.hvd_core_poll.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.hvd_core_wait.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_double]
        lib.hvd_core_handle_error.restype = ctypes.c_char_p
        lib.hvd_core_handle_error.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.hvd_core_output_ndim.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.hvd_core_output_shape.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
        lib.hvd_core_output_nbytes.restype = ctypes.c_int64
        lib.hvd_core_output_nbytes.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.hvd_core_output_copy.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
        lib.hvd_core_recv_splits.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int]
        lib.hvd_core_release.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.hvd_core_cycles.restype = ctypes.c_uint64
        lib.hvd_core_cycles.argtypes = [ctypes.c_void_p]
        lib.hvd_core_bytes_processed.restype = ctypes.c_uint64
        lib.hvd_core_bytes_processed.argtypes = [ctypes.c_void_p]
        lib.hvd_core_set_fusion_threshold.argtypes = [
            ctypes.c_void_p, ctypes.c_int64]
        lib.hvd_core_set_topology.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.c_int64]
        lib.hvd_core_next_delegated.restype = ctypes.c_int64
        lib.hvd_core_next_delegated.argtypes = [ctypes.c_void_p]
        lib.hvd_core_delegated_info.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
        lib.hvd_core_delegated_meta.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.hvd_core_delegated_complete.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.c_char_p]
        lib.hvd_core_delegated_finish.argtypes = [
            ctypes.c_void_p, ctypes.c_int64]
        _lib = lib
        return lib


def reserve_listen_port():
    """Bind + listen an ephemeral TCP port inside the native core and keep
    it open; the next NativeCore whose peers entry names this port adopts
    the socket. Closes the publish-then-rebind rendezvous race."""
    port = load_library().hvd_reserve_listen_port()
    if port <= 0:
        raise OSError("could not reserve a listen port")
    return port


class NativeError(RuntimeError):
    pass


_ENQUEUE_ERRORS = {
    -1: "duplicate tensor name in flight",
    -2: "invalid arguments (shape/dtype/byte count mismatch)",
    -3: "runtime shut down",
    -4: "this rank is not a member of the process set",
}


class NativeCore:
    """One native runtime context (= one rank of an SPMD job).

    transport 'tcp' with peers "host:port,..." for real multi-process jobs;
    'local' with a job-name string for in-process multi-rank tests.
    """

    def __init__(self, rank, size, transport="tcp", peers="",
                 fusion_threshold=0, cache_capacity=0, stall_warning_s=0.0,
                 timeline_path="", delegate_data_ops=False,
                 stall_shutdown_s=0.0):
        self._lib = load_library()
        self._ctx = self._lib.hvd_core_create(
            rank, size, transport.encode(), peers.encode(),
            int(fusion_threshold), int(cache_capacity),
            float(stall_warning_s), timeline_path.encode(),
            1 if delegate_data_ops else 0, float(stall_shutdown_s))
        if not self._ctx:
            raise NativeError(
                f"native core init failed (rank {rank}/{size}, transport "
                f"{transport}) — see stderr for details")
        self.rank = rank
        self.size = size

    # -- lifecycle --------------------------------------------------------
    def close(self):
        if self._ctx:
            self._lib.hvd_core_destroy(self._ctx)
            self._ctx = None

    def request_shutdown(self):
        self._lib.hvd_core_request_shutdown(self._ctx)

    def shutdown_complete(self):
        return bool(self._lib.hvd_core_shutdown_complete(self._ctx))

    # -- process sets -----------------------------------------------------
    def add_process_set(self, ranks):
        arr = (ctypes.c_int * len(ranks))(*ranks)
        ps = self._lib.hvd_core_add_process_set(self._ctx, arr, len(ranks))
        if ps < 0:
            raise NativeError("add_process_set failed")
        return ps

    def remove_process_set(self, ps_id):
        return self._lib.hvd_core_remove_process_set(self._ctx, ps_id) == 0

    # -- submission -------------------------------------------------------
    def enqueue(self, ps_id, name, req_type, array=None, red_op=RED_SUM,
                root_rank=-1, prescale=1.0, postscale=1.0, splits=None):
        data_ptr, shape_arr, ndim = None, None, 0
        if array is not None:
            array = np.ascontiguousarray(array)
            dt = _dtype_table().get(array.dtype)
            if dt is None:
                raise NativeError(
                    f"dtype {array.dtype} unsupported by the native core")
            shape = array.shape
            shape_arr = (ctypes.c_int64 * len(shape))(*shape)
            ndim = len(shape)
            data_ptr = array.ctypes.data_as(ctypes.c_void_p)
        else:
            dt = 0
        splits_arr, nsplits = None, 0
        if splits is not None:
            splits = np.ascontiguousarray(splits, dtype=np.int32)
            splits_arr = splits.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32))
            nsplits = len(splits)
        h = self._lib.hvd_core_enqueue(
            self._ctx, ps_id, name.encode(), req_type, red_op, dt, data_ptr,
            shape_arr, ndim, root_rank, prescale, postscale, splits_arr,
            nsplits)
        if h < 0:
            raise NativeError(
                f"enqueue {name!r}: "
                f"{_ENQUEUE_ERRORS.get(h, f'error {h}')}")
        # Keep the input alive until the cycle copies it (the C side copies
        # at enqueue, synchronously — nothing to hold after return).
        return h

    # -- cycle / completion ----------------------------------------------
    def run_cycle(self):
        """One negotiation+execution cycle (blocking, releases the GIL)."""
        return self._lib.hvd_core_run_cycle(self._ctx)

    def poll(self, handle):
        return self._lib.hvd_core_poll(self._ctx, handle)

    def wait(self, handle, timeout_s=300.0):
        if self._lib.hvd_core_wait(self._ctx, handle, timeout_s) != 0:
            raise NativeError(f"wait on handle {handle} timed out")

    def error(self, handle):
        e = self._lib.hvd_core_handle_error(self._ctx, handle)
        return e.decode() if e else ""

    def output(self, handle, dtype):
        """Copy out the completed handle's output as a numpy array."""
        ndim = self._lib.hvd_core_output_ndim(self._ctx, handle)
        if ndim < 0:
            raise NativeError(f"unknown handle {handle}")
        shape_arr = (ctypes.c_int64 * max(ndim, 1))()
        self._lib.hvd_core_output_shape(self._ctx, handle, shape_arr)
        shape = tuple(shape_arr[i] for i in range(ndim))
        nbytes = self._lib.hvd_core_output_nbytes(self._ctx, handle)
        out = np.empty(shape, dtype=dtype)
        if out.nbytes != nbytes:
            # Shapeless payloads (e.g. join's int32) come back flat.
            out = np.empty(nbytes // np.dtype(dtype).itemsize, dtype=dtype)
        if nbytes > 0:
            rc = self._lib.hvd_core_output_copy(
                self._ctx, handle, out.ctypes.data_as(ctypes.c_void_p),
                out.nbytes)
            if rc != 0:
                raise NativeError("output copy failed")
        return out

    def recv_splits(self, handle):
        arr = (ctypes.c_int32 * self.size)()
        n = self._lib.hvd_core_recv_splits(self._ctx, handle, arr, self.size)
        if n < 0:
            raise NativeError("recv_splits failed")
        return np.array([arr[i] for i in range(n)], dtype=np.int32)

    def release(self, handle):
        self._lib.hvd_core_release(self._ctx, handle)

    # -- stats ------------------------------------------------------------
    def set_fusion_threshold(self, nbytes):
        """Apply an autotuned fusion threshold (all ranks must call with
        the same value at the same cycle boundary)."""
        self._lib.hvd_core_set_fusion_threshold(self._ctx, int(nbytes))

    def set_topology(self, host_of, threshold):
        """Host map for hierarchical collectives: host_of[r] = host index
        of global rank r; buffers >= threshold bytes take the two-level
        (intra-host reduce-scatter / cross-host ring / intra-host
        allgather) allreduce. threshold 0 disables."""
        arr = (ctypes.c_int32 * len(host_of))(*host_of)
        self._lib.hvd_core_set_topology(self._ctx, arr, len(host_of),
                                        int(threshold))

    # -- delegated execution (external XLA data plane) --------------------
    def next_delegated(self):
        """Token of the next negotiated-but-externally-executed response,
        or 0 when none is pending."""
        return int(self._lib.hvd_core_next_delegated(self._ctx))

    def delegated(self, token):
        """Fetch a delegated response descriptor as a dict."""
        ps_id = ctypes.c_int32()
        rtype = ctypes.c_int32()
        dtype = ctypes.c_int32()
        red_op = ctypes.c_int32()
        pre = ctypes.c_double()
        post = ctypes.c_double()
        nt = ctypes.c_int32()
        ns = ctypes.c_int32()
        rc = self._lib.hvd_core_delegated_info(
            self._ctx, token, ctypes.byref(ps_id), ctypes.byref(rtype),
            ctypes.byref(dtype), ctypes.byref(red_op), ctypes.byref(pre),
            ctypes.byref(post), ctypes.byref(nt), ctypes.byref(ns))
        if rc != 0:
            raise NativeError(f"bad delegated token {token}")
        handles = (ctypes.c_int64 * max(1, nt.value))()
        sizes = (ctypes.c_int64 * max(1, ns.value))()
        self._lib.hvd_core_delegated_meta(self._ctx, token, handles, sizes)
        return {
            "token": token,
            "ps_id": ps_id.value,
            "type": rtype.value,
            "dtype": dtype.value,
            "red_op": red_op.value,
            "prescale": pre.value,
            "postscale": post.value,
            "handles": list(handles[:nt.value]),
            "sizes": list(sizes[:ns.value]),
        }

    def delegated_complete(self, handle, array=None, error=""):
        """Write the externally computed result (C-contiguous numpy array)
        into the native entry, or fail it with ``error``."""
        if error:
            self._lib.hvd_core_delegated_complete(
                self._ctx, handle, None, 0, None, 0, error.encode())
            return
        arr = np.ascontiguousarray(array)
        shape = (ctypes.c_int64 * max(1, arr.ndim))(*arr.shape)
        self._lib.hvd_core_delegated_complete(
            self._ctx, handle, arr.ctypes.data_as(ctypes.c_void_p),
            arr.nbytes, shape, arr.ndim, b"")

    def delegated_finish(self, token):
        self._lib.hvd_core_delegated_finish(self._ctx, token)

    def cycles(self):
        return self._lib.hvd_core_cycles(self._ctx)

    def bytes_processed(self):
        return self._lib.hvd_core_bytes_processed(self._ctx)
