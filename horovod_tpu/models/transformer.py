"""Transformer models: BERT-style encoder and a decoder-only LM.

Counterpart of the reference's BERT-large pretraining benchmark config
(BASELINE.json: "BERT-large pretraining (examples/pytorch, torch-xla
backend)"). TPU-first choices: bfloat16 activations with fp32 params,
einsum-formulated attention (MXU-friendly), optional jax.checkpoint
rematerialization per block, and head/hidden dimensions kept in multiples
of 128 for MXU tiling. Sequence/tensor sharding is applied externally via
horovod_tpu.parallel (logical axis annotations would over-couple the model
to one partitioning).
"""

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    hidden: int = 1024
    layers: int = 24
    heads: int = 16
    mlp_ratio: int = 4
    max_len: int = 512
    dtype: jnp.dtype = jnp.bfloat16
    # False | True/"full" (recompute everything) | "dots" (save matmul
    # outputs, recompute elementwise — near-free recompute, most of the
    # memory win; the policy that unlocks larger batches on 16G HBM).
    remat: object = False
    causal: bool = True
    use_rope: bool = True          # decoder LM; BERT uses learned positions
    attention_impl: str = "einsum"  # 'einsum' | 'flash' (pallas kernel)


# BERT-large hyperparameters (the reference benchmark target).
def BertConfig(**overrides):
    base = dict(vocab_size=30522, hidden=1024, layers=24, heads=16,
                mlp_ratio=4, max_len=512, causal=False, use_rope=False)
    base.update(overrides)
    return TransformerConfig(**base)


def _rope(q, k):
    """Rotary position embeddings (applied over the head dim)."""
    *_, seq, head_dim = q.shape
    half = head_dim // 2
    freqs = 1.0 / (10000.0 ** (np.arange(0, half) / half))
    t = np.arange(seq)
    angles = jnp.asarray(np.einsum("s,d->sd", t, freqs))
    sin, cos = jnp.sin(angles), jnp.cos(angles)

    def rot(x):
        x1, x2 = x[..., :half], x[..., half:]
        return jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        ).astype(x.dtype)
    return rot(q), rot(k)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, mask=None):
        cfg = self.cfg
        head_dim = cfg.hidden // cfg.heads
        qkv = nn.DenseGeneral((3, cfg.heads, head_dim), dtype=cfg.dtype,
                              name="qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        # (batch, seq, heads, head_dim) -> attention in einsum form.
        if cfg.use_rope:
            q = q.swapaxes(1, 2)
            k = k.swapaxes(1, 2)
            q, k = _rope(q, k)
            q = q.swapaxes(1, 2)
            k = k.swapaxes(1, 2)
        if cfg.attention_impl == "flash":
            # Pallas kernel path (ops/flash_attention.py): BHSD layout,
            # causal handled in-kernel. Per-sample padding masks need the
            # einsum path (the kernel's kv_len is per-call, not per-row).
            if mask is not None:
                raise ValueError(
                    "attention_impl='flash' does not support padding "
                    "masks; use 'einsum'")
            from ..ops.flash_attention import flash_attention
            # 1024-tiles measured fastest (round-3 sweep, docs/PERF.md:
            # 2048² exceeds the 16M scoped-VMEM stack; _prepare clamps to
            # the sequence for shorter contexts).
            out = flash_attention(
                q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2),
                causal=cfg.causal, block_q=1024,
                block_k=1024).swapaxes(1, 2)
        else:
            scale = 1.0 / np.sqrt(head_dim)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            seq = x.shape[1]
            if cfg.causal:
                causal = jnp.tril(jnp.ones((seq, seq), dtype=bool))
                logits = jnp.where(causal[None, None], logits, -1e30)
            if mask is not None:
                logits = jnp.where(mask[:, None, None, :], logits, -1e30)
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            probs = probs.astype(cfg.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(cfg.hidden, axis=(-2, -1), dtype=cfg.dtype,
                               name="proj")(out)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, mask=None):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x)
        x = x + Attention(cfg, name="attn")(h, mask)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x)
        h = nn.Dense(cfg.hidden * cfg.mlp_ratio, dtype=cfg.dtype,
                     name="mlp_in")(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.hidden, dtype=cfg.dtype, name="mlp_out")(h)
        return x + h


class Backbone(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, mask=None):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                     name="tok_embed")(tokens)
        if not cfg.use_rope:
            pos = nn.Embed(cfg.max_len, cfg.hidden, dtype=cfg.dtype,
                           name="pos_embed")(jnp.arange(tokens.shape[1]))
            x = x + pos[None]
        block = Block
        if cfg.remat == "dots":
            block = nn.remat(
                Block,
                policy=jax.checkpoint_policies.
                dots_with_no_batch_dims_saveable)
        elif cfg.remat:
            block = nn.remat(Block)
        for i in range(cfg.layers):
            x = block(cfg, name=f"block_{i}")(x, mask)
        return nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)


class TransformerLM(nn.Module):
    """Decoder-only causal LM (flagship model for long-context /
    sequence-parallel training)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, mask=None):
        cfg = self.cfg
        x = Backbone(cfg, name="backbone")(tokens, mask)
        # bf16 matmul on the MXU (fp32 here costs several passes of MXU
        # time on a 1024x30k projection), fp32 logits for the softmax.
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)


class BertModel(nn.Module):
    """BERT-style encoder with a masked-LM head (pretraining objective of
    the reference's BERT-large benchmark)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, mask=None):
        cfg = self.cfg
        x = Backbone(cfg, name="backbone")(tokens, mask)
        x = nn.Dense(cfg.hidden, dtype=cfg.dtype, name="mlm_dense")(x)
        x = nn.gelu(x)
        x = nn.LayerNorm(dtype=cfg.dtype, name="mlm_ln")(x)
        return nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                        name="mlm_head")(x).astype(jnp.float32)
