"""ResNet family (v1.5 bottleneck), TPU-first.

The reference benchmarks ResNet-50/101 through tf_cnn_benchmarks and
tf.keras.applications (reference: docs/benchmarks.rst:19-66,
examples/tensorflow2/tensorflow2_synthetic_benchmark.py:24 applications
ResNet50). This is a from-scratch flax implementation: channels-last (NHWC,
the TPU conv layout), bfloat16 compute with fp32 variables, and optional
cross-replica SyncBatchNorm via ``axis_name`` (the TPU-native analog of the
reference's sync_batch_norm, reference: horovod/torch/sync_batch_norm.py —
flax BatchNorm pmeans batch stats over the mesh axis when axis_name is set).
"""

from functools import partial
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


def _space_to_depth(x, block=2):
    """NHWC space-to-depth: (B,H,W,C) -> (B,H/b,W/b,b*b*C) with channel
    order (di*b+dj)*C + c."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, block * block * c)


def stem_weights_to_space_to_depth(w7):
    """Map a (7,7,C,F) stem kernel to the equivalent (4,4,4C,F)
    space-to-depth kernel (zero-pad to 8x8, fold the 2x2 phase into
    input channels) — lets checkpoints trained with either stem load
    into the other."""
    import numpy as np
    k, _, c, f = w7.shape
    assert k == 7, w7.shape
    w8 = np.zeros((8, 8, c, f), w7.dtype)
    w8[1:8, 1:8] = np.asarray(w7)
    w4 = np.zeros((4, 4, 4 * c, f), w7.dtype)
    for da in range(2):
        for db in range(2):
            w4[:, :, (da * 2 + db) * c:(da * 2 + db + 1) * c] = \
                w8[da::2, db::2]
    return w4


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    axis_name: Optional[str] = None   # set to 'hvd' for SyncBatchNorm
    # False | True/"full" | "dots" (save conv outputs, recompute
    # elementwise BN/ReLU) — trades recompute for backward-pass HBM,
    # pushing the batch-size spill cliff out (docs/PERF.md).
    remat: Any = False
    # "conv" (classic 7x7/s2) | "space_to_depth": reorganize the input
    # to (H/2, W/2, 4C) and run an equivalent 4x4/s1 conv — the 7x7
    # stem's contraction dim (7*7*3=147) underfills the MXU; the
    # space-to-depth form (4*4*12=192, no stride) tiles better (the
    # standard MLPerf-era TPU ResNet stem).
    stem: str = "conv"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       axis_name=self.axis_name if train else None)
        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            x = _space_to_depth(x)
            # Exactly equivalent to the 7x7/s2 conv: the 7x7 kernel
            # zero-pads to 8x8 (pad (4,3) in pixels = (2,1) in blocks)
            # and folds its 2x2 phase into the input channels.
            x = conv(self.num_filters, (4, 4), (1, 1),
                     padding=[(2, 1), (2, 1)], name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block_cls = self.block_cls
        if self.remat == "dots":
            block_cls = nn.remat(
                block_cls,
                policy=jax.checkpoint_policies.
                dots_with_no_batch_dims_saveable)
        elif self.remat:
            block_cls = nn.remat(block_cls)
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(self.num_filters * 2 ** i,
                              conv=conv, norm=norm,
                              strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                   block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckBlock)
