"""Small reference models for MNIST-scale smoke tests (reference:
examples/keras/keras_mnist.py model — two conv layers + dense head)."""

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Plain MLP classifier."""

    features: tuple = (128, 64)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.relu(nn.Dense(f)(x))
        return nn.Dense(self.num_classes)(x)


class MnistCNN(nn.Module):
    """LeNet-style CNN matching the reference MNIST example topology
    (reference: examples/keras/keras_mnist.py:55-65 — conv 32, conv 64,
    maxpool, dense 128, dense 10)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (3, 3), dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (3, 3), dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
