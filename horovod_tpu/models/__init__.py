"""Model zoo for examples, tests, and benchmarks.

Counterpart of the reference's examples/ model usage (reference:
examples/keras/keras_mnist.py LeNet-style CNN, examples/tensorflow2
ResNet-50 via tf.keras.applications, examples/pytorch synthetic benchmark).
All models are flax.linen modules designed TPU-first: channels-last,
bfloat16-friendly, static shapes.
"""

from .mlp import MLP, MnistCNN  # noqa: F401
from .resnet import ResNet50, ResNet18, ResNet101  # noqa: F401
from .transformer import (  # noqa: F401
    TransformerLM, TransformerConfig, BertConfig, BertModel,
)
