"""PyTorch binding: the reference's handle-based async API + grad-hook
optimizer (reference: horovod/torch/mpi_ops.py:107-976,
horovod/torch/optimizer.py:36-275) on the horovod_tpu runtime.

Process-level semantics (one process per accelerator under ``hvdrun``).
Async ops return a handle immediately; ``synchronize(handle)`` blocks and
writes the result back (in-place for ``*_`` variants) — the same contract
as the reference's pybind handle manager (mpi_ops_v2.cc:624). bfloat16
tensors enter the data plane natively via dlpack (no fp32 upcast);
results always come back in the input tensor's dtype. Model math itself
can run on the TPU through :func:`tpu_compile` (fx→JAX, compile.py).
Caveat: the compiled data plane runs with JAX x64 disabled, so int64
values beyond 2^31 and float64 precision are not preserved end to end.
"""

import numpy as np

from .. import basics
from ..utils.logging_util import get_logger
from ..ops import collectives as _c
from ..ops import reduce_ops
from ..ops.compression import Compression
from ..process_sets import (ProcessSet, global_process_set,
                            add_process_set, remove_process_set)

Average = reduce_ops.Average
Sum = reduce_ops.Sum
Adasum = reduce_ops.Adasum
Min = reduce_ops.Min
Max = reduce_ops.Max
Product = reduce_ops.Product

init = basics.init
shutdown = basics.shutdown
is_initialized = basics.is_initialized
is_homogeneous = basics.is_homogeneous
mpi_enabled = basics.mpi_enabled
mpi_built = basics.mpi_built
mpi_threads_supported = basics.mpi_threads_supported
gloo_enabled = basics.gloo_enabled
gloo_built = basics.gloo_built
nccl_built = basics.nccl_built
ddl_built = basics.ddl_built
ccl_built = basics.ccl_built
cuda_built = basics.cuda_built
rocm_built = basics.rocm_built
metrics_snapshot = basics.metrics_snapshot


def start_timeline(file_path, mark_cycles=None, jax_profiler_dir=None):
    """Reference: horovod/torch/mpi_ops.py start_timeline (the shared
    basics API surfaced per binding)."""
    from .. import start_timeline as _st
    return _st(file_path, mark_cycles=mark_cycles,
               jax_profiler_dir=jax_profiler_dir)


def stop_timeline():
    from .. import stop_timeline as _st
    return _st()


def _torch():
    import torch
    return torch


_warned_single_mode = [False]


def _warn_single_mode_once():
    """In single-controller mode basics.size() counts virtual devices
    while this binding's world is launcher processes — mixing
    hvd.rank() with hvd.torch.rank() in one script would silently give
    two different worlds. Warn once so the split is visible."""
    rt = basics.runtime()
    if (not _warned_single_mode[0] and rt.mode == basics.MODE_SINGLE
            and rt.size > 1):
        _warned_single_mode[0] = True
        get_logger().warning(
            "horovod_tpu.torch: single-controller mode with %d virtual "
            "devices — torch rank()/size() are PROCESS-level (1 process "
            "here), while horovod_tpu.rank()/size() count virtual "
            "devices. Launch under hvdrun for per-process torch "
            "semantics, or use hvd.tpu_compile to train across the "
            "local devices.", rt.size)


def rank():
    """Process-level rank — deliberately NOT basics.rank()-aliased: in
    single-controller mode basics.size() counts virtual devices, while
    this binding's world is launcher processes. The local/cross getters
    below are topology-backed for the same reason (a virtual-device
    local_size exceeding a process-level size() would be incoherent
    within one binding)."""
    _warn_single_mode_once()
    return basics.runtime().topology.rank


def size():
    _warn_single_mode_once()
    return basics.runtime().topology.size


def local_rank():
    _warn_single_mode_once()
    return basics.runtime().topology.local_rank


def local_size():
    _warn_single_mode_once()
    return basics.runtime().topology.local_size


def cross_rank():
    _warn_single_mode_once()
    return basics.runtime().topology.cross_rank


def cross_size():
    _warn_single_mode_once()
    return basics.runtime().topology.cross_size


def _spmd():
    rt = basics.runtime()
    return rt.mode == basics.MODE_SPMD and rt.topology.size > 1


def _to_np(t):
    """torch tensor -> data-plane array. CPU fp32/int tensors hand over
    their buffer zero-copy via the numpy protocol; bf16 enters through
    dlpack as a NATIVE jax bfloat16 array (no fp32 upcast round-trip —
    numpy has no bf16, but the plane does). Falls back to the historical
    fp32-upcast when dlpack is unavailable."""
    torch = _torch()
    t = t.detach()
    if t.dtype == torch.bfloat16:
        if t.device.type == "cpu":
            try:
                import jax
                return jax.dlpack.from_dlpack(t.contiguous()), \
                    torch.bfloat16
            except (TypeError, RuntimeError, BufferError):
                pass
        return t.float().cpu().numpy(), torch.bfloat16
    return t.cpu().numpy(), None


def _from_np(arr, like, bf16):
    torch = _torch()
    # ascontiguousarray would promote 0-d to (1,): keep scalars 0-d.
    arr = np.ascontiguousarray(arr) if arr.ndim else np.asarray(arr)
    if not arr.flags.writeable:
        # np.asarray(jax_array) is a read-only zero-copy view of the JAX
        # buffer; torch must not alias it (in-place user ops would write
        # into backend-owned memory).
        arr = arr.copy()
    if arr.dtype.name == "bfloat16":
        # ml_dtypes bf16 out of the native-bf16 plane: torch can't read
        # it through numpy — reinterpret the bits (free) instead.
        out = torch.from_numpy(arr.view(np.uint16)).view(torch.bfloat16)
    else:
        out = torch.from_numpy(arr)
    if like is not None:
        # Restore the input dtype: the data plane may have narrowed
        # (int64->int32, float64->float32 under JAX x64-off).
        out = out.to(dtype=like.dtype, device=like.device)
    elif bf16 is not None:
        out = out.to(bf16)
    return out


class _Handle:
    """Torch-side async handle: wraps the framework handle plus the
    write-back target (reference: handle_manager in mpi_ops_v2.cc)."""

    __slots__ = ("inner", "target", "inplace", "bf16", "done", "result",
                 "want_splits", "compression", "comp_ctx")

    def __init__(self, inner, target, inplace, bf16, want_splits=False,
                 compression=None, comp_ctx=None):
        self.inner = inner
        self.target = target
        self.inplace = inplace
        self.bf16 = bf16
        self.done = False
        self.result = None
        self.want_splits = want_splits
        self.compression = compression
        self.comp_ctx = comp_ctx


def _local_handle(value):
    h = _Handle(None, None, False, None)
    h.done = True
    h.result = value
    return h


def synchronize(handle):
    """Block until the handle's op completes; returns the result tensor
    (reference: horovod/torch/mpi_ops.py synchronize)."""
    if handle.done:
        return handle.result
    fn = getattr(handle, "fn", None)
    if fn is not None:  # composite op (_LazyHandle, e.g. sparse allreduce)
        handle.result = fn()
        handle.done = True
        return handle.result
    out = _c.synchronize(handle.inner)
    if handle.compression is not None and handle.target is None:
        # With a write-back target, _from_np below restores the dtype
        # anyway — an explicit decompress would be a redundant full-array
        # cast on the hot gradient path.
        out = handle.compression.decompress(out, handle.comp_ctx)
    if isinstance(out, tuple):  # alltoall resolves to (out, recv_splits)
        data = _from_np(np.asarray(out[0]), handle.target, handle.bf16)
        if handle.want_splits:
            # _from_np(copy) on splits too: np.asarray of a jax array is a
            # read-only view torch must not alias.
            result = (data, _from_np(np.asarray(out[1], np.int32),
                                     None, None))
        else:
            result = data
    else:
        result = _from_np(np.asarray(out), handle.target, handle.bf16)
        if handle.inplace and handle.target is not None:
            handle.target.copy_(result)
            result = handle.target
    handle.done = True
    handle.result = result
    return result


def poll(handle):
    if handle.done:
        return True
    if getattr(handle, "fn", None) is not None:
        # Composite op (_LazyHandle): work happens at synchronize().
        return False
    return _c.poll(handle.inner)


def _allreduce_async_impl(tensor, op, name, prescale, postscale,
                          process_set, inplace, compression=None):
    if op is None:
        op = Average
    if compression is Compression.none:
        compression = None
    if not _spmd():
        scale = (prescale or 1.0) * (postscale or 1.0)
        out = tensor * scale if scale != 1.0 else tensor
        if inplace and out is not tensor:
            tensor.copy_(out)
            out = tensor
        return _local_handle(out)
    arr, bf16 = _to_np(tensor)
    comp_ctx = None
    wire = getattr(compression, "wire_codec", None)
    if compression is not None and wire is None:
        # Compressor classes operate fine on numpy (astype/issubdtype):
        # no device round-trip on the hot gradient path. Wire codecs
        # (int8/fp8) compress INSIDE the collective instead — the codec
        # marker below routes them (docs/compression.md).
        carr, comp_ctx = compression.compress(arr)
        arr = np.ascontiguousarray(carr)
    inner = _c.allreduce_async(arr, op=op, name=name,
                               prescale_factor=prescale or 1.0,
                               postscale_factor=postscale or 1.0,
                               process_set=process_set, codec=wire)
    return _Handle(inner, tensor, inplace, bf16, compression=compression,
                   comp_ctx=comp_ctx)


def allreduce_async(tensor, average=None, name=None, compression=None,
                    op=None, prescale_factor=1.0, postscale_factor=1.0,
                    process_set=global_process_set):
    """Argument order follows the reference (horovod/torch/mpi_ops.py:211:
    tensor, average, name, compression, op, ...) so positional callers of
    drop-in scripts bind correctly."""
    if op is None:
        op = Sum if average is False else Average
    return _allreduce_async_impl(tensor, op, name, prescale_factor,
                                 postscale_factor, process_set, False,
                                 compression)


def allreduce_async_(tensor, average=None, name=None, compression=None,
                     op=None, prescale_factor=1.0, postscale_factor=1.0,
                     process_set=global_process_set):
    if op is None:
        op = Sum if average is False else Average
    return _allreduce_async_impl(tensor, op, name, prescale_factor,
                                 postscale_factor, process_set, True,
                                 compression)


def allreduce(tensor, average=None, name=None, compression=None,
              op=None, prescale_factor=1.0, postscale_factor=1.0,
              process_set=global_process_set):
    return synchronize(allreduce_async(
        tensor, average, name, compression, op, prescale_factor,
        postscale_factor, process_set=process_set))


def allreduce_(tensor, average=None, name=None, compression=None,
               op=None, prescale_factor=1.0, postscale_factor=1.0,
               process_set=global_process_set):
    return synchronize(allreduce_async_(
        tensor, average, name, compression, op, prescale_factor,
        postscale_factor, process_set=process_set))


class _LazyHandle(_Handle):
    """Handle whose work runs at synchronize() time (sparse allreduce is a
    composite of allgathers; reference returns a handle the same way,
    horovod/torch/mpi_ops.py:556)."""

    __slots__ = ("fn",)

    def __init__(self, fn):
        super().__init__(None, None, False, None)
        self.fn = fn

    # poll() reports done only after synchronize (composite op).


def sparse_allreduce_async(tensor, name=None, op=None,
                           process_set=global_process_set):
    """Average/sum a sparse COO tensor across ranks by allgathering its
    indices and values (reference: horovod/torch/mpi_ops.py:556
    sparse_allreduce_async — same allgather formulation). Returns a handle
    resolving to a coalesced sparse tensor.

    With the sparse plane enabled (``HVDTPU_SPARSE``; docs/sparse.md)
    and a row-sparse tensor (``sparse_dim == 1`` — the embedding-grad
    shape), the per-tensor density policy may pick densify-then-
    allreduce past the crossover: the handle then resolves to a DENSE
    tensor (gathering most of the table costs more wire than the dense
    ring; the optimizer routing accepts both). ``coalesce()`` is the
    local row-deduplication either way."""
    torch = _torch()
    if not tensor.is_sparse:
        raise ValueError("sparse_allreduce_async requires a sparse tensor")
    if op is None:
        op = Average
    if not _spmd():
        out = tensor.coalesce()
        return _local_handle(out)
    t = tensor.coalesce()
    from ..ops import sparse as sparse_ops
    plane = sparse_ops._plane()
    # Per-call-site auto name, not one shared fallback: a single key
    # would pool every unnamed sparse tensor into one density EMA (a
    # 1%-dense table and a 60%-dense one blending to a density wrong
    # for both) and collide the .idx/.val allgather names of two
    # in-flight tensors.
    nm = name or _c._auto_name("sparse_allreduce")
    if plane is not None and t.sparse_dim() == 1:
        vals_t = t.values()
        row_elems = sparse_ops.row_elems(tuple(t.shape))
        nnz = int(t.indices().shape[1])
        nset = len(process_set.ranks)
        if nset > 1 and plane.policy.mode_for_name(nm) == "auto":
            nnz = sparse_ops._cohort_nnz(nm, nnz, process_set)
        # world = the cohort the wire spans — the PROCESS SET's size,
        # not the global job's (ops/sparse.py keys the crossover on
        # len(process_set.ranks); a sub-cohort's economics differ).
        path = plane.select(nm, nnz, int(t.shape[0]),
                            row_elems * vals_t.element_size(),
                            8, nset)
        if path == "dense":
            return allreduce_async(t.to_dense(), name=nm, op=op,
                                   process_set=process_set)
    idx_np = t.indices().cpu().numpy().T.astype(np.int64)  # (nnz, ndim)
    values_like = t.values()
    val_np, val_bf16 = _to_np(values_like)  # bf16 rides as fp32
    h_idx = _c.allgather_async(idx_np, name=f"{nm}.idx",
                               process_set=process_set)
    h_val = _c.allgather_async(val_np, name=f"{nm}.val",
                               process_set=process_set)
    # Average divides by the set of ranks whose slices were gathered.
    world = len(process_set.ranks)
    shape = list(t.shape)

    def resolve():
        all_idx = np.asarray(_c.synchronize(h_idx))
        all_val = np.asarray(_c.synchronize(h_val))
        idx_t = torch.from_numpy(
            np.ascontiguousarray(all_idx.T)).to(tensor.device)
        # _from_np restores the original value dtype (bf16/f64) + device.
        val_t = _from_np(all_val, values_like, val_bf16)
        out = torch.sparse_coo_tensor(idx_t, val_t, size=shape).coalesce()
        if op == Average:
            out = torch.sparse_coo_tensor(out.indices(),
                                          out.values() / world,
                                          size=shape).coalesce()
        return out

    return _LazyHandle(resolve)


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      process_set=global_process_set):
    if op is None:
        op = Sum if average is False else Average
    return _grouped_call(
        tensors, lambda arrs: _c.grouped_allreduce(
            arrs, op=op, name=name, process_set=process_set))


def _grouped_allreduce_async_impl(tensors, average, name, op, process_set,
                                  inplace, prescale=1.0, postscale=1.0):
    if op is None:
        op = Sum if average is False else Average
    tensors = list(tensors)
    if not tensors or not _spmd():
        scale = (prescale or 1.0) * (postscale or 1.0)
        if scale != 1.0:
            if inplace:
                for t in tensors:
                    t.mul_(scale)
            else:
                tensors = [t * scale for t in tensors]
        return _local_handle(tensors)
    marsh = [_to_np(t) for t in tensors]
    # Submitted now (async enqueue); the torch-side unmarshal runs at
    # synchronize(), like the single-tensor handles.
    inner = _c.grouped_allreduce_async([m[0] for m in marsh], op=op,
                                       name=name,
                                       prescale_factor=prescale or 1.0,
                                       postscale_factor=postscale or 1.0,
                                       process_set=process_set)

    def resolve():
        outs = _c.synchronize(inner)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        res = [_from_np(np.asarray(o), t, b)
               for o, t, (_, b) in zip(outs, tensors, marsh)]
        if inplace:
            for t, r in zip(tensors, res):
                t.copy_(r)
            return tensors
        return res

    return _LazyHandle(resolve)


def grouped_allreduce_async(tensors, average=None, name=None, op=None,
                            prescale_factor=1.0, postscale_factor=1.0,
                            process_set=global_process_set):
    """Handle-based grouped allreduce (reference:
    horovod/torch/mpi_ops.py:375 grouped_allreduce_async)."""
    return _grouped_allreduce_async_impl(tensors, average, name, op,
                                         process_set, False,
                                         prescale_factor, postscale_factor)


def grouped_allreduce_async_(tensors, average=None, name=None, op=None,
                             prescale_factor=1.0, postscale_factor=1.0,
                             process_set=global_process_set):
    return _grouped_allreduce_async_impl(tensors, average, name, op,
                                         process_set, True,
                                         prescale_factor, postscale_factor)


def grouped_allreduce_(tensors, average=None, name=None, op=None,
                       prescale_factor=1.0, postscale_factor=1.0,
                       process_set=global_process_set):
    return synchronize(grouped_allreduce_async_(
        tensors, average, name, op, prescale_factor, postscale_factor,
        process_set=process_set))


def _grouped_call(tensors, call):
    """Shared torch<->numpy marshalling for grouped collectives: one
    place for the dtype/device round-trip (and safe for iterator
    inputs — materialized before any consumption)."""
    tensors = list(tensors)
    if not tensors or not _spmd():
        # Empty groups are a no-op in every mode (an empty bucket would
        # IndexError inside the backend's group enqueue).
        return tensors
    arrs, bf16s = zip(*[_to_np(t) for t in tensors])
    outs = call(list(arrs))
    return [_from_np(np.asarray(o), t, b)
            for o, t, b in zip(outs, tensors, bf16s)]


def grouped_allgather(tensors, name=None,
                      process_set=global_process_set):
    return _grouped_call(
        tensors, lambda arrs: _c.grouped_allgather(
            arrs, name=name, process_set=process_set))


def grouped_reducescatter(tensors, op=None, name=None,
                          process_set=global_process_set):
    return _grouped_call(
        tensors, lambda arrs: _c.grouped_reducescatter(
            arrs, op=op or Average, name=name, process_set=process_set))


def allgather_async(tensor, name=None, process_set=global_process_set):
    if not _spmd():
        return _local_handle(tensor)
    arr, bf16 = _to_np(tensor)
    return _Handle(_c.allgather_async(arr, name=name,
                                      process_set=process_set),
                   tensor, False, bf16)


def allgather(tensor, name=None, process_set=global_process_set):
    return synchronize(allgather_async(tensor, name, process_set))


def broadcast_async(tensor, root_rank, name=None,
                    process_set=global_process_set):
    if not _spmd():
        return _local_handle(tensor)
    arr, bf16 = _to_np(tensor)
    return _Handle(_c.broadcast_async(arr, root_rank, name=name,
                                      process_set=process_set),
                   tensor, False, bf16)


def broadcast_async_(tensor, root_rank, name=None,
                     process_set=global_process_set):
    if not _spmd():
        return _local_handle(tensor)
    arr, bf16 = _to_np(tensor)
    return _Handle(_c.broadcast_async(arr, root_rank, name=name,
                                      process_set=process_set),
                   tensor, True, bf16)


def broadcast(tensor, root_rank, name=None,
              process_set=global_process_set):
    return synchronize(broadcast_async(tensor, root_rank, name,
                                       process_set))


def broadcast_(tensor, root_rank, name=None,
               process_set=global_process_set):
    return synchronize(broadcast_async_(tensor, root_rank, name,
                                        process_set))


def alltoall_async(tensor, splits=None, name=None,
                   process_set=global_process_set):
    torch = _torch()
    if not _spmd():
        if splits is None:
            return _local_handle(tensor)
        return _local_handle((tensor, torch.as_tensor(
            np.asarray(splits, np.int32))))
    arr, bf16 = _to_np(tensor)
    np_splits = None if splits is None else np.asarray(
        splits.cpu() if hasattr(splits, "cpu") else splits, np.int32)
    return _Handle(_c.alltoall_async(arr, np_splits, name=name,
                                     process_set=process_set),
                   tensor, False, bf16, want_splits=splits is not None)


def alltoall(tensor, splits=None, name=None,
             process_set=global_process_set):
    return synchronize(alltoall_async(tensor, splits, name, process_set))


def reducescatter(tensor, op=None, name=None,
                  process_set=global_process_set):
    if not _spmd():
        return tensor
    arr, bf16 = _to_np(tensor)
    out = _c.reducescatter(arr, op=op or Average, name=name,
                           process_set=process_set)
    return _from_np(np.asarray(out), tensor, bf16)


def join(device=-1):
    if not _spmd():
        return -1
    return _c.join(device)


def barrier(process_set=global_process_set):
    if not _spmd():
        return
    return _c.barrier(process_set=process_set)


def broadcast_object(obj, root_rank=0, name=None):
    from ..functions import broadcast_object as _bo
    return _bo(obj, root_rank=root_rank, name=name)


def allgather_object(obj, name=None):
    from ..functions import allgather_object as _ao
    return _ao(obj, name=name)


def broadcast_parameters(params, root_rank=0):
    """Broadcast a state_dict or named_parameters iterable from root_rank
    (reference: horovod/torch/functions.py broadcast_parameters)."""
    if not _spmd():
        return
    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    tensors = [t for _, t in items if hasattr(t, "copy_")]
    arrs = []
    bf16s = []
    for t in tensors:
        a, b = _to_np(t)
        arrs.append(a)
        bf16s.append(b)
    from ..functions import broadcast_variables as _bv
    outs = _bv(arrs, root_rank=root_rank)
    for t, o, b in zip(tensors, outs, bf16s):
        t.copy_(_from_np(np.asarray(o), t, b))
    # Non-tensor entries ride the object path, keyed by name.
    other = {n: v for n, v in items if not hasattr(v, "copy_")}
    if other:
        synced = broadcast_object(other, root_rank=root_rank,
                                  name="broadcast_parameters.obj")
        if hasattr(params, "items"):
            for n, v in synced.items():
                params[n] = v


def broadcast_optimizer_state(optimizer, root_rank=0):
    """Broadcast the optimizer state dict from root_rank (reference:
    horovod/torch/functions.py broadcast_optimizer_state). The whole
    state dict rides the serialized-object path — simple and correct for
    the once-at-startup call; per-tensor fused broadcast is what
    broadcast_parameters does for the (hot) model weights."""
    if not _spmd():
        return
    state = optimizer.state_dict()
    synced = broadcast_object(state, root_rank=root_rank,
                              name="broadcast_optimizer_state")
    optimizer.load_state_dict(synced)


def _sparse_grad_handle(param, op, name, process_set, postscale):
    """Sparse-grad sync for the optimizer hook: SUBMITTED at hook time
    like the dense path (the hook only fires on the final accumulation
    pass — the `% backward_passes_per_step` guard — so the grad is
    already complete, and deferring submission to synchronize() would
    serialize k tables into k coordinator round-trips that never
    fuse); the result is written back to ``param.grad`` for the inner
    step — re-sparsified when the density policy resolved dense, so
    the layout the inner optimizer sees never flips mid-training."""
    grad = param.grad
    if postscale != 1.0:
        grad = grad * postscale
    handle = sparse_allreduce_async(grad, name=name, op=op,
                                    process_set=process_set)

    def resolve():
        out = synchronize(handle)
        if grad.is_sparse and not out.is_sparse:
            # The density policy resolved dense past the crossover: the
            # WIRE rode the dense ring, but the grad layout the inner
            # optimizer sees must stay stable across steps — a
            # sparse-only optimizer (torch.optim.SparseAdam) would
            # crash the step the EMA crosses d* otherwise.
            out = out.to_sparse(grad.sparse_dim()).coalesce()
        param.grad = out
        return out
    return _LazyHandle(resolve)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=None, backward_passes_per_step=1,
                         op=Average, gradient_predivide_factor=1.0,
                         num_groups=0, groups=None, sparse_as_dense=False,
                         process_set=global_process_set):
    """Grad-hook optimizer wrapper (reference:
    horovod/torch/optimizer.py:36-275): each parameter's
    post-accumulate-grad hook fires an async allreduce; ``step()``
    synchronizes every outstanding handle, writes the averaged gradients
    back, then runs the inner optimizer.

    Sparse gradients (embedding layers with ``sparse=True``):
    ``sparse_as_dense=True`` densifies them into the dense sync;
    ``sparse_as_dense=False`` (default, the reference contract) routes
    them through :func:`sparse_allreduce_async` when ``HVDTPU_SPARSE``
    is set — the policy picks allgather-of-slices vs densify per
    tensor (docs/sparse.md); the result written back to ``.grad``
    stays SPARSE either way, so sparse-only inner optimizers
    (SparseAdam) survive a mid-training path flip, while optimizers
    that reject sparse grads (Adam) want ``sparse_as_dense=True``.
    With the knob unset sparse grads densify exactly as before the
    plane existed."""
    if compression is Compression.none:
        compression = None
    if getattr(optimizer, "_hvd_wrapped", False):
        raise ValueError(
            "optimizer is already wrapped by DistributedOptimizer; "
            "wrapping twice would allreduce every gradient twice")
    cls = type(optimizer)

    if named_parameters is not None:
        named = list(named_parameters)
    else:
        named = []
        for gi, group in enumerate(optimizer.param_groups):
            for pi, p in enumerate(group["params"]):
                named.append((f"param.{gi}.{pi}", p))
    name_of = {p: n for n, p in named}
    covered = set(name_of)
    for gi, group in enumerate(optimizer.param_groups):
        for p in group["params"]:
            if p.requires_grad and p not in covered:
                raise ValueError(
                    "named_parameters does not cover all optimizer "
                    "parameters (reference raises the same; pass "
                    "model.named_parameters() for the FULL model behind "
                    "this optimizer)")

    class _Distributed(cls):
        _hvd_wrapped = True

        def _hvd_hook(self, p):
            def hook(param):
                if self._hvd_sync_disabled:
                    return
                self._hvd_counters[param] = \
                    self._hvd_counters.get(param, 0) + 1
                if self._hvd_counters[param] % backward_passes_per_step:
                    return
                grad = param.grad
                if grad is None:
                    return
                if grad.is_sparse:
                    # The honored sparse_as_dense contract (reference:
                    # horovod/torch/optimizer.py): True densifies into
                    # the normal dense sync; False (default) ROUTES the
                    # sparse gradient when the sparse plane is on —
                    # sparse_allreduce_async's policy picks gather vs
                    # densify per tensor, and step() writes whichever
                    # form back. With HVDTPU_SPARSE unset the routing
                    # would hand a SPARSE tensor to inner optimizers
                    # that reject them (Adam) where the pre-plane code
                    # always densified — the disabled contract keeps
                    # that path byte-for-byte.
                    from ..ops import sparse as _sparse_ops
                    if not sparse_as_dense and _sparse_ops.enabled():
                        post = 1.0
                        if backward_passes_per_step > 1:
                            post = 1.0 / backward_passes_per_step
                        self._hvd_handles[param] = _sparse_grad_handle(
                            param, op, f"grad.{name_of[param]}",
                            process_set, post)
                        return
                    grad = grad.to_dense()
                    param.grad = grad
                pre = 1.0
                post = 1.0
                if gradient_predivide_factor != 1.0:
                    pre = 1.0 / gradient_predivide_factor
                    post = gradient_predivide_factor
                if backward_passes_per_step > 1:
                    post /= backward_passes_per_step
                self._hvd_handles[param] = allreduce_async_(
                    grad, op=op, name=f"grad.{name_of[param]}",
                    prescale_factor=pre, postscale_factor=post,
                    compression=compression, process_set=process_set)
            return hook

        def synchronize(self):
            # (module-level synchronize is shadowed by this method name)
            for handle in list(self._hvd_handles.values()):
                _module_synchronize(handle)
            self._hvd_handles.clear()
            self._hvd_synchronized = True

        def step(self, closure=None):
            if _spmd():
                self.synchronize()
            self._hvd_synchronized = False
            return cls.step(self, closure)

    _module_synchronize = synchronize

    optimizer.__class__ = _Distributed
    # LR schedulers created BEFORE this wrapper (torch's with_counter)
    # shadow .step with an instance attribute that captured the original
    # class step — calls would bypass synchronize() and the next backward
    # would hit DuplicateNameError. Re-wrap the instance attribute so the
    # scheduler's step counting survives AND gradients synchronize.
    _inst_step = optimizer.__dict__.get("step")
    if _inst_step is not None:
        import functools

        @functools.wraps(_inst_step)
        def _dist_inst_step(closure=None):
            if _spmd():
                optimizer.synchronize()
            optimizer._hvd_synchronized = False
            return (_inst_step() if closure is None
                    else _inst_step(closure))

        optimizer.step = _dist_inst_step
    optimizer._hvd_handles = {}
    optimizer._hvd_counters = {}
    optimizer._hvd_sync_disabled = not _spmd()
    optimizer._hvd_synchronized = False
    optimizer._hvd_hook_handles = []
    if _spmd():
        owned = {p for group in optimizer.param_groups
                 for p in group["params"]}
        for _, p in named:
            # Only optimizer-owned params get hooks: named_parameters may
            # legitimately cover the full model while the optimizer trains
            # a subset (fine-tuning) — syncing frozen-into-other-optimizers
            # grads here would be wasted collectives.
            if p.requires_grad and p in owned:
                optimizer._hvd_hook_handles.append(
                    p.register_post_accumulate_grad_hook(
                        optimizer._hvd_hook(p)))
    return optimizer


def tpu_compile(module, input_names=None, example_inputs=None,
                loss_key="loss", compute_dtype=None, verify=False):
    """Compile a torch module to run its math on the TPU via fx→JAX
    (see horovod_tpu/torch/compile.py — the TPU-first replacement for
    the reference's device-tensor adapter, mpi_ops_v2.cc:624).
    ``compute_dtype=jnp.bfloat16`` enables mixed precision (fp32 master
    weights, bf16 matmuls — the torch-xla XLA_USE_BF16 analog);
    ``verify=True`` runs the hvd-lint jaxpr analyzer over each traced
    signature before jitting (docs/lint.md)."""
    from .compile import tpu_compile as _impl
    return _impl(module, input_names=input_names,
                 example_inputs=example_inputs, loss_key=loss_key,
                 compute_dtype=compute_dtype, verify=verify)


def __getattr__(name):
    # Lazy submodule/class exports (reference surface: horovod/torch
    # exposes SyncBatchNorm and the elastic submodule at top level);
    # resolved on demand so importing the binding never imports torch,
    # and cached in globals for identity.
    if name == "SyncBatchNorm":
        from .sync_batch_norm import SyncBatchNorm
        globals()[name] = SyncBatchNorm
        return SyncBatchNorm
    if name == "elastic":
        # importlib, not `from . import`: the from-import form checks
        # hasattr(package, "elastic") mid-import and re-enters this
        # __getattr__ forever.
        import importlib
        mod = importlib.import_module(".elastic", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(name)
