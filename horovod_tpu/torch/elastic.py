"""Torch elastic state objects (reference:
horovod/torch/elastic/state.py:89-174 ``TorchState``,
horovod/torch/elastic/sampler.py:122 ``ElasticSampler``).

``TorchState(model=..., optimizer=..., epoch=0, ...)`` snapshots
state_dicts in memory at ``commit()``, restores them after a failure, and
re-broadcasts from the new rank 0 after a reset.
"""

import copy

from ..elastic import ObjectState, State, run, run_fn  # noqa: F401
from ..functions import broadcast_object


class TorchState(State):
    """Elastic state holding torch modules/optimizers plus scalars."""

    def __init__(self, model=None, optimizer=None, sampler=None, **kwargs):
        super().__init__()
        self._handlers = {}
        if model is not None:
            self._handlers["model"] = model
            self.model = model
        if optimizer is not None:
            self._handlers["optimizer"] = optimizer
            self.optimizer = optimizer
        self._sampler = sampler
        if sampler is not None:
            self.sampler = sampler
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._saved = None
        self.save()

    _TRACKED_TYPES = (int, float, bool, str, bytes, list, tuple, dict,
                      set, type(None))

    def _scalar_state(self):
        """Public attributes of plain-value types — including ones set
        after construction, so `state.best_loss = x` participates in
        commit/restore/sync. Complex objects (SummaryWriter, DataLoader)
        attached as conveniences are deliberately NOT swept: they are
        often non-picklable and would crash commit()/sync()."""
        import numpy as _np
        import torch as _torch
        skip = set(self._handlers)
        if self._sampler is not None:
            skip.add("sampler")
        tracked = self._TRACKED_TYPES + (_np.ndarray, _np.generic,
                                         _torch.Tensor)
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_") and k not in skip
                and isinstance(v, tracked)}

    def save(self):
        self._saved = {
            "handlers": {k: copy.deepcopy(v.state_dict())
                         for k, v in self._handlers.items()},
            "scalars": copy.deepcopy(self._scalar_state()),
        }
        if self._sampler is not None:
            self._saved["sampler"] = {
                "epoch": self._sampler.epoch,
                "processed": set(self._sampler.processed_indices),
            }

    def restore(self):
        for k, sd in self._saved["handlers"].items():
            self._handlers[k].load_state_dict(copy.deepcopy(sd))
        for k, v in self._saved["scalars"].items():
            setattr(self, k, v)
        if self._sampler is not None and "sampler" in self._saved:
            self._sampler.epoch = self._saved["sampler"]["epoch"]
            self._sampler.processed_indices = set(
                self._saved["sampler"]["processed"])
            self._sampler.reset()

    def sync(self):
        payload = {
            "handlers": {k: v.state_dict()
                         for k, v in self._handlers.items()},
            "scalars": self._scalar_state(),
        }
        synced = broadcast_object(payload, root_rank=0,
                                  name="torch_elastic_state")
        for k, sd in synced["handlers"].items():
            self._handlers[k].load_state_dict(sd)
        for k, v in synced["scalars"].items():
            setattr(self, k, v)
        if self._sampler is not None:
            # Union every rank's processed indices so the new shard split
            # is identical everywhere (reference: SamplerStateHandler
            # allgathers processed indices, torch/elastic/state.py).
            from ..functions import allgather_object
            all_processed = allgather_object(
                sorted(self._sampler.processed_indices),
                name="elastic_sampler_sync")
            merged = set()
            for chunk in all_processed:
                merged.update(chunk)
            self._sampler.processed_indices = merged
            self._sampler.reset()
        self.save()


class ElasticSampler:
    """Minimal elastic-aware sampler (reference: sampler.py): shards
    indices by current rank/size and skips indices already processed
    since the last commit, so a reset resumes mid-epoch."""

    def __init__(self, dataset, shuffle=True, seed=0):
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices = set()
        self.reset()

    def set_epoch(self, epoch):
        self.epoch = epoch
        self.processed_indices = set()
        self.reset()

    def record_batch(self, batch_idx, batch_size):
        start = batch_idx * batch_size
        self.processed_indices.update(self.indices[start:start + batch_size])

    def reset(self):
        from .. import basics
        rt = basics.runtime() if basics.is_initialized() else None
        if rt is not None and rt.mode == basics.MODE_SPMD:
            rank, nranks = rt.topology.rank, rt.topology.size
        else:
            rank, nranks = 0, 1
        remaining = [i for i in range(len(self.dataset))
                     if i not in self.processed_indices]
        if self.shuffle:
            import random
            random.Random(self.seed + self.epoch).shuffle(remaining)
        self.indices = remaining[rank::nranks]

    def __iter__(self):
        return iter(self.indices)

    def __len__(self):
        return len(self.indices)
