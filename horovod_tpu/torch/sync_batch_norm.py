"""Cross-rank synchronized BatchNorm for torch (reference:
horovod/torch/sync_batch_norm.py:218 ``SyncBatchNorm``).

Batch statistics (mean/var) are computed over the GLOBAL batch via
allreduce in the forward pass, and the gradient reductions the chain rule
requires (sum_dy, sum_dy_xmu) are allreduced in the backward pass — the
same custom-autograd structure as the reference. Parameter gradients stay
local (the DistributedOptimizer reduces them like any other grad).
"""

import torch
from torch.nn.modules.batchnorm import _BatchNorm

from . import _spmd, allreduce
from ..ops import reduce_ops
from ..process_sets import global_process_set


class SyncBatchNorm(_BatchNorm):
    """Drop-in BatchNorm1d/2d/3d replacement syncing stats across ranks."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True,
                 process_set=global_process_set):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)
        self.process_set = process_set

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D)")

    def forward(self, input):  # noqa: A002 (torch API name)
        if not (self.training and _spmd()):
            return super().forward(input)
        self._check_input_dim(input)
        if self.momentum is None:
            exponential_average_factor = 0.0
        else:
            exponential_average_factor = self.momentum
        if self.track_running_stats and self.num_batches_tracked is not None:
            self.num_batches_tracked.add_(1)
            if self.momentum is None:
                exponential_average_factor = \
                    1.0 / float(self.num_batches_tracked)
        return _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, exponential_average_factor,
            self.process_set)


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var, eps,
                momentum, process_set):
        # STABLE names so the response-cache fast path hits every step
        # (reference uses fixed names, sync_batch_norm.py:162); keyed by
        # channel count so equal-width layers share one cached response
        # and differently-sized layers never collide. Safe because these
        # allreduces are synchronous — one in flight at a time.
        ctx.call_id = input.shape[1]
        c = input.shape[1]
        reduce_dims = [0] + list(range(2, input.dim()))
        local_count = input.numel() // c

        local_sum = input.sum(dim=reduce_dims)
        local_sqsum = (input * input).sum(dim=reduce_dims)
        packed = torch.cat([local_sum, local_sqsum,
                            torch.tensor([float(local_count)],
                                         dtype=local_sum.dtype,
                                         device=local_sum.device)])
        packed = allreduce(packed, op=reduce_ops.Sum,
                           name=f"syncbn.fwd.{ctx.call_id}",
                           process_set=process_set)
        total = float(packed[-1])
        mean = packed[:c] / total
        var = packed[c:2 * c] / total - mean * mean
        invstd = torch.rsqrt(var + eps)

        if running_mean is not None:
            with torch.no_grad():
                running_mean.mul_(1 - momentum).add_(momentum * mean)
                unbiased = var * (total / max(total - 1, 1))
                running_var.mul_(1 - momentum).add_(momentum * unbiased)

        shape = [1, c] + [1] * (input.dim() - 2)
        xhat = (input - mean.view(shape)) * invstd.view(shape)
        out = xhat
        if weight is not None:
            out = out * weight.view(shape)
        if bias is not None:
            out = out + bias.view(shape)
        ctx.save_for_backward(input, weight, mean, invstd)
        ctx.total = total
        ctx.process_set = process_set
        return out

    @staticmethod
    def backward(ctx, grad_output):
        input, weight, mean, invstd = ctx.saved_tensors
        c = input.shape[1]
        reduce_dims = [0] + list(range(2, input.dim()))
        shape = [1, c] + [1] * (input.dim() - 2)
        xmu = input - mean.view(shape)

        sum_dy = grad_output.sum(dim=reduce_dims)
        sum_dy_xmu = (grad_output * xmu).sum(dim=reduce_dims)
        packed = torch.cat([sum_dy, sum_dy_xmu])
        # The stat gradients span the GLOBAL batch (reference: backward
        # allreduces sum_dy / sum_dy_xmu).
        packed = allreduce(packed.detach(), op=reduce_ops.Sum,
                           name=f"syncbn.bwd.{ctx.call_id}",
                           process_set=ctx.process_set)
        g_sum_dy = packed[:c]
        g_sum_dy_xmu = packed[c:]
        total = ctx.total

        w = weight.view(shape) if weight is not None else 1.0
        inv = invstd.view(shape)
        grad_input = (grad_output
                      - g_sum_dy.view(shape) / total
                      - xmu * inv * inv
                      * g_sum_dy_xmu.view(shape) / total) * inv * w

        grad_weight = None
        if weight is not None and ctx.needs_input_grad[1]:
            grad_weight = (grad_output * xmu * inv).sum(dim=reduce_dims)
        grad_bias = None
        if ctx.needs_input_grad[2]:
            grad_bias = grad_output.sum(dim=reduce_dims)
        return (grad_input, grad_weight, grad_bias, None, None, None, None,
                None)


def convert_sync_batchnorm(module, process_set=global_process_set):
    """Recursively replace BatchNorm modules with SyncBatchNorm (the
    torch.nn.SyncBatchNorm.convert_sync_batchnorm analog)."""
    out = module
    if isinstance(module, _BatchNorm) and not isinstance(module,
                                                         SyncBatchNorm):
        out = SyncBatchNorm(module.num_features, module.eps,
                            module.momentum, module.affine,
                            module.track_running_stats,
                            process_set=process_set)
        if module.affine:
            # Reuse the ORIGINAL Parameters by reference: optimizers
            # already holding them keep updating the right tensors, and
            # device placement is preserved (torch's own
            # convert_sync_batchnorm does the same).
            out.weight = module.weight
            out.bias = module.bias
        out.running_mean = module.running_mean
        out.running_var = module.running_var
        out.num_batches_tracked = module.num_batches_tracked
    for name, child in module.named_children():
        out.add_module(name,
                       convert_sync_batchnorm(child, process_set))
    return out
