"""torch.fx → JAX compiler: run torch model math on the TPU.

The reference's torch binding delivers accelerator compute by handing
GPU-resident torch tensors to the collective engine (reference:
horovod/torch/mpi_ops_v2.cc:624, adapter_v2.cc:1-165). This image has no
torch-xla and torch is CPU-only, so a tensor-adapter port would leave the
model math on the host. The TPU-first answer is a *frontend bridge*: trace
the torch module with ``torch.fx`` (HF models via
``transformers.utils.fx``), convert the graph to a pure JAX function over
a flat parameter dict, and let the existing JAX data plane (jit, shard_map
collectives, optax optimizers, the Pallas kernels) do everything else.
The torch module is the model *definition*; the chip runs XLA.

    compiled = tpu_compile(model, input_names=["input_ids", "labels"])
    out = compiled(input_ids=ids, labels=labels)        # jitted forward
    step = compiled.make_train_step(optax.adamw(1e-4))   # fwd+bwd+update
    loss = step(batch)                                   # on the chip
    compiled.copy_params_to_module(model)                # sync back

Supported surface: the op set emitted by fx traces of transformer-family
models (BERT/GPT-style: Linear/LayerNorm/Embedding/Dropout/CELoss modules,
scaled_dot_product_attention, arithmetic, shape ops). Unsupported nodes
raise with the node name and op so coverage gaps are explicit, not silent.
Dropout and attention-dropout are driven by a JAX PRNG key (deterministic
per site); ``train=False`` disables them.

Caveats: runs under JAX x64-off — int64 becomes int32 (fine for token ids
and -100 label sentinels), float64 becomes float32. Data-dependent Python
control flow in the torch module is out of scope (same restriction fx
itself has).
"""

import math
import operator

import numpy as np

from ..utils import envparse

_op_setitem = operator.setitem


def _jnp():
    import jax.numpy as jnp
    return jnp


_DTYPE_MAP_CACHE = None


def _dtype_map():
    """torch dtype -> numpy dtype under JAX x64-off semantics."""
    global _DTYPE_MAP_CACHE
    if _DTYPE_MAP_CACHE is None:
        import torch
        import jax.numpy as jnp
        _DTYPE_MAP_CACHE = {
            torch.float32: jnp.float32, torch.float64: jnp.float32,
            torch.float16: jnp.float16, torch.bfloat16: jnp.bfloat16,
            torch.int64: jnp.int32, torch.int32: jnp.int32,
            torch.int16: jnp.int16, torch.int8: jnp.int8,
            torch.uint8: jnp.uint8, torch.bool: jnp.bool_,
        }
    return _DTYPE_MAP_CACHE


def _to_jax_dtype(dt):
    """Accept a torch dtype, numpy dtype, or jax value's dtype."""
    mapped = _dtype_map().get(dt)
    return mapped if mapped is not None else dt


def _t2j(tensor):
    """torch tensor -> jax array (via numpy; bf16 upcast handled)."""
    import torch
    import jax.numpy as jnp
    t = tensor.detach().cpu()
    if t.dtype == torch.bfloat16:
        return jnp.asarray(t.float().numpy()).astype(jnp.bfloat16)
    if t.dtype == torch.int64:
        return jnp.asarray(t.numpy().astype(np.int32))
    return jnp.asarray(t.numpy())


class _Device:
    """Sentinel for getattr(x, 'device') results; consumed (and ignored)
    by factory-function device= kwargs. Models that branch on
    ``x.device.type`` (e.g. BART's mask helper) see the accelerator
    answer."""

    type = "xla"  # noqa: A003 — mirrors torch.device.type


def _dropout(x, p, train, key):
    jnp = _jnp()
    if not train or p == 0.0 or key is None:
        return x
    import jax
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))


def _flash_enabled():
    from ..ops.flash_attention import bridge_flash_enabled
    return bridge_flash_enabled()


def _note_flash_fallback(reason):
    from ..ops.flash_attention import note_flash_fallback
    note_flash_fallback(reason)


def _resolve_static_mask(attn_mask, jnp):
    """If attn_mask is a compile-time constant that keeps every position
    (HF encoders build their additive mask from shapes/dtypes only, so
    with no padding it constant-folds to zeros during tracing), return
    None; otherwise return the mask unchanged."""
    if attn_mask is None:
        return None
    import jax

    from ..utils.jax_compat import concrete_or_none
    concrete = concrete_or_none(attn_mask)
    if concrete is None:
        return attn_mask
    # The mask is concrete (const-folded, possibly behind a check_rep
    # RewriteTracer under shard_map), but any op on it inside the jit
    # trace would be staged — inspect it at compile time instead.
    import numpy as _np
    m = _np.asarray(concrete)
    if m.dtype == _np.bool_:
        if bool(m.all()):
            return None
    elif bool((m == 0).all()):
        return None
    return attn_mask


def _sdpa(rng_key, train, q=None, k=None, v=None, attn_mask=None,
          dropout_p=0.0, is_causal=False, scale=None, query=None,
          key=None, value=None):
    """torch.nn.functional.scaled_dot_product_attention semantics on jax:
    bool masks keep-where-True; float masks are additive. Accepts both
    positional q/k/v and the keyword spelling (query=/key=/value=) some
    HF models use (e.g. Albert).

    When the mask resolves away at compile time (None, all-True bool, or
    all-zero additive — the no-padding HF encoder case), the call lowers
    to the repo's Pallas flash kernel (ops/flash_attention.py), including
    exact attention dropout via an explicit bernoulli keep-mask; anything
    the kernel does not cover falls back to this einsum lowering with a
    one-time warning."""
    q = query if q is None else q
    k = key if k is None else k
    v = value if v is None else v
    jnp = _jnp()
    if _flash_enabled():
        resolved = _resolve_static_mask(attn_mask, jnp)
        if (resolved is None
                and getattr(q, "ndim", 0) == 4
                and getattr(k, "ndim", 0) == 4
                and getattr(v, "ndim", 0) == 4
                and q.shape[:2] == k.shape[:2] == v.shape[:2]
                and q.shape[-1] == k.shape[-1] == v.shape[-1]
                and q.shape[-1] <= 128):
            from ..ops.flash_attention import _interpret, flash_attention
            dm = None
            seed = None
            rate = 0.0
            if dropout_p and train and rng_key is not None:
                import jax
                rate = float(dropout_p)
                mask_bytes = 2 * q.shape[0] * q.shape[1] \
                    * q.shape[2] * k.shape[2]
                limit = envparse.get_int(
                    envparse.FLASH_DROPOUT_MASK_LIMIT,
                    128 * 1024 * 1024)
                mode = envparse.get_str(envparse.FLASH_DROPOUT,
                                        "auto").lower()
                use_mask = (mode == "mask"
                            or _interpret()
                            or (mode == "auto" and mask_bytes <= limit))
                if use_mask:
                    # Explicit bernoulli keep-mask: measured faster than
                    # the per-tile on-chip prng at bench sizes, exactly
                    # reproducible against the einsum oracle, and the
                    # only option in interpret mode (pltpu prng has no
                    # CPU lowering). Cost: an O(S²) bf16 residual per
                    # attention site held for the backward pass.
                    dm = jax.random.bernoulli(
                        rng_key, 1.0 - rate,
                        q.shape[:3] + (k.shape[2],))
                else:
                    # Big mask (long seq / large batch): seed the
                    # on-chip prng instead — the keep pattern is
                    # regenerated per tile in fwd and both bwd kernels,
                    # no O(S²) residual, so configs whose masks OOM
                    # still train.
                    seed = jax.random.randint(
                        rng_key, (), -2 ** 31, 2 ** 31 - 1,
                        dtype=jnp.int32)
            return flash_attention(
                q, k, v, causal=bool(is_causal), sm_scale=scale,
                dropout_mask=dm, dropout_rate=rate, dropout_seed=seed)
        if resolved is None:
            # Mask folded away but the shapes are outside kernel
            # coverage — still drop the dead mask from the einsum path.
            attn_mask = None
            _note_flash_fallback(
                f"q/k/v shapes {getattr(q, 'shape', None)}/"
                f"{getattr(k, 'shape', None)}/{getattr(v, 'shape', None)}")
        else:
            _note_flash_fallback("mask is not statically all-keep")
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("...qd,...kd->...qk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            s = jnp.where(attn_mask, s, -1e30)
        else:
            s = s + attn_mask.astype(jnp.float32)
    if is_causal:
        sq, sk = q.shape[-2], k.shape[-2]
        causal = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(causal, s, -1e30)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    p = _dropout(p, dropout_p, train, rng_key)
    return jnp.einsum("...qk,...kd->...qd",
                      p.astype(v.dtype), v)


def _cross_entropy(logits, target, ignore_index=-100, reduction="mean",
                   label_smoothing=0.0):
    import jax
    jnp = _jnp()
    logits = logits.astype(jnp.float32)
    n_class = logits.shape[-1]
    logp = jax.nn.log_softmax(logits, axis=-1)
    valid = target != ignore_index
    tgt = jnp.where(valid, target, 0)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    if label_smoothing:
        smooth = -jnp.mean(logp, axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
        del n_class
    nll = jnp.where(valid, nll, 0.0)
    if reduction == "mean":
        return jnp.sum(nll) / jnp.maximum(jnp.sum(valid), 1)
    if reduction == "sum":
        return jnp.sum(nll)
    return nll


def _embedding(weight, ids, padding_idx=None):
    del padding_idx  # affects only the gradient at pad rows; weights there
    # are zero-initialized by torch, matching forward semantics.
    return weight[ids]


def _layer_norm(x, normalized_shape, weight, bias, eps):
    jnp = _jnp()
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=axes, keepdims=True)
    out = (xf - mean) / jnp.sqrt(var + eps)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def _linear(x, weight, bias=None):
    # jnp.matmul(x, W.T) — measured FASTER on v5e than dot_general with
    # transposed dimension numbers (47.8 vs 44.3 samples/s on 24-layer
    # BERT-large): XLA folds the transpose into its preferred MXU
    # layout; explicit rhs-minor contraction defeats that.
    jnp = _jnp()
    out = jnp.matmul(x, weight.T)
    if bias is not None:
        out = out + bias
    return out


def _expand(x, *sizes):
    jnp = _jnp()
    if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
        sizes = tuple(sizes[0])
    # Torch expand: -1 keeps the dim; leading new dims allowed.
    ndim = len(sizes)
    shape = list(sizes)
    offset = ndim - x.ndim
    for i in range(ndim):
        if shape[i] == -1:
            shape[i] = x.shape[i - offset] if i >= offset else 1
    return jnp.broadcast_to(x, tuple(shape))


def _masked_fill(x, mask, value):
    jnp = _jnp()
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


def _to(x, *args, **kwargs):
    # .to(dtype) / .to(device) / .to(device, dtype) / .to(other_tensor)
    jnp = _jnp()
    for a in list(args) + list(kwargs.values()):
        if isinstance(a, _Device) or a is None or isinstance(a, str):
            continue
        if hasattr(a, "dtype") and hasattr(a, "shape"):  # tensor-like
            return x.astype(a.dtype)
        mapped = _to_jax_dtype(a)
        try:
            return x.astype(mapped)
        except TypeError:
            continue
    return x


def _size(x, dim=None):
    return x.shape if dim is None else x.shape[dim]


def _softmax(x, dim=-1, _stacklevel=3, dtype=None):
    # Positional order mirrors F.softmax(input, dim, _stacklevel, dtype);
    # _stacklevel is the legacy warn-location kwarg, inert here.
    import jax
    jnp = _jnp()
    xf = x.astype(jnp.float32)
    out = jax.nn.softmax(xf, axis=dim)
    if dtype is not None:
        return out.astype(_to_jax_dtype(dtype))
    return out.astype(x.dtype)


def _build_function_table():
    import torch
    import torch.nn.functional as F
    import jax
    jnp = _jnp()

    table = {
        operator.add: operator.add, operator.sub: operator.sub,
        operator.mul: operator.mul, operator.truediv: operator.truediv,
        operator.floordiv: operator.floordiv, operator.mod: operator.mod,
        operator.pow: operator.pow, operator.neg: operator.neg,
        operator.eq: operator.eq, operator.ne: operator.ne,
        operator.lt: operator.lt, operator.le: operator.le,
        operator.gt: operator.gt, operator.ge: operator.ge,
        operator.and_: operator.and_, operator.or_: operator.or_,
        operator.invert: operator.invert,
        operator.getitem: lambda x, idx: x[idx],
        operator.matmul: jnp.matmul,
        getattr: _getattr_node,
        torch.matmul: jnp.matmul,
        torch.bmm: jnp.matmul,
        torch.einsum: jnp.einsum,
        torch.cat: lambda ts, dim=0: jnp.concatenate(ts, axis=dim),
        torch.stack: lambda ts, dim=0: jnp.stack(ts, axis=dim),
        torch.where: jnp.where,
        torch.tanh: jnp.tanh, torch.erf: jax.scipy.special.erf,
        torch.exp: jnp.exp, torch.log: jnp.log, torch.sqrt: jnp.sqrt,
        torch.rsqrt: lambda x: 1.0 / jnp.sqrt(x),
        torch.abs: jnp.abs, torch.sigmoid: jax.nn.sigmoid,
        torch.relu: jax.nn.relu,
        torch.cumsum: lambda x, dim: jnp.cumsum(x, axis=dim),
        torch.clamp: lambda x, min=None, max=None: jnp.clip(x, min, max),
        torch.mean: lambda x, dim=None, keepdim=False: jnp.mean(
            x, axis=dim, keepdims=keepdim),
        torch.pow: jnp.power,
        torch.finfo: lambda dt: jnp.finfo(_to_jax_dtype(dt)),
        F.relu: jax.nn.relu,
        F.gelu: _gelu,
        F.silu: jax.nn.silu,
        F.tanh: jnp.tanh,
        F.softmax: _softmax,
        F.linear: _linear,
        F.embedding: lambda ids, w, padding_idx=None, **kw: w[ids],
        F.layer_norm: lambda x, shape, weight=None, bias=None, eps=1e-5:
            _layer_norm(x, shape, weight, bias, eps),
        F.cross_entropy: _cross_entropy,
    }
    # gelu may be traced as the C-level builtin (torch._C._nn.gelu)
    try:
        table[torch._C._nn.gelu] = _gelu
        table[torch._C._nn.linear] = _linear
        table[torch._C._nn.scaled_dot_product_attention] = "sdpa"
    except AttributeError:
        pass
    table[F.scaled_dot_product_attention] = "sdpa"
    table[F.dropout] = "dropout"

    def factory(fill):
        def make(size, *rest, dtype=None, device=None, **kw):
            del device, kw
            if rest:  # torch.ones(a, b, c) calling convention
                size = (size,) + tuple(rest)
            elif isinstance(size, int):
                size = (size,)
            dt = _to_jax_dtype(dtype) if dtype is not None else jnp.float32
            return jnp.full(tuple(size), fill, dtype=dt)
        return make

    def min_max(reduce_fn, arg_fn, pair_fn):
        # torch.min/max have three spellings: full reduce (one arg),
        # per-dim torch.min(x, dim[, keepdim]) -> namedtuple-like
        # (values, indices), elementwise torch.min(x, other). Unknown
        # arguments fail loud (the module's coverage contract) rather
        # than silently misbind.
        import collections
        pair_t = collections.namedtuple("minmax", ["values", "indices"])

        def h(a, *args, **kwargs):
            if kwargs.pop("out", None) is not None:
                raise NotImplementedError("min/max out= unsupported")
            other = kwargs.pop("other", None)
            dim = kwargs.pop("dim", None)
            keepdim = kwargs.pop("keepdim", False)
            if kwargs:
                raise NotImplementedError(
                    f"min/max kwargs {sorted(kwargs)} unsupported")
            import numbers
            rest = list(args)
            if rest:
                first = rest[0]
                if isinstance(first, (bool, np.bool_)):
                    raise NotImplementedError(
                        "min/max bool positional argument is ambiguous")
                if isinstance(first, numbers.Integral):
                    # covers python int AND np.integer: torch's dim must
                    # be a python-level integer, so an Integral
                    # positional is ALWAYS the dim spelling; tensors
                    # (even 0-d) are always elementwise 'other'.
                    if dim is not None:
                        raise NotImplementedError(
                            "min/max got both positional and keyword dim")
                    dim = int(rest.pop(0))
                    if rest and isinstance(rest[0], (bool, np.bool_)):
                        keepdim = bool(rest.pop(0))
                elif other is None:
                    other = rest.pop(0)
            if rest:
                raise NotImplementedError(
                    f"min/max argument pattern {args!r} unsupported")
            if other is not None:
                return pair_fn(a, other)
            if dim is None:
                return reduce_fn(a)
            if isinstance(dim, bool):
                raise NotImplementedError("min/max bool dim is ambiguous")
            return pair_t(reduce_fn(a, axis=dim, keepdims=keepdim),
                          arg_fn(a, axis=dim, keepdims=keepdim))
        return h

    table[torch.min] = min_max(jnp.min, jnp.argmin, jnp.minimum)
    table[torch.max] = min_max(jnp.max, jnp.argmax, jnp.maximum)
    table[torch.minimum] = jnp.minimum
    table[torch.maximum] = jnp.maximum
    table[torch.triu] = lambda x, diagonal=0, **kw: jnp.triu(x, diagonal)
    table[torch.tril] = lambda x, diagonal=0, **kw: jnp.tril(x, diagonal)
    table[torch.ones] = factory(1)
    table[torch.zeros] = factory(0)
    def opt_dtype(dtype):
        # One place for the optional torch->jax dtype mapping (factory
        # fns accept dtype=None meaning "default").
        return _to_jax_dtype(dtype) if dtype is not None else None

    table[torch.full] = \
        lambda size, fill_value, dtype=None, device=None, **kw: \
        jnp.full(tuple(size), fill_value, dtype=opt_dtype(dtype))
    table[torch.full_like] = \
        lambda x, fill_value, dtype=None, device=None, **kw: \
        jnp.full_like(x, fill_value, dtype=opt_dtype(dtype))
    table[torch.zeros_like] = \
        lambda x, dtype=None, device=None, **kw: jnp.zeros_like(
            x, dtype=opt_dtype(dtype))
    table[torch.ones_like] = \
        lambda x, dtype=None, device=None, **kw: jnp.ones_like(
            x, dtype=opt_dtype(dtype))
    table[torch.arange] = lambda *a, dtype=None, device=None, **kw: \
        jnp.arange(*a, dtype=opt_dtype(dtype))
    table[torch.tensor] = lambda v, dtype=None, device=None, **kw: \
        jnp.asarray(v, dtype=opt_dtype(dtype))
    return table


def _gelu(x, approximate="none"):
    import jax
    return jax.nn.gelu(x, approximate=(approximate == "tanh"))


def _getattr_node(obj, name):
    if name == "device":
        return _Device()
    if name == "dtype":
        return obj.dtype
    if name == "shape":
        return obj.shape
    if name == "min":  # torch.finfo(...).min
        return float(obj.min)
    if name == "max":
        return float(obj.max)
    return getattr(obj, name)


_METHODS = None


def _div_inplace(x, o, rounding_mode=None):
    if rounding_mode is not None:
        # floor/trunc division would need the rounding semantics, not a
        # silently-wrong truediv.
        raise NotImplementedError(
            f"div_ rounding_mode={rounding_mode!r} has no jax mapping; "
            "add it to horovod_tpu/torch/compile.py _method_table")
    return x / o


def _normalize_size(s):
    """Torch size spellings: flat ints (x.view(2, 3)) or one iterable
    (x.view((2, 3))) — one helper for every size-taking method."""
    return (tuple(s[0]) if len(s) == 1 and isinstance(s[0], (tuple, list))
            else tuple(s))


def _new_factory(fill):
    """tensor.new_zeros/new_ones/new_full(size...) — fresh array of the
    source's dtype unless overridden; size positional or keyword."""
    def h(x, *s, size=None, dtype=None, device=None, **kw):
        shape = (tuple(size) if size is not None else _normalize_size(s))
        dt = _to_jax_dtype(dtype) if dtype is not None else x.dtype
        return _jnp().full(shape, fill, dtype=dt)
    return h


def _method_table():
    global _METHODS
    if _METHODS is None:
        jnp = _jnp()
        _METHODS = {
            "view": lambda x, *s: x.reshape(_normalize_size(s)),
            "reshape": lambda x, *s: x.reshape(_normalize_size(s)),
            "transpose": lambda x, a, b: jnp.swapaxes(x, a, b),
            "permute": lambda x, *dims: jnp.transpose(
                x, _normalize_size(dims)),
            "contiguous": lambda x: x,
            "clone": lambda x: x,
            "detach": lambda x: x,
            "expand": _expand,
            "expand_as": lambda x, o: _jnp().broadcast_to(x, o.shape),
            "to": _to,
            "type_as": lambda x, o: x.astype(o.dtype),
            "masked_fill": _masked_fill,
            "masked_fill_": _masked_fill,
            # tensor.new_*: fresh arrays inheriting the source's dtype
            # unless overridden (shared helper below the table).
            "new_zeros": _new_factory(0),
            "new_ones": _new_factory(1),
            "new_full": lambda x, size, fill_value, dtype=None,
                device=None, **kw: _new_factory(fill_value)(
                    x, size, dtype=dtype),
            "dim": lambda x: x.ndim,
            "size": _size,
            "numel": lambda x: int(np.prod(x.shape)),
            "unsqueeze": lambda x, d: jnp.expand_dims(x, d),
            "squeeze": lambda x, d=None: jnp.squeeze(
                x, axis=d) if d is not None else jnp.squeeze(x),
            "float": lambda x: x.astype(jnp.float32),
            "long": lambda x: x.astype(jnp.int32),
            "int": lambda x: x.astype(jnp.int32),
            "bool": lambda x: x.astype(bool),
            "softmax": _softmax,
            "mean": lambda x, dim=None, keepdim=False: jnp.mean(
                x, axis=dim, keepdims=keepdim),
            "sum": lambda x, dim=None, keepdim=False: jnp.sum(
                x, axis=dim, keepdims=keepdim),
            "pow": jnp.power,
            "tanh": jnp.tanh,
            "split": lambda x, size, dim=0: tuple(
                jnp.split(x, range(size, x.shape[dim], size), axis=dim)),
            "chunk": lambda x, n, dim=0: tuple(jnp.split(x, n, axis=dim)),
            "flatten": lambda x, start=0, end=-1: _flatten(x, start, end),
            "repeat": lambda x, *reps: jnp.tile(x, _normalize_size(reps)),
            "t": lambda x: x.T,
            "gather": lambda x, dim, index: jnp.take_along_axis(
                x, index, axis=dim),
            "argmax": lambda x, dim=None, keepdim=False: jnp.argmax(
                x, axis=dim, keepdims=keepdim),
            "cumsum": lambda x, dim: jnp.cumsum(x, axis=dim),
            "ne": lambda x, o: x != o,
            "eq": lambda x, o: x == o,
            "mul": operator.mul, "add": operator.add,
            "sub": operator.sub, "div": operator.truediv,
            "neg": operator.neg,
            # In-place spellings: functional results; the interpreter's
            # trailing-underscore rebinding makes the mutation visible
            # to later uses of the target node.
            "add_": lambda x, o, alpha=1: x + (alpha * o
                                               if alpha != 1 else o),
            "sub_": lambda x, o, alpha=1: x - (alpha * o
                                               if alpha != 1 else o),
            "mul_": operator.mul,
            "div_": _div_inplace,
            "clamp_": lambda x, min=None, max=None: jnp.clip(x, min, max),
            "fill_": lambda x, v: jnp.full_like(x, v),
            "zero_": lambda x: jnp.zeros_like(x),
            "copy_": lambda x, o, non_blocking=False: jnp.broadcast_to(
                o.astype(x.dtype), x.shape),
            "item": lambda x: x,   # stays traced; fine under jit
        }
    return _METHODS


def _flatten(x, start, end):
    shape = list(x.shape)
    if end < 0:
        end += len(shape)
    new = shape[:start] + [int(np.prod(shape[start:end + 1]))] \
        + shape[end + 1:]
    return x.reshape(new)


_VIEW_METHODS = frozenset({
    "view", "reshape", "transpose", "permute", "expand", "expand_as",
    "squeeze", "unsqueeze", "narrow", "select", "t", "swapaxes",
    "swapdims", "movedim", "moveaxis", "diagonal", "flatten", "unfold",
    # multi-output view ops: every element of the returned tuple aliases
    # the input, so the tuple node itself joins the alias closure
    "chunk", "split", "unbind", "tensor_split", "hsplit", "vsplit",
})


def _check_inplace_through_views(graph):
    """torch propagates an in-place mutation to every alias; this
    executor rebinds only the direct TARGET node. Any OTHER alias of the
    target (its base chain, sibling views, views created earlier) read
    after the mutation would see the stale value — fail loud at compile
    time instead (the bridge's coverage contract: unsupported aliasing
    raises, never miscomputes)."""
    import torch.fx

    order = {n: i for i, n in enumerate(graph.nodes)}

    # Ops whose tuple results are FRESH tensors (no aliasing with the
    # input): getitem on these extracts an independent tensor, unlike
    # tensor indexing / chunk / split / unbind, which return views.
    fresh_tuple = {"max", "min", "topk", "sort", "median", "mode",
                   "kthvalue"}

    def returns_fresh_tuple(n):
        if not isinstance(n, torch.fx.Node):
            return False
        if n.op == "call_method":
            return n.target in fresh_tuple
        if n.op == "call_function":
            return getattr(n.target, "__name__", "") in fresh_tuple
        return False

    def is_view(n):
        if not isinstance(n, torch.fx.Node):
            return False
        if n.op == "call_function":
            if n.target is operator.getitem:
                base = n.args[0] if n.args else None
                return not returns_fresh_tuple(base)
            # function spellings: torch.chunk/split/transpose/...
            return getattr(n.target, "__name__", "") in _VIEW_METHODS
        return n.op == "call_method" and n.target in _VIEW_METHODS

    def node_base(n):
        if n.args and isinstance(n.args[0], torch.fx.Node):
            return n.args[0]
        return None

    views_of = {}
    for n in graph.nodes:
        if is_view(n):
            b = node_base(n)
            if b is not None:
                views_of.setdefault(b, []).append(n)

    def alias_set(node):
        """node + every fx node sharing memory with it: climb the view
        chain to the root base, then take the root's transitive views."""
        root = node
        while is_view(root) and node_base(root) is not None:
            root = node_base(root)
        out = set()
        stack = [root]
        while stack:
            cur = stack.pop()
            if cur in out:
                continue
            out.add(cur)
            stack.extend(views_of.get(cur, ()))
        return out

    for node in graph.nodes:
        target = None
        if (node.op == "call_function"
                and node.target is _op_setitem
                and node.args
                and isinstance(node.args[0], torch.fx.Node)):
            target = node.args[0]
        elif (node.op == "call_method" and node.target.endswith("_")
              and not node.target.endswith("__") and node.args
              and isinstance(node.args[0], torch.fx.Node)):
            target = node.args[0]
        if target is None:
            continue
        closure = alias_set(target)
        if closure == {target}:
            continue
        # The executor rebinds only `target`. An alias is FRESH (sees
        # the mutation) iff it is the target itself or a view created
        # AFTER the mutation whose base is fresh — it was computed from
        # the rebound value. Every other alias holds the stale
        # pre-mutation array; reading one after the mutation diverges
        # from torch.
        fresh = set()
        for a in sorted(closure, key=order.get):
            if a is target:
                fresh.add(a)
            elif (is_view(a) and node_base(a) in fresh
                    and order[a] > order[node]):
                fresh.add(a)
        stale = closure - fresh
        late = sorted(
            {u.name for a in stale for u in a.users
             if u is not node and order[u] > order[node]})
        if late:
            raise NotImplementedError(
                f"in-place op {node.name!r} mutates {target.name!r}, "
                f"which aliases other tensors read afterwards "
                f"({', '.join(late)}); torch view-aliasing of this form "
                "is not representable in the fx→JAX bridge — rewrite "
                "the module with out-of-place ops")


class _JaxInterpreter:
    """Execute an fx GraphModule with jax values.

    Parameters/buffers arrive as flat name->array dicts; call_module
    nodes look their weights up by the module path. One PRNG key drives
    every dropout site (fold_in by site index) so a jitted step is
    deterministic given the key."""

    def __init__(self, gm, aliases=None):
        import torch
        self.gm = gm
        self.graph = gm.graph
        self.fn_table = _build_function_table()
        self.torch = torch
        # Tied weights (e.g. BERT's decoder<->word-embedding) appear once
        # in the params dict under their canonical name; aliases map the
        # other module paths onto it so the tie survives training (one
        # leaf, gradients from every use site accumulate into it).
        self.aliases = aliases or {}
        # Stable dropout-site numbering: graph order.
        self.site_of = {}
        for node in self.graph.nodes:
            if self._is_dropout_site(node):
                self.site_of[node.name] = len(self.site_of)
        self._value_free = self._compute_value_free()
        _check_inplace_through_views(self.graph)

    def _compute_value_free(self):
        """Names of nodes whose value depends on no placeholder's runtime
        VALUES (only shapes/dtypes), no parameter/buffer, and no RNG.

        JAX omnistaging stages every op inside a jit trace, so HF's
        shape-derived attention-mask chains (ones(size) → expand → sub →
        masked_fill) would reach the attention lowering as tracers even
        though they are compile-time constants. Nodes in this set run
        under ``jax.ensure_compile_time_eval()`` instead, so the all-keep
        mask stays concrete and ``_resolve_static_mask`` can drop it —
        which is what routes no-padding encoders onto the flash kernel.
        """
        import torch.fx
        shape_methods = {"size", "dim", "ndimension"}
        shape_attrs = {"dtype", "shape", "device", "ndim"}
        # Nodes mutated in place anywhere in the graph change value
        # between definition and later uses — never fold those.
        mutated = set()
        for node in self.graph.nodes:
            if node.op == "call_function" and node.target is _op_setitem:
                if isinstance(node.args[0], torch.fx.Node):
                    mutated.add(node.args[0].name)
            elif (node.op == "call_method" and node.target.endswith("_")
                  and not node.target.endswith("__") and node.args
                  and isinstance(node.args[0], torch.fx.Node)):
                mutated.add(node.args[0].name)
        value_free = set()
        for node in self.graph.nodes:
            if node.op in ("placeholder", "get_attr", "call_module",
                           "output"):
                continue
            if node.name in mutated or node.name in self.site_of:
                continue
            if node.op == "call_method" and node.target in shape_methods:
                value_free.add(node.name)
                continue
            if (node.op == "call_function" and node.target is getattr
                    and len(node.args) >= 2
                    and node.args[1] in shape_attrs):
                value_free.add(node.name)
                continue
            if all(d.name in value_free and d.name not in mutated
                   for d in node.all_input_nodes):
                value_free.add(node.name)
        return value_free

    def _is_dropout_site(self, node):
        import torch.nn.functional as F
        if node.op == "call_module":
            sub = self.gm.get_submodule(node.target)
            return isinstance(sub, self.torch.nn.Dropout)
        if node.op == "call_function":
            return self.fn_table.get(node.target) in ("sdpa", "dropout")
        return False

    def run(self, params, buffers, inputs, rng=None, train=False):
        import jax
        import torch.fx
        env = {}

        def load_arg(a):
            return torch.fx.graph.map_arg(a, lambda n: env[n.name])

        for node in self.graph.nodes:
            if node.op == "placeholder":
                name = node.target
                if name in inputs:
                    env[node.name] = inputs[name]
                elif node.args:
                    env[node.name] = node.args[0]  # default value
                else:
                    env[node.name] = None
                continue
            if node.op == "get_attr":
                tgt = self.aliases.get(node.target, node.target)
                if tgt in params:
                    env[node.name] = params[tgt]
                elif tgt in buffers:
                    env[node.name] = buffers[tgt]
                else:
                    raise KeyError(
                        f"get_attr {node.target!r}: not found in params "
                        "or buffers")
                continue
            if node.op == "output":
                out = load_arg(node.args[0])
                # fx wraps collections in immutable variants jit rejects.
                if isinstance(out, dict):
                    out = dict(out)
                elif isinstance(out, list):
                    out = list(out)
                return out

            if node.op == "call_function" and node.target is _op_setitem:
                # In-place indexed assignment (x[idx] = v, e.g. BART's
                # shift_tokens_right; this transformers release's T5
                # takes an fx-proxy branch built from full+cat instead):
                # JAX arrays are immutable, so rebind the
                # TARGET node's env entry to the functional update —
                # later uses of that node see the mutation, like torch.
                # (Mutation through a separate VIEW node would not
                # propagate; fx traces of the supported models assign
                # through the array node itself.)
                target = node.args[0]
                idx = load_arg(node.args[1])
                val = load_arg(node.args[2])
                updated = env[target.name].at[idx].set(val)
                env[target.name] = updated
                env[node.name] = updated
                continue

            args = load_arg(node.args)
            kwargs = load_arg(node.kwargs)
            key = None
            if node.name in self.site_of and rng is not None:
                key = jax.random.fold_in(rng, self.site_of[node.name])

            if node.name in self._value_free:
                # Shape/dtype-derived subgraph: evaluate eagerly so the
                # result stays a compile-time constant under the jit
                # trace (see _compute_value_free).
                with jax.ensure_compile_time_eval():
                    if node.op == "call_method":
                        fn = _method_table().get(node.target)
                    else:
                        fn = self.fn_table.get(node.target)
                    if fn is None or isinstance(fn, str):
                        raise NotImplementedError(
                            f"torch {node.op} {node.target!r} (node "
                            f"{node.name}) has no jax mapping; add it to "
                            "horovod_tpu/torch/compile.py")
                    env[node.name] = fn(*args, **kwargs)
                continue

            if node.op == "call_module":
                sub = self.gm.get_submodule(node.target)
                env[node.name] = self._run_module(
                    node.target, sub, params, args, kwargs, key, train)
            elif node.op == "call_method":
                fn = _method_table().get(node.target)
                if fn is None:
                    raise NotImplementedError(
                        f"torch method {node.target!r} (node {node.name}) "
                        "has no jax mapping; add it to "
                        "horovod_tpu/torch/compile.py _method_table")
                env[node.name] = fn(*args, **kwargs)
                if (node.target.endswith("_")
                        and not node.target.endswith("__")
                        and node.args
                        and isinstance(node.args[0], torch.fx.Node)):
                    # Torch's trailing-underscore in-place convention
                    # (masked_fill_ etc., e.g. BART/T5 shift helpers
                    # replacing -100 label sentinels): later uses of the
                    # TARGET node must see the mutation, so rebind it to
                    # the functional result — same contract as the
                    # setitem handler above.
                    env[node.args[0].name] = env[node.name]
            elif node.op == "call_function":
                fn = self.fn_table.get(node.target)
                if fn == "sdpa":
                    env[node.name] = _sdpa(key, train, *args, **kwargs)
                elif fn == "dropout":
                    x = args[0]
                    p = kwargs.get("p", args[1] if len(args) > 1 else 0.5)
                    training = kwargs.get(
                        "training", args[2] if len(args) > 2 else True)
                    env[node.name] = _dropout(
                        x, p, train and training, key)
                elif fn is None:
                    raise NotImplementedError(
                        f"torch function {node.target} (node {node.name}) "
                        "has no jax mapping; add it to "
                        "horovod_tpu/torch/compile.py "
                        "_build_function_table")
                else:
                    env[node.name] = fn(*args, **kwargs)
            else:
                raise NotImplementedError(f"fx op {node.op}")
        raise RuntimeError("graph had no output node")

    def _run_module(self, path, sub, params, args, kwargs, key, train):
        nn = self.torch.nn

        def p(leaf):
            name = f"{path}.{leaf}"
            return params.get(self.aliases.get(name, name))

        if isinstance(sub, nn.Linear):
            return _linear(args[0], p("weight"), p("bias"))
        if isinstance(sub, nn.LayerNorm):
            return _layer_norm(args[0], sub.normalized_shape,
                               p("weight"), p("bias"), sub.eps)
        if isinstance(sub, nn.Embedding):
            return _embedding(p("weight"), args[0], sub.padding_idx)
        if isinstance(sub, nn.Dropout):
            return _dropout(args[0], sub.p, train, key)
        if isinstance(sub, nn.CrossEntropyLoss):
            return _cross_entropy(args[0], args[1],
                                  ignore_index=sub.ignore_index,
                                  reduction=sub.reduction,
                                  label_smoothing=sub.label_smoothing)
        if isinstance(sub, (nn.GELU,)):
            return _gelu(args[0], getattr(sub, "approximate", "none"))
        if isinstance(sub, nn.ReLU):
            import jax
            return jax.nn.relu(args[0])
        if isinstance(sub, nn.Tanh):
            return _jnp().tanh(args[0])
        if isinstance(sub, nn.Softmax):
            return _softmax(args[0], dim=sub.dim)
        if isinstance(sub, nn.Identity):
            return args[0]
        # HF Conv1D (GPT-2 style): x @ weight + bias, weight (in, out).
        if type(sub).__name__ == "Conv1D" and hasattr(sub, "nf"):
            return _jnp().matmul(args[0], p("weight")) + p("bias")
        raise NotImplementedError(
            f"torch module {type(sub).__name__} at {path!r} has no jax "
            "mapping; add it to horovod_tpu/torch/compile.py "
            "_JaxInterpreter._run_module")


def _check_trace_fidelity(module, gm, example_inputs):
    """Eager module vs traced graph on the example inputs (both torch,
    no jit): catches fx control-flow specialization at compile time."""
    import torch

    def call(m):
        with torch.no_grad():
            if isinstance(example_inputs, dict):
                return m(**example_inputs)
            args = (example_inputs if isinstance(example_inputs,
                                                 (tuple, list))
                    else (example_inputs,))
            return m(*args)

    was_training = module.training
    module.eval()
    gm.eval()
    try:
        ref, traced = call(module), call(gm)
    finally:
        module.train(was_training)
        gm.train(was_training)

    flat_ref = _flatten_out(ref)
    flat_tr = _flatten_out(traced)
    if len(flat_ref) != len(flat_tr):
        raise ValueError(
            f"fx trace output structure ({len(flat_tr)} leaves) does "
            f"not match the eager module ({len(flat_ref)}); the trace "
            "specialized on data-dependent control flow for these "
            "example_inputs")

    def diverged(i, why):
        raise ValueError(
            f"fx trace diverges from the eager module on example_inputs "
            f"(output leaf {i}: {why}): tracing specialized "
            "data-dependent control flow or baked mutable state into a "
            "constant; restructure with tensor ops or trace a wrapper "
            "that pins the intended path")

    for i, (a, b) in enumerate(zip(flat_ref, flat_tr)):
        if torch.is_tensor(a) != torch.is_tensor(b):
            # A constant-folded leaf (tensor on one side, python value on
            # the other) is exactly the divergence this check exists for.
            diverged(i, "tensor vs non-tensor")
        elif torch.is_tensor(a):
            if a.shape != b.shape or not torch.allclose(
                    a.float(), b.float(), rtol=1e-4, atol=1e-5):
                diverged(i, "values differ")
        elif a != b:
            diverged(i, f"{a!r} != {b!r}")


def _flatten_out(out):
    """Flatten nested dict/list/tuple module outputs to leaves (dicts in
    sorted-key order so both sides flatten identically)."""
    if isinstance(out, dict):
        return [leaf for k in sorted(out)
                for leaf in _flatten_out(out[k])]
    if isinstance(out, (list, tuple)):
        return [leaf for v in out for leaf in _flatten_out(v)]
    return [out]


class CompiledModule:
    """A torch module compiled to a jitted JAX callable.

    ``params``/``buffers`` are flat name->jax-array dicts (the pytree the
    train step updates). Forward calls are jitted per (train, input-names)
    signature."""

    def __init__(self, gm, params, buffers, loss_key="loss", aliases=None,
                 compute_dtype=None, verify=False):
        import jax
        self._interp = _JaxInterpreter(gm, aliases=aliases)
        self.params = params
        self.buffers = buffers
        self.loss_key = loss_key
        self.compute_dtype = compute_dtype
        self.verify = verify
        self._jitted = {}
        self._jax = jax

    def apply(self, params, inputs, rng=None, train=False):
        """Pure functional forward (differentiable w.r.t. ``params``).

        With ``compute_dtype`` set (the torch-xla XLA_USE_BF16 analog),
        float params are cast on entry — master weights and gradients
        stay fp32, matmuls ride the MXU in bf16; LayerNorm/softmax/CE
        already compute in fp32 internally."""
        if self.compute_dtype is not None:
            jnp = _jnp()
            params = {
                k: (v.astype(self.compute_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in params.items()}
        return self._interp.run(params, self.buffers, inputs,
                                rng=rng, train=train)

    def __call__(self, rng=None, train=False, **inputs):
        import jax
        sig = (train, rng is not None, tuple(sorted(inputs)))
        inputs = {k: self._coerce(v) for k, v in inputs.items()}
        if sig not in self._jitted:
            def fwd(params, buffers, inputs, rng):
                return self._interp.run(params, buffers, inputs,
                                        rng=rng, train=train)
            if self.verify:
                # Static collective-correctness pass over the traced
                # program before it is jitted (hvd-lint jaxpr layer):
                # once per signature, trace-only, nothing runs on chip.
                from .. import analysis
                analysis.verify_traceable(
                    fwd, (self.params, self.buffers, inputs, rng),
                    mode=self.verify, what="torch-bridge forward")
            self._jitted[sig] = jax.jit(fwd)
        return self._jitted[sig](self.params, self.buffers, inputs, rng)

    @staticmethod
    def _coerce(v):
        import jax.numpy as jnp
        if hasattr(v, "detach"):  # torch tensor
            return _t2j(v)
        return jnp.asarray(v) if not hasattr(v, "devices") else v

    def loss_fn(self):
        """(params, batch, rng) -> scalar loss, for make_train_step-style
        wiring. ``batch`` is the inputs dict; the model output must carry
        ``self.loss_key`` (dict key or attribute)."""
        def fn(params, batch, rng=None):
            out = self.apply(params, batch, rng=rng, train=True)
            if isinstance(out, dict):
                return out[self.loss_key]
            return getattr(out, self.loss_key)
        return fn

    def make_train_step(self, optimizer, process_set=None):
        """Build a jitted distributed train step: forward+backward on the
        chip, gradient allreduce through the JAX binding's in-jit
        collectives, optax update. Returns ``step(batch, rng=None) ->
        loss`` (params/opt state live inside, torch-optimizer style —
        the torch frontend expects stateful steps)."""
        import jax
        from .. import jax as hvd_jax

        dist_opt = optimizer
        if not hasattr(dist_opt, "inner"):  # bare optax transform
            dist_opt = hvd_jax.DistributedOptimizer(
                optimizer, **({"process_set": process_set}
                              if process_set else {}))
        loss = self.loss_fn()

        # Dropout keys ride the batch: a (n, 2) PRNGKey block sharded with
        # it gives each device its own key (per-rank dropout, the torch DP
        # semantic); a bare (2,) key could not shard along the axis.
        step = hvd_jax.make_train_step(
            lambda p, b: loss(p, b[0],
                              rng=(None if b[1] is None else b[1][0])),
            dist_opt)
        opt_state = dist_opt.init(self.params)
        state = {"opt": opt_state}
        from .. import basics

        def run(batch, rng=None):
            batch = {k: self._coerce(v) for k, v in batch.items()}
            rt = basics.runtime()
            # The step shards the batch over the RUNTIME MESH: all local
            # devices in single-controller mode (your batch is global),
            # one device per process under hvdrun (your batch is this
            # rank's local batch — no divisibility constraint beyond
            # the local mesh).
            n = int(rt.mesh.shape[hvd_jax.HVD_AXIS])
            for name, v in batch.items():
                if hasattr(v, "shape") and (v.ndim == 0
                                            or v.shape[0] % n):
                    raise ValueError(
                        f"batch[{name!r}] leading axis {v.shape} must be "
                        f"divisible by the local mesh size {n}: the step "
                        "shards the batch across this runtime's devices")
            if rng is not None:
                # Decorrelate dropout across PROCESSES first (each rank
                # folds its rank in), then across local mesh devices.
                rng = jax.random.fold_in(rng, rt.topology.rank)
                rng = jax.random.split(rng, n)
            new_params, new_opt, loss_val = step(
                self.params, state["opt"], (batch, rng))
            self.params = new_params
            state["opt"] = new_opt
            return loss_val

        return run

    def copy_params_to_module(self, module):
        """Write the (possibly updated) jax parameters back into the torch
        module, so torch-side checkpointing/eval sees trained weights."""
        import torch
        with torch.no_grad():
            for name, p in module.named_parameters():
                if name in self.params:
                    # .copy(): device_get can return a read-only view
                    # torch would warn about aliasing.
                    arr = np.array(
                        self._jax.device_get(self.params[name]),
                        dtype=np.float32)
                    p.copy_(torch.from_numpy(arr).to(p.dtype))


def tpu_compile(module, input_names=None, example_inputs=None,
                loss_key="loss", compute_dtype=None, verify=False):
    """Compile a torch module for TPU execution via fx→JAX.

    HF transformers models are traced with ``transformers.utils.fx``
    (pass ``input_names``); plain ``torch.nn.Module``s go through
    ``torch.fx.symbolic_trace``. Returns a :class:`CompiledModule`.

    ``example_inputs`` (dict of kwargs or tuple of positional args) runs
    a one-shot trace-fidelity check: fx tracing silently SPECIALIZES
    data-dependent Python control flow to the traced branch, so the
    traced graph is compared against the eager module on these inputs
    and a mismatch fails loudly at compile time instead of training on
    the wrong branch.

    ``verify`` runs the hvd-lint jaxpr analyzer over each forward
    signature before it is jitted (True: raise on error-severity
    findings; ``"warn"``: log only) — see docs/lint.md.
    """
    import torch

    gm = None
    if input_names is not None:
        try:
            from transformers.utils import fx as hf_fx
            gm = hf_fx.symbolic_trace(module, input_names=list(input_names))
        except (ImportError, ValueError, TypeError):
            gm = None
    if gm is None:
        gm = torch.fx.symbolic_trace(module)

    if example_inputs is not None:
        _check_trace_fidelity(module, gm, example_inputs)

    params = {n: _t2j(p) for n, p in module.named_parameters()}
    buffers = {n: _t2j(b) for n, b in module.named_buffers()}
    # Tied weights: named_parameters() deduplicates shared tensors; map
    # every non-canonical path to the first-seen name so lookups resolve
    # and the tie is preserved as a single trainable leaf.
    canonical = {}
    aliases = {}
    for n, p in module.named_parameters(remove_duplicate=False):
        key = id(p)
        if key in canonical:
            aliases[n] = canonical[key]
        else:
            canonical[key] = n
    # fx tracing of HF models can introduce fresh buffers on the traced
    # copy (e.g. tensor constants) absent from the original module.
    for n, b in gm.named_buffers():
        if n not in buffers and n not in aliases:
            buffers[n] = _t2j(b)
    for n, p in gm.named_parameters():
        if n not in params and n not in aliases:
            params[n] = _t2j(p)
    return CompiledModule(gm, params, buffers, loss_key=loss_key,
                          aliases=aliases, compute_dtype=compute_dtype,
                          verify=verify)
