"""Parquet shard reading for estimator training (reference:
horovod/spark/common/util.py's DataFrame->parquet prep + petastorm
readers, redesigned over pyarrow).

The shard unit is the parquet part file: rank r trains on files
``files[r::size]`` — deterministic, disjoint, and independent of any
Spark runtime, so the same reader serves Spark executors, hvdrun
workers, and tests.

``AsyncShardBatchLoader`` is the estimator-path analog of the
reference's petastorm async data loaders
(horovod/spark/data_loaders/pytorch_data_loaders.py:71): batch
assembly (index, stack, framework-tensor conversion) runs on a
background thread with a bounded queue, overlapping the next batch's
host work with the current training step.
"""

import numpy as np
import pyarrow.parquet as pq

from ..data.data_loader_base import AsyncDataLoaderMixin, BaseDataLoader


def stack_column(col):
    """Parquet list columns come back as object arrays of arrays; stack
    them into one dense array (shared by the estimator flavors)."""
    if col.dtype == object:
        return np.stack([np.asarray(v) for v in col])
    return col


def shard_files(files, rank, size):
    """Round-robin file assignment; every rank gets >=1 file when
    possible (raises when there are fewer files than ranks — repartition
    the DataFrame to at least ``size`` partitions)."""
    files = sorted(files)
    if len(files) < size:
        raise ValueError(
            f"parquet dataset has {len(files)} part files but the job has "
            f"{size} ranks; repartition the DataFrame to >= {size}")
    return files[rank::size]


class ParquetShard:
    """One rank's slice of a parquet dataset, materialized to numpy.

    Column-major: ``columns[name]`` is the full shard as one array.
    TPU hosts have RAM to hold training shards; streaming readers
    (petastorm in the reference) trade determinism for memory this
    environment doesn't need to save.
    """

    def __init__(self, store, files, columns):
        tables = []
        for f in files:
            with store.fs.open(f, "rb") as fh:
                tables.append(pq.read_table(fh, columns=list(columns)))
        if not tables:
            raise ValueError("empty shard: no parquet files assigned")
        self.columns = {}
        for name in columns:
            parts = [t.column(name).to_numpy(zero_copy_only=False)
                     for t in tables]
            self.columns[name] = np.concatenate(parts)
        self.num_rows = len(next(iter(self.columns.values())))

    def batches(self, batch_size, seed=0, shuffle=True):
        """Infinite batch generator; reshuffles every epoch. Infinite so
        all ranks can run the SAME number of steps per epoch regardless
        of shard-size imbalance (collectives must stay in lockstep)."""
        if self.num_rows == 0:
            # Training on empty batches would NaN/raise mid-job while
            # peers block in the gradient allreduce — fail loudly now.
            raise ValueError(
                "shard has 0 training rows (empty part files, or a "
                "validation split consumed the whole shard); repartition "
                "the dataset or lower the validation fraction")
        rng = np.random.RandomState(seed)
        while True:
            order = (rng.permutation(self.num_rows) if shuffle
                     else np.arange(self.num_rows))
            for start in range(0, self.num_rows - batch_size + 1,
                               batch_size):
                idx = order[start:start + batch_size]
                yield {name: col[idx]
                       for name, col in self.columns.items()}
            if self.num_rows < batch_size:
                # Tiny shard: emit the whole shard rather than nothing.
                yield dict(self.columns)


class ShardBatchLoader(BaseDataLoader):
    """One EPOCH of transformed batches from a ParquetShard: exactly
    ``steps`` batches through ``transform`` (the estimator's
    numpy->framework-tensor conversion). A fresh underlying generator
    position is kept across epochs so data doesn't repeat."""

    def __init__(self, shard, batch_size, steps, transform=None, seed=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._gen = shard.batches(batch_size, seed=seed)
        self.steps = steps
        self.transform = transform or (lambda b: b)

    def __len__(self):
        return self.steps

    def __iter__(self):
        for _ in range(self.steps):
            yield self.transform(next(self._gen))


class AsyncShardBatchLoader(AsyncDataLoaderMixin, ShardBatchLoader):
    """Background-thread variant: each epoch's iteration spawns a
    producer prefetching up to ``async_loader_queue_size`` transformed
    batches while the training step runs."""
