"""Run/data store abstraction for estimator-style training (reference:
horovod/spark/common/store.py:37-166 ``Store``/filesystem stores).

TPU-first redesign: one fsspec-backed implementation covers local disk,
HDFS, S3, GCS and DBFS through a single code path (the reference ships a
separate hand-written class per filesystem). ``Store.create`` picks the
filesystem from the path's protocol; anything fsspec can mount works.

Layout under ``prefix_path``::

    intermediate_train_data[.<idx>]/   parquet training shards
    intermediate_val_data[.<idx>]/     parquet validation shards
    runs/<run_id>/checkpoint.keras     model checkpoint
    runs/<run_id>/logs/                user logs
"""

import os

import fsspec


class Store:
    """Abstracts reading/writing intermediate data and run results
    (reference: horovod/spark/common/store.py:37)."""

    def __init__(self, prefix_path):
        self.prefix_path = prefix_path.rstrip("/")
        self._fs, self._root = fsspec.core.url_to_fs(self.prefix_path)

    # -- path layout -------------------------------------------------------

    def _join(self, *parts):
        return "/".join([self.prefix_path] + [p.strip("/") for p in parts])

    def get_train_data_path(self, idx=None):
        suffix = "" if idx is None else f".{idx}"
        return self._join(f"intermediate_train_data{suffix}")

    def get_val_data_path(self, idx=None):
        suffix = "" if idx is None else f".{idx}"
        return self._join(f"intermediate_val_data{suffix}")

    def get_runs_path(self):
        return self._join("runs")

    def get_run_path(self, run_id):
        return self._join("runs", run_id)

    def get_checkpoint_path(self, run_id):
        return self._join("runs", run_id, self.get_checkpoint_filename())

    def get_logs_path(self, run_id):
        return self._join("runs", run_id, "logs")

    def get_checkpoint_filename(self):
        return "checkpoint.keras"

    # -- filesystem ops ----------------------------------------------------

    def _strip(self, url):
        """fsspec filesystems address paths without the protocol scheme."""
        fs2, path = fsspec.core.url_to_fs(url)
        return path

    def exists(self, path):
        return self._fs.exists(self._strip(path))

    def makedirs(self, path):
        self._fs.makedirs(self._strip(path), exist_ok=True)

    def read(self, path):
        with self._fs.open(self._strip(path), "rb") as f:
            return f.read()

    def write(self, path, data):
        p = self._strip(path)
        parent = p.rsplit("/", 1)[0]
        if parent:
            self._fs.makedirs(parent, exist_ok=True)
        with self._fs.open(p, "wb") as f:
            f.write(data)

    def write_text(self, path, text):
        self.write(path, text.encode("utf-8"))

    def read_text(self, path):
        return self.read(path).decode("utf-8")

    def is_parquet_dataset(self, path):
        p = self._strip(path)
        if not self._fs.exists(p):
            return False
        try:
            return any(f.endswith(".parquet")
                       for f in self._fs.ls(p, detail=False))
        except (OSError, FileNotFoundError):
            return False

    def list_parquet_files(self, path):
        """Sorted parquet part files of a dataset directory — the shard
        unit for rank assignment."""
        p = self._strip(path)
        return sorted(f for f in self._fs.ls(p, detail=False)
                      if f.endswith(".parquet"))

    def open(self, path, mode="rb"):
        return self._fs.open(self._strip(path), mode)

    @property
    def fs(self):
        return self._fs

    # -- factory -----------------------------------------------------------

    @staticmethod
    def create(prefix_path, **kwargs):
        """Store for any fsspec-resolvable path: plain paths and
        ``file://`` map to local disk; ``hdfs://``, ``s3://``, ``gs://``,
        ``dbfs:/`` work when the matching fsspec backend is installed
        (reference: store.py:157 ``Store.create`` protocol dispatch)."""
        if prefix_path.startswith("dbfs:/"):
            prefix_path = "file:///dbfs/" + prefix_path[len("dbfs:/"):]
        return Store(prefix_path, **kwargs)


class LocalStore(Store):
    """Local-disk store (reference: LocalFSStore). Plain ``Store`` over a
    local path behaves identically; this class exists for API parity."""

    def __init__(self, prefix_path):
        super().__init__(os.path.abspath(prefix_path))
