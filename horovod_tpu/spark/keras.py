"""Spark ML-style Keras estimator (reference:
horovod/spark/keras/estimator.py:88 ``KerasEstimator`` +
horovod/spark/keras/remote.py's executor-side training loop).

TPU-first split: the worker-side training loop (``fit_on_parquet``) is
plain Python over a ``Store`` + parquet shards — it runs identically
under Spark barrier tasks, ``hvdrun``, or a test harness. Only the
DataFrame materialization and the ``transform`` step touch pyspark, so
the heavy path is fully testable without a Spark cluster.

    est = KerasEstimator(model=model, optimizer="adam", loss="mse",
                         feature_cols=["x"], label_cols=["y"],
                         store=Store.create("/tmp/run"), epochs=2)
    keras_model = est.fit(df)          # Spark path
    hist = fit_on_parquet(...)         # same loop, no Spark needed
"""

import os
import tempfile
import uuid

import numpy as np

from ._transform import (check_output_width, materialize_df,
                         require_pyspark, transform_with)
from .data import stack_column as _stack_column
from .store import Store


def serialize_model(model):
    """Keras model -> bytes via the native .keras archive."""
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.keras")
        model.save(path)
        with open(path, "rb") as f:
            return f.read()


def deserialize_model(data, custom_objects=None):
    import keras
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.keras")
        with open(path, "wb") as f:
            f.write(data)
        return keras.models.load_model(
            path, custom_objects=custom_objects, compile=False)


def fit_on_parquet(store_prefix, run_id, model_bytes, feature_cols,
                   label_cols, batch_size=32, epochs=1, optimizer=None,
                   loss=None, metrics=None, custom_objects=None,
                   validation=None, callbacks=None,
                   train_steps_per_epoch=None, shuffle_seed=0, verbose=0,
                   train_path=None, compression=None,
                   backward_passes_per_step=1):
    """Train one rank's shard of a materialized parquet dataset; the
    executor-side body of ``KerasEstimator.fit`` (reference:
    horovod/spark/keras/remote.py:31 ``RemoteTrainer``).

    Every rank runs the same number of optimizer steps per epoch (min
    shard size across ranks) so the gradient collectives stay in
    lockstep. Rank 0 writes the trained model to the store's checkpoint
    path. Returns the keras History dict.
    """
    import horovod_tpu.keras as hvd
    from .data import ParquetShard, shard_files

    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    store = Store.create(store_prefix)
    train_path = train_path or store.get_train_data_path()
    files = shard_files(store.list_parquet_files(train_path), rank, size)
    cols = list(feature_cols) + list(label_cols)
    shard = ParquetShard(store, files, cols)

    model = deserialize_model(model_bytes, custom_objects)
    import keras
    opt = keras.optimizers.get(optimizer or "adam")
    model.compile(
        optimizer=hvd.DistributedOptimizer(
            opt, compression=compression,
            backward_passes_per_step=backward_passes_per_step),
        loss=loss, metrics=list(metrics or []))

    val_rows = 0
    n_rows = shard.num_rows
    if validation is not None:
        # Only a float split fraction is supported (the reference also
        # accepts a 0/1 indicator column; fail loudly rather than train
        # silently without validation).
        if not (isinstance(validation, float) and 0.0 < validation < 1.0):
            raise ValueError(
                f"validation must be a float in (0, 1) (got "
                f"{validation!r}); indicator-column validation is not "
                "supported — pre-split the DataFrame instead")
        val_rows = max(1, int(n_rows * validation))
        n_rows -= val_rows

    val_batch = None
    if val_rows:
        # Carve validation rows OUT of the training shard (training on
        # them would optimistically bias val metrics and anything that
        # selects on them, e.g. EarlyStopping).
        order = np.random.RandomState(shuffle_seed).permutation(
            shard.num_rows)
        val_batch = {c: shard.columns[c][order[:val_rows]]
                     for c in cols}
        shard.columns = {c: shard.columns[c][order[val_rows:]]
                         for c in cols}
        shard.num_rows -= val_rows

    # Lockstep step count: min trainable rows across ranks.
    if size > 1:
        n_rows = int(np.min(np.asarray(
            hvd.allgather(np.asarray([n_rows], np.int64)))))
    if n_rows == 0:
        # Raise on ALL ranks (the allgathered min is identical
        # everywhere): one rank raising alone would leave its peers
        # deadlocked in the first gradient allreduce.
        raise ValueError(
            "a rank has 0 training rows after the validation split; "
            "repartition the dataset or lower the validation fraction")
    steps = train_steps_per_epoch or max(1, n_rows // batch_size)

    def to_xy(batch):
        xs = [_stack_column(batch[c]) for c in feature_cols]
        ys = [_stack_column(batch[c]) for c in label_cols]
        return (xs[0] if len(xs) == 1 else tuple(xs),
                ys[0] if len(ys) == 1 else tuple(ys))

    # Async batch assembly overlapping fit steps (reference:
    # pytorch_data_loaders.py:71; see spark/data.py). Keras pulls one
    # continuous stream across epochs; +2 covers its lookahead prefetch.
    from .data import AsyncShardBatchLoader
    loader = AsyncShardBatchLoader(shard=shard, batch_size=batch_size,
                                   steps=steps * epochs + 2,
                                   transform=to_xy,
                                   seed=shuffle_seed + rank)

    fit_kwargs = {}
    if val_batch is not None:
        fit_kwargs["validation_data"] = to_xy(val_batch)

    cbs = [hvd.callbacks.BroadcastGlobalVariablesCallback(0),
           hvd.callbacks.MetricAverageCallback()]
    cbs += list(callbacks or [])

    try:
        history = model.fit(iter(loader), steps_per_epoch=steps,
                            epochs=epochs, callbacks=cbs, verbose=verbose,
                            **fit_kwargs)
    finally:
        loader.close()

    if rank == 0:
        store.write(store.get_checkpoint_path(run_id),
                    serialize_model(model))
    hvd.allreduce(np.zeros(1, np.float32), name="fit.final.barrier")
    return {k: [float(v) for v in vs] for k, vs in
            history.history.items()}


class KerasModel:
    """Trained-model transformer (reference:
    horovod/spark/keras/estimator.py KerasModel): holds the serialized
    model; ``transform`` adds a prediction column per output."""

    def __init__(self, model_bytes, feature_cols, label_cols,
                 custom_objects=None, output_cols=None):
        self.model_bytes = model_bytes
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.custom_objects = custom_objects
        self.output_cols = list(
            output_cols or [f"{c}__output" for c in label_cols])

    def keras_model(self):
        return deserialize_model(self.model_bytes, self.custom_objects)

    def predict(self, features):
        """Local numpy prediction (no Spark needed)."""
        xs = [_stack_column(np.asarray(f)) for f in features]
        preds = np.asarray(self.keras_model().predict(
            xs[0] if len(xs) == 1 else tuple(xs), verbose=0))
        check_output_width(preds.reshape(len(preds), -1),
                           self.output_cols)
        return preds

    def transform(self, df):
        """Append prediction columns to a Spark DataFrame via
        mapInPandas (executor-local inference)."""
        model_bytes = self.model_bytes
        custom_objects = self.custom_objects

        def make_predict():
            model = deserialize_model(model_bytes, custom_objects)
            return lambda feats: model.predict(
                feats[0] if len(feats) == 1 else tuple(feats), verbose=0)

        return transform_with(df, self.feature_cols, self.output_cols,
                              make_predict)


class KerasEstimator:
    """Fit a Keras model to a Spark DataFrame over horovod_tpu ranks
    (reference: horovod/spark/keras/estimator.py:88). Parameters follow
    the reference's core set; petastorm streaming knobs are absorbed by
    the in-memory shard reader (data.py)."""

    def __init__(self, model=None, store=None, optimizer=None, loss=None,
                 metrics=None, feature_cols=None, label_cols=None,
                 batch_size=32, epochs=1, num_proc=None, validation=None,
                 callbacks=None, custom_objects=None, run_id=None,
                 train_steps_per_epoch=None, verbose=1, compression=None,
                 backward_passes_per_step=1):
        if model is None or store is None:
            raise ValueError("KerasEstimator requires model= and store=")
        if not feature_cols or not label_cols:
            raise ValueError("feature_cols and label_cols are required")
        self.model = model
        self.store = (store if isinstance(store, Store)
                      else Store.create(store))
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = metrics
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.validation = validation
        self.callbacks = callbacks
        self.custom_objects = custom_objects
        self.run_id = run_id or f"run_{uuid.uuid4().hex[:8]}"
        self.train_steps_per_epoch = train_steps_per_epoch
        self.verbose = verbose
        self.compression = compression
        self.backward_passes_per_step = backward_passes_per_step

    def fit(self, df):
        require_pyspark("KerasEstimator.fit")
        from . import run as spark_run
        from pyspark import SparkContext

        sc = SparkContext.getOrCreate()
        num_proc = self.num_proc or sc.defaultParallelism
        materialize_df(df, self.store, num_proc)

        spark_run(
            fit_on_parquet, kwargs=dict(
                store_prefix=self.store.prefix_path,
                run_id=self.run_id,
                model_bytes=serialize_model(self.model),
                feature_cols=self.feature_cols,
                label_cols=self.label_cols,
                batch_size=self.batch_size,
                epochs=self.epochs,
                optimizer=self.optimizer,
                loss=self.loss,
                metrics=self.metrics,
                custom_objects=self.custom_objects,
                validation=self.validation,
                callbacks=self.callbacks,
                train_steps_per_epoch=self.train_steps_per_epoch,
                verbose=self.verbose,
                compression=self.compression,
                backward_passes_per_step=self.backward_passes_per_step),
            num_proc=num_proc)
        return self.load(self.store, self.run_id,
                         feature_cols=self.feature_cols,
                         label_cols=self.label_cols,
                         custom_objects=self.custom_objects)

    @staticmethod
    def load(store, run_id, feature_cols, label_cols,
             custom_objects=None):
        """Rehydrate the trained transformer from a store checkpoint."""
        store = store if isinstance(store, Store) else Store.create(store)
        data = store.read(store.get_checkpoint_path(run_id))
        return KerasModel(data, feature_cols, label_cols,
                          custom_objects=custom_objects)
