"""Spark ML-style Torch estimator (reference:
horovod/spark/torch/estimator.py:91 ``TorchEstimator`` +
torch/remote.py's executor-side loop).

Same split as the Keras flavor (spark/keras.py): the worker-side loop
(``fit_on_parquet_torch``) is Spark-free — Store + pyarrow shards + the
torch binding's grad-hook DistributedOptimizer — so it runs under Spark
barrier tasks, ``hvdrun``, or a test harness unchanged. Only DataFrame
materialization and ``transform`` need pyspark.
"""

import io
import uuid

import numpy as np

from ._transform import (check_output_width, materialize_df,
                         require_pyspark, transform_with)
from .data import stack_column as _stack_column
from .store import Store


def serialize_torch(obj):
    import torch
    buf = io.BytesIO()
    torch.save(obj, buf)
    return buf.getvalue()


def deserialize_torch(data):
    import torch
    return torch.load(io.BytesIO(data), weights_only=False)


def _optimizer_spec(optimizer):
    """(class, defaults) — enough to rebuild the optimizer against the
    deserialized model's parameters (reference:
    horovod/spark/torch/remote.py:444 get_optimizer_with_unscaled_lr).
    Multi-param-group optimizers cannot round-trip this way (parameter
    identity does not survive serialization), so they are rejected
    rather than silently rebuilt with one uniform setting. The live
    param_groups[0] hyperparameters are captured (not ``defaults``) so
    post-construction changes — manual decay, schedulers — survive."""
    if len(optimizer.param_groups) > 1:
        raise ValueError(
            "TorchEstimator supports single-param-group optimizers "
            "only: per-group hyperparameters cannot be re-attached to "
            "the deserialized model's parameters on the executors. "
            "Rebuild the groups inside a custom training fn run via "
            "horovod_tpu.spark.run instead.")
    hparams = {k: v for k, v in optimizer.param_groups[0].items()
               if k != "params" and k in optimizer.defaults}
    return type(optimizer), hparams


def _resolve_loss(loss):
    if callable(loss):
        return loss
    import torch.nn.functional as F
    fn = getattr(F, loss, None)
    if fn is None:
        raise ValueError(f"unknown loss {loss!r} (not a callable or a "
                         "torch.nn.functional name)")
    return fn


def fit_on_parquet_torch(store_prefix, run_id, model_bytes, opt_spec,
                         loss, feature_cols, label_cols, batch_size=32,
                         epochs=1, validation=None,
                         train_steps_per_epoch=None, shuffle_seed=0,
                         verbose=0, train_path=None,
                         feature_dtype="float32", label_dtype=None,
                         compression=None, backward_passes_per_step=1):
    """Train one rank's shard; the executor body of
    ``TorchEstimator.fit`` (reference: horovod/spark/torch/remote.py:100
    ``train``). Returns {'loss': [...], 'val_loss': [...]} with metrics
    averaged across ranks; rank 0 checkpoints the model to the store."""
    import torch

    import horovod_tpu.torch as hvd
    from .data import ParquetShard, shard_files

    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    store = Store.create(store_prefix)
    train_path = train_path or store.get_train_data_path()
    files = shard_files(store.list_parquet_files(train_path), rank, size)
    cols = list(feature_cols) + list(label_cols)
    shard = ParquetShard(store, files, cols)

    model = deserialize_torch(model_bytes)
    loss_fn = _resolve_loss(loss)
    opt_cls, opt_defaults = (opt_spec if isinstance(opt_spec, tuple)
                             else deserialize_torch(opt_spec))
    optimizer = opt_cls(model.parameters(), **opt_defaults)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression,
        backward_passes_per_step=backward_passes_per_step)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    n_rows = shard.num_rows
    val_batch = None
    if validation is not None:
        if not (isinstance(validation, float) and 0.0 < validation < 1.0):
            raise ValueError(
                f"validation must be a float in (0, 1) (got "
                f"{validation!r}); pre-split the DataFrame for "
                "indicator-column validation")
        val_rows = max(1, int(n_rows * validation))
        order = np.random.RandomState(shuffle_seed).permutation(n_rows)
        val_batch = {c: shard.columns[c][order[:val_rows]] for c in cols}
        shard.columns = {c: shard.columns[c][order[val_rows:]]
                         for c in cols}
        shard.num_rows -= val_rows
        n_rows -= val_rows

    if size > 1:
        n_rows = int(min(
            int(t) for t in hvd.allgather(
                torch.tensor([n_rows], dtype=torch.int64))))
    if n_rows == 0:
        # Raise on ALL ranks (the allgathered min is identical
        # everywhere): one rank raising alone would leave its peers
        # deadlocked in the first gradient allreduce.
        raise ValueError(
            "a rank has 0 training rows after the validation split; "
            "repartition the dataset or lower the validation fraction")
    steps = train_steps_per_epoch or max(1, n_rows // batch_size)
    k = int(backward_passes_per_step)
    if k > 1:
        # The wrapper syncs every k-th backward and expects grads to
        # accumulate across the window (zero_grad/step only at window
        # boundaries). Trim the epoch to whole windows so no partially
        # accumulated, un-synced gradient is ever applied.
        steps = max(k, (steps // k) * k)

    def to_xy(batch):
        xs = [torch.as_tensor(_stack_column(batch[c])).to(
            getattr(torch, feature_dtype)) for c in feature_cols]
        ys = []
        for c in label_cols:
            y = torch.as_tensor(_stack_column(batch[c]))
            if label_dtype is not None:
                y = y.to(getattr(torch, label_dtype))
            ys.append(y)
        return (xs[0] if len(xs) == 1 else xs,
                ys[0] if len(ys) == 1 else ys)

    # Async batch assembly: stacking + tensor conversion overlap the
    # training step (reference: pytorch_data_loaders.py:71 async loader).
    from .data import AsyncShardBatchLoader
    loader = AsyncShardBatchLoader(shard=shard, batch_size=batch_size,
                                   steps=steps, transform=to_xy,
                                   seed=shuffle_seed + rank)
    history = {"loss": []}
    if val_batch is not None:
        history["val_loss"] = []

    model.train()
    try:
        for epoch in range(epochs):
            total = 0.0
            micro = 0
            for x, y in loader:
                if micro % k == 0:
                    optimizer.zero_grad()
                loss_val = loss_fn(model(x), y)
                loss_val.backward()
                micro += 1
                if micro % k == 0:
                    # k==1: every batch. k>1: the k-th backward fired the
                    # allreduce over the accumulated grads (postscaled
                    # 1/k by the wrapper); step() applies the average.
                    optimizer.step()
                total += float(loss_val.detach())
            # Cross-rank metric averaging (the MetricAverageCallback analog).
            avg = float(hvd.allreduce(
                torch.tensor([total / steps]), name=f"ep{epoch}.loss"))
            history["loss"].append(avg)
            if val_batch is not None:
                # Batched eval: one whole-split forward would allocate
                # activations for 25% of a host-RAM-sized shard at once.
                model.eval()
                n_val = len(next(iter(val_batch.values())))
                vl_sum, vl_n = 0.0, 0
                with torch.no_grad():
                    for start in range(0, n_val, batch_size):
                        chunk = {c: v[start:start + batch_size]
                                 for c, v in val_batch.items()}
                        vx, vy = to_xy(chunk)
                        rows = len(next(iter(chunk.values())))
                        vl_sum += float(loss_fn(model(vx), vy)) * rows
                        vl_n += rows
                model.train()
                # `val_batch is not None` is replica-invariant: it is
                # decided by the `validation` argument (same on every
                # rank) — val_rows = max(1, ...) guarantees a non-None
                # val_batch on EVERY rank whenever validation is set,
                # even for ranks whose shard taint suggests otherwise.
                # hvd-lint: disable=HVD401
                history["val_loss"].append(float(hvd.allreduce(
                    torch.tensor([vl_sum / vl_n]), name=f"ep{epoch}.vloss")))
            if verbose and rank == 0:
                print(f"epoch {epoch}: " + ", ".join(
                    f"{k}={v[-1]:.4f}" for k, v in history.items()),
                    flush=True)

    finally:
        loader.close()
    if rank == 0:
        store.write(store.get_checkpoint_path(run_id),
                    serialize_torch(model))
    hvd.barrier()
    return history


class TorchModel:
    """Trained-model transformer (reference:
    horovod/spark/torch/estimator.py TorchModel)."""

    def __init__(self, model_bytes, feature_cols, label_cols,
                 output_cols=None, feature_dtype="float32"):
        self.model_bytes = model_bytes
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.output_cols = list(
            output_cols or [f"{c}__output" for c in label_cols])
        self.feature_dtype = feature_dtype

    def torch_model(self):
        return deserialize_torch(self.model_bytes)

    def predict(self, features):
        import torch
        model = self.torch_model()
        model.eval()
        xs = [torch.as_tensor(_stack_column(np.asarray(f))).to(
            getattr(torch, self.feature_dtype)) for f in features]
        with torch.no_grad():
            out = np.asarray(model(xs[0] if len(xs) == 1 else xs))
        check_output_width(out.reshape(len(out), -1), self.output_cols)
        return out

    def transform(self, df):
        model_bytes = self.model_bytes
        feature_dtype = self.feature_dtype

        def make_predict():
            import torch
            model = deserialize_torch(model_bytes)
            model.eval()

            def predict(feats):
                xs = [torch.as_tensor(f).to(getattr(torch, feature_dtype))
                      for f in feats]
                with torch.no_grad():
                    return np.asarray(model(
                        xs[0] if len(xs) == 1 else xs))
            return predict

        return transform_with(df, self.feature_cols, self.output_cols,
                              make_predict)


class TorchEstimator:
    """Fit a torch model to a Spark DataFrame over horovod_tpu ranks
    (reference: horovod/spark/torch/estimator.py:91)."""

    def __init__(self, model=None, store=None, optimizer=None, loss=None,
                 feature_cols=None, label_cols=None, batch_size=32,
                 epochs=1, num_proc=None, validation=None, run_id=None,
                 train_steps_per_epoch=None, verbose=1,
                 feature_dtype="float32", label_dtype=None,
                 compression=None, backward_passes_per_step=1):
        if model is None or store is None or optimizer is None:
            raise ValueError(
                "TorchEstimator requires model=, store= and optimizer=")
        if not feature_cols or not label_cols:
            raise ValueError("feature_cols and label_cols are required")
        if loss is None:
            raise ValueError("loss is required (callable or "
                             "torch.nn.functional name)")
        self.model = model
        self.store = (store if isinstance(store, Store)
                      else Store.create(store))
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.validation = validation
        self.run_id = run_id or f"run_{uuid.uuid4().hex[:8]}"
        self.train_steps_per_epoch = train_steps_per_epoch
        self.verbose = verbose
        self.feature_dtype = feature_dtype
        self.label_dtype = label_dtype
        self.compression = compression
        self.backward_passes_per_step = backward_passes_per_step

    def fit(self, df):
        require_pyspark("TorchEstimator.fit")
        from . import run as spark_run
        from pyspark import SparkContext

        sc = SparkContext.getOrCreate()
        num_proc = self.num_proc or sc.defaultParallelism
        materialize_df(df, self.store, num_proc)

        spark_run(
            fit_on_parquet_torch, kwargs=dict(
                store_prefix=self.store.prefix_path,
                run_id=self.run_id,
                model_bytes=serialize_torch(self.model),
                opt_spec=_optimizer_spec(self.optimizer),
                loss=self.loss,
                feature_cols=self.feature_cols,
                label_cols=self.label_cols,
                batch_size=self.batch_size,
                epochs=self.epochs,
                validation=self.validation,
                train_steps_per_epoch=self.train_steps_per_epoch,
                verbose=self.verbose,
                feature_dtype=self.feature_dtype,
                label_dtype=self.label_dtype,
                compression=self.compression,
                backward_passes_per_step=self.backward_passes_per_step),
            num_proc=num_proc)
        return self.load(self.store, self.run_id,
                         feature_cols=self.feature_cols,
                         label_cols=self.label_cols,
                         feature_dtype=self.feature_dtype)

    @staticmethod
    def load(store, run_id, feature_cols, label_cols,
             feature_dtype="float32"):
        store = store if isinstance(store, Store) else Store.create(store)
        data = store.read(store.get_checkpoint_path(run_id))
        return TorchModel(data, feature_cols, label_cols,
                          feature_dtype=feature_dtype)
