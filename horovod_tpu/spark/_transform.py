"""Shared Spark-DataFrame inference scaffolding for the estimator model
transformers (keras.py / torch.py): one mapInPandas body, one pyspark
gate, one output-width check."""

import numpy as np

from .data import stack_column


def require_pyspark(what):
    try:
        import pyspark
        return pyspark
    except ImportError as e:
        raise ImportError(
            f"{what} requires pyspark; use predict() for local numpy "
            "inference.") from e


def check_output_width(preds, output_cols):
    """A (rows, k) prediction can fill len(output_cols) columns only when
    k == len(output_cols) or k == 1 — anything else would silently write
    component 0 of a k-way output into the single column."""
    k = preds.shape[1]
    if k not in (1, len(output_cols)):
        raise ValueError(
            f"model produces {k} output components but output_cols has "
            f"{len(output_cols)} entries; pass output_cols naming one "
            "column per component (or reduce the output in the model)")


def materialize_df(df, store, num_proc):
    """DataFrame -> parquet shards in the store, at least one part file
    per rank (reference: horovod/spark/common/util.py prepare_data).
    Shared by the estimator flavors."""
    path = store.get_train_data_path()
    (df.repartition(max(num_proc, df.rdd.getNumPartitions()))
       .write.mode("overwrite").parquet(path))
    return path


def transform_with(df, feature_cols, output_cols, make_predict):
    """Append prediction columns to a Spark DataFrame via mapInPandas.
    ``make_predict()`` runs once per executor partition stream and
    returns ``fn(list_of_feature_arrays) -> (rows, k) ndarray``."""
    require_pyspark("transform")
    import pandas as pd
    from pyspark.sql.types import DoubleType, StructField, StructType

    schema = StructType(df.schema.fields + [
        StructField(c, DoubleType()) for c in output_cols])

    def infer(iterator):
        predict = make_predict()
        for pdf in iterator:
            feats = [stack_column(pdf[c].to_numpy())
                     for c in feature_cols]
            preds = np.asarray(predict(feats)).reshape(len(pdf), -1)
            check_output_width(preds, output_cols)
            out = pdf.copy()
            for i, c in enumerate(output_cols):
                col = preds if preds.shape[1] == 1 else preds[:, i:i + 1]
                out[c] = pd.Series(col.ravel().astype(float),
                                   index=pdf.index)
            yield out

    return df.mapInPandas(infer, schema=schema)
