"""Spark ML-style Lightning estimator (reference:
horovod/spark/lightning/estimator.py:100 ``TorchEstimator`` [the
lightning flavor] + lightning/remote.py's executor loop).

Design difference from the reference: the reference embeds a full
``pl.Trainer`` on every executor (remote.py:348). Here the estimator
consumes the **LightningModule protocol** — ``training_step``,
``configure_optimizers``, optional ``validation_step`` /
``on_train_epoch_end`` — and drives it with the same Spark-free shard
loop the Keras/Torch flavors use (spark/keras.py, spark/torch.py). Any
real ``pytorch_lightning.LightningModule`` satisfies the protocol (it is
just an ``nn.Module`` with those methods), but the integration neither
imports nor requires the lightning package, which TPU images don't ship.
The optimizer round-trip problem the torch estimator has (rebuilding
param groups on the executor) disappears entirely: Lightning modules
construct their own optimizers on the worker via
``configure_optimizers``.

Batches arrive as ``(features, labels)`` tuples (single-tensor when one
column), the dominant LightningModule convention.
"""

import uuid

import numpy as np

from ._transform import (check_output_width, materialize_df,
                         require_pyspark, transform_with)
from .data import stack_column as _stack_column
from .store import Store
from .torch import deserialize_torch, serialize_torch


def _resolve_optimizers(module):
    """Normalize configure_optimizers() output to (optimizer, schedulers)
    (Lightning accepts several shapes; multi-optimizer setups — GAN
    style — need a custom loop via horovod_tpu.spark.run)."""
    cfg = module.configure_optimizers()
    if cfg is None:
        raise ValueError("configure_optimizers() returned None")
    schedulers = []
    if isinstance(cfg, tuple) and len(cfg) == 2 \
            and isinstance(cfg[0], (list, tuple)):
        opts, schedulers = list(cfg[0]), list(cfg[1])
    elif isinstance(cfg, (list, tuple)):
        opts = list(cfg)
    elif isinstance(cfg, dict):
        opts = [cfg["optimizer"]]
        if cfg.get("lr_scheduler") is not None:
            schedulers = [cfg["lr_scheduler"]]
    else:
        opts = [cfg]
    if len(opts) != 1:
        raise ValueError(
            f"LightningEstimator supports exactly one optimizer; "
            f"configure_optimizers() returned {len(opts)}. Drive "
            "multi-optimizer training with a custom fn via "
            "horovod_tpu.spark.run.")
    # Scheduler dicts ({'scheduler': ..., 'interval': ...}) -> object.
    schedulers = [s["scheduler"] if isinstance(s, dict) else s
                  for s in schedulers]
    return opts[0], schedulers


def _step_loss(out):
    """training_step may return a loss tensor or a dict with 'loss'."""
    if isinstance(out, dict):
        out = out.get("loss")
    if out is None:
        raise ValueError(
            "training_step returned no loss (None or a dict without "
            "'loss'); manual-optimization modules are out of scope")
    return out


def fit_on_parquet_lightning(store_prefix, run_id, module_bytes,
                             feature_cols, label_cols, batch_size=32,
                             epochs=1, validation=None,
                             train_steps_per_epoch=None, shuffle_seed=0,
                             verbose=0, train_path=None,
                             feature_dtype="float32", label_dtype=None,
                             compression=None):
    """Train one rank's shard; the executor body of
    ``LightningEstimator.fit`` (reference:
    horovod/spark/lightning/remote.py:100 ``train``). Returns
    {'loss': [...], 'val_loss': [...]} averaged across ranks; rank 0
    checkpoints the module to the store."""
    import torch

    import horovod_tpu.torch as hvd
    from .data import ParquetShard, shard_files

    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    store = Store.create(store_prefix)
    train_path = train_path or store.get_train_data_path()
    files = shard_files(store.list_parquet_files(train_path), rank, size)
    cols = list(feature_cols) + list(label_cols)
    shard = ParquetShard(store, files, cols)

    module = deserialize_torch(module_bytes)
    optimizer, schedulers = _resolve_optimizers(module)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=module.named_parameters(),
        compression=compression)
    hvd.broadcast_parameters(module.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    n_rows = shard.num_rows
    val_batch = None
    if validation is not None:
        if not (isinstance(validation, float) and 0.0 < validation < 1.0):
            raise ValueError(
                f"validation must be a float in (0, 1) (got "
                f"{validation!r}); pre-split the DataFrame for "
                "indicator-column validation")
        val_rows = max(1, int(n_rows * validation))
        order = np.random.RandomState(shuffle_seed).permutation(n_rows)
        val_batch = {c: shard.columns[c][order[:val_rows]] for c in cols}
        shard.columns = {c: shard.columns[c][order[val_rows:]]
                         for c in cols}
        shard.num_rows -= val_rows
        n_rows -= val_rows

    if size > 1:
        n_rows = int(min(
            int(t) for t in hvd.allgather(
                torch.tensor([n_rows], dtype=torch.int64))))
    if n_rows == 0:
        # Raise on ALL ranks (the allgathered min is identical
        # everywhere) — see spark/torch.py on deadlock avoidance.
        raise ValueError(
            "a rank has 0 training rows after the validation split; "
            "repartition the dataset or lower the validation fraction")
    steps = train_steps_per_epoch or max(1, n_rows // batch_size)

    def to_batch(raw):
        xs = [torch.as_tensor(_stack_column(raw[c])).to(
            getattr(torch, feature_dtype)) for c in feature_cols]
        ys = []
        for c in label_cols:
            y = torch.as_tensor(_stack_column(raw[c]))
            if label_dtype is not None:
                y = y.to(getattr(torch, label_dtype))
            ys.append(y)
        return (xs[0] if len(xs) == 1 else xs,
                ys[0] if len(ys) == 1 else ys)

    # Async batch assembly overlapping the step (reference:
    # pytorch_data_loaders.py:71; see spark/data.py).
    from .data import AsyncShardBatchLoader
    loader = AsyncShardBatchLoader(shard=shard, batch_size=batch_size,
                                   steps=steps, transform=to_batch,
                                   seed=shuffle_seed + rank)
    history = {"loss": []}
    if val_batch is not None:
        history["val_loss"] = []

    module.train()
    global_step = 0
    try:
        for epoch in range(epochs):
            total = 0.0
            for batch in loader:
                optimizer.zero_grad()
                loss = _step_loss(module.training_step(batch, global_step))
                loss.backward()
                optimizer.step()
                total += float(loss.detach())
                global_step += 1
            for sched in schedulers:
                sched.step()
            avg = float(hvd.allreduce(
                torch.tensor([total / steps]), name=f"ep{epoch}.loss"))
            history["loss"].append(avg)
            if val_batch is not None:
                module.eval()
                n_val = len(next(iter(val_batch.values())))
                vl_sum, vl_n = 0.0, 0
                with torch.no_grad():
                    for start in range(0, n_val, batch_size):
                        chunk = {c: v[start:start + batch_size]
                                 for c, v in val_batch.items()}
                        vb = to_batch(chunk)
                        rows = len(next(iter(chunk.values())))
                        # Real pl.LightningModule defines a validation_step
                        # STUB returning None on the base class, so hasattr
                        # alone cannot detect an override — a None loss means
                        # "not implemented here", fall back to training_step.
                        vloss = None
                        if hasattr(module, "validation_step"):
                            out = module.validation_step(
                                vb, start // batch_size)
                            vloss = (out.get("loss")
                                     if isinstance(out, dict) else out)
                        if vloss is None:
                            vloss = _step_loss(module.training_step(
                                vb, start // batch_size))
                        vl_sum += float(vloss) * rows
                        vl_n += rows
                module.train()
                # `val_batch is not None` is replica-invariant: decided
                # by the `validation` argument (same on every rank),
                # with val_rows = max(1, ...) guaranteeing a non-None
                # val_batch on EVERY rank whenever validation is set.
                # hvd-lint: disable=HVD401
                history["val_loss"].append(float(hvd.allreduce(
                    torch.tensor([vl_sum / vl_n]), name=f"ep{epoch}.vloss")))
            if hasattr(module, "on_train_epoch_end"):
                module.on_train_epoch_end()
            if verbose and rank == 0:
                print(f"epoch {epoch}: " + ", ".join(
                    f"{k}={v[-1]:.4f}" for k, v in history.items()),
                    flush=True)

    finally:
        loader.close()
    if rank == 0:
        store.write(store.get_checkpoint_path(run_id),
                    serialize_torch(module))
    hvd.barrier()
    return history


class LightningModel:
    """Trained-module transformer (reference:
    horovod/spark/lightning/estimator.py TorchModel)."""

    def __init__(self, module_bytes, feature_cols, label_cols,
                 output_cols=None, feature_dtype="float32"):
        self.module_bytes = module_bytes
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.output_cols = list(
            output_cols or [f"{c}__output" for c in label_cols])
        self.feature_dtype = feature_dtype

    def lightning_module(self):
        return deserialize_torch(self.module_bytes)

    def predict(self, features):
        import torch
        module = self.lightning_module()
        module.eval()
        xs = [torch.as_tensor(_stack_column(np.asarray(f))).to(
            getattr(torch, self.feature_dtype)) for f in features]
        with torch.no_grad():
            out = np.asarray(module(xs[0] if len(xs) == 1 else xs))
        check_output_width(out.reshape(len(out), -1), self.output_cols)
        return out

    def transform(self, df):
        module_bytes = self.module_bytes
        feature_dtype = self.feature_dtype

        def make_predict():
            import torch
            module = deserialize_torch(module_bytes)
            module.eval()

            def predict(feats):
                xs = [torch.as_tensor(f).to(getattr(torch, feature_dtype))
                      for f in feats]
                with torch.no_grad():
                    return np.asarray(module(
                        xs[0] if len(xs) == 1 else xs))
            return predict

        return transform_with(df, self.feature_cols, self.output_cols,
                              make_predict)


class LightningEstimator:
    """Fit a LightningModule-protocol model to a Spark DataFrame over
    horovod_tpu ranks (reference:
    horovod/spark/lightning/estimator.py:100)."""

    def __init__(self, model=None, store=None, feature_cols=None,
                 label_cols=None, batch_size=32, epochs=1, num_proc=None,
                 validation=None, run_id=None,
                 train_steps_per_epoch=None, verbose=1,
                 feature_dtype="float32", label_dtype=None,
                 compression=None):
        if model is None or store is None:
            raise ValueError("LightningEstimator requires model= and "
                             "store=")
        for method in ("training_step", "configure_optimizers"):
            if not callable(getattr(model, method, None)):
                raise ValueError(
                    f"model must implement the LightningModule protocol; "
                    f"missing {method}()")
        if not feature_cols or not label_cols:
            raise ValueError("feature_cols and label_cols are required")
        self.model = model
        self.store = (store if isinstance(store, Store)
                      else Store.create(store))
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.validation = validation
        self.run_id = run_id or f"run_{uuid.uuid4().hex[:8]}"
        self.train_steps_per_epoch = train_steps_per_epoch
        self.verbose = verbose
        self.feature_dtype = feature_dtype
        self.label_dtype = label_dtype
        self.compression = compression

    def fit(self, df):
        require_pyspark("LightningEstimator.fit")
        from . import run as spark_run
        from pyspark import SparkContext

        sc = SparkContext.getOrCreate()
        num_proc = self.num_proc or sc.defaultParallelism
        materialize_df(df, self.store, num_proc)

        spark_run(
            fit_on_parquet_lightning, kwargs=dict(
                store_prefix=self.store.prefix_path,
                run_id=self.run_id,
                module_bytes=serialize_torch(self.model),
                feature_cols=self.feature_cols,
                label_cols=self.label_cols,
                batch_size=self.batch_size,
                epochs=self.epochs,
                validation=self.validation,
                train_steps_per_epoch=self.train_steps_per_epoch,
                verbose=self.verbose,
                feature_dtype=self.feature_dtype,
                label_dtype=self.label_dtype,
                compression=self.compression),
            num_proc=num_proc)
        return self.load(self.store, self.run_id,
                         feature_cols=self.feature_cols,
                         label_cols=self.label_cols,
                         feature_dtype=self.feature_dtype)

    @staticmethod
    def load(store, run_id, feature_cols, label_cols,
             feature_dtype="float32"):
        store = store if isinstance(store, Store) else Store.create(store)
        data = store.read(store.get_checkpoint_path(run_id))
        return LightningModel(data, feature_cols, label_cols,
                              feature_dtype=feature_dtype)
