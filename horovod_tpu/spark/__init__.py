"""Spark integration: run horovod_tpu training inside Spark executors
(reference: horovod/spark/runner.py:197 ``horovod.spark.run``).

Thin by design: Spark provides placement and the barrier stage; the
rendezvous and topology machinery is the shared cluster core
(runner/cluster.py). Requires pyspark (not bundled in TPU images — the
adapter gates with a clear error).

    import horovod_tpu.spark as hvd_spark
    results = hvd_spark.run(train_fn, args=(lr,), num_proc=4)
"""

from ..runner.cluster import ClusterJob, cluster_task_bootstrap


def _pyspark():
    try:
        import pyspark
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark, which is not installed "
            "in this environment (TPU images ship without Spark). "
            "`pip install pyspark` on a Spark cluster to use this "
            "integration.") from e


def run(fn, args=(), kwargs=None, num_proc=None, start_timeout=120,
        extra_env=None, verbose=True):
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` Spark executors as one
    horovod_tpu job; returns per-rank results ordered by rank
    (reference: horovod/spark/runner.py:197 ``run``)."""
    pyspark = _pyspark()
    from pyspark import BarrierTaskContext, SparkContext

    kwargs = kwargs or {}
    sc = SparkContext.getOrCreate()
    if num_proc is None:
        num_proc = sc.defaultParallelism
    if verbose:
        from ..utils.logging_util import get_logger
        get_logger().info("spark: launching %d-task barrier job", num_proc)
    job = ClusterJob(num_proc, start_timeout=start_timeout)
    task_args = job.task_args()
    env = dict(extra_env or {})

    def _task(_):
        import os
        os.environ.update(env)
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        n, addr, port, token, timeout = task_args
        cluster_task_bootstrap(rank, n, addr, port, token, timeout)
        result = fn(*args, **kwargs)
        return [(rank, result)]

    try:
        pairs = (sc.parallelize(range(num_proc), num_proc)
                 .barrier()
                 .mapPartitions(_task)
                 .collect())
    finally:
        job.shutdown()
    return [r for _, r in sorted(pairs)]


__all__ = ["run", "ClusterJob", "cluster_task_bootstrap"]
