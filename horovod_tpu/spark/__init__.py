"""Spark integration: run horovod_tpu training inside Spark executors
(reference: horovod/spark/runner.py:197 ``horovod.spark.run``), plus the
ML Estimator layer (``KerasEstimator``/``KerasModel``/``Store``,
reference: horovod/spark/keras/estimator.py:88 + common/store.py).

Spark provides placement and the barrier stage; rendezvous and topology
are the shared cluster core (runner/cluster.py), and the estimator's
training loop (``fit_on_parquet``) is Spark-free — only DataFrame
materialization and ``transform`` require pyspark (not bundled in TPU
images; those entry points gate with a clear error).

    import horovod_tpu.spark as hvd_spark
    results = hvd_spark.run(train_fn, args=(lr,), num_proc=4)

    est = hvd_spark.KerasEstimator(model=m, store=hvd_spark.Store.create(
        "/mnt/run"), loss="mse", feature_cols=["x"], label_cols=["y"])
    keras_model = est.fit(df)
"""

from ..runner.cluster import ClusterJob, cluster_task_bootstrap


def _pyspark():
    try:
        import pyspark
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark, which is not installed "
            "in this environment (TPU images ship without Spark). "
            "`pip install pyspark` on a Spark cluster to use this "
            "integration.") from e


def run(fn, args=(), kwargs=None, num_proc=None, start_timeout=120,
        extra_env=None, verbose=True):
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` Spark executors as one
    horovod_tpu job; returns per-rank results ordered by rank
    (reference: horovod/spark/runner.py:197 ``run``)."""
    pyspark = _pyspark()
    from pyspark import BarrierTaskContext, SparkContext

    kwargs = kwargs or {}
    sc = SparkContext.getOrCreate()
    if num_proc is None:
        num_proc = sc.defaultParallelism
    if verbose:
        from ..utils.logging_util import get_logger
        get_logger().info("spark: launching %d-task barrier job", num_proc)
    job = ClusterJob(num_proc, start_timeout=start_timeout)
    task_args = job.task_args()
    env = dict(extra_env or {})

    def _task(_):
        import os
        os.environ.update(env)
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        n, addr, port, token, timeout = task_args
        cluster_task_bootstrap(rank, n, addr, port, token, timeout)
        result = fn(*args, **kwargs)
        return [(rank, result)]

    try:
        pairs = (sc.parallelize(range(num_proc), num_proc)
                 .barrier()
                 .mapPartitions(_task)
                 .collect())
    finally:
        job.shutdown()
    return [r for _, r in sorted(pairs)]


__all__ = ["run", "ClusterJob", "cluster_task_bootstrap", "Store",
           "LocalStore", "KerasEstimator", "KerasModel", "fit_on_parquet",
           "TorchEstimator", "TorchModel", "fit_on_parquet_torch"]


def __getattr__(name):
    # Estimator/store symbols lazily: they pull fsspec/pyarrow/keras/
    # torch, which the plain run() path does not need (and which stay
    # optional dependencies — see pyproject optional-dependencies).
    if name in ("Store", "LocalStore"):
        from . import store as _store_mod
        return getattr(_store_mod, name)
    if name in ("KerasEstimator", "KerasModel", "fit_on_parquet"):
        from . import keras as _keras_mod
        return getattr(_keras_mod, name)
    if name in ("TorchEstimator", "TorchModel", "fit_on_parquet_torch"):
        from . import torch as _torch_mod
        return getattr(_torch_mod, name)
    raise AttributeError(name)
