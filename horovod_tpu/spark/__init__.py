"""Spark integration: run horovod_tpu training inside Spark executors
(reference: horovod/spark/runner.py:197 ``horovod.spark.run``), plus the
ML Estimator layer (``KerasEstimator``/``KerasModel``/``Store``,
reference: horovod/spark/keras/estimator.py:88 + common/store.py).

Spark provides placement and the barrier stage; rendezvous and topology
are the shared cluster core (runner/cluster.py), and the estimator's
training loop (``fit_on_parquet``) is Spark-free — only DataFrame
materialization and ``transform`` require pyspark (not bundled in TPU
images; those entry points gate with a clear error).

    import horovod_tpu.spark as hvd_spark
    results = hvd_spark.run(train_fn, args=(lr,), num_proc=4)

    est = hvd_spark.KerasEstimator(model=m, store=hvd_spark.Store.create(
        "/mnt/run"), loss="mse", feature_cols=["x"], label_cols=["y"])
    keras_model = est.fit(df)
"""

from ..runner.cluster import ClusterJob, cluster_task_bootstrap


def _pyspark():
    try:
        import pyspark
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark, which is not installed "
            "in this environment (TPU images ship without Spark). "
            "`pip install pyspark` on a Spark cluster to use this "
            "integration.") from e


def run(fn, args=(), kwargs=None, num_proc=None, start_timeout=120,
        extra_env=None, verbose=True):
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` Spark executors as one
    horovod_tpu job; returns per-rank results ordered by rank
    (reference: horovod/spark/runner.py:197 ``run``)."""
    pyspark = _pyspark()
    from pyspark import BarrierTaskContext, SparkContext

    kwargs = kwargs or {}
    sc = SparkContext.getOrCreate()
    if num_proc is None:
        num_proc = sc.defaultParallelism
    if verbose:
        from ..utils.logging_util import get_logger
        get_logger().info("spark: launching %d-task barrier job", num_proc)
    job = ClusterJob(num_proc, start_timeout=start_timeout)
    task_args = job.task_args()
    env = dict(extra_env or {})

    def _task(_):
        import os
        os.environ.update(env)
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        n, addr, port, token, timeout = task_args
        cluster_task_bootstrap(rank, n, addr, port, token, timeout)
        result = fn(*args, **kwargs)
        return [(rank, result)]

    try:
        pairs = (sc.parallelize(range(num_proc), num_proc)
                 .barrier()
                 .mapPartitions(_task)
                 .collect())
    finally:
        job.shutdown()
    return [r for _, r in sorted(pairs)]


def _elastic_loop(run_stage, parallelism, num_proc=None, min_np=None,
                  max_np=None, stage_retries=3, log=None):
    """Between-stage elasticity engine (pyspark-free, unit-testable).

    ``run_stage(n)`` launches one barrier stage at n workers and returns
    its results; ``parallelism()`` reports the cluster's CURRENT
    capacity. A failed stage is relaunched at the new capacity, bounded
    to [min_np, max_np]; capacity below min_np aborts. This is the Spark
    mapping of the reference's elastic driver loop (reference:
    horovod/spark/runner.py:309 run_elastic): Spark's barrier stage pins
    the worker set, so membership changes happen at stage boundaries —
    Spark's dynamic allocation supplies the new workers, the relaunch
    supplies the re-rendezvous.
    """
    attempts = 0
    while True:
        avail = parallelism()
        n = min(x for x in (num_proc, max_np, avail) if x is not None)
        if min_np is not None and n < min_np:
            raise RuntimeError(
                f"cluster parallelism {avail} fell below min_np="
                f"{min_np}; aborting elastic job")
        try:
            return run_stage(n)
        except Exception as e:  # noqa: BLE001 — stage failure is the signal
            attempts += 1
            if attempts > stage_retries:
                raise
            if log is not None:
                log.warning(
                    "spark elastic: stage failed (%s); relaunching "
                    "(attempt %d/%d)", e, attempts, stage_retries)


def run_elastic(fn, args=(), kwargs=None, num_proc=None, min_np=None,
                max_np=None, start_timeout=120, extra_env=None,
                stage_retries=3, verbose=True):
    """Elastic analog of :func:`run` (reference:
    horovod/spark/runner.py:309 ``run_elastic``).

    Spark's execution model pins a barrier stage's worker set, so
    elasticity maps to two layers:

    1. **In-stage**: wrap your training loop with
       ``horovod_tpu.elastic.run`` (State commit/restore) exactly as in a
       non-Spark elastic job — worker-side transient failures restore
       from the last commit without losing the stage.
    2. **Between stages** (this function): a failed stage is relaunched
       at the cluster's *current* parallelism, bounded to
       [min_np, max_np] — lost executors shrink the next attempt, Spark
       dynamic allocation can grow it back.

    ``fn`` runs under the same contract as :func:`run`.
    """
    _pyspark()
    from pyspark import SparkContext

    log = None
    if verbose:
        from ..utils.logging_util import get_logger
        log = get_logger()

    def parallelism():
        return SparkContext.getOrCreate().defaultParallelism

    def run_stage(n):
        return run(fn, args=args, kwargs=kwargs, num_proc=n,
                   start_timeout=start_timeout, extra_env=extra_env,
                   verbose=verbose)

    return _elastic_loop(run_stage, parallelism, num_proc=num_proc,
                         min_np=min_np, max_np=max_np,
                         stage_retries=stage_retries, log=log)


__all__ = ["run", "run_elastic", "ClusterJob", "cluster_task_bootstrap",
           "Store", "LocalStore", "KerasEstimator", "KerasModel",
           "fit_on_parquet", "TorchEstimator", "TorchModel",
           "fit_on_parquet_torch"]


def __getattr__(name):
    # Estimator/store symbols lazily: they pull fsspec/pyarrow/keras/
    # torch, which the plain run() path does not need (and which stay
    # optional dependencies — see pyproject optional-dependencies).
    if name in ("Store", "LocalStore"):
        from . import store as _store_mod
        return getattr(_store_mod, name)
    if name in ("KerasEstimator", "KerasModel", "fit_on_parquet"):
        from . import keras as _keras_mod
        return getattr(_keras_mod, name)
    if name in ("TorchEstimator", "TorchModel", "fit_on_parquet_torch"):
        from . import torch as _torch_mod
        return getattr(_torch_mod, name)
    raise AttributeError(name)
