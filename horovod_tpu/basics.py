"""Runtime singleton: topology discovery, mesh construction, init/shutdown.

Design (TPU-first rethink of the reference's HorovodGlobalState +
InitializeHorovodOnce, reference: horovod/common/operations.cc:811,
horovod/common/global_state.h):

The reference runs one process per accelerator and negotiates collectives
between processes over MPI/Gloo. On TPU the natural unit is a **device mesh**
driven by one controller process per host (or one for the whole slice), with
collectives compiled by XLA onto ICI. This runtime therefore supports two
execution modes:

- ``single`` (single-controller): one Python process owns all visible TPU
  chips. Every chip is a *virtual rank*: ``size()`` is the chip count, eager
  collectives operate on arrays stacked along a leading virtual-rank axis and
  lower to one jitted XLA collective over the 1-D replica mesh. This is the
  primary TPU path — the data plane is entirely compiled, the coordination
  machinery only batches and orders work.

- ``spmd`` (launcher-spawned): N processes, Horovod-identical semantics.
  ``rank()``/``size()`` come from launcher env vars (analog of
  HOROVOD_RANK/SIZE, reference: horovod/runner/gloo_run.py:65-77), and the
  eager data plane runs over the TCP backend (CPU fallback, gloo-analog) or
  the global XLA backend (multi-host TPU via jax.distributed).
"""

import atexit
import os
import threading

import jax
import numpy as np

from .exceptions import NotInitializedError
from .utils import envparse
from .utils.logging_util import get_logger

MODE_SINGLE = "single"
MODE_SPMD = "spmd"

# JAX site plugins known to force-select themselves into jax_platforms at
# import time (see init()); module-level so deployments under a new
# force-selecting plugin can extend it without editing init logic.
FORCED_PLATFORM_MARKERS = ("axon",)


class Topology:
    """Process-level topology (reference: rank/size/local/cross getters,
    horovod/common/basics.py:183-264)."""

    def __init__(self, rank, size, local_rank, local_size, cross_rank,
                 cross_size):
        self.rank = rank
        self.size = size
        self.local_rank = local_rank
        self.local_size = local_size
        self.cross_rank = cross_rank
        self.cross_size = cross_size

    @classmethod
    def from_env(cls):
        rank = envparse.get_int(envparse.RANK, 0)
        size = envparse.get_int(envparse.SIZE, 1)
        local_rank = envparse.get_int(envparse.LOCAL_RANK, rank)
        local_size = envparse.get_int(envparse.LOCAL_SIZE, size)
        cross_rank = envparse.get_int(envparse.CROSS_RANK, 0)
        cross_size = envparse.get_int(envparse.CROSS_SIZE, 1)
        return cls(rank, size, local_rank, local_size, cross_rank, cross_size)


class Runtime:
    """Owns topology, mesh, backend, coordinator and process-set table."""

    def __init__(self, mode, topology, backend, mesh, devices):
        self.mode = mode
        self.topology = topology
        self.backend = backend
        self.mesh = mesh            # 1-D jax Mesh over the replica axis 'hvd'
        self.devices = devices      # list of jax devices backing the mesh
        self.process_set_table = None   # attached by process_sets._setup
        self.coordinator = None         # attached by coordinator.start
        self.timeline = None            # attached by timeline module on demand
        self.autotuner = None
        self.metrics_pusher = None      # telemetry.MetricsPusher (SPMD)
        self.tracer = None              # tracing.Tracer (set by Coordinator)
        self._shutdown = False

    @property
    def size(self):
        if self.mode == MODE_SINGLE:
            return len(self.devices)
        return self.topology.size

    @property
    def rank(self):
        return self.topology.rank

    def check_alive(self):
        if self._shutdown:
            raise NotInitializedError("Runtime was shut down; operations")


_runtime = None
_init_lock = threading.Lock()


def _select_devices():
    """All addressable devices form the replica mesh."""
    return list(jax.local_devices())


def _make_replica_mesh(devices):
    return jax.sharding.Mesh(np.array(devices), ("hvd",))


def init(comm=None, process_sets=None):
    """Initialize the runtime (idempotent; reference: horovod_init,
    horovod/common/operations.cc:889).

    Args:
      comm: ignored (MPI communicators do not exist on TPU); accepted for
        signature compatibility with the reference.
      process_sets: optional list of ProcessSet objects to materialize at
        startup (reference: horovod/common/basics.py:48 ``init`` takes
        process_sets).
    """
    global _runtime
    with _init_lock:
        if _runtime is not None and not _runtime._shutdown:
            # Re-sync process sets like the reference's re-init path.
            from . import process_sets as ps_mod
            ps_mod._setup(_runtime, process_sets or [])
            return _runtime

        # Fresh runtime: auto-name counters restart with it so ranks
        # that re-init (elastic restart) agree on generated names.
        from .ops.collectives import reset_auto_name_counters
        reset_auto_name_counters()

        # Honor an EXPLICIT platform request: some site plugins
        # force-select themselves into jax_platforms at import time,
        # which would make every worker of a CPU-plane test job
        # initialize (and serialize on) the real chip. Only override
        # when the CURRENT config still carries a known plugin's self-
        # selection and the env asks for something else — a config the
        # program itself set (e.g. a conftest pinning cpu) wins. There
        # is no general way to tell plugin-set from program-set config,
        # so force-selecting plugins are listed in the module-level
        # FORCED_PLATFORM_MARKERS tuple.
        plat = os.environ.get("JAX_PLATFORMS")
        cur = getattr(jax.config, "jax_platforms", None) or ""
        if plat and any(m in cur and m not in plat
                        for m in FORCED_PLATFORM_MARKERS):
            try:
                jax.config.update("jax_platforms", plat)
            except Exception:  # noqa: BLE001 — backend already committed
                pass

        log = get_logger()
        if envparse.get_bool(envparse.ELASTIC):
            # Elastic workers are spawned WITHOUT rank env: ranks come from
            # the driver's latest membership version via the rendezvous
            # store, so a re-init after a reset lands in the new cohort
            # (reference: horovod/runner/elastic/rendezvous.py:28-60).
            from .runner import rendezvous as rdv
            if rdv.rendezvous_config() is not None:
                rdv.elastic_bootstrap()
                # Liveness lease: one background beat thread for the
                # whole process lifetime (re-inits must not stop it — a
                # worker mid-reset is alive; docs/fault_tolerance.md).
                from .runner import heartbeat
                heartbeat.start_worker_heartbeat()
        topology = Topology.from_env()
        spmd = (envparse.get_env(envparse.SIZE) is not None
                and envparse.get_env(envparse.RANK) is not None)
        if spmd and (topology.size < 1
                     or not 0 <= topology.rank < topology.size):
            raise ValueError(
                f"Invalid launcher topology: rank={topology.rank} "
                f"size={topology.size}")

        if spmd:
            from .backend import make_spmd_backend
            backend = make_spmd_backend(topology)
            devices = _select_devices()
            mesh = _make_replica_mesh(devices[:1])
            runtime = Runtime(MODE_SPMD, topology, backend, mesh, devices)
            log.info("init: spmd mode rank=%d size=%d backend=%s",
                     topology.rank, topology.size, backend.name)
        else:
            from .backend.xla_backend import XlaSingleBackend
            devices = _select_devices()
            mesh = _make_replica_mesh(devices)
            backend = XlaSingleBackend(mesh)
            runtime = Runtime(MODE_SINGLE, topology, backend, mesh, devices)
            log.info("init: single-controller mode, %d device(s) on mesh",
                     len(devices))

        from . import process_sets as ps_mod
        ps_mod._setup(runtime, process_sets or [])

        from .coordinator import Coordinator
        runtime.coordinator = Coordinator(runtime)
        runtime.coordinator.start()

        if envparse.get_bool(envparse.AUTOTUNE):
            from .autotune import ParameterManager
            runtime.autotuner = ParameterManager(runtime)
        else:
            # Tuned overlay values deliberately survive elastic
            # re-inits (the new cohort's tuner re-validates them), but
            # an init WITHOUT a tuner has nothing to re-validate: drop
            # them so a stale tuned value from an earlier job in this
            # process can't shadow the explicit env knobs. sys.modules
            # guard keeps the disabled path import-free.
            import sys as _sys
            overlay_mod = _sys.modules.get(
                "horovod_tpu.autotune.overlay")
            if overlay_mod is not None and overlay_mod.snapshot():
                overlay_mod.clear()

        timeline_path = envparse.get_str(envparse.TIMELINE, "")
        if timeline_path:
            from .timeline import Timeline
            runtime.timeline = Timeline(
                timeline_path,
                mark_cycles=envparse.get_bool(
                    envparse.TIMELINE_MARK_CYCLES))
            runtime.timeline.start()

        # Metrics plane (docs/metrics.md): when the job has a launcher
        # rendezvous, push this rank's snapshot to the driver KV store
        # on a timer so its /metrics route can serve the cluster roll-up.
        if envparse.get_bool(envparse.METRICS):
            from .runner import rendezvous as rdv
            from .telemetry import MetricsPusher
            cfg = rdv.rendezvous_config()
            if cfg is not None:
                addr, port, token = cfg
                runtime.metrics_pusher = MetricsPusher(
                    addr, port, token, topology.rank,
                    interval_s=envparse.get_float(
                        envparse.METRICS_PUSH_INTERVAL, 5.0)).start()

        _runtime = runtime
        return _runtime


def shutdown():
    """Tear down the runtime (reference: horovod_shutdown,
    horovod/common/operations.cc)."""
    global _runtime
    with _init_lock:
        if _runtime is None:
            return
        if _runtime.coordinator is not None:
            _runtime.coordinator.stop()
        if _runtime.timeline is not None:
            _runtime.timeline.stop()
        if _runtime.tracer is not None:
            # Flush + close this cohort's trace shard and push it to the
            # driver KV store (docs/tracing.md); an elastic re-init then
            # opens a fresh shard under the new membership version.
            _runtime.tracer.close()
            from . import tracing
            if tracing.active() is _runtime.tracer:
                tracing._set_active(None)
            _runtime.tracer = None
        if _runtime.metrics_pusher is not None:
            # Final push so shutdown-time counters (elastic restarts)
            # reach the driver before the store loses this rank.
            _runtime.metrics_pusher.stop()
            _runtime.metrics_pusher = None
        _maybe_dump_metrics()
        if _runtime.backend is not None:
            _runtime.backend.close()
        from . import process_sets as ps_mod
        ps_mod._teardown(_runtime)
        _runtime._shutdown = True
        _runtime = None
        # hvd-sanitize thread-leak audit (no-op when HVDTPU_SANITIZE is
        # off): name every non-daemon thread that survived teardown —
        # each one keeps the interpreter from exiting.
        from .analysis import sanitizer
        sanitizer.audit_shutdown()


def _maybe_dump_metrics():
    """Write a final JSON snapshot to HVDTPU_METRICS_DUMP (if set) —
    the file `hvd-metrics diff` consumes and bench.py archives."""
    path = envparse.get_str(envparse.METRICS_DUMP, "")
    if not path or not envparse.get_bool(envparse.METRICS):
        return
    from . import telemetry
    try:
        with open(path, "w") as f:
            f.write(telemetry.render_json(metrics_snapshot(), indent=1))
    except OSError as exc:
        get_logger().warning("could not write metrics dump %s: %s",
                             path, exc)


def metrics_snapshot():
    """JSON-able snapshot of the metrics registry (docs/metrics.md),
    with rank/size/mode context when the runtime is up. Families are
    empty unless HOROVOD_TPU_METRICS is on."""
    from . import telemetry
    snap = telemetry.snapshot()
    if _runtime is not None and not _runtime._shutdown:
        snap["rank"] = _runtime.topology.rank
        snap["size"] = _runtime.size
        snap["mode"] = _runtime.mode
    return snap


atexit.register(shutdown)


def is_initialized():
    return _runtime is not None and not _runtime._shutdown


def runtime():
    if _runtime is None or _runtime._shutdown:
        raise NotInitializedError()
    return _runtime


def rank():
    return runtime().topology.rank


def size():
    return runtime().size


def local_rank():
    return runtime().topology.local_rank


def local_size():
    rt = runtime()
    if rt.mode == MODE_SINGLE:
        return len(rt.devices)
    return rt.topology.local_size


def cross_rank():
    return runtime().topology.cross_rank


def cross_size():
    return runtime().topology.cross_size


def mesh():
    """The 1-D replica mesh (axis name 'hvd') for in-jit collectives."""
    return runtime().mesh


def is_homogeneous():
    """True when every host has the same number of slots (reference:
    horovod_is_homogeneous, horovod/common/operations.cc)."""
    rt = runtime()
    if rt.mode == MODE_SINGLE:
        return True
    return rt.topology.size == rt.topology.local_size * rt.topology.cross_size


# Build-feature queries: kept for API parity with the reference
# (horovod/torch/mpi_ops.py:55-63). On TPU the data plane is XLA.
def mpi_enabled():
    return False


def mpi_built():
    return False


def gloo_enabled():
    return gloo_built()


def gloo_built():
    # Our TCP backend is the gloo-analog CPU data plane; report it built
    # only if the module actually imports.
    try:
        from .backend import tcp_backend  # noqa: F401
        return True
    except ImportError:
        return False


def nccl_built():
    return False


def ddl_built():
    return False


def ccl_built():
    return False


def cuda_built():
    return False


def rocm_built():
    return False


def xla_built():
    return True


def mpi_threads_supported():
    return False
