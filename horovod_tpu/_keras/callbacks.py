"""Keras callback set (reference: horovod/_keras/callbacks.py:200):
weight broadcast at train start, cross-rank metric averaging, LR warmup
and schedules. Backend-agnostic — weights move as numpy lists through the
process-level collectives.
"""

import numpy as np

from . import rank, size, spmd_active
from ..functions import broadcast_variables as _bv
from ..ops import collectives as _c


def _keras():
    import keras
    return keras


def _callback_base():
    return _keras().callbacks.Callback


class BroadcastGlobalVariablesCallbackImpl:
    """Broadcast initial model + optimizer state from root_rank so all
    ranks start identical (reference: callbacks.py
    BroadcastGlobalVariablesCallback)."""

    def __init__(self, root_rank=0):
        self.root_rank = root_rank
        self._done = False

    def on_train_begin(self, logs=None):
        if self._done or not spmd_active():
            return
        model = self.model
        weights = model.get_weights()
        synced = _bv(weights, root_rank=self.root_rank)
        model.set_weights([np.asarray(w) for w in synced])
        self._done = True


class MetricAverageCallbackImpl:
    """Average epoch metrics across ranks (reference: callbacks.py
    MetricAverageCallback)."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs or not spmd_active():
            return
        keys = sorted(k for k, v in logs.items()
                      if isinstance(v, (int, float, np.floating,
                                        np.integer)))
        if not keys:
            return
        vec = np.asarray([float(logs[k]) for k in keys], dtype=np.float64)
        avg = np.asarray(_c.allreduce(vec, name=f"metric_avg.{epoch}"))
        for k, v in zip(keys, avg):
            logs[k] = float(v)


class LearningRateWarmupCallbackImpl:
    """Scale LR from initial_lr/size .. initial_lr over warmup_epochs
    (reference: callbacks.py LearningRateWarmupCallback — gradual warmup
    per Goyal et al. 2017)."""

    def __init__(self, initial_lr, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0):
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self._current_epoch = 0

    def _set_lr(self, lr):
        opt = self.model.optimizer
        try:
            opt.learning_rate = lr
        except AttributeError:
            opt.lr = lr

    def on_epoch_begin(self, epoch, logs=None):
        self._current_epoch = epoch
        if epoch >= self.warmup_epochs:
            return
        # Linear ramp from initial_lr (end of warmup) / size to full.
        base = self.initial_lr / max(1, size())
        progress = (epoch + 1) / self.warmup_epochs
        lr = base + (self.initial_lr - base) * progress
        self._set_lr(lr)
        if self.verbose and rank() == 0:
            print(f"Epoch {epoch}: warmup LR = {lr:.6g}")


class LearningRateScheduleCallbackImpl:
    """Multiply LR by ``multiplier`` within [start_epoch, end_epoch)
    (reference: callbacks.py LearningRateScheduleCallback)."""

    def __init__(self, initial_lr, multiplier, start_epoch=0,
                 end_epoch=None, staircase=True, momentum_correction=True,
                 steps_per_epoch=None, verbose=0):
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.verbose = verbose
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def on_epoch_begin(self, epoch, logs=None):
        if epoch < self.start_epoch:
            return
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return
        lr = self.initial_lr * self.multiplier(epoch)
        opt = self.model.optimizer
        try:
            opt.learning_rate = lr
        except AttributeError:
            opt.lr = lr
        if self.verbose and rank() == 0:
            print(f"Epoch {epoch}: scheduled LR = {lr:.6g}")


def make_callbacks():
    """Bind the impl mixins to the installed keras' Callback base (late so
    importing horovod_tpu never imports keras)."""
    base = _callback_base()

    class BroadcastGlobalVariablesCallback(BroadcastGlobalVariablesCallbackImpl,
                                           base):
        def __init__(self, root_rank=0):
            base.__init__(self)
            BroadcastGlobalVariablesCallbackImpl.__init__(self, root_rank)

    class MetricAverageCallback(MetricAverageCallbackImpl, base):
        def __init__(self):
            base.__init__(self)

    class LearningRateWarmupCallback(LearningRateWarmupCallbackImpl, base):
        def __init__(self, *a, **kw):
            base.__init__(self)
            LearningRateWarmupCallbackImpl.__init__(self, *a, **kw)

    class LearningRateScheduleCallback(LearningRateScheduleCallbackImpl,
                                       base):
        def __init__(self, *a, **kw):
            base.__init__(self)
            LearningRateScheduleCallbackImpl.__init__(self, *a, **kw)

    return (BroadcastGlobalVariablesCallback, MetricAverageCallback,
            LearningRateWarmupCallback, LearningRateScheduleCallback)
