"""Keras elastic callbacks (reference: horovod/_keras/elastic.py:86):
commit state on a batch cadence and keep epoch/batch counters inside the
elastic State so training resumes where it left off."""


def make_elastic_callbacks():
    import keras

    base = keras.callbacks.Callback

    class CommitStateCallback(base):
        """Commit the elastic state every ``batches_per_commit`` batches
        (reference: CommitStateCallbackImpl)."""

        def __init__(self, state, batches_per_commit=1):
            super().__init__()
            self.state = state
            self.batches_per_commit = batches_per_commit

        def on_train_batch_end(self, batch, logs=None):
            if (batch + 1) % self.batches_per_commit == 0:
                self.state.commit()

        def on_epoch_end(self, epoch, logs=None):
            self.state.commit()

    class UpdateBatchStateCallback(base):
        """Track state.batch so a restore resumes mid-epoch (reference:
        UpdateBatchStateCallbackImpl)."""

        def __init__(self, state):
            super().__init__()
            self.state = state

        def on_train_batch_end(self, batch, logs=None):
            self.state.batch = batch + 1

        def on_epoch_end(self, epoch, logs=None):
            self.state.batch = 0

    class UpdateEpochStateCallback(base):
        """Track state.epoch (reference: UpdateEpochStateCallbackImpl)."""

        def __init__(self, state):
            super().__init__()
            self.state = state

        def on_epoch_end(self, epoch, logs=None):
            self.state.epoch = epoch + 1

    return (CommitStateCallback, UpdateBatchStateCallback,
            UpdateEpochStateCallback)
