"""Shared Keras-binding implementation (reference:
horovod/_keras/__init__.py:207). Keras 3 is multi-backend; the wrapper
synchronizes gradients through whichever plane matches how the step runs:

- **jax backend, compiled (the TPU path)**: with a keras distribution
  active (``horovod_tpu.keras.set_data_parallel``) the jitted train step
  is ONE XLA program over the device mesh — the batch is sharded, the
  variables are replicated, and XLA's SPMD partitioner lowers the gradient
  reduction natively into the program. The wrapper's sync is an identity
  there by design: this is the TPU-native answer to the reference's
  XLA custom-call bridge (reference:
  horovod/tensorflow/xla_mpi_ops.cc:174-232), with no host round-trip.
- **tensorflow backend**: symbolic grads route through the TF binding's
  py_function bridge.
- **eager backends (torch / jax-eager)**: concrete grads ride the
  process-level grouped-allreduce plane.

Local gradient aggregation (``backward_passes_per_step``) delegates to
Keras 3's native ``gradient_accumulation_steps`` engine, which is
cond-based and therefore graph-safe on every backend (the reference's
graph-state design: horovod/tensorflow/gradient_aggregation.py:16).
"""

import numpy as np

from .. import basics
from ..ops import collectives as _c
from ..ops import reduce_ops
from ..utils.logging_util import get_logger


def spmd_active():
    rt = basics.runtime()
    return rt.mode == basics.MODE_SPMD and rt.topology.size > 1


def rank():
    return basics.runtime().topology.rank


def size():
    return basics.runtime().topology.size


# One bucket-split algorithm for every frontend's sync plane.
from ..ops.collectives import fusion_buckets as _buckets  # noqa: E402

# One-time note for the explicit IndexedSlices densification (the
# sparse plane routes them instead when HVDTPU_SPARSE is set).
_warned_sparse = [False]


def _reduce_numpy_grads(grads, op, prescale, postscale, name,
                        compression=None, num_groups=0):
    """Grouped allreduce over a list of numpy arrays (None passthrough)."""
    from ..ops.compression import Compression
    dense_idx = [i for i, g in enumerate(grads) if g is not None]
    dense = [np.asarray(grads[i]) for i in dense_idx]
    if not dense:
        return grads
    result = list(grads)
    for b, bucket in enumerate(_buckets(len(dense), num_groups)):
        outs = _c.grouped_allreduce(
            [dense[j] for j in bucket], op=op, name=f"{name}.g{b}",
            compression=compression or Compression.none,
            prescale_factor=prescale, postscale_factor=postscale)
        for j, o in zip(bucket, outs):
            result[dense_idx[j]] = np.asarray(o)
    return result


def _any_jax_tracer(grads):
    import jax
    return any(isinstance(g, jax.core.Tracer)
               for g in grads if g is not None)


def create_distributed_optimizer(keras, optimizer, name=None,
                                 op=reduce_ops.Average,
                                 gradient_predivide_factor=1.0,
                                 backward_passes_per_step=1,
                                 average_aggregated_gradients=True,
                                 compression=None, num_groups=0):
    """Dynamic subclass of the optimizer whose apply() averages gradients
    across ranks first (reference: horovod/_keras/__init__.py:36
    create_distributed_optimizer).

    ``backward_passes_per_step > 1`` enables local gradient aggregation via
    Keras's native ``gradient_accumulation_steps`` (cond-based, graph-safe):
    the parameter update runs every k-th ``apply``. Rank-sync happens per
    micro-batch — for the linear Sum/Average reductions this is
    mathematically identical to the reference's aggregate-then-reduce
    (reference: horovod/tensorflow/gradient_aggregation.py:16); on the
    compiled jax path the sync is free (it lowers into the program), on the
    eager planes it trades the reference's comm saving for simplicity.
    ``average_aggregated_gradients=False`` applies the micro-batch *sum*
    (implemented by prescaling each micro-batch gradient by k so Keras's
    built-in /k division cancels).

    ``compression`` (Compression.fp16/bf16) shrinks the bytes each sync
    moves on the host/eager planes. On the compiled-mesh path
    (set_data_parallel + jax backend) the reduction is lowered natively
    by XLA inside the program — there is no host wire to compress, so
    compression has no effect there (use ICI-native bf16 gradients via
    model dtype policy instead).

    ``num_groups > 0`` bounds the per-sync fusion: the gradient list is
    split into that many contiguous buckets, one grouped collective
    each (the reference's num_groups split) — on the host planes this
    caps the transient fused-buffer size per collective. 0 (default)
    fuses each apply into a single grouped collective.
    """
    k = int(backward_passes_per_step)
    if k < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    # average_aggregated_gradients only has meaning for k > 1; normalize
    # it out of the settings tuple at k == 1 so the re-wrap guard does
    # not reject an equivalent wrap over a no-effect default difference
    # (horovod_tpu.keras defaults True, the tensorflow.keras namespace
    # mirrors the reference's False).
    requested = (op, gradient_predivide_factor, k,
                 average_aggregated_gradients if k > 1 else None,
                 compression, num_groups)
    if getattr(optimizer, "_hvd_wrapped", False):
        # Idempotent when the settings match: the wrapper is named after
        # the wrapped class (for serialization), so users cannot tell an
        # already-wrapped optimizer apart — e.g. after hvd.load_model —
        # and re-wrapping would sync every gradient twice. A re-wrap
        # with DIFFERENT settings cannot be honored (the existing
        # wrapper's closure keeps its own) — fail loudly, like the torch
        # binding's double-wrap error.
        if getattr(optimizer, "_hvd_settings", None) != requested:
            raise ValueError(
                "optimizer is already wrapped by DistributedOptimizer "
                "(e.g. by hvd.load_model) with different settings "
                f"({optimizer._hvd_settings} vs requested {requested}); "
                "rebuild the optimizer from its config and wrap once.")
        return optimizer
    if k > 1:
        if op == reduce_ops.Adasum:
            raise ValueError(
                "backward_passes_per_step > 1 with Adasum is unsupported: "
                "Adasum is nonlinear, so per-micro-batch reduction is not "
                "equivalent to aggregate-then-Adasum. Aggregate in the "
                "training loop instead.")
        if getattr(optimizer, "built", False):
            raise ValueError(
                "backward_passes_per_step > 1 requires wrapping the "
                "optimizer before it is built (accumulator slots are "
                "created at build time).")
        current = getattr(optimizer, "gradient_accumulation_steps", None)
        if current not in (None, k):
            raise ValueError(
                f"optimizer already has gradient_accumulation_steps="
                f"{current}, conflicting with backward_passes_per_step={k}")
        optimizer.gradient_accumulation_steps = k
    cls = type(optimizer)
    backend = keras.backend.backend()
    log = get_logger()

    def _sync(grads):
        if not spmd_active():
            # Single-controller mode: under a keras distribution
            # (set_data_parallel) the jitted step is one XLA program over
            # the mesh and the partitioner inserts the reduction; without
            # one, world size is 1. Either way: identity.
            return grads
        if backend == "tensorflow":
            # Symbolic under tf.function: route through the TF binding's
            # py_function bridge. None grads (unused variables) pass
            # through untouched.
            from .. import tensorflow as hvd_tf
            from ..ops import sparse as sparse_ops
            tf_mod = hvd_tf._tf()
            result = list(grads)
            grads = list(grads)
            routed = set()
            for i, g in enumerate(grads):
                if not isinstance(g, tf_mod.IndexedSlices):
                    continue
                # Explicit sparse handling (never the old implicit
                # densify inside the numpy marshal): with the sparse
                # plane on, embedding grads ride it; otherwise densify
                # HERE, visibly, with a one-time note.
                if sparse_ops.enabled():
                    result[i] = hvd_tf._sparse_allreduce_tf(
                        g, op, f"keras_grads.sp{i}",
                        hvd_tf.global_process_set)
                    routed.add(i)
                else:
                    if not _warned_sparse[0]:
                        _warned_sparse[0] = True
                        log.info(
                            "keras DistributedOptimizer: IndexedSlices "
                            "gradients densify before the sync; set "
                            "HVDTPU_SPARSE for the sparse gather plane "
                            "(docs/sparse.md)")
                    grads[i] = tf_mod.convert_to_tensor(g)
            dense_idx = [i for i, g in enumerate(grads)
                         if g is not None and i not in routed]
            if not dense_idx:
                return result
            for b, bucket in enumerate(_buckets(len(dense_idx),
                                                num_groups)):
                outs = hvd_tf.grouped_allreduce(
                    [grads[dense_idx[j]] for j in bucket], op=op,
                    name=f"keras_grads.g{b}",
                    compression=compression,
                    prescale_factor=(1.0 / gradient_predivide_factor
                                     if gradient_predivide_factor != 1.0
                                     else 1.0),
                    postscale_factor=(gradient_predivide_factor
                                      if gradient_predivide_factor != 1.0
                                      else 1.0))
                for j, o in zip(bucket, outs):
                    result[dense_idx[j]] = o
            return result
        if backend == "jax" and _any_jax_tracer(grads):
            # Jitted train step in multi-process SPMD mode. Only when the
            # processes share the jax.distributed global mesh does a keras
            # distribution make the step one global-SPMD program whose
            # partitioner already reduces the gradients — identity then.
            # A distribution over process-LOCAL devices on the TCP plane
            # would be a silent no-sync (each process training alone), so
            # it does NOT earn the identity: fail with guidance instead.
            rt = basics.runtime()
            if (keras.distribution.distribution() is not None
                    and getattr(rt.backend, "global_mesh_spmd", False)):
                return grads
            raise RuntimeError(
                "DistributedOptimizer cannot sync gradients inside a "
                "jit-compiled keras train step over the host (TCP) data "
                "plane. Either run the job on the jax.distributed global "
                "mesh (HVDTPU_CPU_OPERATIONS=xla) with "
                "horovod_tpu.keras.set_data_parallel() — collectives "
                "then lower into the XLA program — or compile the model "
                "with run_eagerly=True.")
        np_grads = [None if g is None
                    else np.asarray(keras.ops.convert_to_numpy(g))
                    for g in grads]
        outs = _reduce_numpy_grads(
            np_grads, op,
            1.0 / gradient_predivide_factor
            if gradient_predivide_factor != 1.0 else 1.0,
            gradient_predivide_factor
            if gradient_predivide_factor != 1.0 else 1.0,
            "keras_grads", compression=compression,
            num_groups=num_groups)
        return [None if o is None else keras.ops.convert_to_tensor(o)
                for o in outs]

    unaveraged = k > 1 and not average_aggregated_gradients

    def _prepare(grads):
        grads = _sync(list(grads))
        if unaveraged:
            # Keras's accumulation engine applies (sum g_i)/k; the
            # reference's average_aggregated_gradients=False applies the
            # raw sum — prescale each micro-batch gradient by k so the
            # division cancels.
            grads = [None if g is None else g * k for g in grads]
        return grads

    class _Distributed(cls):
        _hvd_wrapped = True

        # Only apply() is overridden: keras-3 BaseOptimizer routes every
        # entry point (apply_gradients, stateless_apply, the trainers)
        # through self.apply, so preparing there too would sync/prescale
        # each gradient twice.
        def apply(self, grads, trainable_variables=None, **kwargs):
            grads = _prepare(grads)
            return cls.apply(self, grads, trainable_variables, **kwargs)

    # Serialization round-trip: keras saves the optimizer under its class
    # name. Naming the wrapper after the wrapped class makes saved
    # configs say e.g. "SGD", which stock keras can deserialize —
    # load_model() then re-wraps (the reference's _keras/__init__.py
    # load-model trick works the same way).
    _Distributed.__name__ = cls.__name__
    _Distributed.__qualname__ = cls.__qualname__
    _Distributed.__module__ = cls.__module__
    optimizer.__class__ = _Distributed
    optimizer._hvd_settings = requested  # re-wrap guard compares these
    if spmd_active():
        log.info("keras DistributedOptimizer (%s backend) wrapping %s "
                 "over %d ranks", backend, cls.__name__, size())
    return optimizer
