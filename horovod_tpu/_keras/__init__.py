"""Shared Keras-binding implementation (reference:
horovod/_keras/__init__.py:207). Keras 3 is multi-backend; gradients are
synchronized through the process-level SPMD plane regardless of which
backend (tensorflow / torch / jax-eager) computes them. The jit-compiled
keras-on-jax path belongs to ``horovod_tpu.jax`` (in-jit psum) instead —
a host-side eager collective cannot run inside a jitted train step.
"""

import numpy as np

from .. import basics
from ..ops import collectives as _c
from ..ops import reduce_ops
from ..utils.logging_util import get_logger


def spmd_active():
    rt = basics.runtime()
    return rt.mode == basics.MODE_SPMD and rt.topology.size > 1


def rank():
    return basics.runtime().topology.rank


def size():
    return basics.runtime().topology.size


def _reduce_numpy_grads(grads, op, prescale, postscale, name):
    """Grouped allreduce over a list of numpy arrays (None passthrough)."""
    dense_idx = [i for i, g in enumerate(grads) if g is not None]
    dense = [np.asarray(grads[i]) for i in dense_idx]
    if not dense:
        return grads
    outs = _c.grouped_allreduce(dense, op=op, name=name,
                                prescale_factor=prescale,
                                postscale_factor=postscale)
    result = list(grads)
    for i, o in zip(dense_idx, outs):
        result[i] = np.asarray(o)
    return result


def create_distributed_optimizer(keras, optimizer, name=None,
                                 op=reduce_ops.Average,
                                 gradient_predivide_factor=1.0,
                                 backward_passes_per_step=1,
                                 average_aggregated_gradients=True):
    """Dynamic subclass of the optimizer whose apply() averages gradients
    across ranks first (reference: horovod/_keras/__init__.py:36
    create_distributed_optimizer)."""
    requested = (op, gradient_predivide_factor, backward_passes_per_step,
                 average_aggregated_gradients)
    if getattr(optimizer, "_hvd_wrapped", False):
        # Idempotent when the settings match: the wrapper is named after
        # the wrapped class (for serialization), so users cannot tell an
        # already-wrapped optimizer apart — e.g. after hvd.load_model —
        # and re-wrapping would sync every gradient twice. A re-wrap
        # with DIFFERENT settings cannot be honored (the existing
        # wrapper's closure keeps its own) — fail loudly, like the torch
        # binding's double-wrap error.
        if getattr(optimizer, "_hvd_settings", None) != requested:
            raise ValueError(
                "optimizer is already wrapped by DistributedOptimizer "
                "(e.g. by hvd.load_model) with different settings "
                f"({optimizer._hvd_settings} vs requested {requested}); "
                "rebuild the optimizer from its config and wrap once.")
        return optimizer
    cls = type(optimizer)
    backend = keras.backend.backend()
    log = get_logger()

    def _sync(grads):
        if not spmd_active():
            return grads
        if backend == "tensorflow":
            # Symbolic under tf.function: route through the TF binding's
            # py_function bridge. None grads (unused variables) pass
            # through untouched.
            from .. import tensorflow as hvd_tf
            dense_idx = [i for i, g in enumerate(grads) if g is not None]
            if not dense_idx:
                return grads
            outs = hvd_tf.grouped_allreduce(
                [grads[i] for i in dense_idx], op=op, name="keras_grads",
                prescale_factor=(1.0 / gradient_predivide_factor
                                 if gradient_predivide_factor != 1.0
                                 else 1.0),
                postscale_factor=(gradient_predivide_factor
                                  if gradient_predivide_factor != 1.0
                                  else 1.0))
            result = list(grads)
            for i, o in zip(dense_idx, outs):
                result[i] = o
            return result
        np_grads = [None if g is None
                    else np.asarray(keras.ops.convert_to_numpy(g))
                    for g in grads]
        outs = _reduce_numpy_grads(
            np_grads, op,
            1.0 / gradient_predivide_factor
            if gradient_predivide_factor != 1.0 else 1.0,
            gradient_predivide_factor
            if gradient_predivide_factor != 1.0 else 1.0,
            "keras_grads")
        return [None if o is None else keras.ops.convert_to_tensor(o)
                for o in outs]

    class _Distributed(cls):
        _hvd_wrapped = True

        def apply(self, grads, trainable_variables=None, **kwargs):
            grads = _sync(list(grads))
            return cls.apply(self, grads, trainable_variables, **kwargs)

        def apply_gradients(self, grads_and_vars, **kwargs):
            gv = list(grads_and_vars)
            grads = _sync([g for g, _ in gv])
            return cls.apply_gradients(
                self, list(zip(grads, [v for _, v in gv])), **kwargs)

    # Serialization round-trip: keras saves the optimizer under its class
    # name. Naming the wrapper after the wrapped class makes saved
    # configs say e.g. "SGD", which stock keras can deserialize —
    # load_model() then re-wraps (the reference's _keras/__init__.py
    # load-model trick works the same way).
    _Distributed.__name__ = cls.__name__
    _Distributed.__qualname__ = cls.__qualname__
    _Distributed.__module__ = cls.__module__
    optimizer.__class__ = _Distributed
    optimizer._hvd_settings = requested  # re-wrap guard compares these
    if spmd_active():
        log.info("keras DistributedOptimizer (%s backend) wrapping %s "
                 "over %d ranks", backend, cls.__name__, size())
    return optimizer
