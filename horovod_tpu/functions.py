"""High-level convenience collectives on Python objects and parameter trees.

Mirrors the reference helpers (reference: horovod/torch/functions.py:269,
horovod/tensorflow/functions.py:66-177): object (de)serialization rides the
byte-tensor broadcast/allgather path; parameter-tree sync broadcasts every
leaf from a root rank in one grouped (fused) operation.

Object-level collectives operate at **process** granularity: in
single-controller mode there is exactly one process that owns all virtual
ranks, so object broadcast/allgather degenerate to identity/[obj] — the
model state is global by construction (the key simplification of the
single-controller TPU design).
"""

import io
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from . import basics
from .ops import reduce_ops
from .ops.collectives import (allgather, broadcast, grouped_allreduce,
                              synchronize, broadcast_async)
from .process_sets import global_process_set


def broadcast_object(obj, root_rank=0, name=None,
                     process_set=global_process_set):
    """Serialize and broadcast an arbitrary object from root_rank
    (reference: horovod/torch/functions.py broadcast_object — serialized
    bytes broadcast as a uint8 tensor preceded by its length)."""
    rt = basics.runtime()
    if rt.mode == basics.MODE_SINGLE:
        return obj
    name = name or "broadcast_object"
    if rt.topology.rank == root_rank:
        buf = io.BytesIO()
        pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
        payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
        length = np.array([payload.size], dtype=np.int64)
    else:
        payload = np.zeros(0, dtype=np.uint8)
        length = np.array([0], dtype=np.int64)
    length = np.asarray(broadcast(jnp.asarray(length), root_rank,
                                  name=f"{name}.len",
                                  process_set=process_set))
    if rt.topology.rank != root_rank:
        payload = np.zeros(int(length[0]), dtype=np.uint8)
    payload = np.asarray(broadcast(jnp.asarray(payload), root_rank,
                                   name=f"{name}.data",
                                   process_set=process_set))
    return pickle.loads(payload.tobytes())


def allgather_object(obj, name=None, process_set=global_process_set):
    """Gather arbitrary objects from every rank into a list (reference:
    horovod/tensorflow/functions.py:177 allgather_object)."""
    rt = basics.runtime()
    if rt.mode == basics.MODE_SINGLE:
        return [obj]
    name = name or "allgather_object"
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
    sizes = np.asarray(allgather(jnp.asarray(
        np.array([payload.size], dtype=np.int64)),
        name=f"{name}.sizes", process_set=process_set))
    gathered = np.asarray(allgather(jnp.asarray(payload),
                                    name=f"{name}.data",
                                    process_set=process_set))
    objs, off = [], 0
    for s in sizes:
        objs.append(pickle.loads(gathered[off:off + int(s)].tobytes()))
        off += int(s)
    return objs


def broadcast_variables(params, root_rank=0, process_set=global_process_set):
    """Broadcast every leaf of a parameter pytree from root_rank (reference:
    horovod/tensorflow/functions.py:66 broadcast_variables,
    horovod/torch/functions.py broadcast_parameters).

    Single-controller mode: parameters are a single global pytree already —
    returns them unchanged (there is no divergent replica copy to overwrite).
    SPMD mode: each leaf is broadcast, fused into as few collectives as the
    fusion threshold allows.
    """
    rt = basics.runtime()
    if rt.mode == basics.MODE_SINGLE:
        return params
    leaves, treedef = jax.tree.flatten(params)
    handles = [broadcast_async(jnp.asarray(leaf), root_rank,
                               name=f"broadcast_variables.{i}",
                               process_set=process_set)
               for i, leaf in enumerate(leaves)]
    out = [synchronize(h) for h in handles]
    return jax.tree.unflatten(treedef, out)


# Reference naming aliases (torch flavor).
broadcast_parameters = broadcast_variables


def broadcast_optimizer_state(opt_state, root_rank=0,
                              process_set=global_process_set):
    """Broadcast an optimizer-state pytree (reference:
    horovod/torch/functions.py broadcast_optimizer_state). Works for any
    optax state: non-array leaves ride the object path."""
    rt = basics.runtime()
    if rt.mode == basics.MODE_SINGLE:
        return opt_state

    def is_array(x):
        # Strings and other non-numeric scalars ride the object path.
        return isinstance(x, (jax.Array, np.ndarray, int, float, complex,
                              bool, np.number))

    leaves, treedef = jax.tree.flatten(opt_state)
    array_idx = [i for i, l in enumerate(leaves) if is_array(l)]
    obj_idx = [i for i, l in enumerate(leaves) if not is_array(l)]
    if array_idx:
        synced = broadcast_variables([jnp.asarray(leaves[i])
                                      for i in array_idx],
                                     root_rank, process_set)
        for i, v in zip(array_idx, synced):
            leaves[i] = v
    if obj_idx:
        objs = broadcast_object([leaves[i] for i in obj_idx], root_rank,
                                process_set=process_set)
        for i, v in zip(obj_idx, objs):
            leaves[i] = v
    return jax.tree.unflatten(treedef, leaves)
