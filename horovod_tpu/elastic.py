"""Elastic fault tolerance: worker-side state machine + retry loop.

The reference's signature capability (reference:
horovod/common/elastic.py:26-176): training state is committed in memory,
collective failures (``HorovodInternalError``) restore it, membership
changes (``HostsUpdatedInterrupt``) re-rendezvous, and in both cases the
runtime resets (``shutdown(); init()``) with new ranks served by the
driver's rendezvous, then ``state.sync()`` re-broadcasts from a surviving
rank. On TPU this is the preemptible-slice story: a preempted host drops
out, the remaining hosts shrink the job, and training resumes from the
last commit without restarting the process tree.

Membership-change notification is poll-based: the driver bumps an
``elastic/version`` counter in its KV store; ``state.check_host_updates``
compares it against the version this worker joined at (the reference pushes
notifications into an in-worker TCP service instead,
horovod/runner/elastic/worker.py:46 — a KV poll at commit granularity is
simpler and costs one HTTP GET per commit).
"""

import functools
import os
import signal
import threading
import time

from . import basics
from .chaos import inject as _chaos_inject
from .exceptions import (PREEMPT_EXIT_CODE, RESTART_EXIT_CODE,
                         CollectiveAbortError, HorovodInternalError,
                         HostsUpdatedInterrupt)
from .telemetry import core as telemetry
from .utils import envparse
from .utils.logging_util import get_logger


# Elastic events are rare (one per commit / failure / reset), so the
# counters resolve through the registry at call time — NULL no-ops when
# HOROVOD_TPU_METRICS is off (docs/metrics.md).
def _m_commits():
    return telemetry.counter("hvd_elastic_commits_total",
                             "State commits (restore points marked)")


def _m_failures():
    return telemetry.counter(
        "hvd_elastic_failures_total",
        "Elastic interruptions by cause", labelnames=("cause",))


def _m_restarts():
    return telemetry.counter(
        "hvd_elastic_restarts_total",
        "Successful runtime resets (shutdown + re-init + re-sync)")


class State:
    """Base elastic state: commit/restore/sync + host-update checks
    (reference: horovod/common/elastic.py:26 ``State``)."""

    def __init__(self):
        self._reset_callbacks = []
        self._last_check = 0.0
        self._commits = 0
        self._check_interval = envparse.get_float(
            envparse.ELASTIC_CHECK_INTERVAL, 0.2)

    def register_reset_callbacks(self, callbacks):
        """Callbacks run after a reset (new world size), e.g. to rescale
        the learning rate (reference: elastic.py:44)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def reset(self):
        """Hook for subclasses (re-build data loaders, etc.)."""

    def commit(self):
        """Snapshot state in memory and check for membership changes
        (reference: elastic.py:70 — commit marks a restore point; raising
        here, between steps, is what keeps restore consistent)."""
        self.save()
        _m_commits().inc()
        self._commits += 1
        # Chaos 'worker' point: commit boundaries are where preemption /
        # hang scenarios are injected (after_commits matcher). Fires
        # AFTER save() so a preempt hand-off persists current progress.
        _chaos_inject("worker", commits=self._commits)
        self.check_host_updates()

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt when the driver published a newer
        membership version than the one this worker joined at."""
        if preempt_requested():
            # SIGTERM arrived since the last commit: hand off now, at a
            # consistent restore point (the commit just saved).
            raise HostsUpdatedInterrupt(skip_sync=True)
        now = time.monotonic()
        if now - self._last_check < self._check_interval:
            return
        self._last_check = now
        from .runner import rendezvous as rdv
        cfg = rdv.rendezvous_config()
        if cfg is None:
            return
        current = rdv.current_elastic_version(*cfg)
        if current > _joined_version():
            raise HostsUpdatedInterrupt(skip_sync=False)

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError


class ObjectState(State):
    """State holding arbitrary picklable attributes — params/opt-state
    pytrees, epoch counters, RNG keys (reference:
    horovod/common/elastic.py:116 ``ObjectState``). JAX arrays are
    immutable, so save/restore are shallow snapshots; sync broadcasts the
    whole attribute dict from the new rank 0 (always a survivor: the
    driver assigns surviving workers the lowest ranks)."""

    def __init__(self, **kwargs):
        super().__init__()
        self._saved_state = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)

    def _public_state(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}

    def save(self):
        self._saved_state = self._public_state()

    def restore(self):
        for k, v in self._saved_state.items():
            setattr(self, k, v)

    def sync(self):
        from .functions import broadcast_object
        synced = broadcast_object(self._saved_state, root_rank=0,
                                  name="elastic.state")
        self._saved_state = synced
        self.restore()


# TPU-flavored alias: the natural JAX elastic state is "a dict of pytrees".
TpuState = ObjectState


def _joined_version():
    return envparse.get_int(envparse.ELASTIC_VERSION, -1)


def _reset():
    """shutdown(); init() — re-rendezvous with new ranks from the driver
    (reference: horovod/torch/elastic/__init__.py:46-48)."""
    basics.shutdown()
    basics.init()


# ---------------------------------------------------------------------------
# Graceful preemption: SIGTERM → hand off at the next commit boundary
# ---------------------------------------------------------------------------
#
# Cloud preemption (and the driver's own scale-down stop) arrives as
# SIGTERM. The default disposition is an abrupt death mid-step: in-memory
# progress since the last persisted commit is lost and the driver counts
# a failure against the host. With the handler installed, SIGTERM only
# sets a flag; the next commit boundary raises HostsUpdatedInterrupt at a
# consistent restore point, the worker persists that commit to the
# driver's KV store, and exits with PREEMPT_EXIT_CODE — which the driver
# treats as a membership change, not a failure (docs/fault_tolerance.md).

_PREEMPT = {"installed": False, "requested": False}


def _publish_exit_marker(code):
    """Best-effort ``elastic.exit/<wid> = rc`` KV marker. The durable
    exit record a *promoted standby* driver — which never spawned this
    process and so cannot ``proc.poll()`` it — reaps instead of an
    exit code (runner/elastic_driver.py ``_AdoptedProc``). Crashes
    leave no marker; the heartbeat timeout covers those."""
    if not envparse.get_str(envparse.RENDEZVOUS_ADDRS, ""):
        # Exit markers only matter to a driver that could ADOPT this
        # worker, i.e. when a standby endpoint list was exported.
        # Without HA the driver reaps real exit codes, and the
        # disabled-mode contract promises zero extra KV traffic.
        return
    from .runner import http_client
    from .runner import rendezvous as rdv
    cfg = rdv.rendezvous_config()
    wid = envparse.get_str(envparse.WORKER_ID)
    if cfg is None or not wid:
        return
    addr, port, token = cfg
    try:
        http_client.put_kv(addr, port, rdv.EXIT_SCOPE, wid, str(code),
                           token=token, retries=2, deadline=5.0)
    except Exception as e:  # noqa: BLE001 — markers must never block exit
        get_logger().debug("elastic: could not publish exit marker: %s",
                           e)


def preempt_requested():
    """True once SIGTERM has been received (elastic workers only)."""
    return _PREEMPT["requested"]


def _reset_preempt_state():
    """Test hook."""
    _PREEMPT["requested"] = False


def _install_preempt_handler(log):
    """Install the SIGTERM→flag handler. Elastic workers only (gated by
    the caller), main thread only (signal API constraint), idempotent."""
    if _PREEMPT["installed"]:
        return
    if threading.current_thread() is not threading.main_thread():
        return

    def _on_sigterm(signum, frame):
        _PREEMPT["requested"] = True
        log.warning("elastic: SIGTERM received; handing off at the "
                    "next commit boundary")

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        return
    _PREEMPT["installed"] = True


def _graceful_preempt_exit(state, log):
    """Persist the last commit (so a replacement slot — or the whole
    respawned cohort in restart mode — can restore it) and leave with
    PREEMPT_EXIT_CODE. Persistence is best-effort: a state that cannot
    pickle, or a store that is already gone, must not turn a graceful
    exit back into a hang."""
    import sys
    _m_failures().labels(cause="preempted").inc()
    try:
        _persist_state(state)
        log.info("elastic: preemption hand-off — last commit persisted")
    except Exception as e:  # noqa: BLE001 — exit regardless
        log.warning("elastic: could not persist commit during "
                    "preemption hand-off: %s", e)
    _publish_exit_marker(PREEMPT_EXIT_CODE)
    try:
        basics.shutdown()
    except Exception:  # noqa: BLE001
        pass
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(PREEMPT_EXIT_CODE)


# ---------------------------------------------------------------------------
# Exit-restart reset: elastic over the compiled (xla-global) data plane
# ---------------------------------------------------------------------------
#
# The reference aborts NCCL comms and re-initializes in-process
# (reference: horovod/common/elastic.py:150-176 + nccl elastic abort).
# jax.distributed cannot re-form inside a live process, so the compiled
# plane resets across a PROCESS boundary instead: on a membership event
# the worker persists its last commit to the driver's KV store and exits
# with RESTART_EXIT_CODE; the elastic driver respawns the same slot
# fresh, the new process re-forms jax.distributed at the new world size,
# and run_fn restores the persisted commit before the first sync().

_STATE_SCOPE = "elastic.state"


def _restart_mode():
    """Exit-restart semantics are required whenever the requested data
    plane is the compiled one (xla-global over jax.distributed)."""
    from .utils import envparse
    if not envparse.get_bool(envparse.ELASTIC):
        return False
    return envparse.get_str(envparse.CPU_OPERATIONS, "").lower() in (
        "xla", "xla-global", "nccl")


def _state_payload(state):
    """The picklable restore-point of a State. save() runs first so a
    graceful membership change persists CURRENT progress (the interrupt
    is raised at step-aligned commit points; after a failure the caller
    already restored, and re-saving the restored attrs is the same
    snapshot). States carrying non-picklable payloads cannot use the
    exit-restart plane — fail loud at persist time, not with a corrupt
    restore."""
    try:
        state.save()
    except NotImplementedError:
        pass
    payload = getattr(state, "_saved_state", None)
    if payload is None:
        raise NotImplementedError(
            f"{type(state).__name__} exposes no _saved_state snapshot; "
            "exit-restart elastic (xla-global plane) needs a picklable "
            "commit payload")
    return payload


def _persist_state(state):
    """Write this worker's restore point to the driver's KV store under
    its worker id AND the cohort's "any" fallback (last-writer: all
    survivors persist the same step-aligned restore point, so any write
    is as good as another for a replacement slot with no history).
    Returns the resolved ``(addr, port, token, wid)`` so callers with
    follow-up KV writes reuse one validation."""
    import base64
    import json
    import pickle

    from .runner import http_client
    from .runner import rendezvous as rdv
    cfg = rdv.rendezvous_config()
    wid = envparse.get_str(envparse.WORKER_ID)
    if cfg is None or not wid:
        raise HorovodInternalError(
            "persisting elastic state requires the hvdrun launcher's "
            "rendezvous (HVDTPU_RENDEZVOUS_ADDR/PORT)")
    addr, port, token = cfg
    payload = base64.b64encode(
        pickle.dumps(_state_payload(state))).decode()
    json_blob = json.dumps({"version": _joined_version(),
                            "payload": payload})
    http_client.put_kv(addr, port, _STATE_SCOPE, wid, json_blob,
                       token=token)
    http_client.put_kv(addr, port, _STATE_SCOPE, "any", json_blob,
                       token=token)
    return addr, port, token, wid


def _persist_and_exit(state, log, rereq):
    """Persist the last commit to the driver's KV store and leave the
    process; the driver respawns this slot fresh (see module note)."""
    import sys

    from .runner import http_client
    from .runner import rendezvous as rdv
    addr, port, token, wid = _persist_state(state)
    if rereq:
        # A transport failure with no process death changes no
        # membership; ask the driver to bump the version so the fresh
        # cohort re-forms (mirrors rendezvous.elastic_bootstrap).
        http_client.put_kv(addr, port, rdv.ELASTIC_SCOPE,
                           f"rereq.{wid}", str(_joined_version() + 1),
                           token=token)
    _publish_exit_marker(RESTART_EXIT_CODE)
    log.info("elastic: persisting commit and exiting for process "
             "restart (compiled plane reset)")
    try:
        basics.shutdown()
    except Exception:  # noqa: BLE001
        pass
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(RESTART_EXIT_CODE)


def _maybe_restore_persisted(state, log):
    """In a fresh exit-restart process: load this slot's persisted
    commit (or the cohort's last-writer fallback) into ``state``."""
    import base64
    import json
    import pickle

    from .runner import http_client
    from .runner import rendezvous as rdv
    cfg = rdv.rendezvous_config()
    wid = envparse.get_str(envparse.WORKER_ID)
    if cfg is None or not wid:
        return
    addr, port, token = cfg
    raw = http_client.get_kv(addr, port, _STATE_SCOPE, wid, token=token)
    if raw is None:
        raw = http_client.get_kv(addr, port, _STATE_SCOPE, "any",
                                 token=token)
    if raw is None:
        return
    try:
        record = json.loads(raw.decode()
                            if isinstance(raw, bytes) else raw)
        payload = pickle.loads(base64.b64decode(record["payload"]))
    except Exception as e:  # noqa: BLE001
        log.warning("elastic: persisted state unreadable (%s); starting "
                    "fresh", e)
        return
    state._saved_state = payload
    state.restore()
    state.save()
    log.info("elastic: restored persisted commit from version %s",
             record.get("version"))


def run_fn(func, reset=_reset):
    """Wrap a training function for elastic execution (reference:
    horovod/common/elastic.py:151 ``run_fn``). The wrapped function takes
    the State first; on HorovodInternalError the last commit is restored,
    on HostsUpdatedInterrupt state is kept; both paths reset the runtime
    and re-sync before retrying."""
    log = get_logger()

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        if envparse.get_bool(envparse.ELASTIC):
            # Launcher-spawned elastic worker: convert SIGTERM (cloud
            # preemption / driver stop) into a commit-boundary hand-off.
            _install_preempt_handler(log)
        if _restart_mode():
            _maybe_restore_persisted(state, log)
        skip_sync = False
        while True:
            if not skip_sync:
                state.sync()
            try:
                result = func(state, *args, **kwargs)
                if envparse.get_bool(envparse.ELASTIC):
                    # Durable success marker for a control plane that
                    # survived a failover: a promoted standby has no
                    # process handle on this worker and reaps the
                    # marker instead of an exit code.
                    _publish_exit_marker(0)
                return result
            except HorovodInternalError as e:
                from . import tracing
                tracing.trace_event(
                    "elastic", "restore",
                    cause=("collective_abort"
                           if isinstance(e, CollectiveAbortError)
                           else "internal"))
                if isinstance(e, CollectiveAbortError):
                    # The stuck-collective watchdog aborted in-flight
                    # ops (guardian.py): the diagnostic names which
                    # ranks never submitted what. The reset below IS
                    # the HostsUpdatedInterrupt-style recovery — the
                    # abort becomes a restore-and-reset, not a job
                    # death.
                    log.warning("elastic: watchdog abort — restoring "
                                "last commit and resetting. %s", e)
                else:
                    log.info("elastic: collective failure (%s); "
                             "restoring last commit", e)
                state.restore()
                skip_sync = False
                if preempt_requested():
                    # Counted once, as cause="preempted", inside the
                    # hand-off — the failure causes are disjoint.
                    _graceful_preempt_exit(state, log)
                _m_failures().labels(
                    cause="collective_abort"
                    if isinstance(e, CollectiveAbortError)
                    else "internal").inc()
                if _restart_mode():
                    _persist_and_exit(state, log, rereq=True)
            except HostsUpdatedInterrupt as e:
                from . import tracing
                tracing.trace_event("elastic", "hosts_updated")
                log.info("elastic: hosts updated; re-rendezvousing")
                skip_sync = e.skip_sync
                if preempt_requested():
                    _graceful_preempt_exit(state, log)
                _m_failures().labels(cause="hosts_updated").inc()
                if _restart_mode():
                    _persist_and_exit(state, log, rereq=False)
            _retry_reset(reset, log)
            _m_restarts().inc()
            state.on_reset()

    return wrapper


def _retry_reset(reset, log, attempts=3):
    """Re-init can itself hit a dying cohort (a peer drops while the new
    mesh forms); retry a few times before giving up — each attempt
    re-fetches the newest membership version."""
    for attempt in range(attempts):
        try:
            reset()
            return
        except (HorovodInternalError, TimeoutError, OSError) as e:
            log.warning("elastic: reset attempt %d failed (%s)",
                        attempt + 1, e)
            try:
                basics.shutdown()
            except Exception:  # noqa: BLE001
                pass
            if attempt == attempts - 1:
                raise


def run(func):
    """Decorator form (reference: horovod/torch/elastic/__init__.py
    ``hvd.elastic.run``)."""
    return run_fn(func)
