"""Elastic fault tolerance: worker-side state machine + retry loop.

The reference's signature capability (reference:
horovod/common/elastic.py:26-176): training state is committed in memory,
collective failures (``HorovodInternalError``) restore it, membership
changes (``HostsUpdatedInterrupt``) re-rendezvous, and in both cases the
runtime resets (``shutdown(); init()``) with new ranks served by the
driver's rendezvous, then ``state.sync()`` re-broadcasts from a surviving
rank. On TPU this is the preemptible-slice story: a preempted host drops
out, the remaining hosts shrink the job, and training resumes from the
last commit without restarting the process tree.

Membership-change notification is poll-based: the driver bumps an
``elastic/version`` counter in its KV store; ``state.check_host_updates``
compares it against the version this worker joined at (the reference pushes
notifications into an in-worker TCP service instead,
horovod/runner/elastic/worker.py:46 — a KV poll at commit granularity is
simpler and costs one HTTP GET per commit).
"""

import functools
import os
import time

from . import basics
from .exceptions import HorovodInternalError, HostsUpdatedInterrupt
from .utils.logging_util import get_logger


class State:
    """Base elastic state: commit/restore/sync + host-update checks
    (reference: horovod/common/elastic.py:26 ``State``)."""

    def __init__(self):
        self._reset_callbacks = []
        self._last_check = 0.0
        self._check_interval = float(
            os.environ.get("HVDTPU_ELASTIC_CHECK_INTERVAL", "0.2"))

    def register_reset_callbacks(self, callbacks):
        """Callbacks run after a reset (new world size), e.g. to rescale
        the learning rate (reference: elastic.py:44)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def reset(self):
        """Hook for subclasses (re-build data loaders, etc.)."""

    def commit(self):
        """Snapshot state in memory and check for membership changes
        (reference: elastic.py:70 — commit marks a restore point; raising
        here, between steps, is what keeps restore consistent)."""
        self.save()
        self.check_host_updates()

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt when the driver published a newer
        membership version than the one this worker joined at."""
        now = time.monotonic()
        if now - self._last_check < self._check_interval:
            return
        self._last_check = now
        from .runner import rendezvous as rdv
        cfg = rdv.rendezvous_config()
        if cfg is None:
            return
        current = rdv.current_elastic_version(*cfg)
        if current > _joined_version():
            raise HostsUpdatedInterrupt(skip_sync=False)

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError


class ObjectState(State):
    """State holding arbitrary picklable attributes — params/opt-state
    pytrees, epoch counters, RNG keys (reference:
    horovod/common/elastic.py:116 ``ObjectState``). JAX arrays are
    immutable, so save/restore are shallow snapshots; sync broadcasts the
    whole attribute dict from the new rank 0 (always a survivor: the
    driver assigns surviving workers the lowest ranks)."""

    def __init__(self, **kwargs):
        super().__init__()
        self._saved_state = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)

    def _public_state(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}

    def save(self):
        self._saved_state = self._public_state()

    def restore(self):
        for k, v in self._saved_state.items():
            setattr(self, k, v)

    def sync(self):
        from .functions import broadcast_object
        synced = broadcast_object(self._saved_state, root_rank=0,
                                  name="elastic.state")
        self._saved_state = synced
        self.restore()


# TPU-flavored alias: the natural JAX elastic state is "a dict of pytrees".
TpuState = ObjectState


def _joined_version():
    return int(os.environ.get("HVDTPU_ELASTIC_VERSION", "-1"))


def _reset():
    """shutdown(); init() — re-rendezvous with new ranks from the driver
    (reference: horovod/torch/elastic/__init__.py:46-48)."""
    basics.shutdown()
    basics.init()


def run_fn(func, reset=_reset):
    """Wrap a training function for elastic execution (reference:
    horovod/common/elastic.py:151 ``run_fn``). The wrapped function takes
    the State first; on HorovodInternalError the last commit is restored,
    on HostsUpdatedInterrupt state is kept; both paths reset the runtime
    and re-sync before retrying."""
    log = get_logger()

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        skip_sync = False
        while True:
            if not skip_sync:
                state.sync()
            try:
                return func(state, *args, **kwargs)
            except HorovodInternalError as e:
                log.info("elastic: collective failure (%s); restoring "
                         "last commit", e)
                state.restore()
                skip_sync = False
            except HostsUpdatedInterrupt as e:
                log.info("elastic: hosts updated; re-rendezvousing")
                skip_sync = e.skip_sync
            _retry_reset(reset, log)
            state.on_reset()

    return wrapper


def _retry_reset(reset, log, attempts=3):
    """Re-init can itself hit a dying cohort (a peer drops while the new
    mesh forms); retry a few times before giving up — each attempt
    re-fetches the newest membership version."""
    for attempt in range(attempts):
        try:
            reset()
            return
        except (HorovodInternalError, TimeoutError, OSError) as e:
            log.warning("elastic: reset attempt %d failed (%s)",
                        attempt + 1, e)
            try:
                basics.shutdown()
            except Exception:  # noqa: BLE001
                pass
            if attempt == attempts - 1:
                raise


def run(func):
    """Decorator form (reference: horovod/torch/elastic/__init__.py
    ``hvd.elastic.run``)."""
    return run_fn(func)
