"""Calibrated α–β collective cost model on the symbolic executor.

Static step-time prediction for schedules the interprocedural verifier
(analysis/schedule.py) extracts — no TPU required. Each collective kind
gets the standard α–β decomposition (PAPERS.md 2506.17615: collective
wall time splits into a per-hop latency term and a per-byte bandwidth
term at TPU scale):

- ring allreduce:       ``2(n-1)·α + 2·((n-1)/n)·B/β``
- allgather / reduce-scatter / all-to-all: ``(n-1)·α + ((n-1)/n)·B/β``
- broadcast (binomial tree): ``ceil(log2 n)·(α + B/β)``
- barrier (dissemination): ``2·ceil(log2 n)·α``

``B`` is payload bytes, ``n`` the world size. The model is **calibrated**
by fitting sub→fin spans from the PR 8 trace shards
(``hvd-lint perf --calibrate <trace-dir>``): a per-kind least-squares
fit of (α, 1/β) over the recorded (world, bytes, duration) tuples, a
compute baseline from the analyzer's per-step critical-path gaps
(PAPERS.md 2004.13336's comm/compute attribution), then a step-level
regression (``wall ≈ fixed_s + serial_fraction × Σ span``) that pins
the composed prediction to recorded whole steps: ``serial_fraction``
captures how much of the summed span time the program actually exposes
(async pipelines overlap their own collectives; a synchronous
per-tensor loop does not), ``fixed_s`` the per-step dispatch cost no
individual span carries. A checked-in :data:`DEFAULT_TABLE` covers the
cold case.

What the model deliberately ignores (docs/lint.md "Model
assumptions"): link congestion from neighbours, DCN vs ICI topology
splits, per-dtype math throughput, and fusion-buffer padding waste
beyond the bucket-count term. It is a *ranking and cliff-finding*
model, not a cycle-accurate one — the ``bench.py --simulate`` lane
archives its residual against measured n=2/4/8 runs exactly so the
extrapolated 256/1024-rank numbers stay honest.

On top of the prediction sit the HVD6xx static performance rules
(docs/lint.md):

- **HVD601** — a literal ``HVDTPU_BUCKET_BYTES`` /
  ``HVDTPU_ZERO_BUCKET_BYTES`` assignment whose value is ≥2× away from
  the predicted bucket optimum at the largest target cohort.
- **HVD602** — a serialization point inside a step loop: a barrier
  co-resident with other collectives, or two-plus distinct synchronous
  per-tensor allreduce call sites (zero overlap opportunity either way).
- **HVD603** — a scale cliff: the predicted comm fraction crosses 50%
  between two probed cohort sizes (requires a calibrated compute
  baseline — the default table carries none, so this rule never fires
  cold).

Pure stdlib — no jax imports; the tracing modules it calibrates from
are imported lazily inside the calibration entry points.
"""

import ast
import json
import math
import os

from .diagnostics import Diagnostic, dedupe

# Default per-kind coefficients: plausible TPU-pod ICI numbers (sub-µs
# per-hop latency, ~1e11 B/s per-link bandwidth) — good enough to rank
# candidates and place bucket optima cold; calibration replaces them.
_DEF_ALPHA = 1e-6     # seconds per latency unit (per hop/round)
_DEF_BYTE_S = 1e-11   # seconds per byte per bandwidth unit (1/β)

MODEL_KINDS = ("allreduce", "allgather", "reducescatter", "broadcast",
               "alltoall", "barrier")

#: Cold-case table. ``compute_s`` is None on purpose: the default table
#: has no idea how long YOUR step computes, so every rule that needs a
#: compute baseline (HVD603) stays silent until calibration supplies
#: one. ``step_bytes`` is a 365M-param fp32 gradient set — the repo's
#: transformer target — used only to place bucket optima and seed
#: autotune priors when no calibration ran.
DEFAULT_TABLE = {
    "format": 1,
    "source": "default",
    "kinds": {k: {"alpha_s": _DEF_ALPHA, "byte_s": _DEF_BYTE_S}
              for k in MODEL_KINDS},
    "compute_s": None,
    "fixed_s": 0.0,
    "step_bytes": int(365e6 * 4),
    "serial_fraction": 1.0,
    "worlds": [],
    "spans": 0,
}

_BUCKET_KNOBS = ("HVDTPU_BUCKET_BYTES", "HVDTPU_ZERO_BUCKET_BYTES",
                 "HOROVOD_TPU_BUCKET_BYTES", "HOROVOD_BUCKET_BYTES")

_DOC_HINT = "see docs/lint.md (HVD6xx) and docs/performance.md " \
            "\"Predicted scaling\""


# -- kind canonicalization --------------------------------------------------
def canonical_kind(kind):
    """Map a terminal collective call name (schedule.ScheduleEvent.kind,
    trace-shard ``k`` field) onto a model kind. Unknown names fall back
    to the ring-allreduce shape — the conservative default."""
    k = (kind or "").lower().rstrip("_")
    if k.endswith("_async"):
        k = k[: -len("_async")]
    if k.startswith("grouped_"):
        k = k[len("grouped_"):]
    if "sparse" in k:
        # sparse_allreduce moves (indices, values) via allgather legs
        return "allgather"
    if "reducescatter" in k or "reduce_scatter" in k \
            or k == "psum_scatter":
        return "reducescatter"
    if "allgather" in k or k == "all_gather":
        return "allgather"
    if "alltoall" in k or k == "all_to_all" or k in ("ppermute",
                                                     "pshuffle"):
        return "alltoall"
    if "broadcast" in k:
        return "broadcast"
    if k in ("barrier", "join"):
        return "barrier"
    # allreduce, psum, pmean, pmax, pmin, and anything unrecognized
    return "allreduce"


def _terms(kind, world):
    """(latency_units, bandwidth_units): ``t = α·lat + B·byte_s·bw``."""
    n = max(2, int(world))
    if kind == "barrier":
        return 2.0 * math.ceil(math.log2(n)), 0.0
    if kind == "broadcast":
        hops = float(math.ceil(math.log2(n)))
        return hops, hops
    if kind in ("allgather", "reducescatter", "alltoall"):
        return float(n - 1), float(n - 1) / n
    # ring allreduce (reduce-scatter + allgather legs)
    return 2.0 * (n - 1), 2.0 * float(n - 1) / n


def _coeff(table, kind):
    row = (table.get("kinds") or {}).get(kind)
    if not row:
        row = DEFAULT_TABLE["kinds"][kind]
    return (float(row.get("alpha_s", _DEF_ALPHA)),
            float(row.get("byte_s", _DEF_BYTE_S)))


def collective_time(kind, nbytes, world, table=None):
    """Predicted wall seconds for one collective of ``nbytes`` payload
    at cohort size ``world``."""
    table = table or DEFAULT_TABLE
    kind = canonical_kind(kind)
    lat, bw = _terms(kind, world)
    alpha, byte_s = _coeff(table, kind)
    return alpha * lat + float(nbytes or 0) * byte_s * bw


def bucket_optimum(total_bytes, world, table=None, kind="allreduce"):
    """Bucket size minimizing exposed comm for ``total_bytes`` split
    into buckets: per-bucket latency overhead ``(T/B)·L`` trades
    against the un-overlappable last-bucket drain ``B·C`` — minimized
    at ``B* = sqrt(T·L/C)``, clamped to ``[64 KiB, T]``."""
    table = table or DEFAULT_TABLE
    total = max(1.0, float(total_bytes))
    lat, bw = _terms(canonical_kind(kind), world)
    alpha, byte_s = _coeff(table, kind)
    lat_s = alpha * lat
    per_byte = byte_s * bw
    if per_byte <= 0.0:
        return int(total)
    opt = math.sqrt(total * lat_s / per_byte)
    return int(min(total, max(64 * 1024, opt)))


# -- table IO ---------------------------------------------------------------
def _normalize_table(doc, source):
    table = dict(DEFAULT_TABLE)
    table["kinds"] = dict(DEFAULT_TABLE["kinds"])
    if isinstance(doc.get("kinds"), dict):
        for kind, row in doc["kinds"].items():
            if isinstance(row, dict):
                table["kinds"][kind] = {
                    "alpha_s": float(row.get("alpha_s", _DEF_ALPHA)),
                    "byte_s": float(row.get("byte_s", _DEF_BYTE_S)),
                }
    for key in ("compute_s", "fixed_s", "step_bytes",
                "serial_fraction", "worlds", "spans"):
        if key in doc:
            table[key] = doc[key]
    table["source"] = doc.get("source", source)
    return table


def load_table(path):
    """Load a model table JSON; raises ValueError on garbage."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"cost-model table {path}: not a JSON object")
    return _normalize_table(doc, source=path)


def save_table(table, path):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(table, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def resolve_table():
    """The session's model table: ``HVDTPU_COSTMODEL_TABLE`` when set
    and readable (unreadable warns and falls back — a stale export
    must not kill a lint run), else :data:`DEFAULT_TABLE`."""
    from ..utils import envparse
    path = envparse.get_str(envparse.COSTMODEL_TABLE)
    if path:
        try:
            return load_table(path)
        except (OSError, ValueError) as exc:
            import warnings
            warnings.warn(f"cost-model table {path!r} unusable ({exc}); "
                          "using the built-in default", stacklevel=2)
    return dict(DEFAULT_TABLE)


def target_ranks_from_env():
    from ..utils import envparse
    raw = envparse.get_str(envparse.PERF_TARGET_RANKS, "8,64,256,1024")
    ranks = []
    for part in raw.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            n = int(part)
        except ValueError:
            continue
        if n >= 2:
            ranks.append(n)
    return sorted(set(ranks)) or [8, 64, 256, 1024]


# -- calibration ------------------------------------------------------------
def _fit_kind(obs):
    """Least-squares (α, byte_s) for one kind over observations
    ``(lat_units, bw_byte_units, dur_s)`` where ``bw_byte_units`` is
    bytes × bandwidth-units (0/None when the span carried no payload
    record — the pre-PR16 shard format). Closed-form 2×2 normal
    equations; degenerate systems fall back to an α-only fit with the
    default byte term."""
    with_bytes = [(l, b, d) for (l, b, d) in obs if b]
    if len(with_bytes) >= 2:
        sxx = sum(l * l for l, _, _ in with_bytes)
        sxy = sum(l * b for l, b, _ in with_bytes)
        syy = sum(b * b for _, b, _ in with_bytes)
        sxd = sum(l * d for l, _, d in with_bytes)
        syd = sum(b * d for _, b, d in with_bytes)
        det = sxx * syy - sxy * sxy
        if det > 1e-30 * max(1.0, sxx) * max(1.0, syy):
            alpha = (sxd * syy - syd * sxy) / det
            byte_s = (syd * sxx - sxd * sxy) / det
            if alpha > 0.0 and byte_s > 0.0:
                return alpha, byte_s
    # α-only: every span's full duration charged to latency; keep the
    # default bandwidth term so payload still scales the prediction.
    rates = [d / l for (l, _, d) in obs if l > 0 and d > 0]
    alpha = sum(rates) / len(rates) if rates else _DEF_ALPHA
    return max(alpha, 1e-9), _DEF_BYTE_S


def _recalibrate_step_level(table, step_model_events, step_walls,
                            exposed):
    """Pin ``compute_s``/``serial_fraction`` to the STEP level: the
    per-kind α–β fit reconstructs individual sub→fin spans, but spans
    overlap (async pipelining) and the step pays fixed dispatch cost no
    span carries — so composing span times naively over- or
    under-shoots the wall step. For each run group's best recorded step
    (first submit → last completion, warm-up naturally excluded by
    taking the min) regress

        wall_step  ≈  compute_s  +  serial_fraction × Σ model span time

    With ≥2 groups at distinct sizes the 2-parameter least squares
    separates fixed cost from scaling cost; a single group solves the
    fraction against the gap-derived compute baseline; with no usable
    step the measured-exposed-comm ratio is the last resort. The
    intercept lands in ``fixed_s`` — per-step dispatch cost that sits
    on the critical path even for fully-async schedules — NOT in
    ``compute_s``, whose job is the hideable compute baseline
    (predict_step lets async comm overlap it)."""
    group_walls = {}         # group_key -> (model_sum, [walls])
    walls = {(k, o): w for (k, o, w) in step_walls}
    model_sums = {}
    for key, occ, events in step_model_events:
        model_sum = sum(collective_time(k, b, w, table)
                        for (k, b, w) in events)
        model_sums[(key, occ)] = model_sum
        wall = walls.get((key, occ))
        if model_sum <= 0.0 or not wall:
            continue
        group_walls.setdefault(key, (model_sum, []))[1].append(wall)

    # Per-group representative step: the MEDIAN wall — robust to both
    # the slow warm-up occurrences at the front of the shard and the
    # occasional straggler step, and the same statistic the bench
    # worker reports, so residuals compare like with like.
    pts = []
    for model_sum, ws in group_walls.values():
        ws.sort()
        mid = len(ws) // 2
        med = (ws[mid] if len(ws) % 2
               else (ws[mid - 1] + ws[mid]) / 2.0)
        pts.append((model_sum, med))
    pts.sort()
    if len(pts) >= 2 and pts[-1][0] > 1.001 * pts[0][0]:
        mean_x = sum(x for x, _ in pts) / len(pts)
        mean_y = sum(y for _, y in pts) / len(pts)
        var = sum((x - mean_x) ** 2 for x, _ in pts)
        cov = sum((x - mean_x) * (y - mean_y) for x, y in pts)
        frac = cov / var
        base = mean_y - frac * mean_x
        if base < 0.0:
            # Fixed cost cannot be negative: slope-only refit.
            base = 0.0
            frac = (sum(x * y for x, y in pts)
                    / sum(x * x for x, _ in pts))
        table["serial_fraction"] = min(1.2, max(0.01, frac))
        table["fixed_s"] = base
        if table["compute_s"] is None:
            table["compute_s"] = 0.0
        return
    if len(pts) == 1:
        # One run group: no leverage to split fixed from scaling cost —
        # fold everything into the fraction (exact reconstruction at
        # the calibrated size for async schedules, where predict_step's
        # hiding makes step = max(compute, serial·Σspan)).
        model_sum, wall = pts[0]
        table["serial_fraction"] = min(
            1.2, max(0.01, wall / model_sum))
        if table["compute_s"] is None:
            table["compute_s"] = 0.0
        return
    # No rank-0 step observed end-to-end: ratio of measured exposed
    # comm (critical-path attribution) over the model's summed span
    # time — async pipelines land well below 1.0, synchronous
    # per-tensor loops at ~1.0.
    fracs = []
    for (key, occ), meas in exposed.items():
        model_sum = model_sums.get((key, occ), 0.0)
        if model_sum > 0.0 and meas:
            fracs.append(min(1.2, max(0.01, meas / model_sum)))
    if fracs:
        table["serial_fraction"] = sum(fracs) / len(fracs)


def fit_shards(shards):
    """Fit a model table from loaded trace shards (merge.load_paths
    output). Returns the table dict (DEFAULT_TABLE shape, ``source:
    "calibrated"``)."""
    from ..tracing import analyze as analyze_mod
    from ..tracing import merge as merge_mod

    # A calibration dir may hold shards from SEVERAL runs (the bench
    # --simulate lane records one per world size). Occurrence counters
    # and rank ids restart per run, so the per-step analysis must stay
    # within one run: group by (directory, world size).
    groups = {}
    for shard in shards:
        meta = shard.get("meta") or {}
        world = int(meta.get("size") or 0) or 2
        key = (os.path.dirname(shard.get("path") or ""), world)
        groups.setdefault(key, []).append(shard)

    obs_by_kind = {}
    worlds = set()
    span_count = 0
    per_step_bytes = []
    step_model_events = []   # (group_key, occ) aligned step inputs
    step_walls = []          # (group_key, occ, first-sub -> last-fin)
    for key, group in sorted(groups.items()):
        world = key[1]
        worlds.add(world)
        for shard in group:
            rank = (shard.get("meta") or {}).get("rank", 0)
            spans = merge_mod.collective_spans(shard)
            by_occ = {}
            for (name, occ), sp in spans.items():
                if sp["sub"] is None or sp["fin"] is None or sp["err"]:
                    continue
                dur = sp["fin"] - sp["sub"]
                if dur <= 0.0:
                    continue
                kind = canonical_kind(sp.get("kind"))
                nbytes = sp.get("bytes")
                lat, bw = _terms(kind, world)
                obs_by_kind.setdefault(kind, []).append(
                    (lat, float(nbytes or 0) * bw, dur))
                span_count += 1
                if rank == 0:
                    by_occ.setdefault(occ, []).append(
                        (kind, nbytes, world, sp["sub"], sp["fin"]))
            for occ, evs in by_occ.items():
                events = [(k, b, w) for (k, b, w, _, _) in evs]
                total = sum(int(b or 0) for _, b, _ in events)
                if total > 0:
                    per_step_bytes.append(total)
                step_model_events.append((key, occ, events))
                wall = (max(f for *_, f in evs)
                        - min(s for *_, s, _ in evs))
                if wall > 0.0:
                    step_walls.append((key, occ, wall))

    table = dict(DEFAULT_TABLE)
    table["kinds"] = dict(DEFAULT_TABLE["kinds"])
    for kind, obs in obs_by_kind.items():
        alpha, byte_s = _fit_kind(obs)
        table["kinds"][kind] = {"alpha_s": alpha, "byte_s": byte_s}

    # Compute baseline + measured exposed comm from the analyzer's
    # per-step critical-path decomposition (2004.13336 attribution),
    # one run group at a time.
    gaps = []
    exposed = {}             # (group_key, occ) -> measured exposed comm
    for key, group in sorted(groups.items()):
        report = analyze_mod.analyze(group)
        for st in report.get("steps", []):
            if st.get("duration_s") is None:
                continue
            gaps.append(float(st.get("critical_gap_s") or 0.0))
            exposed[(key, st["step"])] = float(
                st.get("critical_comm_s") or 0.0)
    table["compute_s"] = (sum(gaps) / len(gaps)) if gaps else None

    if per_step_bytes:
        table["step_bytes"] = int(sum(per_step_bytes)
                                  / len(per_step_bytes))

    _recalibrate_step_level(table, step_model_events, step_walls,
                            exposed)
    table["source"] = "calibrated"
    table["worlds"] = sorted(worlds)
    table["spans"] = span_count
    return table


def fit_paths(paths):
    """``hvd-lint perf --calibrate``: load shards under ``paths`` and
    fit. Unreadable shard files are warned about and skipped
    (merge.load_paths); raises ValueError when no usable span
    survives."""
    from ..tracing import merge as merge_mod
    shards = merge_mod.load_paths(paths)
    table = fit_shards(shards)
    if not table["spans"]:
        raise ValueError(
            f"no usable collective spans under {paths!r} — nothing to "
            "calibrate (need shard.*.jsonl files from an "
            "HVDTPU_TRACE=1 run)")
    return table


# -- schedule extraction ----------------------------------------------------
class _StepLoop:
    """One loop body's directly-submitted collectives."""

    __slots__ = ("line", "events")

    def __init__(self, line):
        self.line = line
        self.events = []


def _walk_program(prog, top_events, loops, cur):
    """Collect direct ScheduleEvents per innermost loop (``loops``) and
    outside any loop (``top_events``)."""
    for node in prog:
        tag = node[0]
        if tag == "ev":
            (cur.events if cur is not None else top_events).append(
                node[1])
        elif tag == "br":
            _walk_program(node[2], top_events, loops, cur)
            _walk_program(node[3], top_events, loops, cur)
        elif tag == "loop":
            inner = _StepLoop(node[1].line)
            loops.append(inner)
            _walk_program(node[2], top_events, loops, inner)


def _entry_modules(verifier):
    """The modules the invocation NAMED, not the package modules the
    corpus pulled in through imports: perf findings and predictions
    stay scoped to the code under review (the self-sweep names the
    whole package, so nothing hides from CI)."""
    seen = set()
    out = []
    for mod in verifier.entries:
        if id(mod) not in seen:
            seen.add(id(mod))
            out.append(mod)
    return sorted(out, key=lambda m: m.path)


def extract_schedules(verifier):
    """Per-function step schedules over a fixpointed Verifier corpus's
    entry modules:
    ``[{"function", "file", "line", "events", "in_loop"}]`` where
    ``events`` is the list of ScheduleEvents submitted once per step
    (the busiest loop body, or the straight-line schedule when the
    function has no collective-bearing loop)."""
    verifier.fixpoint()
    out = []
    for mod in _entry_modules(verifier):
        for qual in sorted(mod.funcs):
            fn = mod.funcs[qual]
            top, loops = [], []
            _walk_program(fn.program, top, loops, None)
            with_events = [lp for lp in loops if lp.events]
            if with_events:
                step = max(with_events, key=lambda lp: len(lp.events))
                out.append({"function": qual, "file": mod.path,
                            "line": step.line, "events": step.events,
                            "in_loop": True, "loops": with_events})
            elif top:
                out.append({"function": qual, "file": mod.path,
                            "line": top[0].line, "events": top,
                            "in_loop": False, "loops": []})
    return out


def _is_async(event):
    return "async" in (event.kind or "")


def predict_step(events, world, table, step_bytes=None):
    """Predicted per-step decomposition at cohort size ``world``:
    ``{"comm_s", "step_s", "comm_fraction", "blocking", "by_kind"}``.
    Payload per event is an even split of ``step_bytes`` (default: the
    table's per-step byte budget). Async submissions hide under
    compute up to the compute baseline; synchronous ones serialize.
    The table's ``fixed_s`` (per-step dispatch/launch cost the
    step-level calibration separated out) is on the critical path
    regardless — async overlap cannot hide under it."""
    n_ev = max(1, len(events))
    per_event = float(step_bytes or table.get("step_bytes")
                      or DEFAULT_TABLE["step_bytes"]) / n_ev
    serial = float(table.get("serial_fraction") or 1.0)
    compute_s = table.get("compute_s")
    fixed_s = float(table.get("fixed_s") or 0.0)
    sync_s, async_s = 0.0, 0.0
    blocking = 0
    by_kind = {}
    for ev in events:
        kind = canonical_kind(ev.kind)
        nbytes = 0.0 if kind == "barrier" else per_event
        t = collective_time(kind, nbytes, world, table) * serial
        by_kind[kind] = by_kind.get(kind, 0.0) + t
        if _is_async(ev):
            async_s += t
        else:
            sync_s += t
            blocking += 1
    if compute_s is None:
        comm_s = sync_s + async_s
        step_s = comm_s + fixed_s
        fraction = comm_s / step_s if step_s > 0.0 else 0.0
    else:
        hidden = min(async_s, float(compute_s))
        comm_s = sync_s + (async_s - hidden)
        step_s = float(compute_s) + comm_s + fixed_s
        fraction = comm_s / step_s if step_s > 0.0 else 0.0
    return {"comm_s": comm_s, "step_s": step_s,
            "comm_fraction": fraction, "blocking": blocking,
            "by_kind": by_kind}


def analyze_corpus(verifier, table=None, target_ranks=None):
    """Predicted scaling for every extracted schedule: per function,
    per probed cohort size — step time, comm fraction, straggler
    sensitivity (seconds of step growth per second of submit skew ×
    blocking collectives), and the bucket optimum at the largest
    target cohort."""
    table = table or resolve_table()
    ranks = list(target_ranks or target_ranks_from_env())
    rows = []
    for sched in extract_schedules(verifier):
        if not sched["events"]:
            continue
        curve = {n: predict_step(sched["events"], n, table)
                 for n in ranks}
        top_n = ranks[-1]
        dominating = max(curve[top_n]["by_kind"].items(),
                         key=lambda kv: kv[1])[0]
        rows.append({
            "function": sched["function"],
            "file": sched["file"],
            "line": sched["line"],
            "in_loop": sched["in_loop"],
            "collectives": len(sched["events"]),
            "curve": curve,
            "dominating": dominating,
            # every blocking collective waits out the slowest rank —
            # step growth per unit submit skew
            "straggler_sensitivity": curve[top_n]["blocking"],
            "bucket_optimum_bytes": bucket_optimum(
                table.get("step_bytes")
                or DEFAULT_TABLE["step_bytes"], top_n, table),
        })
    return {"table": {k: table.get(k) for k in ("source", "compute_s",
                                                "fixed_s", "step_bytes",
                                                "serial_fraction")},
            "target_ranks": ranks, "functions": rows}


def render_report(report):
    """Human-readable predicted-scaling block (``hvd-lint perf`` text
    output)."""
    if not report["functions"]:
        return ""
    lines = [f"predicted scaling (table: {report['table']['source']}, "
             f"n = {'/'.join(str(n) for n in report['target_ranks'])})"]
    for row in report["functions"]:
        loc = f"{row['file']}:{row['line']}"
        lines.append(f"  {row['function']}  [{loc}]  "
                     f"{row['collectives']} collective(s)/step, "
                     f"dominated by {row['dominating']}")
        for n in report["target_ranks"]:
            c = row["curve"][n]
            lines.append(
                f"    n={n:<5d} step {c['step_s'] * 1e3:8.3f} ms   "
                f"comm {c['comm_s'] * 1e3:8.3f} ms "
                f"({c['comm_fraction'] * 100.0:5.1f}%)   "
                f"{c['blocking']} blocking")
    return "\n".join(lines)


# -- HVD6xx rules -----------------------------------------------------------
def _parse_bytes_literal(value):
    """Bytes from a literal knob value: int, or '16 MiB'/'4m'/'65536'
    strings. None when unparseable."""
    if isinstance(value, bool):
        return None
    if isinstance(value, int):
        return value
    if not isinstance(value, str):
        return None
    text = value.strip().lower()
    mult = 1
    for suffix, m in (("gib", 1 << 30), ("gb", 1 << 30), ("g", 1 << 30),
                      ("mib", 1 << 20), ("mb", 1 << 20), ("m", 1 << 20),
                      ("kib", 1 << 10), ("kb", 1 << 10), ("k", 1 << 10),
                      ("b", 1)):
        if text.endswith(suffix):
            text = text[: -len(suffix)].strip()
            mult = m
            break
    try:
        return int(float(text) * mult)
    except ValueError:
        return None


def _env_subscript_name(node):
    """'HVDTPU_X' for ``os.environ["HVDTPU_X"]`` / ``environ[...]``."""
    if not isinstance(node, ast.Subscript):
        return None
    base = node.value
    is_environ = (isinstance(base, ast.Attribute)
                  and base.attr == "environ") \
        or (isinstance(base, ast.Name) and base.id == "environ")
    if not is_environ:
        return None
    key = node.slice
    if isinstance(key, ast.Constant) and isinstance(key.value, str):
        return key.value
    return None


def _literal_bucket_configs(mod):
    """(knob, bytes, line) for every literal bucket-knob write in one
    module: ``os.environ[K] = <const>`` and
    ``os.environ.setdefault(K, <const>)``. Computed values (e.g.
    ``str(256 * 1024)``) are invisible on purpose — the rule only
    speaks when it can read the number."""
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.value, ast.Constant):
            name = _env_subscript_name(node.targets[0])
            if name in _BUCKET_KNOBS:
                nbytes = _parse_bytes_literal(node.value.value)
                if nbytes:
                    out.append((name, nbytes, node.lineno))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "setdefault" \
                and isinstance(node.func.value, ast.Attribute) \
                and node.func.value.attr == "environ" \
                and len(node.args) == 2 \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[1], ast.Constant):
            name = node.args[0].value
            if name in _BUCKET_KNOBS:
                nbytes = _parse_bytes_literal(node.args[1].value)
                if nbytes:
                    out.append((name, nbytes, node.lineno))
    return out


_SYNC_PER_TENSOR = frozenset({"allreduce", "allreduce_"})
#: Distinct synchronous submit sites in one loop body before HVD602
#: calls it a serialization point (the unrolled per-layer shape).
_SYNC_SITE_THRESHOLD = 3


def _rule_601(verifier, table, ranks):
    diags = []
    top_n = ranks[-1]
    step_bytes = table.get("step_bytes") or DEFAULT_TABLE["step_bytes"]
    for mod in _entry_modules(verifier):
        if not any(fn.has_coll for fn in mod.funcs.values()):
            continue
        for knob, configured, line in _literal_bucket_configs(mod):
            kind = "reducescatter" if "ZERO" in knob else "allreduce"
            opt = bucket_optimum(step_bytes, top_n, table, kind=kind)
            ratio = max(configured / opt, opt / configured)
            if ratio < 2.0:
                continue
            diags.append(Diagnostic.make(
                "HVD601",
                f"{knob}={configured} is predicted ~{ratio:.1f}x away "
                f"from the model's bucket optimum (~{opt} bytes) at "
                f"n={top_n}: too-small buckets pay the per-collective "
                "latency once per bucket; too-large ones serialize the "
                "last bucket's drain behind compute",
                file=mod.path, line=line,
                hint="size buckets near sqrt(step_bytes * latency / "
                     "per_byte_cost) for the cohort you deploy at, or "
                     "let the autotuner sweep it; " + _DOC_HINT))
    return diags


def _rule_602(verifier):
    diags = []
    for mod in _entry_modules(verifier):
        for qual in sorted(mod.funcs):
            fn = mod.funcs[qual]
            top, loops = [], []
            _walk_program(fn.program, top, loops, None)
            for loop in loops:
                if not loop.events:
                    continue
                barriers = [e for e in loop.events
                            if canonical_kind(e.kind) == "barrier"]
                others = [e for e in loop.events
                          if canonical_kind(e.kind) != "barrier"]
                if barriers and others:
                    ev = barriers[0]
                    diags.append(Diagnostic.make(
                        "HVD602",
                        f"barrier inside the step loop of {qual} "
                        f"serializes {len(others)} co-resident "
                        "collective(s): every rank drains the full "
                        "negotiation round trip with zero overlap "
                        "opportunity, once per step",
                        file=mod.path, line=ev.line,
                        hint="drop the per-step barrier (collectives "
                             "already synchronize) or move it out of "
                             "the loop; " + _DOC_HINT))
                    continue
                sync_sites = sorted({
                    e.line for e in loop.events
                    if e.kind in _SYNC_PER_TENSOR
                    and not _is_async(e)})
                # Three distinct sites is the hand-unrolled per-layer
                # gradient shape; a couple of per-iteration scalar
                # metric reductions (epoch loss + val loss) are real
                # programs and stay clean.
                if len(sync_sites) >= _SYNC_SITE_THRESHOLD:
                    diags.append(Diagnostic.make(
                        "HVD602",
                        f"{len(sync_sites)} synchronous per-tensor "
                        f"allreduce call sites in one step loop of "
                        f"{qual} (lines "
                        f"{', '.join(str(s) for s in sync_sites)}): "
                        "each blocks before the next submits, so the "
                        "predicted critical path is their serial sum "
                        "at every cohort size",
                        file=mod.path, line=sync_sites[0],
                        hint="switch to allreduce_async + synchronize "
                             "(or grouped_allreduce) so transfers "
                             "pipeline; " + _DOC_HINT))
    return diags


def _rule_603(verifier, table, ranks):
    if table.get("compute_s") is None or len(ranks) < 2:
        # No calibrated compute baseline — a 50% comm fraction claim
        # would be fiction. The default table never fires this rule.
        return []
    diags = []
    for sched in extract_schedules(verifier):
        if not sched["events"] or not sched["in_loop"]:
            continue
        curve = [(n, predict_step(sched["events"], n, table))
                 for n in ranks]
        for (n_lo, lo), (n_hi, hi) in zip(curve, curve[1:]):
            if lo["comm_fraction"] < 0.5 <= hi["comm_fraction"]:
                dominating = max(hi["by_kind"].items(),
                                 key=lambda kv: kv[1])[0]
                diags.append(Diagnostic.make(
                    "HVD603",
                    f"predicted scale cliff in {sched['function']}: "
                    f"comm fraction crosses 50% between n={n_lo} "
                    f"({lo['comm_fraction'] * 100.0:.0f}%) and "
                    f"n={n_hi} ({hi['comm_fraction'] * 100.0:.0f}%), "
                    f"dominated by {dominating} — past that cohort "
                    "the step is communication-bound and more chips "
                    "stop buying speedup",
                    file=sched["file"], line=sched["line"],
                    hint="overlap or shrink the dominating "
                         "collective (async submits, compression, "
                         "larger per-rank batch), or cap deployment "
                         "below the cliff; " + _DOC_HINT))
                break
    return diags


def perf_diagnostics(verifier, table=None, target_ranks=None):
    """The HVD6xx stream over a (shared) Verifier corpus, suppression
    comments applied. Reuses the invocation's fixpoint — never re-runs
    it."""
    from .schedule import _suppress
    table = table or resolve_table()
    ranks = list(target_ranks or target_ranks_from_env())
    verifier.fixpoint()
    diags = (_rule_601(verifier, table, ranks)
             + _rule_602(verifier)
             + _rule_603(verifier, table, ranks))
    return dedupe(sorted(_suppress(diags, verifier.corpus),
                         key=Diagnostic.sort_key))


# -- autotuner warm-start priors --------------------------------------------
def _prior_cost(arm_name, candidate, world, table):
    """Predicted per-step cost of one candidate (lower probes first).
    Deliberately coarse — it only has to ORDER the sweep; measured
    scores still decide."""
    step_bytes = float(table.get("step_bytes")
                       or DEFAULT_TABLE["step_bytes"])
    if arm_name == "host":
        fusion, cycle_ms, _min_bucket = candidate
        fusion = max(1.0, float(fusion or 1))
        buckets = max(1.0, math.ceil(step_bytes / fusion))
        per = collective_time("allreduce", fusion, world, table)
        # each fused buffer waits out half a negotiation cycle on
        # average before it ships
        return buckets * (per + float(cycle_ms or 0.0) / 2e3)
    if arm_name in ("overlap", "zero"):
        kind = "reducescatter" if arm_name == "zero" else "allreduce"
        bucket = max(1.0, float(candidate))
        buckets = max(1.0, math.ceil(step_bytes / bucket))
        lat, bw = _terms(kind, world)
        alpha, byte_s = _coeff(table, kind)
        # (T/B)·latency overhead + un-overlappable last-bucket drain
        return buckets * alpha * lat + bucket * byte_s * bw
    if arm_name == "compression":
        codec, _threshold = candidate
        ratio = {"none": 1.0, "fp16": 0.5, "bf16": 0.5,
                 "int8": 0.25, "fp8": 0.25}.get(str(codec), 0.5)
        return collective_time("allreduce", step_bytes * ratio, world,
                               table)
    return 0.0


def predicted_cost(arm_name, candidate, world, table=None):
    """Public face of the per-candidate prior (autotune's ``predicted``
    store field): predicted per-step seconds for one arm candidate."""
    return _prior_cost(arm_name, candidate, max(2, int(world or 2)),
                       table or resolve_table())


def rank_candidates(arm_name, candidates, world, table=None):
    """Autotune warm-start prior: candidate indices ordered by
    predicted cost (ascending), ties broken by original grid order so
    the result is deterministic and identical on every rank."""
    table = table or resolve_table()
    world = max(2, int(world or 2))
    costs = [(_prior_cost(arm_name, cand, world, table), i)
             for i, cand in enumerate(candidates)]
    return [i for _, i in sorted(costs)]
