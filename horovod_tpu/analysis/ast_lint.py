"""Layer 2: AST linter for user training scripts.

Python-level divergence the jaxpr layer cannot see: the reference
framework's best-known user bug is the *rank-guarded collective* —

    if hvd.rank() == 0:
        hvd.allreduce(tensor)        # other ranks never arrive: hang

which the reference only diagnoses at runtime via the stall inspector
(reference: horovod/common/stall_inspector.cc warning text). Here it is
a static finding. Three rules:

- **HVD201** (error) — a collective call inside an ``if``/``while``
  whose condition depends on ``rank()`` and whose other branch performs
  no collective: only some ranks reach it.
- **HVD202** (warning) — a script that ``init()``s and builds a
  ``DistributedOptimizer`` but never broadcasts initial state (no
  ``broadcast_parameters``/``broadcast_optimizer_state``/Broadcast
  callback, and no elastic state sync): ranks train from divergent
  initializations.
- **HVD203** (warning) — collectives *without an explicit* ``name=``
  under rank-dependent control flow: auto-generated names are assigned
  in call order, so name streams diverge across ranks and the
  negotiation never matches them up.
- **HVD204** (error) — a ``horovod_tpu.checkpoint`` save/restore call
  inside a rank guard: those helpers already write on rank 0 only and
  BARRIER (or broadcast to) every rank internally, so guarding them
  with ``if hvd.rank() == 0:`` means the other ranks never reach the
  barrier — the classic non-root-only checkpointing deadlock.

Suppression: append ``# hvd-lint: disable=HVD201`` (comma-separate for
several rules, or ``disable=all``) to the flagged line or the line
above it; ``# hvd-lint: disable-file=HVD202`` anywhere disables a rule
for the whole file. Pure stdlib — no jax/torch/tf imports.
"""

import ast
import os
import re

from .diagnostics import Diagnostic, dedupe

# Eager named-tensor API (ops/collectives.py + functions.py) plus the
# in-jit spellings (jax.lax collectives) users call inside step bodies.
COLLECTIVE_CALLS = frozenset({
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "grouped_allreduce", "grouped_allreduce_", "grouped_allreduce_async",
    "grouped_allreduce_async_",
    "allgather", "allgather_async", "grouped_allgather",
    "grouped_allgather_async",
    "broadcast", "broadcast_", "broadcast_async", "broadcast_async_",
    "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async", "grouped_reducescatter",
    "grouped_reducescatter_async",
    "barrier", "join",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables", "broadcast_object", "allgather_object",
})
LAX_COLLECTIVE_CALLS = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "psum_scatter",
})
# Exempt from HVD203 (the unnamed-collective warning): ops with no
# user-visible name kwarg, lax collectives (paired by program point,
# not name), and the object/state broadcast helpers, whose names are
# fixed internally (functions.py) — never call-order dependent.
_UNNAMED_OK = (frozenset({
    "barrier", "join",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables", "broadcast_object", "allgather_object",
}) | LAX_COLLECTIVE_CALLS)
RANK_CALLS = frozenset({"rank", "local_rank", "cross_rank", "axis_index"})
BROADCAST_STATE_CALLS = frozenset({
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables", "broadcast_object",
})
DIST_OPT_CALLS = frozenset({
    "DistributedOptimizer", "DistributedAdasumOptimizer",
})
# horovod_tpu.checkpoint helpers that coordinate internally (rank-0
# write + barrier, or restore + broadcast): calling them under a rank
# guard deadlocks the unguarded ranks (HVD204).
CHECKPOINT_CALLS = frozenset({
    "save", "save_step", "restore", "restore_latest",
})
# Presence of any of these identifiers means initial-state sync happens
# through a channel HVD202 should not second-guess.
_SYNC_MARKERS = frozenset({
    "BroadcastGlobalVariablesCallback", "broadcast_global_variables",
})
_ELASTIC_STATE_NAMES = frozenset({
    "TorchState", "TensorFlowKerasState", "KerasState", "ObjectState",
    "State",
})

_SUPPRESS_RE = re.compile(r"hvd-lint:\s*disable=([A-Za-z0-9,\s]+)")
_SUPPRESS_FILE_RE = re.compile(r"hvd-lint:\s*disable-file=([A-Za-z0-9,\s]+)")
_DOC_HINT = "see docs/lint.md"


def _root_name(node):
    """Leftmost Name of an attribute chain (``hvd.torch.rank`` -> hvd)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _terminal_name(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _scan_statements(stmts):
    """Yield nodes in statement bodies without descending into nested
    function/class definitions (code there is defined, not executed,
    under the guard)."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _Analyzer(ast.NodeVisitor):
    def __init__(self, filename):
        self.filename = filename
        self.diags = []
        self.hvd_aliases = set()    # names bound to horovod_tpu modules
        self.hvd_names = set()      # functions imported from horovod_tpu
        self.ckpt_aliases = set()   # names bound to horovod_tpu.checkpoint
        self.ckpt_names = set()     # functions imported from .checkpoint
        self.lax_aliases = {"lax"}  # `jax.lax` / `from jax import lax`
        self.has_init = False
        self.dist_opt_node = None
        self.has_broadcast = False
        self.uses_elastic = False
        self._flagged = set()       # id(call) already reported

    # -- imports -----------------------------------------------------------
    def visit_Import(self, node):
        for alias in node.names:
            target = alias.asname or alias.name.split(".")[0]
            if alias.name.split(".")[0] in ("horovod_tpu", "horovod"):
                self.hvd_aliases.add(target)
                if "elastic" in alias.name:
                    self.uses_elastic = True
                if (alias.name.endswith(".checkpoint")
                        and alias.asname is not None):
                    # `import horovod_tpu.checkpoint as ckpt`
                    self.ckpt_aliases.add(alias.asname)
            if alias.name in ("jax.lax",):
                self.lax_aliases.add(target)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        mod = node.module or ""
        if mod.split(".")[0] in ("horovod_tpu", "horovod"):
            if "elastic" in mod:
                self.uses_elastic = True
            if mod.endswith(".checkpoint"):
                # `from horovod_tpu.checkpoint import save_step [as s]`
                for alias in node.names:
                    self.ckpt_names.add(alias.asname or alias.name)
            for alias in node.names:
                name = alias.asname or alias.name
                if alias.name == "checkpoint":
                    # `from horovod_tpu import checkpoint [as ckpt]`
                    self.ckpt_aliases.add(name)
                if alias.name == "elastic" or name == "elastic":
                    self.uses_elastic = True
                    self.hvd_aliases.add(name)
                elif alias.name in _ELASTIC_STATE_NAMES:
                    self.uses_elastic = True
                elif alias.name == "*":
                    self.hvd_names |= (COLLECTIVE_CALLS | RANK_CALLS
                                       | DIST_OPT_CALLS | {"init"})
                else:
                    self.hvd_names.add(name)
        if mod == "jax":
            for alias in node.names:
                if alias.name == "lax":
                    self.lax_aliases.add(alias.asname or "lax")
        self.generic_visit(node)

    # -- call classification ----------------------------------------------
    def _is_hvd_call(self, call, names):
        term = _terminal_name(call.func)
        if term not in names:
            return False
        if isinstance(call.func, ast.Name):
            # A bare name is horovod's only if it was imported from
            # horovod (a file with no horovod imports has no horovod
            # collectives — bare `broadcast(...)` there is someone
            # else's function).
            return term in self.hvd_names
        root = _root_name(call.func)
        return root in self.hvd_aliases

    def _is_collective(self, call):
        term = _terminal_name(call.func)
        if term in LAX_COLLECTIVE_CALLS:
            root = _root_name(call.func)
            return root in self.lax_aliases or root == "jax"
        return self._is_hvd_call(call, COLLECTIVE_CALLS)

    def _is_rank_call(self, call):
        term = _terminal_name(call.func)
        if term == "axis_index":
            root = _root_name(call.func)
            return root in self.lax_aliases or root == "jax"
        return self._is_hvd_call(call, RANK_CALLS)

    def _is_checkpoint_call(self, call):
        term = _terminal_name(call.func)
        if term not in CHECKPOINT_CALLS:
            return False
        if isinstance(call.func, ast.Name):
            return term in self.ckpt_names
        root = _root_name(call.func)
        if root in self.ckpt_aliases:
            return True
        # `hvd.checkpoint.save(...)` — a horovod alias with an explicit
        # `.checkpoint.` hop in the attribute chain.
        if root in self.hvd_aliases:
            chain = []
            node = call.func
            while isinstance(node, ast.Attribute):
                chain.append(node.attr)
                node = node.value
            return "checkpoint" in chain[1:]
        return False

    def _is_rank_dependent(self, expr):
        return any(isinstance(n, ast.Call) and self._is_rank_call(n)
                   for n in ast.walk(expr))

    def _collectives_in(self, stmts):
        out = []
        for node in _scan_statements(stmts):
            if (isinstance(node, ast.Call) and self._is_collective(node)
                    and id(node) not in self._flagged):
                has_name = any(kw.arg == "name" for kw in node.keywords)
                out.append((node, has_name))
        out.sort(key=lambda item: (item[0].lineno, item[0].col_offset))
        return out

    def _checkpoint_calls_in(self, stmts):
        out = [node for node in _scan_statements(stmts)
               if (isinstance(node, ast.Call)
                   and self._is_checkpoint_call(node)
                   and id(node) not in self._flagged)]
        out.sort(key=lambda n: (n.lineno, n.col_offset))
        return out

    # -- rules -------------------------------------------------------------
    def _report_201(self, call, kind):
        self._flagged.add(id(call))
        fn = _terminal_name(call.func)
        self.diags.append(Diagnostic.make(
            "HVD201",
            f"collective `{fn}` runs only on ranks satisfying the "
            f"{kind} condition: the other ranks never enter it and the "
            "job deadlocks (every rank must call every collective)",
            file=self.filename, line=call.lineno,
            hint="move the collective outside the rank guard — guard "
                 "only the rank-local work (logging, checkpointing); "
                 + _DOC_HINT))

    def _report_203(self, call):
        self._flagged.add(id(call))
        fn = _terminal_name(call.func)
        self.diags.append(Diagnostic.make(
            "HVD203",
            f"collective `{fn}` inside rank-dependent control flow has "
            "no explicit name=: auto-generated names follow call order, "
            "which differs across ranks here, so the negotiation never "
            "matches them (DuplicateNameError / stall)",
            file=self.filename, line=call.lineno,
            hint="pass a stable name= shared by every rank; "
                 + _DOC_HINT))

    def _report_204(self, call, kind):
        self._flagged.add(id(call))
        fn = _terminal_name(call.func)
        self.diags.append(Diagnostic.make(
            "HVD204",
            f"checkpoint `{fn}` inside a rank-guarded `{kind}`: the "
            "checkpoint helpers already write on rank 0 only and "
            "barrier (or broadcast to) EVERY rank internally, so the "
            "unguarded ranks never reach the barrier and the job "
            "deadlocks (the non-root-only checkpointing hazard)",
            file=self.filename, line=call.lineno,
            hint="call it unguarded on every rank — rank selection is "
                 "handled inside horovod_tpu.checkpoint; " + _DOC_HINT))

    def visit_If(self, node):
        if self._is_rank_dependent(node.test):
            body_c = self._collectives_in(node.body)
            else_c = self._collectives_in(node.orelse)
            if body_c and else_c:
                for call, has_name in body_c + else_c:
                    if not has_name and (_terminal_name(call.func)
                                         not in _UNNAMED_OK):
                        self._report_203(call)
            elif body_c or else_c:
                for call, _ in (body_c or else_c):
                    self._report_201(call, "if")
            body_k = self._checkpoint_calls_in(node.body)
            else_k = self._checkpoint_calls_in(node.orelse)
            if bool(body_k) != bool(else_k):
                # Symmetric branches (both checkpoint) still reach the
                # internal barrier on every rank; only the one-sided
                # guard strands the other ranks.
                for call in (body_k or else_k):
                    self._report_204(call, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        if self._is_rank_dependent(node.test):
            for call, _ in self._collectives_in(node.body):
                self._report_201(call, "while")
            for call in self._checkpoint_calls_in(node.body):
                self._report_204(call, "while")
        self.generic_visit(node)

    def visit_Call(self, node):
        term = _terminal_name(node.func)
        if term == "init" and self._is_hvd_call(node, {"init"}):
            self.has_init = True
        elif term in DIST_OPT_CALLS:
            if self.dist_opt_node is None:
                self.dist_opt_node = node
        elif term in BROADCAST_STATE_CALLS:
            self.has_broadcast = True
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if node.attr in _SYNC_MARKERS:
            self.has_broadcast = True
        elif node.attr == "elastic" and _root_name(node) in self.hvd_aliases:
            self.uses_elastic = True
        self.generic_visit(node)

    def visit_Name(self, node):
        if node.id in _SYNC_MARKERS:
            self.has_broadcast = True
        self.generic_visit(node)

    def finish(self):
        if (self.has_init and self.dist_opt_node is not None
                and not self.has_broadcast and not self.uses_elastic):
            self.diags.append(Diagnostic.make(
                "HVD202",
                "script calls init() and builds a DistributedOptimizer "
                "but never broadcasts initial state: ranks start from "
                "divergent parameters/optimizer moments and silently "
                "train different models",
                file=self.filename, line=self.dist_opt_node.lineno,
                hint="after building params/optimizer, call "
                     "broadcast_parameters(...) and "
                     "broadcast_optimizer_state(..., root_rank=0) (or "
                     "use the Broadcast callback / elastic state); "
                     + _DOC_HINT))
        return self.diags


def _apply_suppressions(diags, src):
    lines = src.splitlines()
    file_off = set()
    per_line = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_FILE_RE.search(line)
        if m:
            file_off.update(r.strip().upper()
                            for r in m.group(1).split(","))
        m = _SUPPRESS_RE.search(line)
        if m:
            per_line[i] = {r.strip().upper() for r in m.group(1).split(",")}

    def suppressed(d):
        if "ALL" in file_off or d.rule in file_off:
            return True
        for ln in (d.line, d.line - 1):
            rules = per_line.get(ln)
            if rules and ("ALL" in rules or d.rule in rules):
                # Same-line marker always applies; a previous-line marker
                # only applies if that line is a standalone comment.
                if ln == d.line or lines[ln - 1].lstrip().startswith("#"):
                    return True
        return False

    return [d for d in diags if not suppressed(d)]


def lint_source(src, filename="<string>"):
    """Lint python source text; returns a list of :class:`Diagnostic`."""
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as exc:
        return [Diagnostic.make(
            "HVD001", f"syntax error: {exc.msg}",
            file=filename, line=exc.lineno or 0)]
    analyzer = _Analyzer(filename)
    analyzer.visit(tree)
    diags = analyzer.finish()
    diags = _apply_suppressions(diags, src)
    return dedupe(sorted(diags, key=Diagnostic.sort_key))


def lint_file(path):
    with open(path, encoding="utf-8", errors="replace") as f:
        return lint_source(f.read(), filename=path)


def iter_python_files(paths):
    for path in paths:
        if os.path.isfile(path):
            yield path
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__")
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


def lint_paths(paths):
    """Lint every ``.py`` file under ``paths`` (files or directories)."""
    diags = []
    for path in iter_python_files(paths):
        diags.extend(lint_file(path))
    return diags
