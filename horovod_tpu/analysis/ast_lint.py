"""Layer 2: AST linter for user training scripts.

Python-level divergence the jaxpr layer cannot see: the reference
framework's best-known user bug is the *rank-guarded collective* —

    if hvd.rank() == 0:
        hvd.allreduce(tensor)        # other ranks never arrive: hang

which the reference only diagnoses at runtime via the stall inspector
(reference: horovod/common/stall_inspector.cc warning text). Here it is
a static finding. Three rules:

- **HVD201** (error) — a collective call inside an ``if``/``while``
  whose condition depends on ``rank()`` and whose other branch performs
  no collective: only some ranks reach it.
- **HVD202** (warning) — a script that ``init()``s and builds a
  ``DistributedOptimizer`` but never broadcasts initial state (no
  ``broadcast_parameters``/``broadcast_optimizer_state``/Broadcast
  callback, and no elastic state sync): ranks train from divergent
  initializations.
- **HVD203** (warning) — collectives *without an explicit* ``name=``
  under rank-dependent control flow: auto-generated names are assigned
  in call order, so name streams diverge across ranks and the
  negotiation never matches them up.
- **HVD204** (error) — a ``horovod_tpu.checkpoint`` save/restore call
  inside a rank guard: those helpers already write on rank 0 only and
  BARRIER (or broadcast to) every rank internally, so guarding them
  with ``if hvd.rank() == 0:`` means the other ranks never reach the
  barrier — the classic non-root-only checkpointing deadlock.
- **HVD205** (warning) — a lossy compressor (``Compression.fp16/bf16/
  int8/fp8``) on a broadcast/initial-sync collective, or on a visibly
  integer/bool tensor: compression exists for gradient reduction only
  (reference semantics); state sync must be exact and counts/masks
  have no lossy representation.
- **HVD206** (warning) — a per-tensor eager ``allreduce`` whose tensor
  is the iteration variable of an enclosing ``for`` loop (one blocking
  collective per tensor): each call pays full dispatch + negotiation
  latency serially. The bucketed API reduces the whole set in fused
  buckets — ``grouped_allreduce(list)`` for explicit reductions, or
  ``DistributedOptimizer`` (whose dispatch plane buckets and, under
  ``HVDTPU_OVERLAP=1``, overlaps them with backprop) for gradients.
- **HVD207** (warning) — a raw ``t0 = time.time()/perf_counter()``
  begin read whose elapsed (``clock() - t0``) feeds a metric
  ``observe()``: the ``telemetry.spans.span`` context is the single
  instrument that feeds the histogram AND the timeline AND the trace
  plane, and its disabled mode reads no clock at all. ``monotonic`` /
  ``perf_counter_ns`` pairs and elapsed values that go to logs (not
  metrics) are not findings.
- **HVD210** (warning) — an *unbounded* request buffer in serving
  context (a file under ``serving/``, a class named
  scheduler/router/serving, or a ``handle_*`` request handler): a bare
  ``queue.Queue()``/``SimpleQueue()``, a ``deque()`` without
  ``maxlen``, or ``.append()`` onto a request-named list. The serving
  plane's backpressure contract is bounded-queues-or-429
  (docs/serving.md); an unbounded buffer absorbs overload into memory
  and tail latency where nothing can shed it.
- **HVD212** (warning) — direct worker spawn/terminate outside the
  driver/actuator modules: a hand-constructed
  ``spawn.SlotProcess(...)`` or ``terminate``/``kill``/``send_signal``
  on a worker process handle (``.proc``, ``workers[...]``, or a name
  bound to either). Cohort mutation is a desired-state write the
  elastic drivers reconcile (target files, drain flags, the fleet
  lease ledger); a bypass mutates membership with no journal entry,
  no lease, and no blacklist accounting.
- **HVD213** (warning) — silent degradation in serving/fleet context
  (a file under ``serving/`` or ``fleet/``, a class named
  router/scheduler/worker/arbiter/migration, or a ``handle_*``
  handler): an ``except`` clause catching a transport error
  (``OSError`` and kin, ``URLError``, ``HTTPException``,
  ``TimeoutError``, a ``*TRANSPORT*`` tuple) whose body neither
  re-raises nor records it (no ``raise``, no log call, no metric
  ``inc``/``observe``). The degradation contract is *loud* fallback
  (docs/serving.md); a swallowed transport fault becomes unexplained
  tail latency or quietly lost capacity.

The HVD3xx block is the static half of ``hvd-sanitize`` (runtime half:
analysis/sanitizer.py) — thread-safety and liveness hazards in the kind
of background-thread control plane this framework is built from:

- **HVD301** (warning) — a mutable ``self`` attribute written both by a
  ``threading.Thread`` target (or a method it calls) and by other
  methods, with at least one write outside any ``with <lock>`` block:
  a data race unless some ownership protocol exists (suppress with an
  ownership comment where one does).
- **HVD302** (error) — ``.acquire()`` with no ``.release()`` of the
  same lock in an enclosing/adjacent ``try``/``finally`` in the same
  scope: an exception between them leaks the lock and wedges every
  later acquirer. Use ``with``.
- **HVD303** (warning) — an *unbounded* blocking call (``urlopen``,
  ``subprocess.*``, or ``.wait()``/``.join()``/``.get()`` with no
  timeout) lexically inside a cycle/watchdog/heartbeat loop body (a
  thread target whose thread or method name says coordinator/cycle/
  watchdog/heartbeat/stall, plus the methods it calls): these threads
  pace the data plane, so one unbounded call starves every in-flight
  collective.
- **HVD305** (warning) — a thread constructed with neither
  ``daemon=True`` nor any visible ``join()``/``.daemon = True`` path:
  it will keep the interpreter alive after ``shutdown()``.

**HVD304** (warning, module-wide) — ``os.environ`` read of an
``HVDTPU_*``/``HOROVOD_*`` name outside utils/envparse.py: it bypasses
the prefix fallback AND the knob registry, so the knob drifts out of
docs/knobs.md (the registry<->docs cross-check is rule HVD306,
:func:`check_knob_docs`).

Suppression: append ``# hvd-lint: disable=HVD201`` (comma-separate for
several rules, or ``disable=all``) to the flagged line or the line
above it; ``# hvd-lint: disable-file=HVD202`` anywhere disables a rule
for the whole file. Pure stdlib — no jax/torch/tf imports.
"""

import ast
import os
import re

from .diagnostics import Diagnostic, dedupe

# Eager named-tensor API (ops/collectives.py + functions.py) plus the
# in-jit spellings (jax.lax collectives) users call inside step bodies.
COLLECTIVE_CALLS = frozenset({
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "grouped_allreduce", "grouped_allreduce_", "grouped_allreduce_async",
    "grouped_allreduce_async_",
    "allgather", "allgather_async", "grouped_allgather",
    "grouped_allgather_async",
    "broadcast", "broadcast_", "broadcast_async", "broadcast_async_",
    "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async", "grouped_reducescatter",
    "grouped_reducescatter_async",
    "barrier", "join",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables", "broadcast_object", "allgather_object",
})
LAX_COLLECTIVE_CALLS = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "psum_scatter",
})
# Exempt from HVD203 (the unnamed-collective warning): ops with no
# user-visible name kwarg, lax collectives (paired by program point,
# not name), and the object/state broadcast helpers, whose names are
# fixed internally (functions.py) — never call-order dependent.
_UNNAMED_OK = (frozenset({
    "barrier", "join",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables", "broadcast_object", "allgather_object",
}) | LAX_COLLECTIVE_CALLS)
# Per-tensor eager allreduce spellings (rule HVD206): the grouped_*
# family IS the bucketed API and is exempt by construction.
PER_TENSOR_ALLREDUCE_CALLS = frozenset({
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
})
RANK_CALLS = frozenset({"rank", "local_rank", "cross_rank", "axis_index"})
BROADCAST_STATE_CALLS = frozenset({
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables", "broadcast_object",
})
DIST_OPT_CALLS = frozenset({
    "DistributedOptimizer", "DistributedAdasumOptimizer",
})
# Accepted env spellings of the ZeRO knob (rule HVD208): a script that
# exports any of these and builds an Adasum / sub-cohort optimizer
# will crash at DistributedOptimizer.__init__.
_ZERO_ENV_NAMES = frozenset({
    "HVDTPU_ZERO", "HOROVOD_TPU_ZERO", "HOROVOD_ZERO",
})
# horovod_tpu.checkpoint helpers that coordinate internally (rank-0
# write + barrier, or restore + broadcast): calling them under a rank
# guard deadlocks the unguarded ranks (HVD204).
CHECKPOINT_CALLS = frozenset({
    "save", "save_step", "restore", "restore_latest",
})
# Lossy members of the Compression surface (ops/compression.py): wire
# quantizers plus the narrowing casts. Reference semantics: compression
# exists for gradient REDUCTION — state sync (broadcast) must be exact,
# and integer/bool payloads have no meaningful lossy representation
# (rule HVD205).
LOSSY_COMPRESSORS = frozenset({"fp16", "bf16", "int8", "fp8"})
SYNC_COLLECTIVE_CALLS = frozenset({
    "broadcast", "broadcast_", "broadcast_async", "broadcast_async_",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_variables", "broadcast_object",
})
# Attribute names that mark an integer/bool tensor expression
# (dtype=jnp.int32, x.astype(np.bool_), torch.int64, ...).
_INTY_DTYPE_ATTRS = frozenset({
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "bool_", "bool", "long",
})
# Rule HVD209 (extends HVD205's integer-tensor walk): expressions that
# visibly produce INDEX tensors even without a spelled-out int dtype —
# the indices half of a sparse gradient (`grad.indices`, torch
# `t.indices()`, `t._indices()`) and the index-producing constructions.
# Indices must be exact: a lossy wire format rounds row ids into the
# WRONG rows with no arithmetic error to catch it (docs/sparse.md).
_INDEX_ATTRS = frozenset({"indices", "_indices"})
_INDEX_PRODUCING_CALLS = frozenset({
    "indices", "_indices", "argsort", "argmax", "argmin", "nonzero",
    "flatnonzero", "searchsorted",
})
# Presence of any of these identifiers means initial-state sync happens
# through a channel HVD202 should not second-guess.
_SYNC_MARKERS = frozenset({
    "BroadcastGlobalVariablesCallback", "broadcast_global_variables",
})
_ELASTIC_STATE_NAMES = frozenset({
    "TorchState", "TensorFlowKerasState", "KerasState", "ObjectState",
    "State",
})

_SUPPRESS_RE = re.compile(r"hvd-lint:\s*disable=([A-Za-z0-9,\s]+)")
_SUPPRESS_FILE_RE = re.compile(r"hvd-lint:\s*disable-file=([A-Za-z0-9,\s]+)")
_DOC_HINT = "see docs/lint.md"


def _root_name(node):
    """Leftmost Name of an attribute chain (``hvd.torch.rank`` -> hvd)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _terminal_name(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _scan_statements(stmts):
    """Yield nodes in statement bodies without descending into nested
    function/class definitions (code there is defined, not executed,
    under the guard)."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# Names importable directly from the package that are MODULES, not
# functions: `from horovod_tpu import basics` binds a module alias, so
# `basics.allreduce(...)` must resolve like `hvd.allreduce(...)`, not
# like a bare imported function.
_HVD_SUBMODULES = frozenset({
    "basics", "jax", "torch", "tensorflow", "keras", "elastic",
    "checkpoint", "ops", "functions", "native", "spark", "ray",
    "runner", "compression", "tracing", "telemetry", "chaos",
    "guardian", "analysis", "process_sets", "autotune", "coordinator",
    "backend", "utils", "models", "callbacks", "mpi_ops",
})


class AliasResolver:
    """Import-alias bookkeeping shared by every AST rule layer.

    Every spelling of a collective call — ``hvd.allreduce(...)``,
    ``from horovod_tpu import allreduce``, ``basics.allreduce(...)``,
    ``from horovod_tpu.basics import allreduce as ar`` — resolves here,
    in exactly one place, for the HVD2xx single-hop rules and the
    interprocedural schedule extractor (analysis/schedule.py) alike.
    Feed it every Import/ImportFrom node, then classify calls with
    :meth:`is_collective` / :meth:`is_rank_call` /
    :meth:`is_checkpoint_call` / :meth:`collective_kind`.
    """

    def __init__(self):
        self.hvd_aliases = set()    # names bound to horovod_tpu modules
        self.hvd_names = set()      # functions imported from horovod_tpu
        self.ckpt_aliases = set()   # names bound to horovod_tpu.checkpoint
        self.ckpt_names = set()     # functions imported from .checkpoint
        self.lax_aliases = {"lax"}  # `jax.lax` / `from jax import lax`
        self.uses_elastic = False

    # -- imports -----------------------------------------------------------
    def visit_import(self, node):
        for alias in node.names:
            target = alias.asname or alias.name.split(".")[0]
            if alias.name.split(".")[0] in ("horovod_tpu", "horovod"):
                self.hvd_aliases.add(target)
                if "elastic" in alias.name:
                    self.uses_elastic = True
                if (alias.name.endswith(".checkpoint")
                        and alias.asname is not None):
                    # `import horovod_tpu.checkpoint as ckpt`
                    self.ckpt_aliases.add(alias.asname)
            if alias.name in ("jax.lax",):
                self.lax_aliases.add(target)

    def visit_import_from(self, node):
        mod = node.module or ""
        if mod.split(".")[0] in ("horovod_tpu", "horovod"):
            if "elastic" in mod:
                self.uses_elastic = True
            if mod.endswith(".checkpoint"):
                # `from horovod_tpu.checkpoint import save_step [as s]`
                for alias in node.names:
                    self.ckpt_names.add(alias.asname or alias.name)
            for alias in node.names:
                name = alias.asname or alias.name
                if alias.name == "checkpoint":
                    # `from horovod_tpu import checkpoint [as ckpt]`
                    self.ckpt_aliases.add(name)
                if alias.name == "elastic" or name == "elastic":
                    self.uses_elastic = True
                    self.hvd_aliases.add(name)
                elif alias.name in _ELASTIC_STATE_NAMES:
                    self.uses_elastic = True
                elif alias.name == "*":
                    self.hvd_names |= (COLLECTIVE_CALLS | RANK_CALLS
                                       | DIST_OPT_CALLS | {"init"})
                elif alias.name in _HVD_SUBMODULES:
                    # `from horovod_tpu import basics` — a MODULE alias:
                    # `basics.allreduce(...)` resolves through it.
                    self.hvd_aliases.add(name)
                else:
                    self.hvd_names.add(name)
        if mod == "jax":
            for alias in node.names:
                if alias.name == "lax":
                    self.lax_aliases.add(alias.asname or "lax")

    # -- call classification ----------------------------------------------
    def is_hvd_call(self, call, names):
        term = _terminal_name(call.func)
        if term not in names:
            return False
        if isinstance(call.func, ast.Name):
            # A bare name is horovod's only if it was imported from
            # horovod (a file with no horovod imports has no horovod
            # collectives — bare `broadcast(...)` there is someone
            # else's function).
            return term in self.hvd_names
        root = _root_name(call.func)
        return root in self.hvd_aliases

    def is_collective(self, call):
        term = _terminal_name(call.func)
        if term in LAX_COLLECTIVE_CALLS:
            root = _root_name(call.func)
            return root in self.lax_aliases or root == "jax"
        return self.is_hvd_call(call, COLLECTIVE_CALLS)

    def is_rank_call(self, call):
        term = _terminal_name(call.func)
        if term == "axis_index":
            root = _root_name(call.func)
            return root in self.lax_aliases or root == "jax"
        return self.is_hvd_call(call, RANK_CALLS)

    def is_checkpoint_call(self, call):
        term = _terminal_name(call.func)
        if term not in CHECKPOINT_CALLS:
            return False
        if isinstance(call.func, ast.Name):
            return term in self.ckpt_names
        root = _root_name(call.func)
        if root in self.ckpt_aliases:
            return True
        # `hvd.checkpoint.save(...)` — a horovod alias with an explicit
        # `.checkpoint.` hop in the attribute chain.
        if root in self.hvd_aliases:
            chain = []
            node = call.func
            while isinstance(node, ast.Attribute):
                chain.append(node.attr)
                node = node.value
            return "checkpoint" in chain[1:]
        return False

    def collective_kind(self, call):
        """Terminal collective name (``allreduce``, ``psum``, ...) when
        ``call`` is a collective, else None."""
        return _terminal_name(call.func) if self.is_collective(call) \
            else None


class _Analyzer(ast.NodeVisitor):
    def __init__(self, filename):
        self.filename = filename
        self.diags = []
        self.res = AliasResolver()  # shared import-alias bookkeeping
        self.has_init = False
        self.dist_opt_node = None
        self.has_broadcast = False
        self.int_names = set()      # names assigned integer-looking values
        self.index_names = set()    # names assigned index-producing exprs
        self.zero_env_set = False   # script set HVDTPU_ZERO-family env
        self._flagged = set()       # id(call) already reported

    # -- imports -----------------------------------------------------------
    def visit_Import(self, node):
        self.res.visit_import(node)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        self.res.visit_import_from(node)
        self.generic_visit(node)

    # -- call classification (delegated to the shared resolver) ------------
    def _is_hvd_call(self, call, names):
        return self.res.is_hvd_call(call, names)

    def _is_collective(self, call):
        return self.res.is_collective(call)

    def _is_rank_call(self, call):
        return self.res.is_rank_call(call)

    def _is_checkpoint_call(self, call):
        return self.res.is_checkpoint_call(call)

    def _is_rank_dependent(self, expr):
        return any(isinstance(n, ast.Call) and self._is_rank_call(n)
                   for n in ast.walk(expr))

    def _collectives_in(self, stmts):
        out = []
        for node in _scan_statements(stmts):
            if (isinstance(node, ast.Call) and self._is_collective(node)
                    and id(node) not in self._flagged):
                has_name = any(kw.arg == "name" for kw in node.keywords)
                out.append((node, has_name))
        out.sort(key=lambda item: (item[0].lineno, item[0].col_offset))
        return out

    def _checkpoint_calls_in(self, stmts):
        out = [node for node in _scan_statements(stmts)
               if (isinstance(node, ast.Call)
                   and self._is_checkpoint_call(node)
                   and id(node) not in self._flagged)]
        out.sort(key=lambda n: (n.lineno, n.col_offset))
        return out

    # -- rules -------------------------------------------------------------
    def _report_201(self, call, kind):
        self._flagged.add(id(call))
        fn = _terminal_name(call.func)
        self.diags.append(Diagnostic.make(
            "HVD201",
            f"collective `{fn}` runs only on ranks satisfying the "
            f"{kind} condition: the other ranks never enter it and the "
            "job deadlocks (every rank must call every collective)",
            file=self.filename, line=call.lineno,
            hint="move the collective outside the rank guard — guard "
                 "only the rank-local work (logging, checkpointing); "
                 + _DOC_HINT))

    def _report_203(self, call):
        self._flagged.add(id(call))
        fn = _terminal_name(call.func)
        self.diags.append(Diagnostic.make(
            "HVD203",
            f"collective `{fn}` inside rank-dependent control flow has "
            "no explicit name=: auto-generated names follow call order, "
            "which differs across ranks here, so the negotiation never "
            "matches them (DuplicateNameError / stall)",
            file=self.filename, line=call.lineno,
            hint="pass a stable name= shared by every rank; "
                 + _DOC_HINT))

    def _report_204(self, call, kind):
        self._flagged.add(id(call))
        fn = _terminal_name(call.func)
        self.diags.append(Diagnostic.make(
            "HVD204",
            f"checkpoint `{fn}` inside a rank-guarded `{kind}`: the "
            "checkpoint helpers already write on rank 0 only and "
            "barrier (or broadcast to) EVERY rank internally, so the "
            "unguarded ranks never reach the barrier and the job "
            "deadlocks (the non-root-only checkpointing hazard)",
            file=self.filename, line=call.lineno,
            hint="call it unguarded on every rank — rank selection is "
                 "handled inside horovod_tpu.checkpoint; " + _DOC_HINT))

    def visit_If(self, node):
        if self._is_rank_dependent(node.test):
            body_c = self._collectives_in(node.body)
            else_c = self._collectives_in(node.orelse)
            if body_c and else_c:
                for call, has_name in body_c + else_c:
                    if not has_name and (_terminal_name(call.func)
                                         not in _UNNAMED_OK):
                        self._report_203(call)
            elif body_c or else_c:
                for call, _ in (body_c or else_c):
                    self._report_201(call, "if")
            body_k = self._checkpoint_calls_in(node.body)
            else_k = self._checkpoint_calls_in(node.orelse)
            if bool(body_k) != bool(else_k):
                # Symmetric branches (both checkpoint) still reach the
                # internal barrier on every rank; only the one-sided
                # guard strands the other ranks.
                for call in (body_k or else_k):
                    self._report_204(call, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        if self._is_rank_dependent(node.test):
            for call, _ in self._collectives_in(node.body):
                self._report_201(call, "while")
            for call in self._checkpoint_calls_in(node.body):
                self._report_204(call, "while")
        self.generic_visit(node)

    # -- HVD206: per-tensor allreduce in a loop ----------------------------
    def _report_206(self, call):
        self._flagged.add(id(call))
        fn = _terminal_name(call.func)
        self.diags.append(Diagnostic.make(
            "HVD206",
            f"per-tensor `{fn}` over the loop variable: one blocking "
            "collective per tensor pays dispatch + negotiation latency "
            "serially, which the bucketed API amortizes into fused "
            "buckets",
            file=self.filename, line=call.lineno,
            hint="collect the tensors and make one grouped_allreduce() "
                 "call, or reduce gradients through "
                 "DistributedOptimizer (bucketed dispatch; "
                 "HVDTPU_OVERLAP=1 overlaps buckets with backprop); "
                 + _DOC_HINT))

    @staticmethod
    def _is_adasum_call(call):
        """op=...Adasum — per-tensor reduction IS Adasum's semantics
        (bucketing it would change the math: rule HVD405), so HVD206's
        use-the-grouped-API advice must not fire."""
        return any(kw.arg == "op" and _terminal_name(kw.value) == "Adasum"
                   for kw in call.keywords)

    @staticmethod
    def _tensor_is_loop_var(expr, names):
        """True when the reduced tensor IS the loop variable or a
        subscript/attribute/arithmetic view of it. Values that reach
        the loop variable only THROUGH a function call
        (``allreduce(train_step(model, batch))``) are new per-iteration
        data — the canonical per-batch metric reduction — and cannot be
        bucketed, so the walk stops at Call boundaries."""
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Name) and n.id in names:
                return True
            if isinstance(n, ast.Call):
                continue
            stack.extend(ast.iter_child_nodes(n))
        return False

    def visit_For(self, node):
        # A per-tensor eager allreduce whose tensor IS (or indexes
        # through) the loop variable — the reduce-one-tensor-per-
        # iteration shape. An unrelated allreduce in a training loop
        # (one metric per epoch/batch) is not a finding.
        names = {n.id for n in ast.walk(node.target)
                 if isinstance(n, ast.Name)}
        if names:
            for sub in _scan_statements(node.body):
                if (isinstance(sub, ast.Call)
                        and id(sub) not in self._flagged
                        and self._is_hvd_call(
                            sub, PER_TENSOR_ALLREDUCE_CALLS)
                        and not self._is_adasum_call(sub)
                        and sub.args
                        and self._tensor_is_loop_var(sub.args[0], names)):
                    self._report_206(sub)
        self.generic_visit(node)

    def _check_206_comp(self, node):
        # The comprehension spelling of the same shape:
        # [allreduce(g) for g in grads].
        names = set()
        for gen in node.generators:
            names |= {n.id for n in ast.walk(gen.target)
                      if isinstance(n, ast.Name)}
        if not names:
            return
        body = [node.value, node.key] if isinstance(node, ast.DictComp) \
            else [node.elt]
        for part in body:
            for sub in ast.walk(part):
                if (isinstance(sub, ast.Call)
                        and id(sub) not in self._flagged
                        and self._is_hvd_call(
                            sub, PER_TENSOR_ALLREDUCE_CALLS)
                        and not self._is_adasum_call(sub)
                        and sub.args
                        and self._tensor_is_loop_var(sub.args[0], names)):
                    self._report_206(sub)

    def visit_ListComp(self, node):
        self._check_206_comp(node)
        self.generic_visit(node)

    def visit_SetComp(self, node):
        self._check_206_comp(node)
        self.generic_visit(node)

    def visit_GeneratorExp(self, node):
        self._check_206_comp(node)
        self.generic_visit(node)

    def visit_DictComp(self, node):
        self._check_206_comp(node)
        self.generic_visit(node)

    # -- HVD205: lossy compression misuse ----------------------------------
    @staticmethod
    def _lossy_compression_kw(call):
        """Name of the lossy Compression member passed as
        ``compression=`` (``Compression.int8`` / ``hvd.Compression.fp16``
        / a bare imported alias), or None."""
        for kw in call.keywords:
            if kw.arg != "compression":
                continue
            if isinstance(kw.value, (ast.Attribute, ast.Name)):
                term = _terminal_name(kw.value)
                if term in LOSSY_COMPRESSORS:
                    return term
        return None

    @staticmethod
    def _expr_is_inty(expr):
        """Integer/bool evidence inside one expression: an int/bool
        dtype attribute or a randint construction."""
        for n in ast.walk(expr):
            if (isinstance(n, ast.Attribute)
                    and n.attr in _INTY_DTYPE_ATTRS):
                return True
            if (isinstance(n, ast.Call)
                    and _terminal_name(n.func) == "randint"):
                return True
        return False

    def _looks_integer_tensor(self, expr):
        """True when the tensor expression is visibly integer/bool
        (:meth:`_expr_is_inty`) or names a variable previously assigned
        one (one-hop local dataflow — visit_Assign records those)."""
        if self._expr_is_inty(expr):
            return True
        return any(isinstance(n, ast.Name) and n.id in self.int_names
                   for n in ast.walk(expr))

    @staticmethod
    def _expr_is_indexy(expr):
        """Index-tensor evidence inside one expression (rule HVD209):
        a ``.indices`` access (attr or call — the sparse-gradient
        halves) or an index-producing construction (argsort/argmax/
        nonzero/searchsorted)."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and n.attr in _INDEX_ATTRS:
                return True
            if (isinstance(n, ast.Call)
                    and _terminal_name(n.func)
                    in _INDEX_PRODUCING_CALLS):
                return True
        return False

    def _looks_index_tensor(self, expr):
        """HVD209's walk: visibly index-producing, or a name one-hop
        assigned from an index-producing expression."""
        if self._expr_is_indexy(expr):
            return True
        return any(isinstance(n, ast.Name) and n.id in self.index_names
                   for n in ast.walk(expr))

    # -- HVD208: ZeRO × Adasum / non-global process set --------------------
    def _note_zero_env(self, node):
        """Record ``os.environ["HVDTPU_ZERO"] = "1"`` (any accepted
        prefix spelling, any truthy value)."""
        for target in node.targets:
            if not isinstance(target, ast.Subscript):
                continue
            base = target.value
            is_env = ((isinstance(base, ast.Attribute)
                       and base.attr == "environ")
                      or (isinstance(base, ast.Name)
                          and base.id == "environ"))
            key = target.slice
            if (is_env and isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and key.value in _ZERO_ENV_NAMES
                    and isinstance(node.value, ast.Constant)
                    and str(node.value.value).strip().lower()
                    in ("1", "true", "yes", "on")):
                self.zero_env_set = True

    def _report_208(self, call, why):
        self._flagged.add(id(call))
        self.diags.append(Diagnostic.make(
            "HVD208",
            f"ZeRO sharded update combined with {why}: Adasum's "
            "per-tensor scale-invariant combination does not "
            "reduce-scatter, and a non-global process set derives a "
            "shard plan over the wrong replica axis — "
            "DistributedOptimizer raises at __init__ either way",
            file=self.filename, line=call.lineno,
            hint="drop zero=/HVDTPU_ZERO for this optimizer (or switch "
                 "to op=Average/Sum on the global process set); "
                 + _DOC_HINT))

    def _check_208(self, node):
        term = _terminal_name(node.func)
        if term not in DIST_OPT_CALLS or id(node) in self._flagged:
            return
        zero_on = self.zero_env_set
        for kw in node.keywords:
            if kw.arg == "zero":
                if isinstance(kw.value, ast.Constant):
                    # An explicit constant wins over the env knob —
                    # mirror __init__, where zero=False opts this
                    # optimizer out even under HVDTPU_ZERO=1.
                    zero_on = bool(kw.value.value)
                else:
                    # zero=<flag>: statically unknown — treat as
                    # reachable-on (the combination is never valid).
                    zero_on = True
        if not zero_on:
            return
        reasons = []
        if term == "DistributedAdasumOptimizer":
            reasons.append("Adasum (DistributedAdasumOptimizer)")
        for kw in node.keywords:
            if kw.arg == "op" and _terminal_name(kw.value) == "Adasum":
                reasons.append("op=Adasum")
            elif (kw.arg == "process_set"
                    and _terminal_name(kw.value) != "global_process_set"):
                reasons.append("a non-global process_set")
        if reasons:
            self._report_208(node, " and ".join(reasons))

    def visit_Assign(self, node):
        # One-hop dataflow for HVD205: `labels = ...int32...` marks the
        # NAME, so a later `allreduce(labels, compression=...)` is
        # recognizable. Reassignment from a float-looking value clears
        # the mark (last write wins, like the interpreter).
        self._note_zero_env(node)
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if names:
            inty = self._expr_is_inty(node.value)
            indexy = self._expr_is_indexy(node.value)
            for name in names:
                if inty:
                    self.int_names.add(name)
                else:
                    self.int_names.discard(name)
                if indexy:
                    self.index_names.add(name)
                else:
                    self.index_names.discard(name)
        self.generic_visit(node)

    def _report_205(self, call, comp, why):
        self._flagged.add(id(call))
        fn = _terminal_name(call.func)
        self.diags.append(Diagnostic.make(
            "HVD205",
            f"lossy compressor `Compression.{comp}` on `{fn}`: {why}",
            file=self.filename, line=call.lineno,
            hint="compression is for gradient reduction "
                 "(allreduce/grouped_allreduce of float gradients) "
                 "only — drop the compression= argument here; "
                 + _DOC_HINT))

    def _check_205(self, node):
        comp = self._lossy_compression_kw(node)
        if comp is None or id(node) in self._flagged:
            return
        term = _terminal_name(node.func)
        if (term in SYNC_COLLECTIVE_CALLS
                and self._is_hvd_call(node, SYNC_COLLECTIVE_CALLS)):
            self._report_205(
                node, comp,
                "broadcast/initial-sync collectives must be exact — a "
                "lossy wire format would start ranks from divergent "
                "(and silently different) state")
        elif (self._is_collective(node) and node.args
                and self._looks_integer_tensor(node.args[0])):
            self._report_205(
                node, comp,
                "the tensor is integer/bool, which has no meaningful "
                "lossy representation (counts and masks corrupt "
                "silently)")

    def _report_209(self, call, comp, why):
        self._flagged.add(id(call))
        fn = _terminal_name(call.func)
        self.diags.append(Diagnostic.make(
            "HVD209",
            f"lossy compressor `Compression.{comp}` on `{fn}`: {why}",
            file=self.filename, line=call.lineno,
            hint="only the VALUES half of a sparse gradient may ride a "
                 "wire codec (the sparse plane's row-wise int8 does "
                 "this; docs/sparse.md) — drop the compression= "
                 "argument here; " + _DOC_HINT))

    def _check_209(self, node):
        """HVD209: lossy codec on an index tensor / the indices half of
        a sparse gradient. Runs after HVD205 (the _flagged set dedups:
        an index tensor with a visible int dtype stays an HVD205
        finding; this rule catches the sparse spellings HVD205's
        dtype walk cannot see)."""
        comp = self._lossy_compression_kw(node)
        if comp is None or id(node) in self._flagged:
            return
        if (self._is_collective(node) and node.args
                and self._looks_index_tensor(node.args[0])):
            self._report_209(
                node, comp,
                "the tensor is (or derives from) an index tensor — "
                "indices must cross the wire exactly, or rows "
                "scatter-add into the wrong slots")

    def visit_Call(self, node):
        term = _terminal_name(node.func)
        if term == "init" and self._is_hvd_call(node, {"init"}):
            self.has_init = True
        elif term in DIST_OPT_CALLS:
            if self.dist_opt_node is None:
                self.dist_opt_node = node
            self._check_208(node)
        elif term in BROADCAST_STATE_CALLS:
            self.has_broadcast = True
        self._check_205(node)
        self._check_209(node)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if node.attr in _SYNC_MARKERS:
            self.has_broadcast = True
        elif (node.attr == "elastic"
                and _root_name(node) in self.res.hvd_aliases):
            self.res.uses_elastic = True
        self.generic_visit(node)

    def visit_Name(self, node):
        if node.id in _SYNC_MARKERS:
            self.has_broadcast = True
        self.generic_visit(node)

    def finish(self):
        if (self.has_init and self.dist_opt_node is not None
                and not self.has_broadcast and not self.res.uses_elastic):
            self.diags.append(Diagnostic.make(
                "HVD202",
                "script calls init() and builds a DistributedOptimizer "
                "but never broadcasts initial state: ranks start from "
                "divergent parameters/optimizer moments and silently "
                "train different models",
                file=self.filename, line=self.dist_opt_node.lineno,
                hint="after building params/optimizer, call "
                     "broadcast_parameters(...) and "
                     "broadcast_optimizer_state(..., root_rank=0) (or "
                     "use the Broadcast callback / elastic state); "
                     + _DOC_HINT))
        return self.diags


# ==========================================================================
# HVD207: raw begin/end timing pairs instead of the span API
# ==========================================================================

# Clocks the span API replaces. monotonic/perf_counter_ns are exempt:
# they back interval bookkeeping (stall ages, cycle pacing), not metric
# observations.
_SPAN_CLOCKS = frozenset({"time", "perf_counter"})


def _is_span_clock_call(node):
    """``time.time()`` / ``time.perf_counter()`` (or the bare
    from-imported spellings) with no arguments."""
    if not isinstance(node, ast.Call) or node.args or node.keywords:
        return False
    term = _terminal_name(node.func)
    if term not in _SPAN_CLOCKS:
        return False
    if isinstance(node.func, ast.Attribute):
        return _root_name(node.func) == "time"
    return True


def _clock_in(node):
    """The clock call inside an expression that may be conditioned
    (``t0 = time.perf_counter() if metrics_on else 0.0``)."""
    if _is_span_clock_call(node):
        return node
    if isinstance(node, ast.IfExp):
        for branch in (node.body, node.orelse):
            if _is_span_clock_call(branch):
                return branch
    return None


class _RawTimingAnalyzer:
    """HVD207 over one module: per scope, find ``t0 = <clock>()``
    followed by ``.observe(<clock>() - t0)`` (directly, or through one
    ``elapsed = <clock>() - t0`` hop)."""

    def __init__(self, filename):
        self.filename = filename
        self.diags = []

    def run(self, tree):
        scopes = [tree] + [n for n in ast.walk(tree)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
        for scope in scopes:
            self._scan_scope(scope)
        return self.diags

    @staticmethod
    def _scope_walk(scope):
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _elapsed_of(expr, begin_names):
        """The begin-variable name when ``expr`` is
        ``<clock>() - <t0>``, else None."""
        if (isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Sub)
                and _is_span_clock_call(expr.left)
                and isinstance(expr.right, ast.Name)
                and expr.right.id in begin_names):
            return expr.right.id
        return None

    def _scan_scope(self, scope):
        # Separate passes: the scope walk is not in source order, so
        # begin names must be fully collected before elapsed ones.
        assigns = [n for n in self._scope_walk(scope)
                   if isinstance(n, ast.Assign) and len(n.targets) == 1
                   and isinstance(n.targets[0], ast.Name)]
        begin_names = {n.targets[0].id: n.lineno for n in assigns
                       if _clock_in(n.value) is not None}
        if not begin_names:
            return
        elapsed_names = {}  # name -> (begin name, lineno)
        for n in assigns:
            t0 = self._elapsed_of(n.value, begin_names)
            if t0 is not None:
                elapsed_names[n.targets[0].id] = (t0, n.lineno)
        for node in self._scope_walk(scope):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "observe" and node.args):
                continue
            arg = node.args[0]
            t0 = self._elapsed_of(arg, begin_names)
            if t0 is None and isinstance(arg, ast.Name) \
                    and arg.id in elapsed_names:
                t0 = elapsed_names[arg.id][0]
            if t0 is None:
                continue
            self.diags.append(Diagnostic.make(
                "HVD207",
                f"raw `{t0} = time.time()/perf_counter()` begin/end "
                "pair feeding `.observe()`: the span API is the single "
                "instrument for the histogram, the timeline AND the "
                "trace plane, and its disabled mode reads no clock",
                file=self.filename, line=node.lineno,
                hint="wrap the timed region in `with telemetry.span("
                     "names, ACTIVITY, histogram=...)`; if the "
                     "observation is genuinely conditional (a span "
                     "observes unconditionally), document why and "
                     "suppress with `# hvd-lint: disable=HVD207`; "
                     + _DOC_HINT))


# ==========================================================================
# HVD210: unbounded request buffering in serving code
# ==========================================================================

class _RequestBufferAnalyzer:
    """HVD210 over one module: in serving context — a file under
    ``serving/``, a class whose name says scheduler/router/serving, or
    a ``handle_*`` request handler — flag request buffers with no
    bound: a bare ``queue.Queue()``/``queue.SimpleQueue()``, a
    ``deque()`` without ``maxlen``, or ``.append()`` onto a
    request-named list. The serving plane's backpressure contract
    (docs/serving.md) is that the *only* wait station is a bounded
    queue whose overflow answers 429 + Retry-After; any unbounded
    buffer silently converts overload into memory growth and tail
    latency instead of a reject the client can act on."""

    _CTX_CLASS_RE = re.compile(r"scheduler|router|serving", re.IGNORECASE)
    _CTX_FUNC_RE = re.compile(r"^handle_", re.IGNORECASE)
    _BUF_NAME_RE = re.compile(
        r"request|pending|backlog|queue|inbox|waiting", re.IGNORECASE)

    def __init__(self, filename):
        self.filename = filename
        self.diags = []
        parts = os.path.normpath(filename).split(os.sep)
        self._serving_file = "serving" in parts
        self._queue_ctors = set()    # local names of queue.Queue et al.
        self._deque_ctors = set()
        self._buffers = {}           # unparsed target -> assign lineno

    # -- import bookkeeping ------------------------------------------------
    def _note_imports(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == "queue":
                    for a in node.names:
                        if a.name in ("Queue", "LifoQueue",
                                      "PriorityQueue", "SimpleQueue"):
                            self._queue_ctors.add(a.asname or a.name)
                elif node.module == "collections":
                    for a in node.names:
                        if a.name == "deque":
                            self._deque_ctors.add(a.asname or a.name)

    def _ctor_kind(self, call):
        """'queue' / 'deque' / None for a constructor call node."""
        fn = call.func
        if isinstance(fn, ast.Attribute) and isinstance(fn.value,
                                                        ast.Name):
            if fn.value.id == "queue" and fn.attr in (
                    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"):
                return "queue"
            if fn.value.id == "collections" and fn.attr == "deque":
                return "deque"
        elif isinstance(fn, ast.Name):
            if fn.id in self._queue_ctors:
                return "queue"
            if fn.id in self._deque_ctors:
                return "deque"
        return None

    @staticmethod
    def _is_unbounded(kind, call):
        """True when the constructor carries no effective bound."""
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "SimpleQueue":
            return True  # SimpleQueue has no maxsize at all
        if isinstance(call.func, ast.Name) \
                and call.func.id == "SimpleQueue":
            return True
        bound_kw = "maxsize" if kind == "queue" else "maxlen"
        bound_pos = 0 if kind == "queue" else 1
        candidates = []
        if len(call.args) > bound_pos:
            candidates.append(call.args[bound_pos])
        candidates.extend(kw.value for kw in call.keywords
                          if kw.arg == bound_kw)
        for value in candidates:
            if isinstance(value, ast.Constant) \
                    and value.value in (0, None):
                continue  # explicit "infinite" spelling
            return False  # some bound expression is present
        return True

    def _report(self, node, what):
        self.diags.append(Diagnostic.make(
            "HVD210",
            f"{what} in serving scheduler/handler code: overload "
            "becomes unbounded memory growth and tail latency instead "
            "of backpressure the client can act on",
            file=self.filename, line=node.lineno,
            hint="bound the buffer (queue.Queue(maxsize=...) sized by "
                 "HVDTPU_SERVING_QUEUE_LIMIT, deque(maxlen=...)) and "
                 "answer 429 + Retry-After when full — see "
                 "docs/serving.md \"Backpressure\"; suppress with "
                 "`# hvd-lint: disable=HVD210` only for buffers whose "
                 "growth is bounded elsewhere; " + _DOC_HINT))

    # -- context walk ------------------------------------------------------
    def run(self, tree):
        self._note_imports(tree)
        self._walk(tree.body, self._serving_file)
        return self.diags

    def _walk(self, stmts, ctx):
        for node in stmts:
            node_ctx = ctx
            if isinstance(node, ast.ClassDef):
                node_ctx = ctx or bool(
                    self._CTX_CLASS_RE.search(node.name))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                node_ctx = ctx or bool(
                    self._CTX_FUNC_RE.search(node.name))
            if node_ctx:
                self._scan_statement(node)
            for field in ("body", "orelse", "finalbody", "handlers"):
                children = getattr(node, field, None)
                if not children:
                    continue
                if field == "handlers":
                    for h in children:
                        self._walk(h.body, node_ctx)
                else:
                    self._walk(children, node_ctx)

    def _scan_statement(self, stmt):
        """One SIMPLE statement — compound statements contribute
        through their bodies, which the context walk owns (so nothing
        is scanned twice)."""
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.Expr,
                                 ast.Return, ast.AugAssign)):
            return
        if isinstance(stmt, ast.Assign):
            self._scan_assign(stmt)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._scan_call(node)

    def _scan_assign(self, node):
        value = node.value
        if isinstance(value, ast.Call):
            kind = self._ctor_kind(value)
            if kind and self._is_unbounded(kind, value):
                ctor = _unparse(value.func)
                self._report(
                    node, f"unbounded `{ctor}()` request buffer")
                return
        if isinstance(value, (ast.List, ast.ListComp)) or (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id == "list"):
            for target in node.targets:
                name = _unparse(target)
                if self._BUF_NAME_RE.search(name.split(".")[-1]):
                    self._buffers[name] = node.lineno

    def _scan_call(self, node):
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "append"):
            return
        owner = _unparse(node.func.value)
        if owner in self._buffers:
            self._report(
                node, f"`{owner}.append(...)` grows a request list "
                      "without bound")


# ==========================================================================
# HVD3xx: concurrency & liveness (the static half of hvd-sanitize)
# ==========================================================================

# Thread names / target-method names that mark a collective-pacing loop
# (the coordinator cycle driver also runs the watchdog scans).
_LOOP_ROLE_RE = re.compile(r"coordinator|cycle|watchdog|heartbeat|stall",
                           re.IGNORECASE)
_ENV_PREFIXES = ("HVDTPU_", "HOROVOD_TPU_", "HOROVOD_")
# Attribute calls that block forever without a bound.
_WAITY_METHODS = frozenset({"wait", "join", "get"})
_BOUND_KWARGS = frozenset({"timeout", "deadline"})


def _unparse(node):
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — diagnostics only
        return "<expr>"


def _is_os_environ(node):
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os")


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ==========================================================================
# HVD212: hand-rolled cohort mutation (worker lifecycle outside the
# driver/actuator modules)
# ==========================================================================

#: Modules allowed to spawn/terminate worker processes: the elastic
#: drivers that reconcile desired state (and the launcher/ray shims
#: that implement the SlotProcess surface), plus the fleet actuator
#: module, which is the only legal cohort-mutation surface outside
#: them (docs/fault_tolerance.md "Fleet arbitration").
_LIFECYCLE_OWNER_SUFFIXES = (
    "runner/spawn.py", "runner/elastic_driver.py", "runner/standby.py",
    "runner/job.py", "ray/elastic.py", "fleet/actuators.py")

_KILL_METHODS = frozenset({"terminate", "kill", "send_signal"})


class _WorkerLifecycleAnalyzer:
    """HVD212 over one module: direct worker spawn/terminate outside
    the lifecycle-owner modules. Constructing a
    ``spawn.SlotProcess(...)`` by hand, or calling
    ``terminate``/``kill``/``send_signal`` on a worker process (a
    ``.proc`` attribute, a ``workers[...]`` entry, or a name bound to
    either), mutates a cohort behind the back of the elastic driver —
    no journal entry, no fleet lease, no blacklist accounting, and
    the next discovery tick fights the change. Cohort mutation is a
    desired-state write (target files, drain flags) the drivers
    reconcile; only the modules in ``_LIFECYCLE_OWNER_SUFFIXES`` own
    process handles."""

    def __init__(self, filename):
        self.filename = filename
        self.diags = []
        norm = os.path.normpath(filename).replace(os.sep, "/")
        self._owner = norm.endswith(_LIFECYCLE_OWNER_SUFFIXES)
        self._spawn_ctors = set()   # local names bound to SlotProcess
        self._spawn_mods = set()    # aliases of horovod_tpu.runner.spawn
        self._hvd_module = False    # file imports horovod at all
        self._proc_names = set()    # locals holding worker process handles

    # -- import bookkeeping ------------------------------------------------
    def _note_imports(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    root = a.name.split(".")[0]
                    if root in ("horovod_tpu", "horovod"):
                        self._hvd_module = True
                    if a.name.endswith(".spawn") \
                            and root in ("horovod_tpu", "horovod"):
                        self._spawn_mods.add(
                            a.asname or a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.split(".")[0] in ("horovod_tpu", "horovod") \
                        or node.level:
                    self._hvd_module = True
                for a in node.names:
                    name = a.asname or a.name
                    if a.name == "SlotProcess":
                        self._spawn_ctors.add(name)
                    elif a.name == "spawn":
                        self._spawn_mods.add(name)

    def _is_spawn_ctor(self, call):
        fn = call.func
        if isinstance(fn, ast.Name):
            return fn.id in self._spawn_ctors
        if isinstance(fn, ast.Attribute) and fn.attr == "SlotProcess":
            root = _root_name(fn)
            return root in self._spawn_mods or self._hvd_module
        return False

    @staticmethod
    def _worker_receiver(node):
        """True when the call receiver reads like a worker process
        handle: any ``.proc`` hop or ``workers``/``.workers[...]``
        container access in the chain."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in ("proc", "workers"):
                return True
            if isinstance(sub, ast.Name) and sub.id == "workers":
                return True
        return False

    def _report(self, node, what):
        self.diags.append(Diagnostic.make(
            "HVD212",
            f"{what} outside the driver/actuator modules: the cohort "
            "mutates with no journal entry, no fleet lease, and no "
            "blacklist accounting, and the next discovery reconcile "
            "fights it",
            file=self.filename, line=node.lineno,
            hint="mutate cohorts through desired state the drivers "
                 "reconcile — autoscale.write_target for membership, "
                 "fleet/actuators.py drain flags for serving, the "
                 "arbiter's lease ledger for chip transfers — see "
                 "docs/fault_tolerance.md \"Fleet arbitration\"; "
                 "suppress with `# hvd-lint: disable=HVD212` only in "
                 "launcher shims that own the process table; "
                 + _DOC_HINT))

    def run(self, tree):
        if self._owner:
            return []
        self._note_imports(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and self._is_spawn_ctor(node.value):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        self._proc_names.add(target.id)
            if not isinstance(node, ast.Call):
                continue
            if self._is_spawn_ctor(node):
                self._report(node, "direct `SlotProcess(...)` spawn")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _KILL_METHODS:
                recv = node.func.value
                if isinstance(recv, ast.Name) \
                        and recv.id in self._proc_names:
                    self._report(
                        node, f"`{recv.id}.{node.func.attr}()` on a "
                              "hand-spawned worker process")
                elif self._hvd_module and self._worker_receiver(recv):
                    self._report(
                        node,
                        f"`{_unparse(node.func)}()` on a worker "
                        "process handle")
        return self.diags


# ==========================================================================
# HVD213: silently swallowed transport errors in serving/fleet code
# ==========================================================================

#: Exception names that read as transport/IO failures. Matched on the
#: bare name or the last attribute hop (``urllib.error.URLError``,
#: ``http.client.HTTPException``, ``socket.timeout``).
_TRANSPORT_EXC_NAMES = frozenset({
    "OSError", "IOError", "EnvironmentError", "ConnectionError",
    "ConnectionResetError", "ConnectionRefusedError",
    "ConnectionAbortedError", "BrokenPipeError", "TimeoutError",
    "InterruptedError", "URLError", "HTTPException",
    "timeout", "gaierror", "herror",
})
# HTTPError is deliberately absent: it means the peer ANSWERED (with
# an error status) — a protocol outcome the handler usually translates
# into a status-code return, not a vanished transport failure.

#: Name patterns like ``_TRANSPORT_ERRORS`` — a tuple constant of
#: transport exception types bound to a module-level name.
_TRANSPORT_NAME_RE = re.compile(r"transport|network", re.IGNORECASE)

#: Attribute calls inside a handler that count as "the failure was
#: observed": a log record or a metric update.
_OBSERVE_ATTRS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception",
    "critical", "log", "inc", "observe", "set",
})


class _SilentDegradationAnalyzer:
    """HVD213 over one module: in serving/fleet context — a file under
    ``serving/`` or ``fleet/``, a class whose name says
    router/scheduler/worker/arbiter/migration, or a ``handle_*``
    request handler — flag an ``except`` clause that catches a
    transport error (``OSError`` and kin, ``URLError``,
    ``HTTPException``, ``TimeoutError``, a ``*TRANSPORT*`` tuple) and
    neither re-raises nor records it (no ``raise``, no log call, no
    metric ``inc``/``observe``). The serving plane's degradation
    contract (docs/serving.md "Live migration") is *loud* fallback:
    every skipped peer, failed migration, or dead-marked worker leaves
    a log line or a counter bump; a silent swallow turns a transport
    fault into unexplained tail latency or quietly lost capacity."""

    _CTX_CLASS_RE = re.compile(
        r"serving|router|scheduler|arbiter|fleet|worker|migrat",
        re.IGNORECASE)
    _CTX_FUNC_RE = re.compile(r"^handle_", re.IGNORECASE)

    def __init__(self, filename):
        self.filename = filename
        self.diags = []
        parts = os.path.normpath(filename).split(os.sep)
        self._ctx_file = "serving" in parts or "fleet" in parts

    @classmethod
    def _transport_type(cls, node):
        """The transport-ish spelling in an except type expr, or None.

        Handles bare names, dotted names (last hop decides), and
        tuples (any transport element taints the whole clause — the
        handler body is shared)."""
        if node is None:
            return None
        if isinstance(node, ast.Tuple):
            for elt in node.elts:
                hit = cls._transport_type(elt)
                if hit:
                    return hit
            return None
        if isinstance(node, ast.Name):
            if node.id in _TRANSPORT_EXC_NAMES \
                    or _TRANSPORT_NAME_RE.search(node.id):
                return node.id
            return None
        if isinstance(node, ast.Attribute):
            if node.attr in _TRANSPORT_EXC_NAMES:
                return _unparse(node)
        return None

    @staticmethod
    def _handler_observes(handler):
        """True when the handler body re-raises or records the error:
        any ``raise``, or any call whose attribute name is a log/metric
        verb (``log.warning``, ``counter.inc``, ...)."""
        for sub in ast.walk(handler):
            if isinstance(sub, ast.Raise):
                return True
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _OBSERVE_ATTRS:
                return True
            # A CLI front-end printing the failure (stderr) is loud.
            if isinstance(sub.func, ast.Name) and sub.func.id == "print":
                return True
        return False

    @staticmethod
    def _deferred_reraise(handler, func):
        """True for the retry-ladder idiom: the handler stashes the
        bound exception (``except OSError as e: last = e``) and the
        enclosing function raises it — or raises *through* it (``raise
        X(...) from last``) — after the loop. The error is not
        swallowed, just deferred past the last attempt."""
        if func is None or not handler.name:
            return False
        aliases = {handler.name}
        # Two passes so a chain (a = e; b = a) inside the handler
        # still resolves.
        for _ in range(2):
            for sub in ast.walk(handler):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                value = sub.value
                if not (isinstance(value, ast.Name)
                        and value.id in aliases):
                    continue
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        aliases.add(tgt.id)
        in_handler = set()
        for sub in ast.walk(handler):
            in_handler.add(id(sub))
        for sub in ast.walk(func):
            if not isinstance(sub, ast.Raise) or id(sub) in in_handler:
                continue
            for expr in (sub.exc, sub.cause):
                if expr is None:
                    continue
                for name in ast.walk(expr):
                    if isinstance(name, ast.Name) \
                            and name.id in aliases:
                        return True
        return False

    def _report(self, handler, spelled):
        self.diags.append(Diagnostic.make(
            "HVD213",
            f"`except {spelled}` in serving/fleet code swallows a "
            "transport error without a log, metric, or re-raise: the "
            "failure disappears — degraded capacity and skipped peers "
            "become unexplained tail latency with no audit trail",
            file=self.filename, line=handler.lineno,
            hint="record the fallback before taking it — a "
                 "`log.warning(...)` naming what failed and what "
                 "happens instead, or a counter bump "
                 "(hvd_serving_migrations_total{outcome}), or re-raise "
                 "— see docs/serving.md \"Live migration\" fallback "
                 "ladder; suppress with `# hvd-lint: disable=HVD213` "
                 "only where the caller records the degradation; "
                 + _DOC_HINT))

    def run(self, tree):
        self._walk(tree.body, self._ctx_file, None)
        return self.diags

    def _walk(self, stmts, ctx, func):
        for node in stmts:
            node_ctx = ctx
            node_func = func
            if isinstance(node, ast.ClassDef):
                node_ctx = ctx or bool(
                    self._CTX_CLASS_RE.search(node.name))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                node_ctx = ctx or bool(
                    self._CTX_FUNC_RE.search(node.name))
                node_func = node
            if node_ctx and isinstance(node, ast.Try):
                for handler in node.handlers:
                    spelled = self._transport_type(handler.type)
                    if spelled \
                            and not self._handler_observes(handler) \
                            and not self._deferred_reraise(handler,
                                                           func):
                        self._report(handler, spelled)
            for field in ("body", "orelse", "finalbody", "handlers"):
                children = getattr(node, field, None)
                if not children:
                    continue
                if field == "handlers":
                    for h in children:
                        self._walk(h.body, node_ctx, node_func)
                else:
                    self._walk(children, node_ctx, node_func)


class _ProtocolOrderAnalyzer:
    """HVD704/HVD705 over one module: AST-level companions to the
    hvd-model protocol checker (docs/modelcheck.md) — they catch the
    two bug shapes the models prove fatal *before* anything runs.

    Context: a file under ``fleet/`` or ``runner/``, or a class whose
    name says arbiter/ledger/journal/lease — the modules that execute
    the control-plane protocols.

    HVD704: within one function, an actuation call (``set_train_slots``
    / ``set_serve_slots`` / ``drain`` / ``write_target``) appears
    *before* the first durable ledger/journal write (a call like
    ``ledger.advance(...)`` / ``self._jrec(...)``). The arbiter's
    contract is ledger-before-actuation (fleet/ledger.py): a crash
    between an early actuation and its late write strands an effect the
    recovery protocol cannot see — exactly the ``actuate_before_ledger``
    counterexample hvd-model minimizes.

    HVD705: a ``<...>server.put(...)`` KV write carrying positional
    scope/key/value but no ``term=`` keyword. An unfenced write slips
    the split-brain fence (journal_spec.term_fences) — the
    ``skip_fence`` counterexample.
    """

    _CTX_CLASS_RE = re.compile(r"arbiter|ledger|journal|lease",
                               re.IGNORECASE)
    _DURABLE_RECV_RE = re.compile(r"ledger|journal", re.IGNORECASE)
    _DURABLE_ATTRS = frozenset({
        "record", "advance", "open", "mark_transfer", "set_split",
        "put", "write"})
    _ACTUATION_ATTRS = frozenset({
        "set_train_slots", "set_serve_slots", "drain", "write_target"})

    def __init__(self, filename):
        self.filename = filename
        self.diags = []
        parts = os.path.normpath(filename).split(os.sep)
        self._ctx_file = "fleet" in parts or "runner" in parts

    @staticmethod
    def _dotted(node):
        """Best-effort dotted receiver text ('self.ledger' etc.)."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
        return ".".join(reversed(parts))

    def _is_durable_write(self, call):
        func = call.func
        if isinstance(func, ast.Name) and func.id == "_jrec":
            return True
        if not isinstance(func, ast.Attribute):
            return False
        if func.attr == "_jrec":
            return True
        if func.attr not in self._DURABLE_ATTRS:
            return False
        return bool(self._DURABLE_RECV_RE.search(
            self._dotted(func.value)))

    def _is_actuation(self, call):
        func = call.func
        if isinstance(func, ast.Name):
            return func.id in self._ACTUATION_ATTRS
        return (isinstance(func, ast.Attribute)
                and func.attr in self._ACTUATION_ATTRS)

    def _check_function(self, func_node):
        durable_line = None
        actuation = None
        for sub in ast.walk(func_node):
            if not isinstance(sub, ast.Call):
                continue
            if self._is_durable_write(sub):
                if durable_line is None or sub.lineno < durable_line:
                    durable_line = sub.lineno
            elif self._is_actuation(sub):
                if actuation is None or sub.lineno < actuation[1]:
                    name = (sub.func.attr
                            if isinstance(sub.func, ast.Attribute)
                            else sub.func.id)
                    actuation = (name, sub.lineno)
        if (durable_line is not None and actuation is not None
                and actuation[1] < durable_line):
            name, lineno = actuation
            self.diags.append(Diagnostic.make(
                "HVD704",
                f"actuation `{name}(...)` at line {lineno} precedes "
                f"the first durable ledger/journal write (line "
                f"{durable_line}) in `{func_node.name}` — a crash in "
                "the window strands an effect the recovery protocol "
                "cannot see (ledger-before-actuation, "
                "fleet/ledger.py)",
                file=self.filename, line=lineno,
                hint="write the lease/journal state first, actuate "
                     "second — recovery replays resume_action() from "
                     "the ledger; hvd-model minimizes the crash "
                     "interleaving (docs/modelcheck.md); suppress "
                     "with `# hvd-lint: disable=HVD704` where the "
                     "early call is not an actuation; " + _DOC_HINT))

    def _check_unfenced_put(self, call):
        func = call.func
        if not (isinstance(func, ast.Attribute) and func.attr == "put"):
            return
        recv = self._dotted(func.value)
        if not recv or not recv.split(".")[-1].endswith("server"):
            return
        if len(call.args) < 3:
            return      # backend .put(key, value) shims, not KV writes
        if any(kw.arg == "term" for kw in call.keywords):
            return
        self.diags.append(Diagnostic.make(
            "HVD705",
            f"`{recv}.put(...)` writes KV state without a `term=` "
            "fence in a protocol module — a resurrected stale primary "
            "could mutate cohort state after a newer term took over "
            "(split-brain; journal_spec.term_fences)",
            file=self.filename, line=call.lineno,
            hint="pass term= (runner/http_server.py rejects stale "
                 "writers with 409); hvd-model's `skip_fence` seeded "
                 "bug shows the interleaving (docs/modelcheck.md); "
                 "suppress with `# hvd-lint: disable=HVD705` for "
                 "stores that are never HA-replicated; " + _DOC_HINT))

    def run(self, tree):
        self._walk(tree.body, self._ctx_file)
        return self.diags

    def _walk(self, stmts, ctx):
        for node in stmts:
            node_ctx = ctx
            if isinstance(node, ast.ClassDef):
                node_ctx = ctx or bool(
                    self._CTX_CLASS_RE.search(node.name))
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and node_ctx:
                self._check_function(node)
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        self._check_unfenced_put(sub)
                continue
            body = getattr(node, "body", None)
            if isinstance(body, list):
                self._walk(body, node_ctx)


class _HandRollResharding:
    """HVD211 over one module: a ``device_get(...)`` result that flows
    — through any chain of reshape / ravel / asarray / concatenate /
    pad / stack / split / indexing hops — into a ``device_put(...)``
    call is a hand-rolled reshard: it materializes the fully-replicated
    leaf on host and bypasses the redistribution planner
    (``horovod_tpu/resharding/``), whose programs are windowed to
    ``HVDTPU_RESHARD_BUCKET_BYTES``, digest-verified across ranks, and
    proven deadlock-free under hvd-sim. device_get alone (telemetry,
    checkpoint writers, test asserts) and device_put of fresh data are
    both fine — only the get→transform→put chain is the smell.

    Files under a ``resharding`` directory component are exempt (the
    planner's own executor legitimately stages host windows)."""

    _HOP_FUNCS = {"asarray", "array", "reshape", "ravel", "concatenate",
                  "pad", "stack", "hstack", "vstack", "split",
                  "ascontiguousarray", "flatten", "transpose", "copy",
                  "astype", "squeeze", "expand_dims"}

    def __init__(self, filename):
        self.filename = filename
        self.diags = []
        parts = os.path.normpath(filename).split(os.sep)
        self._exempt = "resharding" in parts
        self._tainted = set()

    @staticmethod
    def _call_name(call):
        fn = call.func
        if isinstance(fn, ast.Name):
            return fn.id
        if isinstance(fn, ast.Attribute):
            return fn.attr
        return None

    def _is_tainted(self, node):
        """Does this expression carry device_get-derived data?"""
        if isinstance(node, ast.Name):
            return node.id in self._tainted
        if isinstance(node, ast.Attribute):
            return self._is_tainted(node.value)
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self._is_tainted(node.value)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._is_tainted(e) for e in node.elts)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp,
                             ast.SetComp)):
            return (self._is_tainted(node.elt)
                    or any(self._is_tainted(g.iter)
                           for g in node.generators))
        if isinstance(node, ast.BinOp):
            return (self._is_tainted(node.left)
                    or self._is_tainted(node.right))
        if isinstance(node, ast.Call):
            name = self._call_name(node)
            if name == "device_get":
                return True
            if name in self._HOP_FUNCS:
                if isinstance(node.func, ast.Attribute) \
                        and self._is_tainted(node.func.value):
                    return True  # tainted.reshape(...) method hop
                return any(self._is_tainted(a) for a in node.args)
        return False

    def run(self, tree):
        if self._exempt:
            return self.diags
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                if self._is_tainted(node.value):
                    for tgt in node.targets:
                        for leaf in ast.walk(tgt):
                            if isinstance(leaf, ast.Name):
                                self._tainted.add(leaf.id)
            elif isinstance(node, ast.AnnAssign) and node.value:
                if self._is_tainted(node.value) \
                        and isinstance(node.target, ast.Name):
                    self._tainted.add(node.target.id)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and self._call_name(node) == "device_put"):
                continue
            payloads = list(node.args[:1]) + [
                kw.value for kw in node.keywords if kw.arg in
                (None, "x", "arrays")]
            if any(self._is_tainted(a) for a in payloads):
                self.diags.append(Diagnostic.make(
                    "HVD211",
                    "device_get-derived data flows into device_put: a "
                    "hand-rolled reshard that materializes the full "
                    "replica on host, outside the planner's "
                    "HVDTPU_RESHARD_BUCKET_BYTES window, digest "
                    "checks, and hvd-sim deadlock proofs",
                    file=self.filename, line=node.lineno,
                    hint="express the transition as (src Spec, dst "
                         "Spec) and run resharding.plan_redistribution "
                         "+ execute_host / make_jit_executor (docs/"
                         "resharding.md); suppress with `# hvd-lint: "
                         "disable=HVD211` only for bounded scalar/"
                         "debug moves; " + _DOC_HINT))
        return self.diags


def _is_thread_ctor(node):
    if not isinstance(node, ast.Call):
        return False
    if isinstance(node.func, ast.Name):
        return node.func.id == "Thread"
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr == "Thread"
            and _root_name(node.func) == "threading")


def _thread_kwargs(call):
    """(target_attr_on_self or None, name constant or '', daemon bool)."""
    target, tname, daemon = None, "", False
    for kw in call.keywords:
        if kw.arg == "target":
            v = kw.value
            if (isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self"):
                target = v.attr
        elif kw.arg == "name":
            tname = _const_str(kw.value) or ""
        elif kw.arg == "daemon":
            daemon = (isinstance(kw.value, ast.Constant)
                      and bool(kw.value.value))
    return target, tname, daemon


class _ConcurrencyAnalyzer:
    """HVD301/302/303/304/305 over one module."""

    def __init__(self, filename):
        self.filename = filename
        self.diags = []

    def run(self, tree):
        self._scan_env_reads(tree)
        for scope in self._scopes(tree):
            self._scan_acquires(scope)
        self._scan_thread_lifetimes(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)
        return self.diags

    # -- HVD304: raw env reads ---------------------------------------------
    def _scan_env_reads(self, tree):
        for node in ast.walk(tree):
            name, line = None, 0
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr == "get"
                        and _is_os_environ(f.value) and node.args):
                    name, line = _const_str(node.args[0]), node.lineno
                elif (isinstance(f, ast.Attribute) and f.attr == "getenv"
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "os" and node.args):
                    name, line = _const_str(node.args[0]), node.lineno
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and _is_os_environ(node.value)):
                name, line = _const_str(node.slice), node.lineno
            if name and name.startswith(_ENV_PREFIXES):
                self.diags.append(Diagnostic.make(
                    "HVD304",
                    f"raw os.environ read of {name!r} bypasses "
                    "utils/envparse.py — no HVDTPU_/HOROVOD_TPU_/"
                    "HOROVOD_ prefix fallback, and the knob never "
                    "reaches the registry that keeps docs/knobs.md "
                    "honest (rule HVD306)",
                    file=self.filename, line=line,
                    hint="read it via envparse.get_*(envparse.<NAME>) "
                         "and register() user-facing knobs; "
                         + _DOC_HINT))

    # -- HVD302: bare acquire ----------------------------------------------
    def _scopes(self, tree):
        """Module + every function, each scanned as one scope (a
        release in a different function cannot protect this one)."""
        yield tree
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def _scope_walk(self, scope):
        """Walk a scope without descending into nested functions."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _scan_acquires(self, scope):
        acquires = []
        released = set()
        for node in self._scope_walk(scope):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                if node.func.attr == "acquire":
                    acquires.append((node, _unparse(node.func.value)))
            if isinstance(node, ast.Try):
                for st in node.finalbody:
                    for sub in ast.walk(st):
                        if (isinstance(sub, ast.Call)
                                and isinstance(sub.func, ast.Attribute)
                                and sub.func.attr == "release"):
                            released.add(_unparse(sub.func.value))
        for call, base in acquires:
            if base in released:
                continue
            self.diags.append(Diagnostic.make(
                "HVD302",
                f"`{base}.acquire()` with no `{base}.release()` in a "
                "try/finally in this scope: an exception between "
                "acquire and release leaks the lock and every later "
                "acquirer blocks forever",
                file=self.filename, line=call.lineno,
                hint=f"use `with {base}:` (or release in a finally); "
                     + _DOC_HINT))

    # -- HVD305: thread lifetime -------------------------------------------
    def _scan_thread_lifetimes(self, tree):
        join_bases, daemon_bases = set(), set()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"):
                join_bases.add(_unparse(node.func.value))
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and t.attr == "daemon"
                            and isinstance(node.value, ast.Constant)
                            and node.value.value):
                        daemon_bases.add(_unparse(t.value))
        assigned = {}  # id(thread_call) -> target text
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and _is_thread_ctor(node.value):
                assigned[id(node.value)] = _unparse(node.targets[0])
        for node in ast.walk(tree):
            if not _is_thread_ctor(node):
                continue
            _target, _tname, daemon = _thread_kwargs(node)
            if daemon:
                continue
            tgt = assigned.get(id(node))
            if tgt and (tgt in join_bases or tgt in daemon_bases):
                continue
            self.diags.append(Diagnostic.make(
                "HVD305",
                "thread started with neither daemon=True nor a "
                "visible join()/.daemon = True path"
                + (f" (assigned to {tgt})" if tgt else "")
                + ": it outlives shutdown() and keeps the interpreter "
                "from exiting",
                file=self.filename, line=node.lineno,
                hint="pass daemon=True, or keep a handle and join it "
                     "on the shutdown path; " + _DOC_HINT))

    # -- HVD301 + HVD303: per-class thread analysis ------------------------
    def _scan_class(self, cls):
        methods = {n.name: n for n in cls.body
                   if isinstance(n, ast.FunctionDef)}
        thread_calls = []
        for node in ast.walk(cls):
            if _is_thread_ctor(node):
                target, tname, _daemon = _thread_kwargs(node)
                if target in methods:
                    thread_calls.append((target, tname))
        if not thread_calls:
            return
        closures = {t: self._closure(t, methods)
                    for t, _ in thread_calls}
        thread_side = set().union(*closures.values())
        self._rule_301(cls, methods, thread_side,
                       [t for t, _ in thread_calls])
        for target, tname in thread_calls:
            if _LOOP_ROLE_RE.search(tname or "") \
                    or _LOOP_ROLE_RE.search(target):
                role = tname or target
                for mname in sorted(closures[target]):
                    self._rule_303(methods[mname], role)

    def _closure(self, start, methods):
        """Methods reachable from ``start`` through ``self.X()`` calls
        — the code that runs on the thread, statically."""
        seen, stack = set(), [start]
        while stack:
            cur = stack.pop()
            if cur in seen or cur not in methods:
                continue
            seen.add(cur)
            for node in ast.walk(methods[cur]):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in methods):
                    stack.append(node.func.attr)
        return seen

    def _attr_writes(self, fn):
        """[(attr, lineno, locked)] for self.<attr> assignments in
        ``fn`` (plain, augmented, subscript, tuple targets); ``locked``
        = lexically inside a ``with`` whose context mentions a lock."""
        out = []

        def targets_of(node):
            if isinstance(node, ast.Assign):
                return node.targets
            return [node.target]

        def self_attr(t):
            if isinstance(t, ast.Subscript):
                t = t.value
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                return t.attr
            return None

        def rec(node, locked):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            if isinstance(node, ast.With):
                holds = locked or any(
                    "lock" in _unparse(item.context_expr).lower()
                    for item in node.items)
                for st in node.body:
                    rec(st, holds)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                for t in targets_of(node):
                    elts = (t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t])
                    for e in elts:
                        attr = self_attr(e)
                        if attr:
                            out.append((attr, node.lineno, locked))
            for child in ast.iter_child_nodes(node):
                rec(child, locked)

        for st in fn.body:
            rec(st, False)
        return out

    def _rule_301(self, cls, methods, thread_side, targets):
        thread_writes, other_writes = {}, {}
        for mname, fn in methods.items():
            if mname in thread_side:
                side = thread_writes
            elif mname == "__init__":
                # Pre-start initialization: the universal ownership
                # handoff — the thread does not exist yet.
                continue
            else:
                side = other_writes
            for attr, lineno, locked in self._attr_writes(fn):
                side.setdefault(attr, []).append((mname, lineno, locked))
        for attr in sorted(set(thread_writes) & set(other_writes)):
            entries = thread_writes[attr] + other_writes[attr]
            unlocked = [e for e in entries if not e[2]]
            if not unlocked:
                continue
            mname, lineno, _ = min(unlocked, key=lambda e: e[1])
            t_methods = sorted({m for m, _, _ in thread_writes[attr]})
            o_methods = sorted({m for m, _, _ in other_writes[attr]})
            self.diags.append(Diagnostic.make(
                "HVD301",
                f"attribute `self.{attr}` of class {cls.name} is "
                f"written both on the thread side "
                f"({', '.join(t_methods)}; thread target(s) "
                f"{', '.join(sorted(targets))}) and from "
                f"{', '.join(o_methods)}, with at least one write "
                "outside any lock: concurrent writes race",
                file=self.filename, line=lineno,
                hint="guard every write with one lock, or document "
                     "the ownership protocol and suppress with "
                     "`# hvd-lint: disable=HVD301 — <why>`; "
                     + _DOC_HINT))

    def _rule_303(self, fn, role):
        for node in self._scope_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            term = _terminal_name(f)
            what = None
            if term == "urlopen":
                what = "urlopen"
            elif (_root_name(f) == "subprocess"
                    or (isinstance(f, ast.Name) and f.id == "Popen")):
                what = _unparse(f)
            elif (isinstance(f, ast.Attribute) and term in _WAITY_METHODS
                    and not node.args
                    and not any(kw.arg in _BOUND_KWARGS
                                for kw in node.keywords)):
                what = f"{_unparse(f)}() with no timeout"
            if what is None:
                continue
            self.diags.append(Diagnostic.make(
                "HVD303",
                f"unbounded blocking call `{what}` inside the "
                f"{role!r} loop body (method {fn.name}): this thread "
                "paces the data plane, so the call starves every "
                "in-flight collective for its duration",
                file=self.filename, line=node.lineno,
                hint="bound it (timeout=/deadline=) or move it to a "
                     "non-critical thread; " + _DOC_HINT))


# ==========================================================================
# HVD306: knob registry <-> docs/knobs.md cross-check
# ==========================================================================

_DOC_KNOB_RE = re.compile(
    r"^\|\s*`(?:HVDTPU_|HOROVOD_TPU_|HOROVOD_)([A-Z0-9_]+)`"
    r"\s*\|\s*([^|]*)\|")


def _norm_default(text):
    """Comparable form of a default: parentheticals dropped
    ("0 (off)" == "0"), em-dash/empty equivalent, case-folded."""
    text = re.sub(r"\(.*?\)", "", text).strip()
    if text in ("—", "-", "–"):
        text = ""
    return text.lower()


def check_knob_docs(doc_path):
    """Cross-check ``envparse.KNOBS`` against the knob table rows of
    ``docs/knobs.md``: every registered knob needs a documented row,
    every documented row needs a registration, and the documented
    default must match the registered one (rule HVD306 — the registry
    is the docs' source of truth, so the default field is checked
    data, not decoration). Returns a list of :class:`Diagnostic`."""
    from ..utils import envparse
    try:
        with open(doc_path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError as exc:
        return [Diagnostic.make(
            "HVD306", f"cannot read knob docs: {exc}", file=doc_path)]
    documented = {}
    for lineno, line in enumerate(lines, start=1):
        m = _DOC_KNOB_RE.match(line.strip())
        if m:
            documented.setdefault(m.group(1), (lineno, m.group(2)))
    diags = []
    for name, (lineno, doc_default) in sorted(documented.items()):
        reg = envparse.KNOBS.get(name)
        if reg is None:
            continue  # reported below as undocumented-registration
        if _norm_default(doc_default) != _norm_default(reg["default"]):
            diags.append(Diagnostic.make(
                "HVD306",
                f"knob {name}: documented default "
                f"{doc_default.strip()!r} disagrees with the "
                f"registered default {reg['default']!r}",
                file=doc_path, line=lineno,
                hint="align the docs row and the register() call in "
                     "utils/envparse.py; " + _DOC_HINT))
    for name in sorted(set(envparse.KNOBS) - set(documented)):
        diags.append(Diagnostic.make(
            "HVD306",
            f"knob {name} is registered in utils/envparse.py but has "
            f"no table row in {os.path.basename(doc_path)}",
            file=doc_path, line=0,
            hint=f"add a `HVDTPU_{name}` row (or drop the "
                 "registration); " + _DOC_HINT))
    for name in sorted(set(documented) - set(envparse.KNOBS)):
        diags.append(Diagnostic.make(
            "HVD306",
            f"knob {name} is documented but not registered in "
            "utils/envparse.py — nothing reads it through the "
            "registry, so it will silently drift",
            file=doc_path, line=documented[name][0],
            hint="register() it in utils/envparse.py (or drop the "
                 "row); " + _DOC_HINT))
    return diags


_DOC_METRIC_RE = re.compile(r"^\|\s*`(hvd_[a-z0-9_]+)`\s*\|\s*"
                            r"([a-z]+)\s*\|")
_METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})
#: The metric families the serving/fleet registries own — the drift
#: check is scoped to them so rows registered elsewhere (coordinator,
#: elastic, ...) stay out of scope.
_METRIC_PREFIXES = ("hvd_serving_", "hvd_fleet_")


def _registered_metrics(source_paths):
    """``name -> (kind, file, line)`` scraped from
    ``telemetry.counter/gauge/histogram("name", ...)`` calls in the
    metric factory modules."""
    out = {}
    for path in source_paths:
        try:
            _, tree = parse_cached(path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr in _METRIC_FACTORIES):
                continue
            if not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str):
                out.setdefault(first.value,
                               (func.attr, path, node.lineno))
    return out


def check_metric_docs(doc_path, source_paths=None):
    """Cross-check the serving/fleet metric registries
    (``serving/metrics.py``, ``fleet/metrics.py``) against the table
    rows of ``docs/metrics.md``: every registered metric needs a
    documented row, every documented ``hvd_serving_*``/``hvd_fleet_*``
    row needs a registration, and the documented type column must match
    the registered factory (rule HVD307 — the registry is the docs'
    source of truth). Returns a list of :class:`Diagnostic`."""
    if source_paths is None:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        source_paths = [os.path.join(pkg, "serving", "metrics.py"),
                        os.path.join(pkg, "fleet", "metrics.py")]
    try:
        with open(doc_path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError as exc:
        return [Diagnostic.make(
            "HVD307", f"cannot read metric docs: {exc}",
            file=doc_path)]
    documented = {}
    for lineno, line in enumerate(lines, start=1):
        m = _DOC_METRIC_RE.match(line.strip())
        if m and m.group(1).startswith(_METRIC_PREFIXES):
            documented.setdefault(m.group(1), (lineno, m.group(2)))
    registered = {
        name: rec
        for name, rec in _registered_metrics(source_paths).items()
        if name.startswith(_METRIC_PREFIXES)}
    diags = []
    for name in sorted(set(documented) & set(registered)):
        doc_line, doc_kind = documented[name]
        reg_kind, _, _ = registered[name]
        if doc_kind != reg_kind:
            diags.append(Diagnostic.make(
                "HVD307",
                f"metric {name}: documented type {doc_kind!r} "
                f"disagrees with the registered factory "
                f"{reg_kind!r}",
                file=doc_path, line=doc_line,
                hint="align the docs row and the telemetry factory "
                     "call; " + _DOC_HINT))
    for name in sorted(set(registered) - set(documented)):
        _, src_file, src_line = registered[name]
        diags.append(Diagnostic.make(
            "HVD307",
            f"metric {name} is registered in "
            f"{os.path.basename(src_file)} but has no table row in "
            f"{os.path.basename(doc_path)}",
            file=src_file, line=src_line,
            hint=f"add a `{name}` row to docs/metrics.md (or drop "
                 "the factory); " + _DOC_HINT))
    for name in sorted(set(documented) - set(registered)):
        diags.append(Diagnostic.make(
            "HVD307",
            f"metric {name} is documented but not registered in the "
            "serving/fleet metric modules — nothing emits it, so the "
            "row is stale",
            file=doc_path, line=documented[name][0],
            hint="register it through telemetry.counter/gauge/"
                 "histogram (or drop the row); " + _DOC_HINT))
    return diags


def _apply_suppressions(diags, src):
    lines = src.splitlines()
    file_off = set()
    per_line = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_FILE_RE.search(line)
        if m:
            file_off.update(r.strip().upper()
                            for r in m.group(1).split(","))
        m = _SUPPRESS_RE.search(line)
        if m:
            per_line[i] = {r.strip().upper() for r in m.group(1).split(",")}

    def suppressed(d):
        if "ALL" in file_off or d.rule in file_off:
            return True
        for ln in (d.line, d.line - 1):
            rules = per_line.get(ln)
            if rules and ("ALL" in rules or d.rule in rules):
                # Same-line marker always applies; a previous-line marker
                # only applies if that line is a standalone comment.
                if ln == d.line or lines[ln - 1].lstrip().startswith("#"):
                    return True
        return False

    return [d for d in diags if not suppressed(d)]


# Parsed-corpus cache shared by every analysis layer of one process:
# within one CLI invocation the AST linter, the interprocedural
# verifier, and the schedule simulator all consume the same files —
# re-reading and re-parsing per leg dominated the --self/dogfood wall
# time. Keyed by (mtime_ns, size) so an edited file re-parses; trees
# are treated as read-only by every consumer.
_PARSE_CACHE = {}
_PARSE_CACHE_MAX = 2048


def parse_cached(path):
    """``(src, tree)`` for ``path``, parsed at most once per content
    version per process. Raises ``OSError``/``SyntaxError`` exactly
    like an uncached open+parse would."""
    path = os.path.abspath(path)
    try:
        st = os.stat(path)
        token = (st.st_mtime_ns, st.st_size)
    except OSError:
        token = None
    hit = _PARSE_CACHE.get(path)
    if hit is not None and hit[0] == token and token is not None:
        return hit[1], hit[2]
    with open(path, encoding="utf-8", errors="replace") as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
        _PARSE_CACHE.clear()
    _PARSE_CACHE[path] = (token, src, tree)
    return src, tree


def _lint_tree(src, tree, filename):
    analyzer = _Analyzer(filename)
    analyzer.visit(tree)
    diags = analyzer.finish()
    diags.extend(_RawTimingAnalyzer(filename).run(tree))
    diags.extend(_RequestBufferAnalyzer(filename).run(tree))
    diags.extend(_WorkerLifecycleAnalyzer(filename).run(tree))
    diags.extend(_SilentDegradationAnalyzer(filename).run(tree))
    diags.extend(_ProtocolOrderAnalyzer(filename).run(tree))
    diags.extend(_HandRollResharding(filename).run(tree))
    diags.extend(_ConcurrencyAnalyzer(filename).run(tree))
    diags = _apply_suppressions(diags, src)
    return dedupe(sorted(diags, key=Diagnostic.sort_key))


def lint_source(src, filename="<string>"):
    """Lint python source text; returns a list of :class:`Diagnostic`."""
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as exc:
        return [Diagnostic.make(
            "HVD001", f"syntax error: {exc.msg}",
            file=filename, line=exc.lineno or 0)]
    return _lint_tree(src, tree, filename)


def lint_file(path):
    try:
        src, tree = parse_cached(path)
    except SyntaxError as exc:
        return [Diagnostic.make(
            "HVD001", f"syntax error: {exc.msg}",
            file=path, line=exc.lineno or 0)]
    return _lint_tree(src, tree, path)


def iter_python_files(paths):
    for path in paths:
        if os.path.isfile(path):
            yield path
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__")
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


def lint_paths(paths):
    """Lint every ``.py`` file under ``paths`` (files or directories)."""
    diags = []
    for path in iter_python_files(paths):
        diags.extend(lint_file(path))
    return diags
