"""``hvd-lint``: static collective-correctness linter CLI.

Runs the AST layer over scripts/directories and prints structured
findings with ``file:line`` + fix hints:

    hvd-lint train.py examples/
    hvd-lint --format json --fail-on warning src/
    hvd-lint --list-rules

Exit codes: 0 no findings at/above ``--fail-on``; 1 findings; 2 usage
or internal error. The jaxpr layer needs traced inputs, so it is an API
(``horovod_tpu.analysis.check_fn``) and a bridge flag (``verify=``)
rather than a CLI mode — see docs/lint.md.
"""

import argparse
import json
import sys

from . import ast_lint
from .diagnostics import ERROR, RULES


def _build_parser():
    parser = argparse.ArgumentParser(
        prog="hvd-lint",
        description="Static collective-correctness linter for "
                    "horovod_tpu training scripts.")
    parser.add_argument("paths", nargs="*", default=["."],
                        help="python files or directories (default: .)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--rules", default="",
                        help="comma-separated rule ids to enable "
                             "(default: all)")
    parser.add_argument("--fail-on", choices=("error", "warning", "never"),
                        default="error",
                        help="lowest severity that fails the run "
                             "(default: error)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    return parser


def main(argv=None):
    parser = _build_parser()
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, (severity, title) in sorted(RULES.items()):
            print(f"{rule}  {severity:7s}  {title}")
        return 0

    only = {r.strip().upper() for r in args.rules.split(",") if r.strip()}
    try:
        diags = ast_lint.lint_paths(args.paths)
    except OSError as exc:
        print(f"hvd-lint: {exc}", file=sys.stderr)
        return 2
    if only:
        diags = [d for d in diags if d.rule in only]
    diags.sort(key=lambda d: d.sort_key())

    if args.format == "json":
        print(json.dumps([d.to_dict() for d in diags], indent=1))
    else:
        for d in diags:
            print(d.format())
        errors = sum(d.severity == ERROR for d in diags)
        print(f"hvd-lint: {len(diags)} finding(s) "
              f"({errors} error(s), {len(diags) - errors} warning(s))")

    if args.fail_on == "never":
        return 0
    if args.fail_on == "warning":
        return 1 if diags else 0
    return 1 if any(d.severity == ERROR for d in diags) else 0


if __name__ == "__main__":
    sys.exit(main())
