"""``hvd-lint``: static collective-correctness + concurrency linter CLI.

Runs the AST layer over scripts/directories and prints structured
findings with ``file:line`` + fix hints:

    hvd-lint train.py examples/
    hvd-lint verify train.py             # + HVD4xx + simulated HVD5xx
    hvd-lint perf train.py               # + α–β cost model + HVD6xx
    hvd-lint perf --calibrate ./hvd_traces --write-table model.json
    hvd-lint explain ./traces --program train.py   # postmortem → line
    hvd-lint --format json --fail-on warning src/
    hvd-lint --format sarif src/ > lint.sarif
    hvd-lint --write-baseline lint-baseline.json src/
    hvd-lint --baseline lint-baseline.json src/   # fail on NEW only
    hvd-lint --self                 # sweep horovod_tpu/ itself (CI)
    hvd-lint --check-knobs          # knob registry vs docs/knobs.md
    hvd-lint --list-rules

``verify`` is the interprocedural mode: on top of the single-hop rules
it builds a call graph over each script plus the ``horovod_tpu``
modules it imports, propagates a rank-dependence taint lattice,
extracts the symbolic per-rank collective schedule, applies the
heuristic HVD4xx family (analysis/schedule.py), and then **executes**
the extracted schedules in the symbolic N-rank simulator
(analysis/simulate.py): proven deadlocks (HVD501) and digest
mismatches (HVD502) are emitted with per-rank counterexample traces
(SARIF ``codeFlows``), approximations stay HVD503 warnings, and a
proven finding supersedes the heuristic one on the same event. Both
layers share one parsed corpus and one call-graph fixpoint per
invocation.

``perf`` is everything ``verify`` does plus the calibrated α–β cost
model (analysis/costmodel.py) over the SAME parsed corpus and
call-graph fixpoint: every extracted schedule gets a predicted
per-step critical path and comm/compute split at the probed cohort
sizes (``--target-ranks`` / ``HVDTPU_PERF_TARGET_RANKS``), and the
static HVD6xx performance rules (bucket pessimality, serialization
points, scale cliffs) join the finding stream. ``--calibrate DIR``
fits the model table from PR 8 trace shards first
(``--write-table FILE`` persists it; ``--table FILE`` /
``HVDTPU_COSTMODEL_TABLE`` loads one); without a table the checked-in
default covers the cold case.

``explain`` is the postmortem loop (analysis/explain.py): point it at
a flight-recorder postmortem bundle directory (and the program via
``--program``) and it names the first divergent slot, the matching
HVD5xx diagnosis, and the submitting source line.

``--self`` is the hvd-sanitize self-analysis: every rule — collective
HVD2xx + concurrency HVD3xx + the interprocedural HVD4xx — over the
installed ``horovod_tpu`` package, plus the knob-docs cross-check
(HVD306) when the repo's docs/knobs.md is present, failing on
warnings — the framework must hold itself to the rules it enforces on
user scripts.

Baselines (analysis/baseline.py): ``--write-baseline FILE`` records
current findings keyed by rule x file x content-hash;
``--baseline FILE`` (default: the ``HVDTPU_LINT_BASELINE`` knob) then
fails only on findings NOT in the record — the supported way to turn
a new warning-strength rule on in CI without fixing the world first.
SARIF output (analysis/sarif.py) marks baseline-suppressed results
with ``suppressions`` instead of dropping them.

Exit codes: 0 no NEW findings at/above ``--fail-on``; 1 findings; 2
usage or internal error. The jaxpr layer needs traced inputs, so it is
an API (``horovod_tpu.analysis.check_fn``) and a bridge flag
(``verify=``) rather than a CLI mode — see docs/lint.md.
"""

import argparse
import json
import os
import sys
import time

from . import (ast_lint, baseline as baseline_mod, costmodel, explain
               as explain_mod, sarif, simulate)
from .diagnostics import ERROR, RULES, dedupe, Diagnostic


def _package_dir():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _default_knob_docs():
    """docs/knobs.md next to the package (repo checkouts); None when
    absent (pip installs ship no docs — nothing to cross-check)."""
    path = os.path.join(os.path.dirname(_package_dir()), "docs",
                        "knobs.md")
    return path if os.path.isfile(path) else None


def _default_metric_docs():
    """docs/metrics.md next to the package; None when absent."""
    path = os.path.join(os.path.dirname(_package_dir()), "docs",
                        "metrics.md")
    return path if os.path.isfile(path) else None


def _build_parser():
    parser = argparse.ArgumentParser(
        prog="hvd-lint",
        description="Static collective-correctness and concurrency "
                    "linter for horovod_tpu training scripts (and, "
                    "via --self, for horovod_tpu itself). Prepend the "
                    "`verify` subcommand for the interprocedural "
                    "schedule verifier + symbolic simulator "
                    "(HVD4xx/HVD5xx), or `explain` to map a "
                    "postmortem bundle back to source.")
    parser.add_argument("paths", nargs="*", default=[],
                        help="python files or directories (default: . "
                             "unless only --check-knobs is requested)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--rules", default="",
                        help="comma-separated rule ids to enable "
                             "(default: all)")
    parser.add_argument("--fail-on", choices=("error", "warning", "never"),
                        default="error",
                        help="lowest severity that fails the run "
                             "(default: error; --self implies warning)")
    parser.add_argument("--self", dest="self_sweep", action="store_true",
                        help="sweep the horovod_tpu package itself with "
                             "every rule (incl. the interprocedural "
                             "HVD4xx family and the simulated HVD5xx) "
                             "+ the knob-docs cross-check, failing on "
                             "warnings (the hvd-sanitize "
                             "self-analysis)")
    parser.add_argument("--check-knobs", action="store_true",
                        help="cross-check the envparse knob registry "
                             "against docs/knobs.md (HVD306); with no "
                             "paths given, runs only the cross-check")
    parser.add_argument("--knobs-md", default="", metavar="PATH",
                        help="knob docs to cross-check against "
                             "(default: the repo's docs/knobs.md)")
    parser.add_argument("--check-metrics", action="store_true",
                        help="cross-check the serving/fleet metric "
                             "registries against docs/metrics.md "
                             "(HVD307); with no paths given, runs "
                             "only the cross-check")
    parser.add_argument("--metrics-md", default="", metavar="PATH",
                        help="metric docs to cross-check against "
                             "(default: the repo's docs/metrics.md)")
    parser.add_argument("--baseline", default="", metavar="FILE",
                        help="fail only on findings NOT recorded in "
                             "FILE (default: the HVDTPU_LINT_BASELINE "
                             "knob); recorded ones are reported as "
                             "suppressed")
    parser.add_argument("--write-baseline", default="", metavar="FILE",
                        help="record the current findings as the "
                             "accepted baseline and exit 0")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    perf = parser.add_argument_group(
        "perf (the `perf` subcommand / --self)")
    perf.add_argument("--calibrate", default="", metavar="DIR",
                      help="fit the α–β model table from the trace "
                           "shards under DIR before analyzing "
                           "(unreadable/torn shards are skipped with "
                           "a warning)")
    perf.add_argument("--write-table", default="", metavar="FILE",
                      help="persist the calibrated table as JSON "
                           "(with no paths: calibrate-and-write only)")
    perf.add_argument("--table", default="", metavar="FILE",
                      help="model table to predict with (default: "
                           "the HVDTPU_COSTMODEL_TABLE knob, else the "
                           "built-in default table)")
    perf.add_argument("--target-ranks", default="", metavar="LIST",
                      help="comma-separated cohort sizes to probe "
                           "(default: the HVDTPU_PERF_TARGET_RANKS "
                           "knob, else 8,64,256,1024)")
    return parser


def _collect(paths, verify, perf=False, table=None, ranks=None,
             want_report=False):
    """One invocation, one parsed corpus: the AST layer shares file
    reads with the verifier through the parse cache, and the verify
    and perf legs share ONE Verifier (one corpus load, one call-graph
    fixpoint — the perf leg's ``Verifier.fixpoint()`` is idempotent).
    Returns ``(diags, perf_report_or_None)``."""
    diags = ast_lint.lint_paths(paths)
    report = None
    if verify or perf:
        verifier = simulate.Verifier()
        for path in ast_lint.iter_python_files(paths):
            verifier.add_path(path)
        if verify:
            # heuristic HVD4xx + simulated HVD5xx
            diags.extend(simulate.run_combined(verifier))
        if perf:
            diags.extend(costmodel.perf_diagnostics(
                verifier, table=table, target_ranks=ranks))
            if want_report:
                report = costmodel.analyze_corpus(
                    verifier, table=table, target_ranks=ranks)
    return dedupe(sorted(diags, key=Diagnostic.sort_key)), report


def _explain_main(argv):
    parser = argparse.ArgumentParser(
        prog="hvd-lint explain",
        description="Map a flight-recorder postmortem bundle back to "
                    "the source line where the per-rank schedules "
                    "diverged.")
    parser.add_argument("bundle", help="directory holding the "
                        "postmortem.*.jsonl shards")
    parser.add_argument("--program", action="append", default=[],
                        metavar="PATH",
                        help="the training program (repeatable) whose "
                             "extracted schedule maps slots to source "
                             "lines")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    args = parser.parse_args(argv)
    try:
        report = explain_mod.explain_bundle(args.bundle, args.program)
    except explain_mod.ExplainError as exc:
        print(f"hvd-lint explain: {exc}", file=sys.stderr)
        return 2
    except OSError as exc:
        print(f"hvd-lint explain: {exc}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(explain_mod.to_json(report))
    else:
        print(explain_mod.render_report(report))
    return 0


def _baseline_path(args):
    if args.baseline:
        return args.baseline, True
    from ..utils import envparse
    path = envparse.get_str(envparse.LINT_BASELINE)
    # the env-default baseline is best-effort: a job exported the knob
    # but the file is gone -> run unfiltered rather than die in CI
    return (path, False) if path else (None, False)


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    argv = list(argv)
    if argv and argv[0] == "explain":
        return _explain_main(argv[1:])
    verify = bool(argv) and argv[0] == "verify"
    perf = bool(argv) and argv[0] == "perf"
    if verify or perf:
        argv = argv[1:]
    verify = verify or perf  # perf = verify + the cost-model layer
    parser = _build_parser()
    args = parser.parse_args(argv)
    t_start = time.perf_counter()

    if args.list_rules:
        for rule, (severity, title) in sorted(RULES.items()):
            print(f"{rule}  {severity:7s}  {title}")
        return 0

    fail_on = args.fail_on
    # An explicit --knobs-md implies the cross-check: a user who named
    # the file expects it to be read.
    check_knobs = (args.check_knobs or args.self_sweep
                   or bool(args.knobs_md))
    check_metrics = (args.check_metrics or args.self_sweep
                     or bool(args.metrics_md))
    paths = list(args.paths)
    if args.self_sweep:
        paths = [_package_dir()]
        verify = True
        perf = True   # the perf leg rides the same corpus — HVD6xx
        if fail_on == "error":
            fail_on = "warning"
    elif not paths and not (check_knobs or check_metrics) \
            and not args.calibrate:
        paths = ["."]
    # `hvd-lint --check-knobs`/`--check-metrics` with no paths runs
    # ONLY the cross-check(s).

    table, ranks = None, None
    if perf:
        if args.calibrate:
            try:
                table = costmodel.fit_paths([args.calibrate])
            except (OSError, ValueError) as exc:
                print(f"hvd-lint perf: {exc}", file=sys.stderr)
                return 2
            worlds = "/".join(str(w) for w in table["worlds"])
            compute = ("none" if table["compute_s"] is None
                       else f"{table['compute_s'] * 1e3:.3f} ms")
            print(f"hvd-lint perf: calibrated {table['spans']} span(s) "
                  f"at world size(s) {worlds or '?'} "
                  f"(compute baseline: {compute})")
            if args.write_table:
                try:
                    costmodel.save_table(table, args.write_table)
                except OSError as exc:
                    print(f"hvd-lint perf: cannot write table: {exc}",
                          file=sys.stderr)
                    return 2
                print("hvd-lint perf: model table -> "
                      f"{args.write_table}")
            if not paths:
                return 0
        elif args.table:
            try:
                table = costmodel.load_table(args.table)
            except (OSError, ValueError) as exc:
                print(f"hvd-lint perf: cannot read table: {exc}",
                      file=sys.stderr)
                return 2
        if args.target_ranks:
            ranks = sorted({int(p) for p in
                            args.target_ranks.split(",")
                            if p.strip().isdigit() and int(p) >= 2}) \
                or None

    diags, perf_report = [], None
    try:
        if paths:
            diags, perf_report = _collect(
                paths, verify, perf=perf, table=table, ranks=ranks,
                want_report=(perf and not args.self_sweep
                             and args.format == "text"))
    except OSError as exc:
        print(f"hvd-lint: {exc}", file=sys.stderr)
        return 2

    if check_knobs:
        # An explicit --knobs-md that cannot be read surfaces as an
        # HVD306 diagnostic from check_knob_docs. A missing DEFAULT
        # docs file is only tolerated for the implicit --self case
        # (pip installs ship no docs); an explicit --check-knobs that
        # finds nothing to check must not report green.
        doc_path = args.knobs_md or _default_knob_docs()
        if doc_path:
            diags.extend(ast_lint.check_knob_docs(doc_path))
        elif args.check_knobs or args.knobs_md:
            print("hvd-lint: no knob docs found (no docs/knobs.md "
                  "next to the package); pass --knobs-md PATH",
                  file=sys.stderr)
            return 2

    if check_metrics:
        # Same tolerance contract as the knob cross-check: implicit
        # (--self) skips silently when the docs are absent, explicit
        # --check-metrics must not report green on nothing.
        doc_path = args.metrics_md or _default_metric_docs()
        if doc_path:
            diags.extend(ast_lint.check_metric_docs(doc_path))
        elif args.check_metrics or args.metrics_md:
            print("hvd-lint: no metric docs found (no docs/metrics.md "
                  "next to the package); pass --metrics-md PATH",
                  file=sys.stderr)
            return 2

    only = {r.strip().upper() for r in args.rules.split(",") if r.strip()}
    if only:
        diags = [d for d in diags if d.rule in only]
    diags.sort(key=lambda d: d.sort_key())

    if args.write_baseline:
        try:
            baseline_mod.write_baseline(diags, args.write_baseline)
        except OSError as exc:
            print(f"hvd-lint: cannot write baseline: {exc}",
                  file=sys.stderr)
            return 2
        print(f"hvd-lint: baseline recorded ({len(diags)} finding(s) "
              f"-> {args.write_baseline})")
        return 0

    suppressed = []
    base_path, explicit = _baseline_path(args)
    if base_path:
        try:
            doc = baseline_mod.load_baseline(base_path)
        except (OSError, ValueError) as exc:
            if explicit:
                print(f"hvd-lint: cannot read baseline: {exc}",
                      file=sys.stderr)
                return 2
            doc = None
        if doc is not None:
            diags, suppressed = baseline_mod.filter_new(diags, doc)

    if args.format == "json":
        print(json.dumps([d.to_dict() for d in diags], indent=1))
    elif args.format == "sarif":
        sarif.write_sarif(None, diags, suppressed=suppressed)
    else:
        if perf_report is not None:
            report_text = costmodel.render_report(perf_report)
            if report_text:
                print(report_text)
        for d in diags:
            print(d.format())
            trace_text = simulate.render_trace(d)
            if trace_text:
                print(trace_text)
        errors = sum(d.severity == ERROR for d in diags)
        tail = (f", {len(suppressed)} baseline-suppressed"
                if suppressed else "")
        elapsed = time.perf_counter() - t_start
        print(f"hvd-lint: {len(diags)} finding(s) "
              f"({errors} error(s), {len(diags) - errors} warning(s)"
              f"{tail}) in {elapsed:.2f}s")

    if fail_on == "never":
        return 0
    if fail_on == "warning":
        return 1 if diags else 0
    return 1 if any(d.severity == ERROR for d in diags) else 0


if __name__ == "__main__":
    sys.exit(main())
