"""Symbolic N-rank schedule simulator — proven HVD5xx findings.

The HVD4xx verifier (schedule.py) flags divergence *heuristically*: it
pattern-matches guard shapes (rank-tainted branches, divergent loop
bounds) without asking whether the resulting per-rank schedules could
still reconcile. This layer answers that question by **executing** the
extracted schedules: it instantiates symbolic cohorts, forks the
per-rank event streams at every rank-tainted decision, and runs them
through a lockstep matching semantics that mirrors the coordinator's
negotiation — an event completes only when every member of its process
set has submitted a compatible head ``(kind, name, process_set, op)``.
What cannot reconcile is a **proven** finding with a counterexample:

- **HVD501** — proven deadlock: the symbolic ranks' heads are
  irreconcilable (different slots, or a rank's schedule is exhausted
  while peers still wait). Emitted with a full per-rank counterexample
  trace: each rank's event list up to the hang point with source
  locations, plus the taint chain (fork points) that split the paths.
- **HVD502** — proven digest mismatch: the heads *match* as a
  negotiation slot (same name / same call site) but diverge in a
  statically-computable field (kind or op) — exactly what the runtime
  guardian's digest compare aborts on (``CollectiveMismatchError``
  foretold at lint time).
- **HVD503** — possible hang: bounded exploration (scenario caps,
  inline depth, loop widening, data-dependent trip counts) forced an
  approximation, so divergence could neither be proven nor refuted.
  Proven findings are errors; approximations stay warnings.

Symbolic cohorts: ``any n >= 2`` (rank ``r`` vs. ``rest`` — the
abstraction that generalizes a counterexample to every world size,
and subsumes a concrete n=2 run, which would be byte-identical up to
labels), plus concrete ``n=3`` (three ranks are what expose three-way
forks such as ``if rank()==0 … elif rank()==1 …``).

Semantics and deliberate approximations (docs/lint.md "Simulator
semantics"):

- Only **rank-tainted** decisions fork the cohort; replica-invariant
  branches move every rank together (both arms are still explored).
  ``split`` assigns the first symbolic rank to reach the branch to the
  ``then`` arm and the rest to ``else``.
- **Non-global process sets complete immediately**: their membership is
  statically unknown, so divergence involving them stays HVD404's
  heuristic territory, and member-only guarded collectives
  (``if ps.included(): allreduce(..., process_set=ps)``) are exempt by
  construction.
- **Unnamed collectives** match when their kinds agree: fixed-name ops
  (barrier, the object/state broadcasts) really do negotiate one
  internal name, and auto-named call sites are HVD203's business — the
  simulator never *proves* anything about a name it cannot compute.
  f-string names likewise make a slot unprovable and it is skipped.
- **Exception handlers** are never executed; a tainted argument
  steering a callee's *parameter* guard is not forked (that shape stays
  HVD401's); recursion and inline depth are capped.

Rule ownership (mirrors the 201-vs-401 contract): a proven HVD501/502
supersedes the heuristic HVD401/HVD402/HVD404 finding on the same
event — one report per defect, the proven one. HVD503 is only emitted
where no heuristic already covers the shape. A ``# hvd-lint:
disable=HVD40x`` suppression on the superseded heuristic carries over:
the human already waived that exact divergence.

Pure stdlib — no jax imports.
"""

import itertools
import os

from .ast_lint import iter_python_files
from .diagnostics import Diagnostic, dedupe, relative_to_cwd
from .schedule import Verifier, _suppress

_DOC_HINT = "see docs/lint.md"

#: scenario budget per (function, cohort): the cartesian product of
#: branch/loop choices is cut here; hitting the cap flags approximation
_MAX_SCENARIOS = 96
_MAX_INLINE_DEPTH = 4
#: per-rank stream cap (runaway loop x inline guard)
_MAX_RANK_EVENTS = 200
#: trace events kept per rank in the emitted counterexample
_TRACE_EVENTS = 20

#: (cohort label, symbolic rank labels). Order matters: findings are
#: deduped first-wins, so the any-n abstraction (whose counterexample
#: generalizes to every world size) takes precedence. A concrete
#: ``n=2`` cohort would be byte-identical to the two-symbolic-rank
#: any-n run (the matcher only sees labels), so it is subsumed rather
#: than simulated twice; ``n=3`` is what reaches the deepest arm of a
#: three-way ``elif`` fork.
COHORTS = (
    ("any n >= 2", ("r", "rest")),
    ("n=3", ("0", "1", "2")),
)

_SUPERSEDED_RULES = frozenset({"HVD401", "HVD402", "HVD404"})


class _Return(Exception):
    pass


class _Raise(Exception):
    pass


class _Break(Exception):
    pass


class _Cont(Exception):
    pass


class _Trunc(Exception):
    pass


class SimEvent:
    """One symbolic collective submission in a generated stream."""

    __slots__ = ("kind", "name", "pattern", "pset", "op", "file",
                 "line")

    def __init__(self, ev, path):
        self.kind = ev.kind
        self.name = ev.name
        self.pattern = ev.pattern
        self.pset = ev.pset
        self.op = ev.op
        self.file = path
        self.line = ev.line

    def slot(self):
        """Negotiation-slot key: explicit names key the slot; unnamed
        events key by kind (fixed-name ops match across call sites,
        auto-named hazards are HVD203's, not provable here)."""
        if self.name is not None:
            return ("n", self.name)
        return ("u", self.kind)

    def describe(self):
        out = f"`{self.kind}`"
        if self.name is not None:
            out += f"(name={self.name!r})"
        elif self.pattern is not None:
            out += f"(name~/{self.pattern}/)"
        if self.op is not None:
            out += f" op={self.op}"
        return out


class _Decision:
    __slots__ = ("key", "choices", "tainted", "line", "file", "loop")

    def __init__(self, key, choices, tainted, line, file, loop):
        self.key = key
        self.choices = choices
        self.tainted = tainted
        self.line = line
        self.file = file
        self.loop = loop


def _body_divergent(prog):
    """Does this loop body contain a rank-tainted fork point? (Such
    loops are widened to two base iterations so a divergent first
    iteration can desynchronize against a second.)"""
    for node in prog:
        tag = node[0]
        if tag == "br":
            if node[1].tainted or _body_divergent(node[2]) \
                    or _body_divergent(node[3]):
                return True
        elif tag == "loop":
            if node[1].frame.tainted or _body_divergent(node[2]):
                return True
        elif tag == "exit":
            if any(f.tainted for f in node[1].ctx):
                return True
    return False


def _loop_data_dependent(loop):
    return loop.kind == "while" and any(
        cls == "call" for cls in loop.body_assigns.values())


class _Survey:
    """Static pre-pass over one entry function (inlined callees
    included): collects the ordered **rank-tainted** decision points
    (the only ones that can fork the cohort — replica-invariant
    branches move every rank together and are swept with uniform
    all-then/all-else patterns instead of being enumerated), whether
    any collective event is reachable, and the approximation flags."""

    def __init__(self, fn):
        self.decisions = []           # tainted decisions only
        self._seen = set()
        self.has_events = False
        self.approx = []              # list of reason strings
        self.tainted_lines = set()    # (file, line) of fork points
        self._walk(fn.program, fn.module.path, 0, frozenset({fn}))

    def _walk(self, prog, path, depth, stack):
        for node in prog:
            tag = node[0]
            if tag == "ev":
                self.has_events = True
            elif tag == "call":
                callee = node[1].callee
                if not callee.has_coll_trans:
                    continue
                if depth >= _MAX_INLINE_DEPTH or callee in stack:
                    self.approx.append(
                        f"call to {callee.qualname} not inlined "
                        "(depth/recursion cap)")
                    continue
                self._walk(callee.program, callee.module.path,
                           depth + 1, stack | {callee})
            elif tag == "br":
                frame = node[1]
                if id(node) not in self._seen:
                    self._seen.add(id(node))
                    if frame.tainted:
                        self.decisions.append(_Decision(
                            id(node), ("split", "then", "else"), True,
                            frame.line, path, loop=False))
                        self.tainted_lines.add((path, frame.line))
                self._walk(node[2], path, depth, stack)
                self._walk(node[3], path, depth, stack)
            elif tag == "loop":
                loop = node[1]
                if id(node) not in self._seen:
                    self._seen.add(id(node))
                    if loop.frame.tainted:
                        self.decisions.append(_Decision(
                            id(node), ("split", "uniform"), True,
                            loop.line, path, loop=True))
                        self.tainted_lines.add((path, loop.line))
                    elif _loop_data_dependent(loop):
                        # each rank's own data picks the trip count —
                        # real divergence, but not statically
                        # enumerable: approximation (HVD402 owns the
                        # heuristic diagnosis)
                        self.approx.append(
                            f"data-dependent `while` trip count at "
                            f"{relative_to_cwd(path)}:{loop.line}")
                        self.tainted_lines.add((path, loop.line))
                self._walk(node[2], path, depth, stack)
            elif tag == "exit":
                if any(f.tainted for f in node[1].ctx):
                    self.tainted_lines.add((path, node[1].line))
            # "opt" (exception handlers): never simulated


class _RankRun:
    """Generate one symbolic rank's event stream for one scenario.

    ``choice`` assigns the tainted decisions; every replica-invariant
    branch takes ``clean_arm`` uniformly (both sweeps are run per
    scenario), and clean loops always run their widened base count."""

    def __init__(self, rank_i, choice, clean_arm, first_reach, forks):
        self.rank_i = rank_i
        self.choice = choice          # tainted decision key -> choice
        self.clean_arm = clean_arm    # "then" | "else"
        self.first_reach = first_reach  # decision key -> rank index
        self.forks = forks            # (path, line) -> fork dict
        self.out = []
        self.truncated = False

    def run(self, fn):
        try:
            self._block(fn.program, fn.module.path, 0, frozenset({fn}))
        except (_Return, _Raise):
            pass
        except _Trunc:
            self.truncated = True
        return self.out

    def _fork(self, path, frame, loop=False):
        key = (path, frame.line)
        if key in self.forks:
            return
        if loop:
            why = (f"`{frame.kind}` loop trip count is rank-tainted — "
                   "ranks iterate different numbers of times")
        elif frame.direct:
            why = ("condition tests rank()/membership directly — "
                   "arms differ per rank")
        else:
            why = ("condition is rank-tainted through data flow (a "
                   "variable or return value derived from rank()) — "
                   "arms can differ per rank")
        self.forks[key] = {"file": path, "line": frame.line,
                           "why": why}

    def _exit_fork(self, path, exit_):
        key = (path, exit_.line)
        if key not in self.forks:
            self.forks[key] = {
                "file": path, "line": exit_.line,
                "why": f"rank-gated `{exit_.kind}` ends this rank's "
                       "schedule early"}

    def _block(self, prog, path, depth, stack):
        for node in prog:
            tag = node[0]
            if tag == "ev":
                if len(self.out) >= _MAX_RANK_EVENTS:
                    raise _Trunc
                self.out.append(SimEvent(node[1], path))
            elif tag == "call":
                callee = node[1].callee
                if not callee.has_coll_trans:
                    continue
                if depth >= _MAX_INLINE_DEPTH or callee in stack:
                    continue  # surveyed as approximation already
                try:
                    self._block(callee.program, callee.module.path,
                                depth + 1, stack | {callee})
                except _Return:
                    pass
            elif tag == "br":
                frame, then_prog, else_prog = node[1], node[2], node[3]
                c = self.choice.get(id(node), self.clean_arm)
                if c == "split":
                    first = self.first_reach.setdefault(id(node),
                                                        self.rank_i)
                    arm = then_prog if self.rank_i == first \
                        else else_prog
                    self._fork(path, frame)
                else:
                    arm = then_prog if c == "then" else else_prog
                self._block(arm, path, depth, stack)
            elif tag == "loop":
                loop, body = node[1], node[2]
                c = self.choice.get(id(node), "uniform")
                iters = 2 if _body_divergent(body) else 1
                if c == "split":
                    first = self.first_reach.setdefault(id(node),
                                                        self.rank_i)
                    if self.rank_i == first:
                        iters += 1
                    self._fork(path, loop.frame, loop=True)
                for _ in range(iters):
                    try:
                        self._block(body, path, depth, stack)
                    except _Break:
                        break
                    except _Cont:
                        continue
            elif tag == "exit":
                exit_ = node[1]
                if any(f.tainted for f in exit_.ctx):
                    self._exit_fork(path, exit_)
                if exit_.kind == "return":
                    raise _Return
                if exit_.kind == "raise":
                    raise _Raise
                if exit_.kind == "continue":
                    raise _Cont
                raise _Break
            # "opt": exception handlers are never executed


def _gen_streams(fn, ranks, choice, clean_arm):
    """Per-rank streams for one scenario. Returns
    ``(streams, forks, truncated)``."""
    first_reach, forks = {}, {}
    streams, truncated = {}, False
    for i, label in enumerate(ranks):
        run = _RankRun(i, choice, clean_arm, first_reach, forks)
        streams[label] = run.run(fn)
        truncated = truncated or run.truncated
    return streams, list(forks.values()), truncated


# -- lockstep matcher -------------------------------------------------------
def _lockstep(streams, ranks):
    """Run the per-rank streams through the coordinator's matching
    semantics. Returns ``None`` (schedules reconcile) or a finding
    descriptor dict."""
    idx = {r: 0 for r in ranks}
    matched = {r: [] for r in ranks}

    def head(r):
        s = streams[r]
        return s[idx[r]] if idx[r] < len(s) else None

    while True:
        live = {r: h for r in ranks
                for h in (head(r),) if h is not None}
        if not live:
            return None
        # Non-global process sets: membership statically unknown —
        # complete immediately (divergence there stays HVD404's).
        progressed = False
        for r, h in list(live.items()):
            if h.pset != "global":
                matched[r].append((h, "matched"))
                idx[r] += 1
                progressed = True
        if progressed:
            continue
        if len(live) < len(ranks):
            return {"type": "deadlock", "blocked": live,
                    "matched": matched}
        # Every rank's head is a global collective: one negotiation
        # slot must cover them all.
        heads = list(live.values())
        if any(h.pattern is not None for h in heads):
            # f-string names: the slot is not statically computable —
            # assume it matches (never *prove* from an unknown name)
            for r, h in live.items():
                matched[r].append((h, "matched"))
                idx[r] += 1
            continue
        slots = {h.slot() for h in heads}
        if len(slots) > 1:
            # Distinct slots never negotiate together: different
            # explicit names, an explicit name racing an unnamed op
            # (whose fixed/auto internal name cannot equal it), or
            # unnamed ops of different kinds (fixed names differ, and
            # per-call-site auto names carry the kind). Proven hang.
            return {"type": "deadlock", "blocked": live,
                    "matched": matched}
        # One slot. Statically-computable field compatibility: the
        # guardian digest compares kind/op (incl. the Adasum fence —
        # Sum vs Adasum on one slot is a digest abort).
        kinds = {h.kind for h in heads}
        ops = {h.op for h in heads if h.op is not None}
        if len(kinds) > 1:
            return {"type": "mismatch", "field": "kind",
                    "blocked": live, "matched": matched}
        if len(ops) > 1:
            return {"type": "mismatch", "field": "op",
                    "blocked": live, "matched": matched}
        for r, h in live.items():
            matched[r].append((h, "matched"))
            idx[r] += 1


# -- finding construction ---------------------------------------------------
def _rel(path):
    return relative_to_cwd(path)


def _trace_event(ev, status):
    return {"kind": ev.kind, "name": ev.name,
            "file": _rel(ev.file), "line": ev.line, "status": status}


def _build_trace(cohort, ranks, result, forks):
    trace = {"cohort": cohort, "ranks": [], "forks": [
        {"file": _rel(f["file"]), "line": f["line"], "why": f["why"]}
        for f in sorted(forks, key=lambda f: (f["file"], f["line"]))]}
    blocked = result["blocked"]
    blocked_status = ("mismatched" if result["type"] == "mismatch"
                      else "blocked")
    for r in ranks:
        events = [_trace_event(e, status)
                  for e, status in result["matched"][r]]
        dropped = 0
        if len(events) > _TRACE_EVENTS:
            dropped = len(events) - _TRACE_EVENTS
            events = events[-_TRACE_EVENTS:]
        if r in blocked:
            events.append(_trace_event(blocked[r], blocked_status))
        entry = {"rank": r,
                 "end": blocked_status if r in blocked
                 else "exhausted",
                 "events": events}
        if dropped:
            entry["dropped"] = dropped
        trace["ranks"].append(entry)
    return trace


def _covered_lines(result, forks):
    lines = {(f["file"], f["line"]) for f in forks}
    for h in result["blocked"].values():
        lines.add((h.file, h.line))
    return lines


def _fork_summary(forks):
    if not forks:
        return "program entry"
    return ", ".join(
        f"{_rel(f['file'])}:{f['line']}"
        for f in sorted(forks, key=lambda f: (f["file"], f["line"])))


def _make_finding(fn, cohort, ranks, result, forks):
    blocked = result["blocked"]
    anchor = next(blocked[r] for r in ranks if r in blocked)
    trace = _build_trace(cohort, ranks, result, forks)
    if result["type"] == "deadlock":
        states = []
        for r in ranks:
            if r in blocked:
                h = blocked[r]
                states.append(f"rank {r} blocks at {h.describe()} "
                              f"({_rel(h.file)}:{h.line})")
            else:
                states.append(f"rank {r} exhausts its schedule and "
                              "never submits it")
        diag = Diagnostic.make(
            "HVD501",
            f"proven deadlock (cohort {cohort}): the per-rank "
            "schedules are irreconcilable — "
            + "; ".join(states)
            + f". Schedules fork at {_fork_summary(forks)}; "
            "counterexample trace attached",
            file=anchor.file, line=anchor.line,
            hint="every rank must submit the same collective "
                 "sequence: hoist collectives out of rank-dependent "
                 "paths, or make the gating value replica-invariant "
                 "(allreduce the flag first); " + _DOC_HINT,
            trace=trace)
    else:
        field = result["field"]
        values = ", ".join(
            f"rank {r}: {blocked[r].kind if field == 'kind' else blocked[r].op}"
            f" ({_rel(blocked[r].file)}:{blocked[r].line})"
            for r in ranks if r in blocked)
        diag = Diagnostic.make(
            "HVD502",
            f"proven digest mismatch (cohort {cohort}): matched "
            f"collective slot {anchor.describe()} diverges on "
            f"`{field}` across ranks — {values}. The runtime "
            "guardian digest compare aborts exactly here "
            "(CollectiveMismatchError foretold at lint time)",
            file=anchor.file, line=anchor.line,
            hint="every rank must submit identical collective "
                 "metadata for one named slot — align the op/kind "
                 "across the diverging paths; " + _DOC_HINT,
            trace=trace)
    diag._covered = _covered_lines(result, forks)
    return diag


# -- per-function driver ----------------------------------------------------
def _scenarios(decisions):
    """Choice assignments for the tainted decisions. Full cartesian
    product while it fits the budget; past the cap, a **linear
    fallback** explores each fork point independently (that decision
    split, the others held uniform) plus the everything-splits case —
    deadlocks overwhelmingly manifest from a single fork, so the
    fallback stays sound for what it proves and is simply silent on
    exotic multi-fork interactions (documented approximation)."""
    total = 1
    for d in decisions:
        total *= len(d.choices)
    if total <= _MAX_SCENARIOS:
        return [
            {d.key: c for d, c in zip(decisions, combo)}
            for combo in itertools.product(
                *[d.choices for d in decisions])]

    def uniform(arm):
        return {d.key: ("uniform" if d.loop else arm)
                for d in decisions}

    out = [{d.key: "split" for d in decisions}]
    for d in decisions:
        for arm in ("then", "else"):
            sc = uniform(arm)
            sc[d.key] = "split"
            out.append(sc)
    return out[:_MAX_SCENARIOS]


def _simulate_function(fn, seen, findings, approx_notes):
    if not fn.program:
        return
    survey = _Survey(fn)
    if not survey.has_events:
        return
    tainted = [d for d in survey.decisions if d.tainted]
    data_dep = any("data-dependent" in a for a in survey.approx)
    if not tainted and not data_dep:
        # no rank-tainted fork point: every rank runs the identical
        # schedule — reconciles trivially, nothing to explore
        return
    truncated_any = False
    proven_here = False
    if tainted:
        scenarios = _scenarios(survey.decisions)
        for choice in scenarios:
            for cohort, ranks in COHORTS:
                for clean_arm in ("then", "else"):
                    streams, forks, truncated = _gen_streams(
                        fn, ranks, choice, clean_arm)
                    truncated_any = truncated_any or truncated
                    result = _lockstep(streams, ranks)
                    if result is None:
                        continue
                    if truncated and any(r not in result["blocked"]
                                         for r in ranks):
                        # a rank "exhausted" by the event cap is not
                        # a proven exhaustion — approximation only
                        continue
                    diag = _make_finding(fn, cohort, ranks, result,
                                         forks)
                    key = (diag.rule, diag.file, diag.line)
                    if key not in seen:
                        seen.add(key)
                        findings.append(diag)
                    proven_here = True
    if proven_here:
        return
    if truncated_any or survey.approx:
        reasons = []
        if truncated_any:
            reasons.append("event cap")
        reasons.extend(survey.approx[:2])
        anchor = min(survey.tainted_lines) if survey.tainted_lines \
            else (fn.module.path, getattr(fn.node, "lineno", 1) or 1)
        approx_notes.append({
            "fn": fn.qualname,
            "file": anchor[0],
            "line": anchor[1],
            "covered": set(survey.tainted_lines) | {anchor},
            "reason": "; ".join(reasons),
        })


def simulate_verifier(verifier):
    """Run the simulator over every function of an already-fixpointed
    :class:`Verifier`'s corpus. Returns ``(proven_diags,
    approx_notes)`` — both pre-suppression; :func:`combine` applies
    ownership and suppression."""
    seen, findings, approx_notes = set(), [], []
    for path in sorted(verifier.corpus.modules):
        mod = verifier.corpus.modules[path]
        for qual in sorted(mod.funcs):
            _simulate_function(mod.funcs[qual], seen, findings,
                               approx_notes)
    return findings, approx_notes


# -- ownership + assembly ---------------------------------------------------
def combine(heur_raw, proven_raw, approx_notes, corpus):
    """Assemble the final ``hvd-lint verify`` finding stream:

    1. standard suppression comments on both layers;
    2. a suppressed heuristic HVD4xx carries over to the proven
       finding covering the same lines (the human waived that exact
       divergence);
    3. a surviving proven HVD501/502 supersedes the heuristic
       HVD401/402/404 on the same event (no double reports);
    4. HVD503 approximation warnings are emitted only where no
       heuristic or proven finding already covers the shape.
    """
    heur_kept = _suppress(heur_raw, corpus)
    kept_ids = {id(d) for d in heur_kept}
    suppressed_lines = {(d.file, d.line) for d in heur_raw
                        if id(d) not in kept_ids}

    proven = _suppress(proven_raw, corpus)
    proven = [d for d in proven
              if not (getattr(d, "_covered", set()) & suppressed_lines)
              and (d.file, d.line) not in suppressed_lines]

    covered = set()
    for d in proven:
        covered |= getattr(d, "_covered", set())
        covered.add((d.file, d.line))
    heur_final = [d for d in heur_kept
                  if not (d.rule in _SUPERSEDED_RULES
                          and (d.file, d.line) in covered)]

    heur_lines = {(d.file, d.line) for d in heur_kept
                  if d.rule.startswith("HVD4")}
    blocked = covered | heur_lines | suppressed_lines
    approx = []
    for note in approx_notes:
        if note["covered"] & blocked:
            continue
        approx.append(Diagnostic.make(
            "HVD503",
            f"possible hang in {note['fn']}: bounded simulation "
            f"({note['reason']}) could neither prove nor refute "
            "schedule divergence under rank-tainted control flow",
            file=note["file"], line=note["line"],
            hint="restructure toward a statically-checkable schedule "
                 "(replica-invariant bounds, fewer rank-dependent "
                 "paths), or suppress with a rationale; " + _DOC_HINT))
    approx = _suppress(approx, corpus)

    return dedupe(sorted(heur_final + proven + approx,
                         key=Diagnostic.sort_key))


def run_combined(verifier):
    """HVD4xx + HVD5xx over one shared corpus and one fixpoint."""
    heur_raw = verifier.run()
    proven_raw, approx_notes = simulate_verifier(verifier)
    return combine(heur_raw, proven_raw, approx_notes, verifier.corpus)


def verify_and_simulate_paths(paths):
    """The ``hvd-lint verify`` pipeline: heuristic HVD4xx + proven
    HVD5xx over every ``.py`` file under ``paths``, one shared parsed
    corpus and call-graph fixpoint for both layers."""
    verifier = Verifier()
    for path in iter_python_files(paths):
        verifier.add_path(path)
    return run_combined(verifier)


def verify_and_simulate_source(src, filename="<string>"):
    verifier = Verifier()
    try:
        verifier.add_source(src, filename)
    except SyntaxError as exc:
        return [Diagnostic.make(
            "HVD001", f"syntax error: {exc.msg}",
            file=filename, line=exc.lineno or 0)]
    return run_combined(verifier)


def simulate_paths(paths):
    """HVD5xx findings only (the simulator's own stream, after
    ownership/suppression) — what the fixture pins assert on."""
    return [d for d in verify_and_simulate_paths(paths)
            if d.rule.startswith("HVD5")]


def simulate_source(src, filename="<string>"):
    return [d for d in verify_and_simulate_source(src, filename)
            if d.rule.startswith("HVD5")]


# -- trace rendering --------------------------------------------------------
def render_trace(diag):
    """Human-readable counterexample for a HVD501/502 finding (the CLI
    text formatter appends this under the finding line). Format is
    golden-pinned — tooling parses it."""
    trace = getattr(diag, "trace", None)
    if not trace:
        return ""
    lines = [f"    counterexample (cohort: {trace['cohort']})"]
    for entry in trace["ranks"]:
        lines.append(f"      rank {entry['rank']}:")
        if entry.get("dropped"):
            lines.append(f"        ... {entry['dropped']} earlier "
                         "event(s) elided ...")
        for i, ev in enumerate(entry["events"], start=1):
            name = f"(name={ev['name']!r})" if ev["name"] else ""
            lines.append(
                f"        {i}. {ev['kind']}{name}  "
                f"{ev['file']}:{ev['line']}  [{ev['status']}]")
        if entry["end"] == "exhausted":
            lines.append("        (schedule exhausted — submits "
                         "nothing further)")
    if trace["forks"]:
        lines.append("      forks:")
        for f in trace["forks"]:
            lines.append(f"        - {f['file']}:{f['line']}: "
                         f"{f['why']}")
    return "\n".join(lines)
