"""``hvd-model`` — explicit-state model checker for the control-plane
protocols (HA terms, fleet leases, KV migration).

Explores the bounded state space of each protocol model
(machines.py) with crash/restart, message loss, duplication, and
reorder injected at every step, checks the safety invariants on every
state and bounded liveness on complete explorations, and renders
violations through the hvd-lint machinery: HVD701 (safety), HVD702
(liveness), HVD703 (budget), with minimized counterexample traces as
text interleavings or SARIF codeFlows. See docs/modelcheck.md.

Exit codes: 0 all explored models clean, 1 violations (at --fail-on
severity) found, 2 usage error.
"""

import argparse
import json
import sys
import time

from ..diagnostics import ERROR, worst_severity
from . import machines
from .model import explore, result_diagnostics


def _parser():
    parser = argparse.ArgumentParser(
        prog="hvd-model",
        description="Explicit-state model checker for the "
                    "control-plane protocols (docs/modelcheck.md).")
    parser.add_argument(
        "--protocol", default="all",
        choices=("all",) + machines.PROTOCOLS,
        help="which protocol to check (default: all)")
    parser.add_argument(
        "--seed-bug", default=None, metavar="NAME",
        help="re-introduce a named historical bug into the model "
             "(the mutation proof; see --list). Requires a single "
             "--protocol.")
    parser.add_argument(
        "--depth", type=int, default=24,
        help="BFS depth bound (default: 24)")
    parser.add_argument(
        "--max-states", type=int, default=100000,
        help="state-count bound (default: 100000)")
    parser.add_argument(
        "--budget-s", type=float, default=None, metavar="SECONDS",
        help="wall-clock bound across ALL explored models; running "
             "out is itself a finding (HVD703)")
    parser.add_argument(
        "--format", default="text", choices=("text", "json", "sarif"))
    parser.add_argument(
        "--fail-on", default="warning",
        choices=("error", "warning", "never"),
        help="exit 1 at this severity (default: warning — budget "
             "overruns fail CI too)")
    parser.add_argument(
        "--keep-going", action="store_true",
        help="collect every violation per model instead of stopping "
             "at the first")
    parser.add_argument(
        "--list", action="store_true",
        help="list protocols, their invariants, and seeded bugs")
    return parser


def _list_models():
    for proto in machines.PROTOCOLS:
        for model in machines.build(proto):
            invs = ", ".join(name for name, _ in model.invariants)
            goals = ", ".join(name for name, _ in model.liveness)
            print(f"{proto}: invariants [{invs}] liveness [{goals}]")
        bugs = ", ".join(machines.BUGS.get(proto, ())) or "none"
        print(f"{proto}: seeded bugs: {bugs}")


def main(argv=None):
    args = _parser().parse_args(argv)
    if args.list:
        _list_models()
        return 0
    protocols = (machines.PROTOCOLS if args.protocol == "all"
                 else (args.protocol,))
    if args.seed_bug is not None and args.protocol == "all":
        print("hvd-model: --seed-bug needs a single --protocol",
              file=sys.stderr)
        return 2
    t0 = time.monotonic()
    diags, summaries = [], []
    for proto in protocols:
        try:
            models = machines.build(proto, bug=args.seed_bug)
        except ValueError as exc:
            print(f"hvd-model: {exc}", file=sys.stderr)
            return 2
        for model in models:
            remaining = None
            if args.budget_s is not None:
                remaining = max(0.5, args.budget_s
                                - (time.monotonic() - t0))
            result = explore(
                model, max_depth=args.depth,
                max_states=args.max_states, deadline_s=remaining,
                stop_on_first=not args.keep_going)
            diags.extend(result_diagnostics(model, result))
            summaries.append(
                f"{model.name}: {result.states} state(s), "
                f"{result.edges} edge(s), depth {result.depth}, "
                f"{'complete' if result.complete else 'INCOMPLETE'}, "
                f"{len(result.violations)} violation(s) in "
                f"{result.elapsed_s:.2f}s")

    if args.format == "json":
        print(json.dumps([d.to_dict() for d in diags], indent=1))
    elif args.format == "sarif":
        from .. import sarif
        sarif.write_sarif(None, diags, tool="hvd-model")
    else:
        from ..simulate import render_trace
        for d in diags:
            print(d.format())
            trace_text = render_trace(d)
            if trace_text:
                print(trace_text)
        for line in summaries:
            print(f"hvd-model: {line}")
        bug = f" [seeded bug: {args.seed_bug}]" if args.seed_bug else ""
        print(f"hvd-model: {len(diags)} finding(s) across "
              f"{len(summaries)} model(s){bug} in "
              f"{time.monotonic() - t0:.2f}s")

    if args.fail_on == "never" or not diags:
        return 0
    if args.fail_on == "error":
        return 1 if worst_severity(diags) == ERROR else 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
