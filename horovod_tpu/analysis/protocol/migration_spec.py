"""Pure transition spec of KV-cache live migration
(serving/migration.py + the admission fence in serving/kv_cache.py).

This module IS the migration handshake's state machine:
``serving/migration.py`` chunk-packs and reassembles through these
functions and ``kv_cache.PagePool`` admission-checks through
:func:`admits` (spec-is-implementation, enforced by
tests/test_protocol_model.py), while the ``hvd-model`` checker
replays the same functions under injected chunk loss, duplication,
reorder, and restarts. Stdlib-pure — no sockets, no locks, no clock:
time enters only as the explicit ``now`` argument.
"""


class StagingLimit(RuntimeError):
    """Inbound staging is at its concurrent-transfer bound; the wire
    layer (serving/migration.py ``StagingFull``) maps this to a 429."""


def chunk_pages(pages, max_bytes):
    """Greedily pack page entries into chunks whose encoded payload
    stays under ``max_bytes`` (at least one page per chunk — a single
    page past the bound still ships and the target's 413 makes the
    overflow loud). Always returns >= 1 chunk so a pageless (cold)
    record still carries its commit metadata."""
    max_bytes = int(max_bytes)
    chunks, cur, size = [], [], 0
    for pg in pages:
        sz = len(pg.get("payload", "")) + 128   # +json framing slack
        if cur and size + sz > max_bytes:
            chunks.append(cur)
            cur, size = [], 0
        cur.append(pg)
        size += sz
    chunks.append(cur)
    return chunks


def stage_chunk(entries, payload, *, max_staged, ttl_s, now):
    """Stage one inbound chunk against the reassembly state
    ``entries`` (``mid -> {chunks, total, meta, t}``), returning the
    assembled record when the migration is complete, else None.

    This is the one transition of the target's staging machine —
    ``InboundStaging.offer`` executes it under its lock with the real
    clock; the model checker executes it with a frozen one. Mutates
    ``entries`` in place: stale entries past ``ttl_s`` are swept, a
    completed transfer's entry is deleted *before* the record is
    handed to the importer (the dedup that makes a duplicated chunk
    of a finished migration reassemble nothing — the ``double_import``
    seeded bug removes exactly this line). Raises ValueError on a
    malformed chunk and :class:`StagingLimit` at the bound."""
    mid = str(payload["mid"])
    chunk = int(payload["chunk"])
    total = int(payload["total"])
    if total < 1 or not 0 <= chunk < total:
        raise ValueError(f"chunk {chunk} outside total {total}")
    for stale in [m for m, e in entries.items()
                  if now - e["t"] > ttl_s]:
        del entries[stale]
    entry = entries.get(mid)
    if entry is None:
        if len(entries) >= max_staged:
            raise StagingLimit(
                f"{len(entries)} inbound migrations already staged")
        entry = {"chunks": {}, "total": total, "meta": None, "t": now}
        entries[mid] = entry
    entry["t"] = now
    entry["chunks"][chunk] = list(payload.get("pages", []))
    if payload.get("meta") is not None:
        entry["meta"] = dict(payload["meta"])
    if (entry["meta"] is None
            or len(entry["chunks"]) < entry["total"]):
        return None
    del entries[mid]
    record = dict(entry["meta"])
    record["pages"] = [pg for i in sorted(entry["chunks"])
                       for pg in entry["chunks"][i]]
    return record


def admits(free, need, watermark):
    """The watermark admission predicate: may ``need`` pages be
    allocated out of ``free`` while keeping the reserve intact? One
    predicate for prefill admission, import placement
    (kv_cache.alloc_admit), and the model checker's invariant — the
    reserve is what lets running sequences keep growing during decode
    instead of deadlocking against arrivals."""
    return int(free) - int(need) >= int(watermark)


#: Source-side classification of a target's deterministic refusal:
#: outcome label -> (metric outcome, try the next peer?). Structural
#: refusals (the peer is full/draining) are worth another peer;
#: payload/version refusals mean the record itself cannot land and the
#: source falls back to recompute immediately.
REFUSAL_POLICY = {
    "no_headroom": ("no_headroom", True),
    "draining": ("no_headroom", True),
    "version_fenced": ("version_fence", False),
    "digest_mismatch": ("digest_mismatch", False),
    "geometry_mismatch": ("digest_mismatch", False),
    "too_large": ("refused", False),
}


def classify_refusal(outcome):
    """``(metric_outcome, try_next_peer)`` for one refusal outcome
    label (unknown labels count as a terminal ``refused``)."""
    return REFUSAL_POLICY.get(str(outcome), ("refused", False))


__all__ = ["StagingLimit", "chunk_pages", "stage_chunk", "admits",
           "REFUSAL_POLICY", "classify_refusal"]
