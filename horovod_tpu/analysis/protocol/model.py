"""Explicit-state BFS explorer for the control-plane protocol specs.

The checker is deliberately small-model: each protocol
(analysis/protocol/machines.py) is a :class:`Model` whose actions call
the SAME spec functions the runtime executes, with crash/restart,
message loss, duplication, and reorder expressed as fault actions
enabled at every step. :func:`explore` walks the reachable state
space breadth-first up to a bounded depth/state/wall-clock budget,
checks every safety invariant on every state, and — when the bounded
space was covered completely — checks bounded liveness: every
reachable state must reach a goal state over *fair* (non-fault) edges
alone, i.e. the protocol cannot be wedged by any prefix of faults
once the faults stop.

Counterexamples come out minimized twice over: BFS order makes the
violating trace shortest by construction, and :func:`minimize` then
greedily deletes steps that the violation does not actually need
(replaying candidate traces through the model), which strips fault
injections a shorter organic path can do without. Violations render
through the existing hvd-lint machinery — :func:`violation_diagnostic`
emits HVD701/702/703 :class:`Diagnostic` objects whose ``trace`` dict
reuses the simulator's counterexample schema, so ``hvd-lint``'s text
renderer and the SARIF ``codeFlows`` writer need nothing new.
"""

import collections
import copy
import dataclasses
import json
import time

from ..diagnostics import Diagnostic


def _anchor(fn):
    """(file, line) of a spec function — counterexample steps point at
    the transition's source, not at the model harness."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return "<model>", 0
    return code.co_filename, code.co_firstlineno


@dataclasses.dataclass
class Action:
    """One enabled transition: ``run`` takes an already-deep-copied
    state, mutates it, and returns it. ``fault`` actions model the
    environment (crash, loss, duplication); everything else is fair
    scheduling. ``anchor`` is the spec function the step executes."""

    label: str
    actor: str
    run: object
    fault: bool = False
    anchor: tuple = ("<model>", 0)


@dataclasses.dataclass
class Step:
    label: str
    actor: str
    fault: bool
    file: str
    line: int


@dataclasses.dataclass
class Violation:
    kind: str          # "safety" | "liveness" | "budget"
    name: str          # invariant / goal name
    message: str
    trace: list        # [Step]; the minimized counterexample


@dataclasses.dataclass
class CheckResult:
    model: str
    states: int = 0
    edges: int = 0
    depth: int = 0
    complete: bool = False
    elapsed_s: float = 0.0
    violations: list = dataclasses.field(default_factory=list)

    @property
    def ok(self):
        return self.complete and not self.violations


class Model:
    """A protocol model: subclass-free — construct with callables.

    ``init()`` returns the initial state (a JSON-able dict);
    ``actions(state)`` returns the list of *enabled* :class:`Action`;
    ``invariants`` is ``[(name, check)]`` where ``check(state)``
    returns None when the invariant holds, else a message;
    ``liveness`` is ``[(name, goal)]`` where ``goal(state)`` is True
    on goal states."""

    def __init__(self, name, init, actions, invariants=(),
                 liveness=()):
        self.name = name
        self.init = init
        self.actions = actions
        self.invariants = list(invariants)
        self.liveness = list(liveness)


def canon(state):
    """Canonical serialization — the visited-set key."""
    return json.dumps(state, sort_keys=True, separators=(",", ":"))


def _steps_from(parents, key):
    steps = []
    while True:
        parent, action = parents[key]
        if parent is None:
            break
        file, line = action.anchor
        steps.append(Step(action.label, action.actor, action.fault,
                          file, line))
        key = parent
    steps.reverse()
    return steps


def replay(model, labels):
    """Replay a label sequence from init; the list of visited states,
    or None when some label is not enabled where the sequence needs
    it (deterministic: labels are unique per state by construction)."""
    state = model.init()
    out = [state]
    for label in labels:
        for action in model.actions(state):
            if action.label == label:
                state = action.run(copy.deepcopy(state))
                break
        else:
            return None
        out.append(state)
    return out


def minimize(model, steps, failing):
    """Greedy delta-minimization: drop any step whose removal keeps
    ``failing(final_state)`` true, until a fixpoint. BFS already made
    the trace shortest; this strips injected faults and setup steps a
    violation does not actually depend on."""
    labels = [s.label for s in steps]
    by_label = {s.label: s for s in steps}
    changed = True
    while changed:
        changed = False
        for i in range(len(labels)):
            candidate = labels[:i] + labels[i + 1:]
            states = replay(model, candidate)
            if states is not None and failing(states[-1]):
                labels = candidate
                changed = True
                break
    return [by_label[label] for label in labels]


def explore(model, max_depth=24, max_states=100000, deadline_s=None,
            stop_on_first=True):
    """BFS over the model's reachable states within the budget;
    returns a :class:`CheckResult`. ``complete`` is True only when the
    bounded space was exhausted without tripping any budget — liveness
    is only *judged* on a complete exploration (an incomplete one gets
    a ``budget`` violation instead, rendered as HVD703)."""
    t0 = time.monotonic()
    result = CheckResult(model=model.name)
    init = model.init()
    init_key = canon(init)
    parents = {init_key: (None, None)}
    states = {init_key: init}
    fair_succ = collections.defaultdict(set)
    queue = collections.deque([(init_key, 0)])
    budget_hit = None

    def violated(key):
        state = states[key]
        for name, check in model.invariants:
            msg = check(state)
            if msg is not None:
                steps = _steps_from(parents, key)
                steps = minimize(
                    model, steps,
                    lambda final, _c=check: _c(final) is not None)
                result.violations.append(Violation(
                    "safety", name, msg, steps))
                return True
        return False

    if violated(init_key) and stop_on_first:
        result.states = 1
        result.elapsed_s = time.monotonic() - t0
        return result

    while queue:
        if deadline_s is not None and time.monotonic() - t0 > deadline_s:
            budget_hit = f"wall clock over {deadline_s:.1f}s"
            break
        key, depth = queue.popleft()
        state = states[key]
        for action in model.actions(state):
            succ = action.run(copy.deepcopy(state))
            succ_key = canon(succ)
            result.edges += 1
            if not action.fault:
                fair_succ[key].add(succ_key)
            if succ_key in parents:
                continue
            if depth >= max_depth:
                # A genuinely new state past the horizon: the bounded
                # space was NOT covered (an already-seen successor at
                # the horizon costs nothing).
                budget_hit = f"depth bound {max_depth} reached"
                continue
            if len(parents) >= max_states:
                budget_hit = f"state bound {max_states} reached"
                queue.clear()
                break
            parents[succ_key] = (key, action)
            states[succ_key] = succ
            result.depth = max(result.depth, depth + 1)
            if violated(succ_key) and stop_on_first:
                queue.clear()
                break
            queue.append((succ_key, depth + 1))

    result.states = len(parents)
    result.complete = budget_hit is None and not (
        result.violations and stop_on_first)
    if budget_hit is not None:
        result.violations.append(Violation(
            "budget", "exploration",
            f"bounded exploration incomplete: {budget_hit} after "
            f"{len(parents)} state(s)", []))

    if result.complete and model.liveness:
        _check_liveness(model, result, parents, states, fair_succ)
    result.elapsed_s = time.monotonic() - t0
    return result


def _check_liveness(model, result, parents, states, fair_succ):
    """Bounded liveness under fair scheduling: from every reachable
    state a goal state must be reachable over fair edges alone.
    Backward reachability from the goal set over the fair edge
    relation; any state left outside is a wedge — its shortest
    incoming trace is the counterexample."""
    preds = collections.defaultdict(set)
    for src, succs in fair_succ.items():
        for dst in succs:
            preds[dst].add(src)
    for name, goal in model.liveness:
        can_reach = {key for key, state in states.items()
                     if goal(state)}
        frontier = collections.deque(can_reach)
        while frontier:
            key = frontier.popleft()
            for pred in preds[key]:
                if pred not in can_reach:
                    can_reach.add(pred)
                    frontier.append(pred)
        wedged = [key for key in states if key not in can_reach]
        if not wedged:
            continue
        # Shortest trace = the wedged state discovered earliest.
        key = min(wedged,
                  key=lambda k: len(_steps_from(parents, k)))
        result.violations.append(Violation(
            "liveness", name,
            f"{len(wedged)} reachable state(s) cannot reach the "
            f"{name!r} goal over fair (fault-free) scheduling — the "
            "protocol is wedged once the faults stop",
            _steps_from(parents, key)))


# -- rendering through the hvd-lint machinery ------------------------------

def _trace_dict(model_name, steps):
    """The simulator's counterexample schema (analysis/simulate.py
    render_trace, analysis/sarif.py codeFlows): one "rank" per
    protocol actor, events carrying the global step index so the
    interleaving stays readable after the per-actor split."""
    per_actor = {}
    for i, step in enumerate(steps, start=1):
        per_actor.setdefault(step.actor, []).append({
            "kind": step.label,
            "name": f"step {i}",
            "file": step.file,
            "line": step.line,
            "status": "fault" if step.fault else "ok",
        })
    ranks = [{"rank": actor, "events": events, "end": ""}
             for actor, events in per_actor.items()]
    return {"cohort": model_name, "ranks": ranks, "forks": []}


def violation_diagnostic(model, violation):
    """One :class:`Diagnostic` per violation: HVD701 (safety), HVD702
    (liveness), HVD703 (budget). Location anchors at the last spec
    transition of the counterexample — the step that lands in the bad
    state."""
    rule = {"safety": "HVD701", "liveness": "HVD702",
            "budget": "HVD703"}[violation.kind]
    if violation.trace:
        file, line = violation.trace[-1].file, violation.trace[-1].line
    else:
        file, line = _anchor(model.init)
    kind_txt = {"safety": "invariant", "liveness": "liveness goal",
                "budget": "budget"}[violation.kind]
    message = (f"protocol {model.name!r}, {kind_txt} "
               f"{violation.name!r}: {violation.message}")
    hint = ("replay the counterexample with `hvd-model --protocol "
            f"{model.name} --format text` and see docs/modelcheck.md "
            "\"Reading a counterexample\""
            if violation.trace else
            "raise --depth/--max-states/--budget-s, or shrink the "
            "model's bounds (docs/modelcheck.md \"Budgets\")")
    trace = (_trace_dict(model.name, violation.trace)
             if violation.trace else None)
    return Diagnostic.make(rule, message, file=file, line=line,
                           hint=hint, trace=trace)


def result_diagnostics(model, result):
    """Every violation of one :class:`CheckResult` as Diagnostics."""
    return [violation_diagnostic(model, v) for v in result.violations]


__all__ = ["Action", "Step", "Violation", "CheckResult", "Model",
           "canon", "replay", "minimize", "explore",
           "violation_diagnostic", "result_diagnostics"]
