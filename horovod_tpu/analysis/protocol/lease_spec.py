"""Pure transition spec of the fleet lease ledger (fleet/ledger.py).

This module IS the lease state machine: ``fleet/ledger.py`` imports
and executes these functions (spec-is-implementation, enforced by
tests/test_protocol_model.py), and the ``hvd-model`` checker explores
the same chain/validation/resume rules under injected arbiter crashes.
Stdlib-pure — no backend, no clock, no journal.
"""

TRAIN_TO_SERVE = "train_to_serve"
SERVE_TO_TRAIN = "serve_to_train"
DIRECTIONS = (TRAIN_TO_SERVE, SERVE_TO_TRAIN)

#: Per-direction state chains. ``rolled_back`` is reachable only from
#: ``proposed`` (nothing actuated yet); every later state rolls
#: forward — the transfer state machine in docs/fault_tolerance.md.
CHAINS = {
    TRAIN_TO_SERVE: ("proposed", "preempting", "resharding",
                     "activating", "complete"),
    SERVE_TO_TRAIN: ("proposed", "draining", "returning", "complete"),
}
TERMINAL_STATES = ("complete", "rolled_back")


class LeaseStateError(RuntimeError):
    """An illegal lease transition was attempted; the message names
    the lease, its state, and the requested state."""


def next_state(direction, state):
    """The successor of ``state`` on ``direction``'s chain (None at
    the end)."""
    chain = CHAINS[direction]
    idx = chain.index(state)
    return chain[idx + 1] if idx + 1 < len(chain) else None


def resume_action(lease):
    """What a freshly-promoted arbiter must do with a recovered
    in-flight lease: ``None`` (terminal — nothing), ``"rollback"``
    (``proposed`` — the ledger won the race, no actuation happened),
    or ``"roll_forward"`` (re-issue the current state's idempotent
    actuation and keep going)."""
    state = lease["state"]
    if state in TERMINAL_STATES:
        return None
    if state == "proposed":
        return "rollback"
    return "roll_forward"


def check_transition(lease, state):
    """Validate one requested transition against the chain invariants
    (raises :class:`LeaseStateError`): ``rolled_back`` only from
    ``proposed``; everything else must be the chain successor."""
    direction = lease["direction"]
    current = lease["state"]
    if state == "rolled_back":
        if current != "proposed":
            raise LeaseStateError(
                f"lease {lease['id']}: cannot roll back from "
                f"{current!r} — actuation may have started; roll "
                "forward instead")
        return
    chain = CHAINS[direction]
    if state not in chain:
        raise LeaseStateError(
            f"lease {lease['id']}: {state!r} is not a {direction} "
            f"state (chain: {' -> '.join(chain)})")
    if state != next_state(direction, current):
        raise LeaseStateError(
            f"lease {lease['id']}: illegal transition "
            f"{current!r} -> {state!r} (chain: {' -> '.join(chain)})")


__all__ = ["TRAIN_TO_SERVE", "SERVE_TO_TRAIN", "DIRECTIONS", "CHAINS",
           "TERMINAL_STATES", "LeaseStateError", "next_state",
           "resume_action", "check_transition"]
