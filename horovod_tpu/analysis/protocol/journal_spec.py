"""Pure transition spec of the HA driver journal (runner/journal.py).

This module IS the journal's state machine: ``runner/journal.py``
imports and executes these functions (spec-is-implementation, enforced
by tests/test_protocol_model.py), and the ``hvd-model`` checker
(analysis/protocol/machines.py) explores the same functions under
injected crashes and stale-primary resurrections. Everything here is
stdlib-pure — no I/O, no locks, no clock — so one transition step is
one function call in both worlds.
"""

import hashlib
import json

#: KV scopes replicated through the journal (everything else is
#: ephemeral and re-published by workers after a failover). The
#: ``fleet`` scope holds the chip-budget arbiter's lease ledger
#: (fleet/ledger.py): a lease must be durable *before* any actuation
#: it authorises, so a standby promotion mid-transfer can resume or
#: roll it back deterministically (docs/fault_tolerance.md "Fleet
#: arbitration").
DURABLE_SCOPES = ("elastic.state", "elastic.exit", "fleet")


class JournalError(RuntimeError):
    """A journal file could not be read or an entry could not be
    applied; the message names the file/entry."""


def durable_key(scope, key):
    """True when a worker-written KV key belongs to the durable
    partition (journaled; survives failover)."""
    del key
    return scope in DURABLE_SCOPES


def term_fences(writer_term, observed_term):
    """The split-brain fence predicate: True when a mutation carrying
    ``writer_term`` must be refused because the store has already
    observed a newer primary at ``observed_term`` (docs/
    fault_tolerance.md "Split-brain fencing")."""
    return int(writer_term) < int(observed_term)


def new_state():
    """Empty driver state — the single replicated structure."""
    return {
        "term": 0,
        "version": -1,
        "rank_order": [],
        "workers": {},       # wid -> {"host": h, "slot": i}
        "blacklist": [],     # sorted host list
        "fail_counts": {},
        "resets": 0,
        "kv": {},            # durable scopes only: scope -> {key: str}
    }


def apply_entry(state, entry):
    """Apply one journal entry to a state dict (pure state transition —
    shared by the primary's bookkeeping, crash recovery, and the
    standby replica, so the three can never drift)."""
    op = entry.get("op")
    if op == "membership":
        state["version"] = entry["version"]
        state["rank_order"] = list(entry["rank_order"])
        state["workers"] = {w: dict(rec)
                            for w, rec in entry["workers"].items()}
        state["resets"] = entry.get("resets", state["resets"])
        # The assignment table IS durable KV state: a promoted standby
        # re-serves the same version so the running cohort never
        # re-rendezvouses for a takeover alone.
        kv = state["kv"]
        for scope in [s for s in kv if s.startswith("assign.")]:
            del kv[scope]
        kv[f"assign.{entry['version']}"] = dict(entry["assign"])
        kv.setdefault("elastic", {})["version"] = str(entry["version"])
    elif op == "fail_count":
        state["fail_counts"][entry["host"]] = entry["count"]
        if entry.get("blacklisted"):
            bl = set(state["blacklist"])
            bl.add(entry["host"])
            state["blacklist"] = sorted(bl)
    elif op == "kv_put":
        state["kv"].setdefault(entry["scope"], {})[entry["key"]] = \
            entry["value"]
    elif op == "kv_delete":
        state["kv"].get(entry["scope"], {}).pop(entry["key"], None)
    elif op == "kv_clear":
        state["kv"].pop(entry["scope"], None)
    elif op == "term":
        state["term"] = entry["term"]
    else:
        raise JournalError(f"journal entry seq={entry.get('seq')} has "
                           f"unknown op {op!r}")
    if entry.get("term", 0) > state["term"]:
        state["term"] = entry["term"]
    return state


def state_digest(state):
    """Canonical SHA-256 over the state — the acceptance check that a
    journal-replayed standby equals the pre-failover primary."""
    blob = json.dumps(state, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


__all__ = ["DURABLE_SCOPES", "JournalError", "durable_key",
           "term_fences", "new_state", "apply_entry", "state_digest"]
