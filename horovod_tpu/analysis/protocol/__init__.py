"""Control-plane protocol specs + the ``hvd-model`` explicit-state
checker (docs/modelcheck.md).

The three hand-rolled distributed protocols — the term-fenced HA
journal (runner/journal.py), the fleet lease ledger (fleet/ledger.py),
and the KV-migration handshake (serving/migration.py) — keep their
*pure transition logic* here, as first-class state-machine specs:

- :mod:`journal_spec`   — journal entry application, state digests,
  the durable-scope partition, and the term-fence predicate.
- :mod:`lease_spec`     — lease state chains, transition validation,
  and the promoted-arbiter resume rule (roll forward xor back).
- :mod:`migration_spec` — chunk packing, inbound staging reassembly,
  the watermark admission predicate, and refusal classification.

**Spec-is-implementation**: the runtime modules import and execute
these functions (tests/test_protocol_model.py asserts the delegation
by identity), so the model the checker explores can never drift from
shipped code. :mod:`model` is the explicit-state BFS explorer
(crash/restart, message loss, duplication, reorder injected at every
step), :mod:`machines` builds the three protocol models (plus their
seeded-bug mutants for the mutation proof), and :mod:`cli` is the
``hvd-model`` entry point emitting HVD7xx findings as text/JSON/SARIF.

Everything in the spec modules is stdlib-pure: importing them from the
runtime costs no jax, no parser stack, no simulator.
"""

from . import journal_spec, lease_spec, migration_spec  # noqa: F401
